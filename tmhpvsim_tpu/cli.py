"""Console entrypoints: ``metersim`` and ``pvsim``.

Same commands, flags and env vars as the reference (SURVEY.md §2.5):
``--amqp-url`` (env AMQP_URL), ``--exchange`` (env TMHPVSIM_EXCHANGE,
default 'meter'), counted ``-v`` (WARN - 10/level), ``--realtime/
--no-realtime`` (default realtime), positional FILE on pvsim — plus the
TPU-era extensions: ``--backend {asyncio,jax}``, ``--seed``, ``--chains``,
``--duration``, ``--start``, ``--sharded``.

The default transport URL is ``local://default`` (in-process fanout) so
the two apps run out of the box without a broker; ``tcp://HOST:PORT``
speaks to the in-tree ``fanoutbroker`` server (cross-process, no external
services); any amqp:// URL selects real AMQP (runtime/broker.py).
"""

from __future__ import annotations

import logging
import os

import click

from tmhpvsim_tpu.runtime import asyncrun


def _common_options(f):
    f = click.option(
        "--amqp-url", default=lambda: os.environ.get("AMQP_URL"),
        help="broker URL: amqp://... (RabbitMQ), tcp://HOST:PORT (the "
             "in-tree fanoutbroker command), or local://NAME (in-process; "
             "the default, 'local://default')",
    )(f)
    f = click.option(
        "--exchange",
        default=lambda: os.environ.get("TMHPVSIM_EXCHANGE", "meter"),
        help="The name of the exchange (defaults to 'meter')",
    )(f)
    f = click.option(
        "-v", "--verbose", count=True,
        help="Increase logging level from default WARN",
    )(f)
    f = click.option(
        "--realtime/--no-realtime", default=True,
        help="Switch off rate limiting (for simulation)",
    )(f)
    f = click.option("--seed", type=int, default=None,
                     help="PRNG seed (default: nondeterministic)")(f)
    f = click.option("--duration", "duration_s", type=int, default=None,
                     help="Stop after this many simulated seconds "
                          "(default: run forever)")(f)
    f = click.option("--start", default=None,
                     help="Simulation start time 'YYYY-MM-DD HH:MM:SS' "
                          "(default: now)")(f)
    f = click.option("--trace", "trace", default=None,
                     help="Record a streaming event timeline and export "
                          "Chrome-trace JSON here on exit (open in "
                          "Perfetto / chrome://tracing); crashes dump the "
                          "last 30 s to PATH.crash.json (obs/trace.py)")(f)
    return f


def _chaos_options(f):
    f = click.option(
        "--chaos", "chaos", default=None, metavar="SPEC",
        help="Deterministic fault-injection plan, e.g. "
             "'broker.publish=raise@n3;serve.dispatch=delay:0.2@every5' "
             "(grammar in runtime/faults.py).  Unset: $TMHPVSIM_CHAOS; "
             "no spec anywhere = injection compiled out")(f)
    f = click.option(
        "--chaos-seed", "chaos_seed", type=int, default=0,
        show_default=True,
        help="seed of the probability-triggered chaos rules")(f)
    return f


def _obs_port_option(f):
    f = click.option(
        "--obs-port", "obs_port", type=int, default=None, metavar="PORT",
        help="Bind the live ops plane on --obs-bind:PORT (0 picks a "
             "free one): /metrics (OpenMetrics), /podmetrics, /healthz, "
             "/readyz, /flight — and turn on cross-process trace "
             "propagation (trace_id/span_id riding every message's "
             "out-of-band meta).  Unset: no socket is bound and no "
             "stamps are added anywhere (obs/live.py)")(f)
    f = click.option(
        "--obs-bind", "obs_bind", default="127.0.0.1", show_default=True,
        metavar="HOST",
        help="Interface the live ops plane binds (with --obs-port): the "
             "loopback default keeps it host-local; 0.0.0.0 (or a "
             "specific interface) makes every pod worker's /metrics — "
             "and process 0's /podmetrics fleet view — scrapeable "
             "across hosts")(f)
    return f


def _activate_chaos(chaos, chaos_seed) -> None:
    """Arm fault injection from --chaos, else from $TMHPVSIM_CHAOS."""
    from tmhpvsim_tpu.runtime import faults

    if chaos:
        try:
            faults.activate(faults.FaultPlan.parse(chaos,
                                                   seed=chaos_seed))
        except ValueError as e:
            raise click.UsageError(f"bad --chaos spec: {e}") from e
    else:
        faults.install_from_env()


def _maybe_supervise(subcommand: str, supervise: int,
                     grace_s=None) -> None:
    """``--supervise N``: rerun this command as a restarting child
    (runtime/supervise.py) and exit with its final code.  A supervised
    child (env marker set) falls through and just runs.  ``grace_s``
    (``--preempt-grace``) bounds the child's final-snapshot window after
    a forwarded stop signal before the supervisor SIGKILLs it."""
    if supervise <= 0:
        return
    from tmhpvsim_tpu.runtime import supervise as sup

    if os.environ.get(sup.ENV_RESTART) is not None:
        return
    raise SystemExit(sup.run_supervised(sup.child_argv(subcommand),
                                        max_restarts=supervise,
                                        grace_s=grace_s))


def _setup_logging(verbose: int) -> None:
    # -v -> INFO, -vv -> DEBUG (metersim.py:92-93)
    logging.basicConfig(level=logging.WARN - 10 * verbose)


def _parse_start(start):
    import datetime as dt

    return dt.datetime.fromisoformat(start) if start else None


def _parse_site_grid(spec):
    """'LAT0:LAT1:NLAT,LON0:LON1:NLON' -> SiteGrid (None passes through)."""
    if not spec:
        return None
    from tmhpvsim_tpu.config import SiteGrid

    try:
        lat_part, lon_part = spec.split(",")
        lat0, lat1, n_lat = lat_part.split(":")
        lon0, lon1, n_lon = lon_part.split(":")
        return SiteGrid.regular(
            (float(lat0), float(lat1)), (float(lon0), float(lon1)),
            int(n_lat), int(n_lon),
        )
    except ValueError as e:
        raise click.UsageError(
            f"bad --site-grid {spec!r} (want LAT0:LAT1:NLAT,LON0:LON1:NLON)"
        ) from e


@click.command()
@click.option("--host", default="127.0.0.1", show_default=True,
              help="interface to listen on")
@click.option("--port", type=int, default=5673, show_default=True,
              help="TCP port (0 picks a free one)")
@click.option("--max-backlog", type=int, default=None,
              help="per-subscriber buffered messages before oldest-first "
                   "drop (default 10000; tcpbroker.dropped_total counts "
                   "the drops)")
@click.option("-v", "--verbose", count=True)
def fanoutbroker(host, port, max_backlog, verbose):
    """Standalone fanout broker for tcp:// transports — the in-tree
    replacement for the external RabbitMQ server the reference's
    deployment needs (runtime/tcpbroker.py): run this in one shell, then
    ``metersim --amqp-url tcp://HOST:PORT`` and ``pvsim out.csv
    --amqp-url tcp://HOST:PORT`` in two others."""
    from tmhpvsim_tpu.runtime.tcpbroker import (MAX_SUBSCRIBER_BACKLOG,
                                                TcpFanoutBroker)

    _setup_logging(verbose)

    async def run():
        broker = TcpFanoutBroker(
            host, port,
            max_backlog=(MAX_SUBSCRIBER_BACKLOG if max_backlog is None
                         else max_backlog))
        await broker.start()
        click.echo(f"fanout broker listening on {broker.host}:{broker.port}",
                   err=True)
        await broker.serve_forever()

    asyncrun(run())


@click.command()
@_common_options
@click.option("--backend", type=click.Choice(["asyncio", "jax"]),
              default="asyncio",
              help="asyncio: per-second numpy sampling (reference); jax: "
                   "device-batched blocks feeding the same publisher")
@click.option("--compile-cache", "compile_cache", default=None,
              metavar="DIR",
              help="Persistent XLA compilation-cache base directory (jax "
                   "backend; a per-device-kind subdir is created under "
                   "it).  Unset: $TMHPVSIM_COMPILE_CACHE, else "
                   "~/.cache/tmhpvsim_tpu/xla; 'off' disables "
                   "(engine/compilecache.py)")
@_obs_port_option
@_chaos_options
def metersim(amqp_url, exchange, verbose, realtime, seed, duration_s, start,
             trace, backend, compile_cache, obs_port, obs_bind, chaos,
             chaos_seed):
    """1 Hz electricity-demand producer (reference metersim.py:79-95)."""
    from tmhpvsim_tpu.apps.metersim import metersim_main

    _setup_logging(verbose)
    _activate_chaos(chaos, chaos_seed)
    if compile_cache is not None and backend != "jax":
        raise click.UsageError("--compile-cache requires --backend=jax")
    asyncrun(metersim_main(amqp_url, exchange, realtime, seed, duration_s,
                           _parse_start(start), backend=backend,
                           trace=trace, compile_cache=compile_cache,
                           obs_port=obs_port, obs_bind=obs_bind))


@click.command()
@click.argument("file")
@_common_options
@click.option("--backend", type=click.Choice(["asyncio", "jax"]),
              default="asyncio",
              help="asyncio: reference-compatible streaming; jax: blockwise "
                   "device simulation (no broker)")
@click.option("--chains", "n_chains", type=int, default=1,
              help="Independent stochastic chains (jax backend)")
@click.option("--chain", type=int, default=0,
              help="Which chain to write to FILE (jax backend)")
@click.option("--sharded/--no-sharded", default=False,
              help="Shard chains over all available devices (jax backend)")
@click.option("--mesh-scenario", "mesh_scenario", type=int, default=0,
              show_default=True, metavar="M",
              help="Scenario axis length of the 2D (chains, scenario) "
                   "device mesh (jax backend, with --sharded): 0 keeps "
                   "the flat 1D chain mesh; M >= 1 reshapes the device "
                   "pool to (n_devices//M, M).  Batch results are "
                   "bit-identical under any M; scenario serving "
                   "parallelises what-if batches over the scenario "
                   "axis (parallel/mesh.py)")
@click.option("--coordinator", "coordinator", default=None,
              envvar="JAX_COORDINATOR_ADDRESS", metavar="HOST:PORT",
              help="jax.distributed coordinator address for multi-host "
                   "runs (jax backend; env JAX_COORDINATOR_ADDRESS)")
@click.option("--num-processes", "num_processes", type=int, default=None,
              envvar="JAX_NUM_PROCESSES", metavar="K",
              help="Total process count of the multi-host run (jax "
                   "backend; env JAX_NUM_PROCESSES)")
@click.option("--process-id", "process_id", type=int, default=None,
              envvar="JAX_PROCESS_ID", metavar="I",
              help="This process's index in [0, K) (jax backend; env "
                   "JAX_PROCESS_ID)")
@click.option("--checkpoint", default=None,
              help="Checkpoint file: saved per block, resumed when present "
                   "(jax backend)")
@click.option("--block-s", type=int, default=None,
              help="Seconds per device block, multiple of 60 (jax backend; "
                   "default: min(8640, duration))")
@click.option("--site-grid", "site_grid_spec", default=None,
              help="Multi-site lat/lon grid 'LAT0:LAT1:NLAT,LON0:LON1:NLON' "
                   "— one chain per site, geometry on device (jax backend; "
                   "overrides --chains)")
@click.option("--sites-csv", "sites_csv", default=None,
              type=click.Path(exists=True, dir_okay=False),
              help="Arbitrary site list from a CSV (columns latitude, "
                   "longitude [, altitude, surface_tilt, surface_azimuth, "
                   "albedo]) — one chain per row (jax backend; overrides "
                   "--chains; mutually exclusive with --site-grid)")
@click.option("--fleet-csv", "fleet_csv", default=None,
              type=click.Path(exists=True, dir_okay=False),
              help="Heterogeneous fleet from a CSV (columns latitude, "
                   "longitude [, altitude, surface_tilt, surface_azimuth, "
                   "albedo, dc_capacity_scale, ac_limit_w, weather_regime, "
                   "demand_scale, demand_shift_w, cohort]) — one chain per "
                   "row, per-site parameters on device (jax backend; "
                   "overrides --chains; mutually exclusive with "
                   "--site-grid/--sites-csv; fleet/params.py)")
@click.option("--fleet-synth", "fleet_synth", type=int, default=None,
              metavar="N",
              help="Synthetic seeded national fleet of N sites — geometry, "
                   "inverter limits, weather regimes and demand profiles "
                   "sampled reproducibly (jax backend; overrides --chains; "
                   "mutually exclusive with --fleet-csv; "
                   "fleet.FleetParams.synthetic)")
@click.option("--fleet-seed", "fleet_seed", type=int, default=0,
              show_default=True,
              help="seed of the --fleet-synth sampler (independent of "
                   "--seed, which drives the weather/demand draws)")
@click.option("--profile", "profile_dir", default=None,
              help="Write a jax.profiler device trace to this directory "
                   "(jax backend; view in TensorBoard/Perfetto)")
@click.option("--output", type=click.Choice(["trace", "reduce", "ensemble"]),
              default="trace",
              help="trace: per-second CSV rows (one chain); reduce: "
                   "on-device per-chain statistics only; ensemble: "
                   "per-second fleet-mean rows — reduce/ensemble scale to "
                   "100k+ chains (jax backend)")
@click.option("--prng-impl", type=click.Choice(["threefry2x32", "rbg"]),
              default="threefry2x32",
              help="PRNG: threefry2x32 = fully counter-based (default, "
                   "and the fast mode on current TPU backends — rbg's "
                   "vmapped per-chain draws serialize there); rbg = TPU "
                   "hardware bit generator (jax backend; see "
                   "config.SimConfig.prng_impl)")
@click.option("--block-impl",
              type=click.Choice(["auto", "wide", "scan", "scan2"]),
              default="auto",
              help="reduce/ensemble block formulation: auto picks "
                   "scan-fused on accelerators, wide on CPU; scan2 nests "
                   "per-minute RNG tiles (jax backend, see "
                   "config.SimConfig.block_impl)")
@click.option("--tune", type=click.Choice(["off", "auto", "force"]),
              default="off",
              help="runtime autotuner: auto = use/populate the persistent "
                   "per-device plan cache (short real-block probes on a "
                   "miss); force = re-probe even on a hit; the resolved "
                   "plan is echoed in the logs (jax backend, see "
                   "config.SimConfig.tune)")
@click.option("--telemetry", type=click.Choice(["off", "light", "full"]),
              default="off",
              help="in-graph numerics telemetry (jax backend, reduce "
                   "mode): light = NaN/Inf counters + moments on the "
                   "device scan carry, checked per block by the drift "
                   "sentinel; full adds the csi histogram + cloud "
                   "occupancy; off pays nothing (obs/telemetry.py)")
@click.option("--telemetry-strict", is_flag=True, default=False,
              help="escalate drift-sentinel WARNs (NaN/Inf, reference "
                   "band escape) to a hard error")
@click.option("--analytics", type=click.Choice(["off", "risk", "full"]),
              default="off",
              help="on-device fleet-risk analytics (jax backend, reduce "
                   "mode): risk = residual quantile sketch, exceedance "
                   "curve, loss-of-load probability and ramp extrema on "
                   "the device scan carry, surfaced as the RunReport "
                   "'fleet' section; full adds per-regime conditional "
                   "means; off pays nothing (obs/analytics.py)")
@click.option("--metrics", "metrics_path", default=None,
              help="Stream metric snapshots to this file: .prom = "
                   "Prometheus text exposition (atomic rewrite), anything "
                   "else = JSONL append — per block on the jax backend, "
                   "at end of run on asyncio (obs/)")
@click.option("--run-report", "run_report_path", default=None,
              help="Write the schema-versioned RunReport JSON here after "
                   "the run: config/plan/timing on the jax backend; the "
                   "asyncio backend's report carries the 'streaming' "
                   "section (join latency quantiles, funnel/broker/retry "
                   "counters)")
@click.option("--compile-cache", "compile_cache", default=None,
              metavar="DIR",
              help="Persistent XLA compilation-cache base directory (jax "
                   "backend; a per-device-kind subdir is created under "
                   "it, and the resolved plan's block functions are "
                   "AOT-warmed into it at build time).  Unset: "
                   "$TMHPVSIM_COMPILE_CACHE, else "
                   "~/.cache/tmhpvsim_tpu/xla; 'off' disables "
                   "(engine/compilecache.py)")
@click.option("--blocks-per-dispatch", "blocks_per_dispatch", type=int,
              default=0,
              help="Blocks fused into one device dispatch (jax backend): "
                   "0 = auto (per-block, or the autotuner's probed choice "
                   "under --tune); K > 1 runs K blocks as one jitted scan "
                   "— bit-identical results, fewer host round-trips "
                   "(config.SimConfig.blocks_per_dispatch)")
@click.option("--compute-dtype", "compute_dtype",
              type=click.Choice(["auto", "f32", "bf16"]),
              default="auto",
              help="Mixed-precision compute path (jax backend): bf16 "
                   "narrows the per-second RNG streams + physics chain; "
                   "accumulators/carry stay f32 and the drift sentinel "
                   "gates it — telemetry auto-escalates to 'light' "
                   "(config.SimConfig.compute_dtype)")
@click.option("--kernel-impl", "kernel_impl",
              type=click.Choice(["auto", "exact", "table"]),
              default="auto",
              help="Transcendental kernels for the solar/pv models (jax "
                   "backend): exact = jnp ops (byte-identical HLO), "
                   "table = minimax polynomials + day-of-year LUT, "
                   "validated to published ULP bounds "
                   "(config.SimConfig.kernel_impl, models/tables.py)")
@click.option("--rng-batch", "rng_batch",
              type=click.Choice(["auto", "scan", "block"]),
              default="auto",
              help="Second-noise RNG generation (jax backend): scan = "
                   "draw per minute inside the scan body; block = hoist "
                   "every draw into whole-block counter-mode tensors "
                   "generated before the scan — bit-identical by "
                   "construction (same fold_in keying), asserted in "
                   "tests; auto lets the autotuner probe "
                   "(config.SimConfig.rng_batch)")
@click.option("--geom-stride", "geom_stride",
              type=click.Choice(["0", "1", "30", "60"]),
              default="0",
              help="Solar-geometry stride seconds (jax backend): evaluate "
                   "the transcendental geometry chain every S seconds and "
                   "lerp trig-free quantities back to 1 Hz; error bound "
                   "published in models/solar.py:STRIDE_MAX_ABS_ERR; "
                   "1 = byte-identical HLO, 0 = auto "
                   "(config.SimConfig.geom_stride)")
@click.option("--output-overlap", "output_overlap",
              type=click.Choice(["auto", "off"]),
              default="auto",
              help="Double-buffered trace/ensemble host output (jax "
                   "backend): overlap block N's gather/CSV with block "
                   "N+1's device dispatch; forced off by --checkpoint "
                   "(config.SimConfig.output_overlap)")
@click.option("--checkpoint-keep", "checkpoint_keep", type=int, default=3,
              show_default=True, metavar="N",
              help="Checkpoint generations retained on disk (jax "
                   "backend): the anchor plus the newest N rotated "
                   ".g<gen> snapshots named by the sidecar integrity "
                   "manifest; a torn latest generation falls back to "
                   "the newest one that verifies (engine/checkpoint.py)")
@click.option("--checkpoint-async", "checkpoint_async",
              type=click.Choice(["off", "on"]), default="off",
              show_default=True,
              help="Background checkpoint writes (jax backend): the "
                   "scan loop pays only the device->host gather; "
                   "serialization, checksums, fsync and rotation happen "
                   "on a writer thread.  off = today's synchronous save")
@click.option("--preempt-grace", "preempt_grace", type=float, default=0.0,
              show_default=True, metavar="S",
              help="Preemption grace seconds (jax backend): SIGTERM "
                   "finishes the current block, drains one final "
                   "snapshot and exits cleanly; with --supervise the "
                   "supervisor SIGKILLs a child still alive S seconds "
                   "after the stop signal.  0 = SIGTERM dies immediately")
@click.option("--pod-obs", "pod_obs", type=click.Choice(["off", "on"]),
              default="off", show_default=True,
              help="Pod-scale observability (jax backend): at every block "
                   "boundary of a multi-process run, gather per-host "
                   "heartbeats (one small process_allgather), compute "
                   "skew/straggler verdicts (WARN + pod.straggler_total "
                   "when a host's block wall exceeds the pod median by "
                   "--pod-straggler-factor) and emit the RunReport 'pod' "
                   "section; the live ops plane additionally serves "
                   "/podmetrics.  off pays nothing: no gathers, no "
                   "stamps, byte-identical HLO (obs/pod.py)")
@click.option("--pod-straggler-factor", "pod_straggler_factor", type=float,
              default=2.0, show_default=True, metavar="X",
              help="Straggler threshold for --pod-obs: a host whose block "
                   "wall exceeds the pod median by this factor is flagged "
                   "(config.SimConfig.pod_straggler_factor)")
@click.option("--phase-obs", "phase_obs", type=click.Choice(["off", "on"]),
              default="off", show_default=True,
              help="Semantic phase scopes (jax backend): wrap the block "
                   "step's stages (rng, markov, csi, geometry, physics, "
                   "...) in jax.named_scope frames so any device trace "
                   "captured with --profile is attributable per phase "
                   "(obs/attribution.py; RunReport 'attribution' "
                   "section).  off lowers to byte-identical HLO")
@click.option("--supervise", "supervise", type=int, default=0,
              metavar="N",
              help="Run as a supervised child and warm-restart it on a "
                   "crash up to N times: the restarted run resumes from "
                   "--checkpoint and recompiles nothing under the "
                   "persistent compile cache (runtime/supervise.py)")
@_obs_port_option
@_chaos_options
def pvsim(file, amqp_url, exchange, verbose, realtime, seed, duration_s,
          start, trace, backend, n_chains, chain, sharded, mesh_scenario,
          coordinator, num_processes, process_id, checkpoint,
          block_s, site_grid_spec, sites_csv, fleet_csv, fleet_synth,
          fleet_seed, profile_dir, output,
          prng_impl, block_impl, tune, telemetry, telemetry_strict,
          analytics, metrics_path, run_report_path, compile_cache,
          blocks_per_dispatch, compute_dtype, kernel_impl, rng_batch,
          geom_stride, output_overlap,
          checkpoint_keep, checkpoint_async, preempt_grace,
          pod_obs, pod_straggler_factor, phase_obs,
          supervise, obs_port, obs_bind, chaos, chaos_seed):
    """PV simulation + meter join -> CSV (reference pvsim.py:103-121)."""
    _setup_logging(verbose)
    _maybe_supervise("pvsim", supervise,
                     grace_s=preempt_grace if preempt_grace > 0 else None)
    _activate_chaos(chaos, chaos_seed)
    if (site_grid_spec or sites_csv) and backend != "jax":
        raise click.UsageError("--site-grid/--sites-csv require "
                               "--backend=jax")
    if site_grid_spec and sites_csv:
        raise click.UsageError("--site-grid and --sites-csv are mutually "
                               "exclusive")
    if (fleet_csv or fleet_synth is not None) and backend != "jax":
        raise click.UsageError("--fleet-csv/--fleet-synth require "
                               "--backend=jax")
    if fleet_csv and fleet_synth is not None:
        raise click.UsageError("--fleet-csv and --fleet-synth are mutually "
                               "exclusive")
    if (fleet_csv or fleet_synth is not None) and \
            (site_grid_spec or sites_csv):
        raise click.UsageError("--fleet-csv/--fleet-synth carry their own "
                               "geometry and are mutually exclusive with "
                               "--site-grid/--sites-csv")
    if fleet_synth is not None and fleet_synth < 1:
        raise click.UsageError("--fleet-synth must be >= 1")
    if profile_dir and backend != "jax":
        raise click.UsageError("--profile requires --backend=jax")
    if output != "trace" and backend != "jax":
        raise click.UsageError(f"--output={output} requires --backend=jax")
    if prng_impl != "threefry2x32" and backend != "jax":
        raise click.UsageError("--prng-impl requires --backend=jax")
    if block_impl != "auto" and backend != "jax":
        raise click.UsageError("--block-impl requires --backend=jax")
    if tune != "off" and backend != "jax":
        raise click.UsageError("--tune requires --backend=jax")
    if (telemetry != "off" or telemetry_strict) and backend != "jax":
        raise click.UsageError("--telemetry requires --backend=jax")
    if analytics != "off" and backend != "jax":
        raise click.UsageError("--analytics requires --backend=jax")
    if compile_cache is not None and backend != "jax":
        raise click.UsageError("--compile-cache requires --backend=jax")
    if mesh_scenario != 0 and backend != "jax":
        raise click.UsageError("--mesh-scenario requires --backend=jax")
    if mesh_scenario < 0:
        raise click.UsageError("--mesh-scenario must be >= 0")
    if mesh_scenario != 0 and not sharded:
        raise click.UsageError("--mesh-scenario requires --sharded")
    if (coordinator or num_processes is not None
            or process_id is not None) and backend != "jax":
        raise click.UsageError("--coordinator/--num-processes/--process-id "
                               "require --backend=jax")
    if blocks_per_dispatch != 0 and backend != "jax":
        raise click.UsageError("--blocks-per-dispatch requires "
                               "--backend=jax")
    if compute_dtype != "auto" and backend != "jax":
        raise click.UsageError("--compute-dtype requires --backend=jax")
    if kernel_impl != "auto" and backend != "jax":
        raise click.UsageError("--kernel-impl requires --backend=jax")
    if rng_batch != "auto" and backend != "jax":
        raise click.UsageError("--rng-batch requires --backend=jax")
    if geom_stride != "0" and backend != "jax":
        raise click.UsageError("--geom-stride requires --backend=jax")
    if output_overlap != "auto" and backend != "jax":
        raise click.UsageError("--output-overlap requires --backend=jax")
    if checkpoint_keep != 3 and backend != "jax":
        raise click.UsageError("--checkpoint-keep requires --backend=jax")
    if checkpoint_async != "off" and backend != "jax":
        raise click.UsageError("--checkpoint-async requires --backend=jax")
    if preempt_grace != 0.0 and backend != "jax":
        raise click.UsageError("--preempt-grace requires --backend=jax")
    if pod_obs != "off" and backend != "jax":
        raise click.UsageError("--pod-obs requires --backend=jax")
    if phase_obs != "off" and backend != "jax":
        raise click.UsageError("--phase-obs requires --backend=jax")
    if pod_straggler_factor <= 0:
        raise click.UsageError("--pod-straggler-factor must be > 0")
    if checkpoint_keep < 1:
        raise click.UsageError("--checkpoint-keep must be >= 1")
    if preempt_grace < 0:
        raise click.UsageError("--preempt-grace must be >= 0")
    if backend == "jax":
        from tmhpvsim_tpu.apps.pvsim import pvsim_jax

        if duration_s is None:
            raise click.UsageError("--duration is required with --backend=jax")
        if sites_csv:
            from tmhpvsim_tpu.config import SiteGrid

            try:
                site_grid = SiteGrid.from_csv(sites_csv)
            except ValueError as e:
                raise click.UsageError(str(e)) from e
        else:
            site_grid = _parse_site_grid(site_grid_spec)
        fleet = None
        if fleet_csv or fleet_synth is not None:
            from tmhpvsim_tpu.fleet import FleetParams

            try:
                fleet = (FleetParams.from_csv(fleet_csv) if fleet_csv
                         else FleetParams.synthetic(fleet_synth,
                                                    seed=fleet_seed))
            except ValueError as e:
                raise click.UsageError(str(e)) from e
        if seed is None:
            from tmhpvsim_tpu.engine import checkpoint as _ckpt

            if checkpoint and _ckpt.resumable(checkpoint):
                # resuming without --seed: adopt the checkpoint's seed (a
                # fresh random one would fail the config echo check);
                # resumable() also sees rotated generations and per-host
                # shards where a bare os.path.exists would miss
                seed = _ckpt.peek_meta(checkpoint).get(
                    "config", {}).get("seed")
            if seed is None:
                # honour the advertised nondeterministic default ('seed or
                # 0' would collapse every unseeded run onto seed 0)
                import secrets

                seed = secrets.randbits(31)
        pvsim_jax(file, duration_s, n_chains, seed, start, chain,
                  sharded, checkpoint=checkpoint, block_s=block_s,
                  realtime=realtime,
                  mesh_scenario=mesh_scenario,
                  coordinator=coordinator,
                  num_processes=num_processes,
                  process_id=process_id,
                  site_grid=site_grid, fleet=fleet,
                  profile_dir=profile_dir,
                  output=output, prng_impl=prng_impl,
                  block_impl=block_impl, tune=tune,
                  telemetry=telemetry,
                  telemetry_strict=telemetry_strict,
                  analytics=analytics,
                  metrics_path=metrics_path,
                  run_report_path=run_report_path,
                  trace=trace, compile_cache=compile_cache,
                  blocks_per_dispatch=blocks_per_dispatch,
                  compute_dtype=compute_dtype, kernel_impl=kernel_impl,
                  rng_batch=rng_batch, geom_stride=int(geom_stride),
                  output_overlap=output_overlap,
                  checkpoint_keep=checkpoint_keep,
                  checkpoint_async=checkpoint_async,
                  preempt_grace_s=preempt_grace,
                  pod_obs=pod_obs,
                  pod_straggler_factor=pod_straggler_factor,
                  phase_obs=phase_obs,
                  obs_port=obs_port, obs_bind=obs_bind)
        return

    from tmhpvsim_tpu.apps.pvsim import pvsim_main

    asyncrun(pvsim_main(file, amqp_url, exchange, realtime, seed, duration_s,
                        _parse_start(start), trace=trace,
                        metrics_path=metrics_path,
                        run_report_path=run_report_path,
                        obs_port=obs_port, obs_bind=obs_bind))


@click.command()
@click.option(
    "--amqp-url", default=lambda: os.environ.get("AMQP_URL"),
    help="broker URL the server listens on: amqp://... (RabbitMQ), "
         "tcp://HOST:PORT (the in-tree fanoutbroker command), or "
         "local://NAME (in-process; the default, 'local://default')")
@click.option("--exchange",
              default=lambda: os.environ.get("TMHPVSIM_SCENARIO_EXCHANGE",
                                             "scenario"),
              show_default="scenario",
              help="request exchange; replies go to each request's own "
                   "reply_to exchange")
@click.option("-v", "--verbose", count=True,
              help="Increase logging level from default WARN")
@click.option("--seed", type=int, default=0, show_default=True,
              help="PRNG seed of the served simulation")
@click.option("--duration", "duration_s", type=int, default=86_400,
              show_default=True,
              help="maximum scenario horizon in simulated seconds (the "
                   "base simulation the server answers from)")
@click.option("--start", default=None,
              help="Simulation start time 'YYYY-MM-DD HH:MM:SS'")
@click.option("--chains", "n_chains", type=int, default=1024,
              show_default=True,
              help="stochastic chains per scenario evaluation")
@click.option("--block-s", type=int, default=None,
              help="Seconds per device block, multiple of 60 "
                   "(default: min(8640, duration))")
@click.option("--block-impl",
              type=click.Choice(["auto", "wide", "scan", "scan2"]),
              default="auto",
              help="block formulation (config.SimConfig.block_impl)")
@click.option("--tune", type=click.Choice(["off", "auto", "force"]),
              default="off",
              help="runtime autotuner for the served plan "
                   "(config.SimConfig.tune)")
@click.option("--mesh-scenario", "mesh_scenario", type=int, default=0,
              metavar="M", show_default=True,
              help="width of the scenario axis of a 2-D (chains, "
                   "scenario) device mesh: the vmapped request batch "
                   "shards over M scenario shards while chains shard "
                   "over the rest; batch buckets round UP to multiples "
                   "of M (padding rows are bit-inert).  0 = unsharded "
                   "serving (the default)")
@click.option("--window-ms", type=float, default=10.0, show_default=True,
              help="micro-batch coalescing window: the first pending "
                   "request waits at most this long for company before "
                   "the fused dispatch")
@click.option("--max-batch", type=int, default=16, show_default=True,
              help="most requests per fused dispatch")
@click.option("--batch-sizes", default=None, metavar="B1,B2,...",
              help="explicit batch buckets (each is one compiled dispatch "
                   "shape, AOT-warmed at startup); default: powers of two "
                   "up to --max-batch")
@click.option("--queue-limit", type=int, default=1024, show_default=True,
              help="pending requests beyond this are rejected with a "
                   "typed 'busy' reply")
@click.option("--timeout-s", type=float, default=60.0, show_default=True,
              help="per-request wall clock before a typed 'timeout' reply")
@click.option("--drain-timeout", "drain_timeout_s", type=float,
              default=30.0, show_default=True,
              help="shutdown drain budget: past this deadline queued "
                   "requests get typed 'draining' rejections instead of "
                   "holding shutdown on a stuck dispatch")
@click.option("--supervise", "supervise", type=int, default=0,
              metavar="N",
              help="Run as a supervised child and warm-restart it on a "
                   "crash up to N times; the AOT-warmed compile cache "
                   "makes the restarted server compile nothing fresh "
                   "(runtime/supervise.py)")
@click.option("--fleet", "fleet_n", type=int, default=0, metavar="N",
              show_default="0 (single worker)",
              help="serve with a fleet of N replicated warm workers "
                   "behind a shard-affinity router (consistent hashing "
                   "on site_index/cohort, least-loaded fallback, "
                   "supervised warm respawn; serve/fleet.py).  0 keeps "
                   "the single-worker server byte-identical to "
                   "previous releases")
@click.option("--batching", type=click.Choice(["window", "continuous"]),
              default=None,
              help="dispatch scheduler: 'window' retires every row of "
                   "a fused batch together; 'continuous' backfills "
                   "freed slots from the queue each block so short "
                   "requests never wait out long ones (default: window "
                   "single-worker, continuous with --fleet)")
@click.option("--quota-rate", type=float, default=None, metavar="R",
              help="per-tenant admission quota in requests/s (token "
                   "bucket at the router; requires --fleet).  Over-"
                   "quota requests get typed 'busy' with retry_after_ms")
@click.option("--quota-burst", type=float, default=None, metavar="B",
              help="token-bucket burst size of --quota-rate "
                   "(default: R)")
@click.option("--trace", "trace", default=None,
              help="Record the serving event timeline and export "
                   "Chrome-trace JSON here on exit; crashes dump the "
                   "last 30 s to PATH.crash.json (obs/trace.py)")
@click.option("--metrics", "metrics_path", default=None,
              help="Stream metric snapshots to this file (.prom = "
                   "Prometheus text exposition, else JSONL append)")
@click.option("--run-report", "run_report_path", default=None,
              help="Write the RunReport JSON (with the 'serving' SLO "
                   "section) here on shutdown")
@click.option("--compile-cache", "compile_cache", default=None,
              metavar="DIR",
              help="Persistent XLA compilation-cache base directory; the "
                   "scenario dispatch for every batch bucket is AOT-warmed "
                   "into it at startup, so a warm restart compiles "
                   "nothing fresh.  Unset: $TMHPVSIM_COMPILE_CACHE, else "
                   "~/.cache/tmhpvsim_tpu/xla; 'off' disables "
                   "(engine/compilecache.py)")
@_obs_port_option
@_chaos_options
def serve(amqp_url, exchange, verbose, seed, duration_s, start, n_chains,
          block_s, block_impl, tune, mesh_scenario, window_ms, max_batch,
          batch_sizes, queue_limit, timeout_s, drain_timeout_s, supervise,
          fleet_n, batching, quota_rate, quota_burst, trace, metrics_path,
          run_report_path, compile_cache, obs_port, obs_bind, chaos,
          chaos_seed):
    """Long-lived scenario server: a warm simulation answering "what-if"
    queries over the broker (serve/).  Each request perturbs bounded
    scenario knobs (demand scale/shift, DC-capacity scale, weather
    bias, curtailment cap, horizon); concurrent requests within the
    window coalesce into ONE fused device dispatch.  SIGINT/SIGTERM
    drain in-flight requests and reject new ones with a typed error."""
    from tmhpvsim_tpu.config import SimConfig
    from tmhpvsim_tpu.serve.server import ServeConfig, serve_main

    _setup_logging(verbose)
    _maybe_supervise("serve", supervise)
    _activate_chaos(chaos, chaos_seed)
    if mesh_scenario < 0:
        raise click.UsageError("--mesh-scenario must be >= 0")
    if fleet_n < 0:
        raise click.UsageError("--fleet must be >= 0")
    if (quota_rate is not None or quota_burst is not None) and not fleet_n:
        raise click.UsageError("--quota-rate/--quota-burst need --fleet "
                               "(quotas live at the router)")
    sim_kw = dict(duration_s=duration_s, n_chains=n_chains, seed=seed,
                  output="reduce", block_impl=block_impl, tune=tune,
                  mesh_scenario=mesh_scenario)
    if start:
        sim_kw["start"] = start
    sim_kw["block_s"] = block_s if block_s else min(8640, duration_s)
    try:
        buckets = tuple(int(b) for b in batch_sizes.split(",")) \
            if batch_sizes else ()
    except ValueError as e:
        raise click.UsageError(
            f"bad --batch-sizes {batch_sizes!r} (want B1,B2,...)") from e
    cfg = ServeConfig(
        sim=SimConfig(**sim_kw),
        url=amqp_url or "local://default", exchange=exchange,
        window_s=window_ms / 1e3, max_batch=max_batch,
        batch_sizes=buckets, queue_limit=queue_limit,
        timeout_s=timeout_s, drain_timeout_s=drain_timeout_s,
        batching=batching or "window")
    if fleet_n:
        from tmhpvsim_tpu.serve.fleet import (FleetConfig,
                                              serve_fleet_main)

        fcfg = FleetConfig(
            base=cfg, n_workers=fleet_n,
            batching=batching or "continuous",
            quota_rate=quota_rate, quota_burst=quota_burst,
            inflight_limit=queue_limit, auto_respawn=True)
        asyncrun(serve_fleet_main(
            fcfg, compile_cache=compile_cache, trace=trace,
            metrics_path=metrics_path,
            run_report_path=run_report_path,
            obs_port=obs_port, obs_bind=obs_bind))
        return
    asyncrun(serve_main(cfg, compile_cache=compile_cache, trace=trace,
                        metrics_path=metrics_path,
                        run_report_path=run_report_path,
                        obs_port=obs_port, obs_bind=obs_bind))


@click.group()
def main():
    """tmhpvsim-tpu: TPU-native PV simulation & streaming."""


main.add_command(metersim)
main.add_command(pvsim)
main.add_command(fanoutbroker)
main.add_command(serve)


if __name__ == "__main__":
    main()
