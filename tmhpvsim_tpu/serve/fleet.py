"""In-process serving fleet: N replicated warm workers behind the
shard-affinity router.

:class:`ServeFleet` stands up ``n_workers`` full
:class:`~tmhpvsim_tpu.serve.server.ScenarioServer` replicas — each a
warm ``Simulation`` with its own metrics registry and its own request
exchange ``{exchange}.w{i}`` — plus one
:class:`~tmhpvsim_tpu.serve.router.ScenarioRouter` facing the clients'
exchange, all over the same broker url.  Workers default to
**continuous batching** (the fleet exists for throughput; the window
scheduler remains available via ``FleetConfig.batching``).

Warmth is the tfp.mcmc "compile once, sample forever" discipline at
fleet scale: under a populated persistent compile cache
(engine/compilecache.py) every replica AFTER the first deserialises its
executables — ``executor.compile_cold_total == 0`` — so standing up or
respawning a worker costs cache loads, not compiles.  The chaos
acceptance test pins this for a replacement worker.

Supervision rides :func:`~tmhpvsim_tpu.runtime.supervise
.supervise_service` (the in-process analogue of ``--supervise``'s
subprocess loop, same decorrelated backoff): with ``auto_respawn`` on,
a worker whose :meth:`~tmhpvsim_tpu.serve.server.ScenarioServer.kill`
fires (the chaos SIGKILL stand-in) is respawned warm, and the restart
count lands on ``resilience.supervised_restarts.{name}`` in the fleet
registry — the v16 ``serving.fleet`` per-worker ``restarts`` column.

Metrics: the router's ``router.*`` family lives on the fleet registry;
each worker life keeps its own registry, and :meth:`worker_snapshot`
sums counters across a worker's lives (a killed life's counts must not
vanish from the partition invariant the report tools check).
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import logging
from typing import List, Optional, Tuple

from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs.trace import Tracer
from tmhpvsim_tpu.runtime.supervise import supervise_service
from tmhpvsim_tpu.serve.router import ScenarioRouter, WorkerHandle
from tmhpvsim_tpu.serve.server import ScenarioServer, ServeConfig

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class FleetConfig:
    """One fleet: the per-worker template + the tier knobs."""

    #: per-worker template; ``base.exchange`` is the CLIENT-facing
    #: exchange the router subscribes (workers get ``.w{i}`` suffixes)
    base: ServeConfig
    n_workers: int = 2
    #: worker scheduler — the fleet defaults to continuous batching
    batching: str = "continuous"
    #: per-tenant token-bucket quota (requests/s; None = no quotas)
    quota_rate: Optional[float] = None
    quota_burst: Optional[float] = None
    #: whole-router queue-depth shed threshold
    inflight_limit: int = 1024
    #: failover re-routes allowed per request
    reroute_cap: int = 1
    health_period_s: float = 0.1
    #: supervised warm respawns per worker (``auto_respawn``)
    max_restarts: int = 3
    auto_respawn: bool = False


class FleetWorker:
    """One worker slot: the current server life + its past lives'
    counter snapshots (summed into :meth:`snapshot`)."""

    def __init__(self, index: int, name: str, exchange: str):
        self.index = index
        self.name = name
        self.exchange = exchange
        self.server: Optional[ScenarioServer] = None
        self.registry: Optional[obs_metrics.MetricsRegistry] = None
        self.lives = 0
        self._dead_counters: List[dict] = []

    def ready(self) -> tuple:
        if self.server is None:
            return False, {"spawned": False}
        return self.server.readiness()

    def retire_life(self) -> None:
        if self.registry is not None:
            self._dead_counters.append(
                self.registry.snapshot().get("counters", {}))

    def snapshot(self) -> dict:
        """Current life's snapshot with counters summed across ALL
        lives — a killed life's requests stay in the partition."""
        snap = (self.registry.snapshot() if self.registry is not None
                else {"counters": {}, "gauges": {}, "histograms": {}})
        if self._dead_counters:
            counters = dict(snap.get("counters", {}))
            for dead in self._dead_counters:
                for k, v in dead.items():
                    counters[k] = counters.get(k, 0) + v
            snap = {**snap, "counters": counters}
        return snap


class ServeFleet:
    """See module docstring."""

    def __init__(self, cfg: FleetConfig, *, registry=None,
                 tracer: Optional[Tracer] = None):
        if cfg.n_workers < 1:
            raise ValueError(f"n_workers {cfg.n_workers} must be >= 1")
        self.cfg = cfg
        self.registry = registry or obs_metrics.get_registry()
        self.tracer = tracer
        self.workers = [
            FleetWorker(i, f"w{i}", f"{cfg.base.exchange}.w{i}")
            for i in range(cfg.n_workers)]
        self.router: Optional[ScenarioRouter] = None
        self._supervisors: List[asyncio.Task] = []
        self._stopping = False

    def worker_config(self, i: int) -> ServeConfig:
        return dataclasses.replace(
            self.cfg.base, exchange=self.workers[i].exchange,
            batching=self.cfg.batching)

    async def _spawn(self, i: int) -> None:
        w = self.workers[i]
        w.retire_life()
        reg = obs_metrics.MetricsRegistry()
        server = ScenarioServer(self.worker_config(i), registry=reg,
                                tracer=self.tracer)
        await server.start()
        w.server, w.registry = server, reg
        w.lives += 1
        logger.info("fleet worker %s up (life %d) on exchange %r",
                    w.name, w.lives, w.exchange)

    async def start(self) -> None:
        for i in range(self.cfg.n_workers):
            await self._spawn(i)
        handles = [WorkerHandle(w.name, w.exchange, w.ready)
                   for w in self.workers]
        self.router = ScenarioRouter(
            self.cfg.base.url, self.cfg.base.exchange, handles,
            registry=self.registry, tracer=self.tracer,
            quota_rate=self.cfg.quota_rate,
            quota_burst=self.cfg.quota_burst,
            inflight_limit=self.cfg.inflight_limit,
            request_timeout_s=self.cfg.base.timeout_s,
            health_period_s=self.cfg.health_period_s,
            reroute_cap=self.cfg.reroute_cap)
        await self.router.start()
        if self.cfg.auto_respawn:
            self._supervisors = [
                asyncio.create_task(supervise_service(
                    self._worker_run(i),
                    max_restarts=self.cfg.max_restarts,
                    name=self.workers[i].name,
                    registry=self.registry))
                for i in range(self.cfg.n_workers)]

    def _worker_run(self, i: int):
        async def run(attempt: int) -> None:
            w = self.workers[i]
            if w.server is None or w.server._stopped:
                await self._spawn(i)  # warm respawn: zero cold compiles
            await w.server.died.wait()
            if self._stopping:
                return
            raise RuntimeError(f"fleet worker {w.name} died")

        return run

    def readiness(self) -> tuple:
        """Fleet ``/readyz``: the router's (ready iff >= 1 worker is)."""
        if self.router is None:
            return False, {"router": "not started"}
        return self.router.readiness()

    async def kill_worker(self, i: int) -> None:
        """Chaos: simulated SIGKILL of worker ``i`` (no drain, no
        farewell replies; the router health loop sheds and re-routes)."""
        w = self.workers[i]
        if w.server is not None:
            logger.warning("fleet: killing worker %s", w.name)
            await w.server.kill()

    async def respawn_worker(self, i: int) -> None:
        """Manual warm respawn (``auto_respawn`` does this itself)."""
        await self._spawn(i)

    async def stop(self, drain_timeout_s: Optional[float] = None)\
            -> None:
        self._stopping = True
        # wake supervisors so they exit their died.wait() cleanly
        for w in self.workers:
            if w.server is not None:
                w.server.died.set()
        for t in self._supervisors:
            t.cancel()
        if self._supervisors:
            await asyncio.wait(self._supervisors, timeout=2.0)
        self._supervisors = []
        if self.router is not None:
            await self.router.stop(
                drain_timeout_s if drain_timeout_s is not None
                else self.cfg.base.drain_timeout_s)
        for w in self.workers:
            if w.server is not None:
                with contextlib.suppress(Exception):
                    await w.server.stop()

    # ------------------------------------------------------------------
    # reporting
    # ------------------------------------------------------------------

    def worker_snapshots(self) -> List[Tuple[str, dict]]:
        return [(w.name, w.snapshot()) for w in self.workers]

    def fleet_doc(self) -> Optional[dict]:
        """The v16 ``serving.fleet`` sub-doc for this fleet's run."""
        from tmhpvsim_tpu.obs.report import fleet_serving_section

        return fleet_serving_section(self.registry.snapshot(),
                                     self.worker_snapshots())

    def attach_report(self, rep) -> None:
        rep.attach_fleet_serving(self.registry.snapshot(),
                                 self.worker_snapshots())


async def serve_fleet_main(cfg: FleetConfig, *,
                           compile_cache: Optional[str] = None,
                           trace: Optional[str] = None,
                           metrics_path: Optional[str] = None,
                           run_report_path: Optional[str] = None,
                           obs_port: Optional[int] = None,
                           obs_bind: str = "127.0.0.1",
                           install_signals: bool = True) -> None:
    """App orchestrator behind ``pvsim serve --fleet N``: the fleet
    analogue of :func:`~tmhpvsim_tpu.serve.server.serve_main`.  One
    metrics registry carries the router + supervisor families (each
    worker life keeps its own, merged into the v16 run report);
    ``/readyz`` is the ROUTER's readiness — the fleet serves while at
    least one worker is up."""
    import signal

    from tmhpvsim_tpu.engine import compilecache as cc
    from tmhpvsim_tpu.obs import trace as obs_trace
    from tmhpvsim_tpu.obs.live import maybe_obs_server

    registry = obs_metrics.MetricsRegistry()
    sink = None
    if metrics_path:
        sink = obs_metrics.make_sink(metrics_path)
        registry.add_sink(sink)
    tracer = Tracer() if trace else None
    fleet = ServeFleet(cfg, registry=registry, tracer=tracer)
    if obs_port is not None:
        obs_trace.enable_propagation(True)
    stop = asyncio.Event()
    async with maybe_obs_server(obs_port, host=obs_bind,
                                registry=registry, tracer=tracer,
                                ready=fleet.readiness):
        with obs_metrics.use_registry(registry):
            if compile_cache is not None:
                cc.configure(compile_cache)
            if install_signals:
                loop = asyncio.get_running_loop()
                for sig in (signal.SIGINT, signal.SIGTERM):
                    with contextlib.suppress(NotImplementedError):
                        loop.add_signal_handler(sig, stop.set)
            try:
                await fleet.start()
                await stop.wait()
            except asyncio.CancelledError:
                raise
            except BaseException:
                if tracer:
                    with contextlib.suppress(Exception):
                        tracer.dump_flight(trace + ".crash.json")
                raise
            finally:
                with contextlib.suppress(Exception):
                    await fleet.stop()
                if tracer:
                    with contextlib.suppress(Exception):
                        tracer.export(trace, process_name="pvsim-fleet")
                if run_report_path:
                    try:
                        from tmhpvsim_tpu.obs.report import RunReport

                        w0 = fleet.workers[0].server
                        rep = RunReport(
                            "pvsim.serve-fleet",
                            config=(w0.engine.sim.config
                                    if w0 and w0.engine else cfg.base.sim),
                            plan=(w0.engine.sim.plan
                                  if w0 and w0.engine else None))
                        rep.attach_metrics(registry)
                        fleet.attach_report(rep)
                        rep.write(run_report_path)
                    except Exception as err:
                        logger.warning("run report write failed: %s", err)
                if sink is not None:
                    registry.flush(event="end")
                    registry.remove_sink(sink)
                    with contextlib.suppress(Exception):
                        sink.close()
