"""Shard-affinity request router: the front of the serving fleet.

One :class:`ScenarioRouter` faces the clients' request exchange and
spreads traffic across N replicated warm workers, each a full
:class:`~tmhpvsim_tpu.serve.server.ScenarioServer` on its own request
exchange over the SAME broker url (local://, tcp:// or amqp://ws).

Routing.  Requests carrying a site selector (``site_index`` /
``cohort``, PR 13) route by **consistent hashing** on the selector key:
the ring (``vnodes`` virtual nodes per worker, stable md5 hashes) keeps
a selector pinned to the same worker across requests — so each worker's
per-selector device work and its duplicate-id replay LRU stay hot — and
moves only ~1/N of the keyspace when the fleet changes.  Shardless
requests fall back to **least-loaded** among ready workers.

Health.  Each worker's ``ready`` callable (wired to its ``/readyz``
readiness — warm AND not draining AND breaker closed, obs/live.py) is
polled every ``health_period_s``; a worker that stops answering ready
is taken out of rotation, and its in-flight requests are re-routed to
the next ring preference (once per request: ``reroute_cap``).  The
router stamps the chosen worker into the forwarded ``Message.meta``
(``"worker"``) and echoes it on the reply, so a stitched trace reads
client -> route -> admit -> dispatch -> reply with the worker named.

Exactly-once replies.  The router rewrites ``reply_to`` to its own
reply exchange and forwards each worker reply to the client's original
exchange at most once (an answered-id LRU): a failover re-route that
makes two workers answer the same id yields ONE client reply, and a
replayed id that was already answered or is still in flight is rejected
``duplicate`` at the router — it never reaches a second worker, so a
replay can never double-execute (the satellite pin).

Admission control.  Layered ahead of routing: per-tenant token-bucket
quotas (``quota_rate``/``quota_burst``; requests carry an optional
``tenant`` meta field) and whole-router queue-depth shedding
(``inflight_limit``).  Both reject with typed ``busy`` carrying a
``retry_after_ms`` hint derived from the quota refill time or the
router's observed reply latency x queue depth — the client's
``ResiliencePolicy`` backs off by the router's arithmetic, not jitter.

Metrics (``router.*``): requests/routed/replies/rejected/rerouted/
dup_replies counters, pending + ready-worker gauges, per-worker
``router.inflight.{name}`` gauges and a reply-latency histogram — the
RunReport v16 ``serving.fleet`` section reads them.
"""

from __future__ import annotations

import asyncio
import bisect
import contextlib
import dataclasses
import datetime as _dt
import hashlib
import inspect
import logging
import time
import uuid
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs import trace as obs_trace
from tmhpvsim_tpu.obs.trace import Tracer
from tmhpvsim_tpu.runtime.broker import make_transport
from tmhpvsim_tpu.runtime.resilience import (ResiliencePolicy, forever)
from tmhpvsim_tpu.serve import schema

logger = logging.getLogger(__name__)

#: virtual nodes per worker on the hash ring — enough that removing one
#: worker of four moves ~25% of keys, not a contiguous arc
VNODES = 64

#: tenants remembered by the quota LRU (an abusive tenant cardinality
#: must not grow router memory)
TENANTS_CAP = 1024

#: answered request ids remembered for exactly-once forwarding (LRU)
ANSWERED_CAP = 4096

MAX_RETRY_AFTER_MS = 60_000


def _stable_hash(key: str) -> int:
    return int.from_bytes(
        hashlib.md5(key.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over worker names (stable md5, ``vnodes``
    virtual nodes each).  ``preference(key)`` walks the ring from the
    key's position and returns every worker once, in ring order — the
    failover order for that key."""

    def __init__(self, names: Sequence[str], vnodes: int = VNODES):
        self._names = list(names)
        self._ring: List[Tuple[int, str]] = sorted(
            (_stable_hash(f"{name}#{v}"), name)
            for name in self._names for v in range(vnodes))
        self._hashes = [h for h, _ in self._ring]

    def preference(self, key: str) -> List[str]:
        if not self._ring:
            return []
        out: List[str] = []
        seen = set()
        i = bisect.bisect(self._hashes, _stable_hash(key))
        for k in range(len(self._ring)):
            name = self._ring[(i + k) % len(self._ring)][1]
            if name not in seen:
                seen.add(name)
                out.append(name)
                if len(out) == len(self._names):
                    break
        return out


class TokenBucket:
    """Per-tenant admission quota: ``burst`` tokens refilled at
    ``rate``/s.  ``now`` injectable for tests."""

    def __init__(self, rate: float, burst: float,
                 now=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._now = now
        self._last = now()

    def _refill(self) -> None:
        t = self._now()
        self._tokens = min(self.burst,
                           self._tokens + (t - self._last) * self.rate)
        self._last = t

    def take(self) -> bool:
        self._refill()
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def retry_after_s(self) -> float:
        """Seconds until one token is available (0 when one already is)."""
        self._refill()
        if self._tokens >= 1.0:
            return 0.0
        return (1.0 - self._tokens) / self.rate if self.rate > 0 \
            else float("inf")


@dataclasses.dataclass
class WorkerHandle:
    """One routed worker: its request exchange and its readiness
    callable (sync or async ``() -> (ok, detail)`` — a wired
    ``ScenarioServer.readiness`` in-process, or an HTTP ``/readyz``
    probe for a subprocess worker)."""

    name: str
    exchange: str
    ready: Callable


@dataclasses.dataclass
class _Pending:
    """One in-flight routed request."""

    meta: dict          # the forwarded request meta (for re-route)
    reply_to: str       # the client's original reply exchange
    worker: str         # currently assigned worker name
    key: Optional[str]  # routing key (None = least-loaded fallback)
    t0: float           # monotonic at admit
    reroutes: int = 0


class ScenarioRouter:
    """See module docstring."""

    def __init__(self, url: str, exchange: str,
                 workers: Sequence[WorkerHandle], *,
                 registry=None, tracer: Optional[Tracer] = None,
                 quota_rate: Optional[float] = None,
                 quota_burst: Optional[float] = None,
                 inflight_limit: int = 1024,
                 request_timeout_s: float = 60.0,
                 health_period_s: float = 0.25,
                 reroute_cap: int = 1,
                 answered_cap: int = ANSWERED_CAP,
                 reply_exchange: Optional[str] = None):
        if not workers:
            raise ValueError("router needs at least one worker")
        names = [w.name for w in workers]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate worker names: {names}")
        self._url = url
        self._exchange = exchange
        self.workers: Dict[str, WorkerHandle] = {
            w.name: w for w in workers}
        self.reply_exchange = reply_exchange or \
            f"{exchange}.router.{uuid.uuid4().hex[:12]}"
        self._ring = HashRing(names)
        self._quota_rate = quota_rate
        self._quota_burst = (quota_burst if quota_burst is not None
                             else (quota_rate or 0.0))
        self._buckets: OrderedDict = OrderedDict()
        self._inflight_limit = int(inflight_limit)
        self._request_timeout_s = float(request_timeout_s)
        self._health_period_s = float(health_period_s)
        self._reroute_cap = int(reroute_cap)
        self._answered_cap = int(answered_cap)
        self._pending: Dict[str, _Pending] = {}
        self._answered: OrderedDict = OrderedDict()
        self._ready: set = set()
        self._inflight: Dict[str, int] = {n: 0 for n in names}
        self._worker_tx: Dict[str, object] = {}
        self._client_tx: Dict[str, object] = {}
        self._req_tx = None
        self._rep_tx = None
        self._tasks: List[asyncio.Task] = []
        self._send_tasks: set = set()
        self._draining = False
        self._stopped = False
        self._ewma_reply_s: Optional[float] = None
        self.tracer = tracer
        reg = registry or obs_metrics.get_registry()
        self.registry = reg
        self._c_requests = reg.counter("router.requests_total")
        self._c_routed = reg.counter("router.routed_total")
        self._c_replies = reg.counter("router.replies_total")
        self._c_rejected = reg.counter("router.rejected_total")
        self._c_quota = reg.counter("router.quota_rejected_total")
        self._c_shed = reg.counter("router.shed_total")
        self._c_rerouted = reg.counter("router.rerouted_total")
        self._c_dup_replies = reg.counter("router.dup_replies_total")
        self._c_timeouts = reg.counter("router.timeouts_total")
        self._c_down = reg.counter("router.worker_down_total")
        self._g_pending = reg.gauge("router.pending")
        self._g_ready = reg.gauge("router.workers_ready")
        self._h_reply = reg.histogram("router.reply_latency_s")
        self._g_worker = {n: reg.gauge(f"router.inflight.{n}")
                          for n in names}
        self._consume_policy = ResiliencePolicy(
            attempts=forever, base_delay_s=0.1, max_delay_s=2.0,
            name="router.consume", registry=reg)
        self._reply_consume_policy = ResiliencePolicy(
            attempts=forever, base_delay_s=0.1, max_delay_s=2.0,
            name="router.reply_consume", registry=reg)
        self._publish_policy = ResiliencePolicy(
            attempts=3, base_delay_s=0.05, max_delay_s=0.5,
            name="router.publish", registry=reg)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def readiness(self) -> tuple:
        """``(ok, detail)`` for the fleet's ``/readyz``: ready iff at
        least one worker is."""
        ok = bool(self._ready) and not self._draining
        return ok, {"workers_ready": sorted(self._ready),
                    "workers": sorted(self.workers),
                    "draining": self._draining,
                    "pending": len(self._pending)}

    async def start(self) -> None:
        # seed the ready set synchronously so the first routed request
        # does not race the first health tick
        await self._health_tick()
        self._req_tx = make_transport(self._url, self._exchange)
        await self._req_tx.__aenter__()
        self._rep_tx = make_transport(self._url, self.reply_exchange)
        await self._rep_tx.__aenter__()
        for name, w in self.workers.items():
            tx = make_transport(self._url, w.exchange)
            await tx.__aenter__()
            self._worker_tx[name] = tx
        self._tasks = [
            asyncio.create_task(self._consume_requests()),
            asyncio.create_task(self._consume_replies()),
            asyncio.create_task(self._health_loop()),
        ]
        if self.tracer:
            self.tracer.instant("router.start", "serve",
                                workers=sorted(self.workers))
        logger.info(
            "scenario router on %s exchange %r -> %d worker(s) %s",
            self._url, self._exchange, len(self.workers),
            sorted(self.workers))

    def begin_drain(self) -> None:
        self._draining = True

    async def stop(self, drain_timeout_s: float = 30.0) -> None:
        """Drain: stop admitting, give in-flight requests up to the
        deadline to come back, then close."""
        if self._stopped:
            return
        self._stopped = True
        self._draining = True
        deadline = time.monotonic() + drain_timeout_s
        while self._pending and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        for t in self._tasks:
            t.cancel()
        if self._tasks:
            await asyncio.wait(self._tasks, timeout=1.0)
        self._tasks = []
        if self._send_tasks:
            await asyncio.wait(list(self._send_tasks), timeout=1.0)
        for tx in [self._req_tx, self._rep_tx,
                   *self._worker_tx.values(),
                   *self._client_tx.values()]:
            if tx is not None:
                with contextlib.suppress(Exception):
                    await tx.__aexit__(None, None, None)
        self._worker_tx.clear()
        self._client_tx.clear()
        self._req_tx = self._rep_tx = None
        if self.tracer:
            self.tracer.instant("router.stop", "serve")

    # ------------------------------------------------------------------
    # health
    # ------------------------------------------------------------------

    async def _check_ready(self, w: WorkerHandle) -> bool:
        try:
            r = w.ready()
            if inspect.isawaitable(r):
                r = await r
            ok = bool(r[0]) if isinstance(r, tuple) else bool(r)
        except Exception:
            ok = False
        return ok

    async def _health_tick(self) -> None:
        ready = set()
        for name, w in self.workers.items():
            if await self._check_ready(w):
                ready.add(name)
        went_down = self._ready - ready
        self._ready = ready
        self._g_ready.set(len(ready))
        for name in went_down:
            self._c_down.inc()
            logger.warning("router: worker %r went not-ready; "
                           "re-routing its in-flight requests", name)
            if self.tracer:
                self.tracer.instant("router.worker_down", "serve",
                                    worker=name)
            self._reroute_worker(name)

    async def _health_loop(self) -> None:
        while True:
            await asyncio.sleep(self._health_period_s)
            await self._health_tick()
            self._sweep_timeouts()

    def _sweep_timeouts(self) -> None:
        now = time.monotonic()
        stale = [rid for rid, p in self._pending.items()
                 if now - p.t0 > self._request_timeout_s]
        for rid in stale:
            p = self._pending.pop(rid)
            self._dec_inflight(p.worker)
            self._c_timeouts.inc()
            self._finish(rid, p, schema.error_meta(
                rid, "timeout",
                f"no worker reply within "
                f"{self._request_timeout_s:g} s",
                trace_id=p.meta.get("trace_id")), count_reply=False)
        self._g_pending.set(len(self._pending))

    # ------------------------------------------------------------------
    # request path
    # ------------------------------------------------------------------

    async def _consume_requests(self) -> None:
        async def run():
            if self._req_tx is None:
                tx = make_transport(self._url, self._exchange)
                await tx.__aenter__()
                self._req_tx = tx
            try:
                async for item in self._req_tx.subscribe(
                        with_meta=True):
                    _t, _v, meta = item
                    self._handle(meta)
            except BaseException:
                tx, self._req_tx = self._req_tx, None
                if tx is not None:
                    with contextlib.suppress(Exception):
                        await tx.__aexit__(None, None, None)
                raise

        await self._consume_policy.call(run)

    @staticmethod
    def routing_key(meta: dict) -> Optional[str]:
        """The shard-affinity key of a request (None = shardless)."""
        sc = meta.get("scenario")
        if isinstance(sc, dict):
            site = sc.get("site_index", -1)
            if isinstance(site, int) and not isinstance(site, bool) \
                    and site >= 0:
                return f"site:{site}"
            cohort = sc.get("cohort", -1)
            if isinstance(cohort, int) and not isinstance(cohort, bool) \
                    and cohort >= 0:
                return f"cohort:{cohort}"
        return None

    def _retry_after_ms(self) -> int:
        per = self._ewma_reply_s if self._ewma_reply_s is not None \
            else 0.1
        load = max(1.0, len(self._pending) / max(1, len(self._ready)
                                                 or 1) / 8.0)
        ms = int(per * load * 1000.0)
        return max(1, min(MAX_RETRY_AFTER_MS, ms))

    def _bucket_for(self, tenant: str) -> TokenBucket:
        b = self._buckets.get(tenant)
        if b is None:
            b = TokenBucket(self._quota_rate, self._quota_burst)
            self._buckets[tenant] = b
            while len(self._buckets) > TENANTS_CAP:
                self._buckets.popitem(last=False)
        else:
            self._buckets.move_to_end(tenant)
        return b

    def _handle(self, meta) -> None:
        if not isinstance(meta, dict) or \
                meta.get("op") != schema.OP_REQUEST:
            return
        with obs_trace.extracted(meta):
            self._handle_traced(meta)

    def _handle_traced(self, meta: dict) -> None:
        self._c_requests.inc()
        rid = meta.get("id") if isinstance(meta.get("id"), str) else None
        reply_to = meta.get("reply_to") \
            if isinstance(meta.get("reply_to"), str) else None
        tid = meta.get("trace_id")
        tid = tid if isinstance(tid, str) else None
        try:
            if self._draining:
                raise schema.RequestError(
                    "draining", "router is draining; retry elsewhere")
            if rid is None or reply_to is None:
                raise schema.RequestError(
                    "invalid", "request needs string id and reply_to")
            # exactly-once guard: a replayed id that is in flight or
            # already answered never reaches a (second) worker
            if rid in self._pending or rid in self._answered:
                if rid in self._answered:
                    self._answered.move_to_end(rid)
                raise schema.RequestError(
                    "duplicate",
                    f"request id {rid!r} already routed")
            tenant = meta.get("tenant")
            tenant = tenant if isinstance(tenant, str) and tenant \
                else "default"
            if self._quota_rate is not None:
                bucket = self._bucket_for(tenant)
                if not bucket.take():
                    self._c_quota.inc()
                    raise schema.RequestError(
                        "busy",
                        f"tenant {tenant!r} over quota "
                        f"({self._quota_rate:g}/s)",
                        retry_after_ms=int(
                            bucket.retry_after_s() * 1000) + 1)
            if len(self._pending) >= self._inflight_limit:
                self._c_shed.inc()
                raise schema.RequestError(
                    "busy",
                    f"router at in-flight limit "
                    f"({self._inflight_limit})",
                    retry_after_ms=self._retry_after_ms())
            key = self.routing_key(meta)
            worker = self._pick_worker(key)
            if worker is None:
                raise schema.RequestError(
                    "unavailable", "no worker is ready",
                    retry_after_ms=self._retry_after_ms())
        except schema.RequestError as err:
            self._c_rejected.inc()
            if reply_to:
                self._send(reply_to, schema.error_meta(
                    rid, err.code, str(err), trace_id=tid,
                    retry_after_ms=err.retry_after_ms))
            return
        fwd = dict(meta)
        fwd["reply_to"] = self.reply_exchange
        fwd["worker"] = worker  # the stitched-trace worker stamp
        self._pending[rid] = _Pending(
            meta=fwd, reply_to=reply_to, worker=worker, key=key,
            t0=time.monotonic())
        self._inc_inflight(worker)
        self._g_pending.set(len(self._pending))
        self._c_routed.inc()
        if self.tracer:
            self.tracer.instant("router.route", "serve", id=rid,
                                worker=worker,
                                **({"key": key} if key else {}))
        self._send_worker(worker, fwd, rid)

    def _pick_worker(self, key: Optional[str]) -> Optional[str]:
        if not self._ready:
            return None
        if key is not None:
            for name in self._ring.preference(key):
                if name in self._ready:
                    return name
            return None
        # shardless: least-loaded among ready (ties by name for
        # determinism)
        return min(sorted(self._ready),
                   key=lambda n: self._inflight[n])

    def _inc_inflight(self, worker: str) -> None:
        self._inflight[worker] = self._inflight.get(worker, 0) + 1
        self._g_worker[worker].set(self._inflight[worker])

    def _dec_inflight(self, worker: str) -> None:
        self._inflight[worker] = max(
            0, self._inflight.get(worker, 0) - 1)
        g = self._g_worker.get(worker)
        if g is not None:
            g.set(self._inflight[worker])

    # ------------------------------------------------------------------
    # failover
    # ------------------------------------------------------------------

    def _reroute_worker(self, dead: str) -> None:
        """Re-route every in-flight request assigned to ``dead``.  The
        answered-id LRU keeps this exactly-once for the client even if
        the dead worker's reply later limps in through a partition."""
        for rid, p in list(self._pending.items()):
            if p.worker != dead:
                continue
            self._dec_inflight(dead)
            if p.reroutes >= self._reroute_cap:
                self._pending.pop(rid)
                self._c_rejected.inc()
                self._finish(rid, p, schema.error_meta(
                    rid, "unavailable",
                    f"worker {dead!r} died and the re-route budget "
                    f"({self._reroute_cap}) is spent",
                    trace_id=p.meta.get("trace_id"),
                    retry_after_ms=self._retry_after_ms()),
                    count_reply=False)
                continue
            nxt = self._pick_worker(p.key)
            if nxt is None or nxt == dead:
                self._pending.pop(rid)
                self._c_rejected.inc()
                self._finish(rid, p, schema.error_meta(
                    rid, "unavailable",
                    f"worker {dead!r} died with no ready fallback",
                    trace_id=p.meta.get("trace_id"),
                    retry_after_ms=self._retry_after_ms()),
                    count_reply=False)
                continue
            p.worker = nxt
            p.reroutes += 1
            p.meta = dict(p.meta)
            p.meta["worker"] = nxt
            self._inc_inflight(nxt)
            self._c_rerouted.inc()
            if self.tracer:
                self.tracer.instant("router.reroute", "serve", id=rid,
                                    worker=nxt, dead=dead)
            self._send_worker(nxt, p.meta, rid)
        self._g_pending.set(len(self._pending))

    # ------------------------------------------------------------------
    # reply path
    # ------------------------------------------------------------------

    async def _consume_replies(self) -> None:
        async def run():
            if self._rep_tx is None:
                tx = make_transport(self._url, self.reply_exchange)
                await tx.__aenter__()
                self._rep_tx = tx
            try:
                async for _t, _v, meta in self._rep_tx.subscribe(
                        with_meta=True):
                    if not isinstance(meta, dict) or \
                            meta.get("op") != schema.OP_REPLY:
                        continue
                    self._on_reply(meta)
            except BaseException:
                tx, self._rep_tx = self._rep_tx, None
                if tx is not None:
                    with contextlib.suppress(Exception):
                        await tx.__aexit__(None, None, None)
                raise

        await self._reply_consume_policy.call(run)

    def _on_reply(self, meta: dict) -> None:
        rid = meta.get("id")
        p = self._pending.pop(rid, None) if isinstance(rid, str) \
            else None
        if p is None:
            # late/duplicate reply (a rerouted twin, or one that limped
            # in after the timeout sweep): drop — exactly-once
            self._c_dup_replies.inc()
            return
        self._dec_inflight(p.worker)
        self._g_pending.set(len(self._pending))
        latency = time.monotonic() - p.t0
        self._h_reply.observe(latency)
        e = self._ewma_reply_s
        self._ewma_reply_s = (latency if e is None
                              else 0.2 * latency + 0.8 * e)
        out = dict(meta)
        out["worker"] = p.worker  # stitched trace: who answered
        self._finish(rid, p, out)

    def _finish(self, rid: str, p: _Pending, reply_meta: dict,
                count_reply: bool = True) -> None:
        """Forward one reply to the client's original exchange and
        remember the id as answered (exactly-once)."""
        self._answered[rid] = None
        while len(self._answered) > self._answered_cap:
            self._answered.popitem(last=False)
        if count_reply:
            self._c_replies.inc()
        if self.tracer:
            self.tracer.instant("router.reply", "serve", id=rid,
                                worker=p.worker,
                                ok=bool(reply_meta.get("ok")))
        self._send(p.reply_to, reply_meta)

    # ------------------------------------------------------------------
    # publish plumbing
    # ------------------------------------------------------------------

    def _send_worker(self, worker: str, meta: dict, rid: str) -> None:
        task = asyncio.create_task(
            self._publish_worker(worker, meta, rid))
        self._send_tasks.add(task)
        task.add_done_callback(self._send_tasks.discard)

    async def _publish_worker(self, worker: str, meta: dict,
                              rid: str) -> None:
        tx = self._worker_tx.get(worker)
        if tx is None:
            return
        try:
            await self._publish_policy.call(
                tx.publish, 0.0, _now(), meta=meta,
                name="router.forward")
        except Exception:
            # the worker's transport is gone: treat as a death — the
            # health loop's reroute path owns recovery, but kick it now
            # for this request rather than waiting a tick
            logger.warning("router: forward to %r failed", worker,
                           exc_info=True)
            p = self._pending.get(rid)
            if p is not None and p.worker == worker:
                self._ready.discard(worker)
                self._reroute_worker(worker)

    def _send(self, exchange: str, meta: dict) -> None:
        task = asyncio.create_task(self._publish_client(exchange, meta))
        self._send_tasks.add(task)
        task.add_done_callback(self._send_tasks.discard)

    async def _publish_client(self, exchange: str, meta: dict) -> None:
        async def attempt():
            tx = self._client_tx.get(exchange)
            if tx is None:
                tx = make_transport(self._url, exchange)
                await tx.__aenter__()
                self._client_tx[exchange] = tx
            try:
                await tx.publish(0.0, _now(), meta=meta)
            except BaseException:
                self._client_tx.pop(exchange, None)
                with contextlib.suppress(Exception):
                    await tx.__aexit__(None, None, None)
                raise

        with contextlib.suppress(Exception):
            await self._publish_policy.call(
                attempt, name="router.reply_forward")


def _now() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc).replace(tzinfo=None)
