"""Scenario-serving runtime: a warm, micro-batching pvsim query server.

A long-lived asyncio server (``pvsim serve``) builds one
:class:`~tmhpvsim_tpu.engine.simulation.Simulation` at startup — under
the persistent compile cache + AOT warm-up, so a warm restart performs
zero fresh compiles — pins the base chain state device-resident, and
answers "what-if" scenario queries over the existing broker transports
(``local://`` / ``tcp://`` / AMQP).  Each request perturbs a bounded
set of scenario knobs (demand scale/shift, DC-capacity scale,
weather-regime bias, curtailment cap, horizon) and picks a result mode;
a micro-batcher coalesces concurrent requests within a configurable
window into ONE fused dispatch with the knobs stacked on a leading
``vmap`` axis over the chain axis (``SimConfig.serve_batch_sizes``).

Modules: :mod:`.schema` (request/reply wire format + validation +
scenario→pytree encoding), :mod:`.batcher` (the window/occupancy
coalescer), :mod:`.server` (the asyncio server, the warm engine
wrapper, graceful shutdown).
"""

from tmhpvsim_tpu.serve.schema import (  # noqa: F401
    Request,
    RequestError,
    Scenario,
)
from tmhpvsim_tpu.serve.batcher import MicroBatcher  # noqa: F401
from tmhpvsim_tpu.serve.server import (  # noqa: F401
    ScenarioClient,
    ScenarioEngine,
    ScenarioServer,
    ServeConfig,
)
