"""The scenario server: a warm Simulation answering broker queries.

Three pieces:

* :class:`ScenarioEngine` — the warm executor.  Builds ONE
  :class:`~tmhpvsim_tpu.engine.simulation.Simulation` (reduce mode,
  ``serve_batch_sizes`` = the batch buckets) so the persistent compile
  cache + AOT warm-up pre-compile every dispatch shape at startup; the
  base chain state and per-block host inputs are computed once and
  reused by every query (the state is protected from donation by a
  device-side copy per batch).  ``run()`` is synchronous and runs on
  the micro-batcher's single worker thread.
* :class:`ScenarioServer` — the asyncio front: subscribes the request
  exchange, validates (serve/schema.py), rejects duplicates/overload
  with typed errors, coalesces through the
  :class:`~tmhpvsim_tpu.serve.batcher.MicroBatcher`, publishes replies
  to each request's ``reply_to`` exchange, and records the SLO metrics
  the RunReport ``serving`` section reads.  SIGINT/SIGTERM start a
  drain: in-flight requests complete, new ones get typed ``draining``
  rejections.
* :class:`ScenarioClient` — request/reply correlation for callers
  (bench's load generator, the tests): one reply-exchange subscription
  demultiplexed by request id, so out-of-order replies and other
  clients' replies on a shared exchange are handled by construction.

:func:`serve_main` is the app orchestrator behind ``pvsim serve``:
per-run registry, compile cache, flight recorder (crash dumps at
``trace + '.crash.json'``), run report on exit.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import datetime as _dt
import logging
import signal
import uuid
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.obs import analytics as flt
from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs import trace as obs_trace
from tmhpvsim_tpu.obs.trace import Tracer
from tmhpvsim_tpu.runtime.broker import make_transport
from tmhpvsim_tpu.runtime.resilience import (CircuitBreaker,
                                             ResiliencePolicy, forever)
from tmhpvsim_tpu.serve import schema
from tmhpvsim_tpu.serve.batcher import ContinuousBatcher, MicroBatcher
from tmhpvsim_tpu.serve.schema import Request, RequestError, Scenario

logger = logging.getLogger(__name__)

#: completed request ids remembered for duplicate rejection (an LRU —
#: serving forever must not grow memory per request)
RECENT_IDS_CAP = 4096


def _now() -> _dt.datetime:
    """Naive UTC wall time — the brokers' timestamp convention."""
    return _dt.datetime.now(_dt.timezone.utc).replace(tzinfo=None)


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to ``max_batch`` (plus ``max_batch`` itself):
    a partial batch pads to the next bucket, so the compiled-executable
    set stays logarithmic in the batch cap."""
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return tuple(out)


@dataclasses.dataclass
class ServeConfig:
    """One server: the simulation it answers from + the serving knobs.

    ``sim.duration_s`` is the maximum scenario horizon; requests ask
    for any ``horizon_s`` in ``[1, sim.duration_s]`` and pay only the
    blocks their batch's longest horizon needs.
    """

    sim: SimConfig
    url: str = "local://default"
    exchange: str = "scenario"
    #: micro-batch window: the first pending request waits at most this
    #: long for company before the batch dispatches
    window_s: float = 0.010
    max_batch: int = 16
    #: explicit batch buckets; () -> ``default_buckets(max_batch)``
    batch_sizes: Tuple[int, ...] = ()
    #: pending requests beyond this are rejected ``busy``
    queue_limit: int = 1024
    #: per-request wall clock before a typed ``timeout`` reply
    timeout_s: float = 60.0
    #: graceful-drain hard deadline: past it, queued requests get typed
    #: ``draining`` rejections and shutdown proceeds (``--drain-timeout``)
    drain_timeout_s: float = 30.0
    #: completed request ids remembered for duplicate rejection (LRU)
    recent_ids_cap: int = RECENT_IDS_CAP
    #: consecutive dispatch failures that open the circuit breaker
    #: (requests shed with typed ``unavailable`` while open)
    breaker_threshold: int = 5
    #: seconds an open breaker waits before letting a probe batch through
    breaker_reset_s: float = 30.0
    #: batch scheduler: ``"window"`` (the PR-7 coalescer — every row of
    #: a dispatch retires together) or ``"continuous"`` (rolling
    #: block-granular dispatch with backfill; see serve/batcher.py).
    #: The default stays "window" so a fleet-off server lowers to the
    #: byte-identical HLO of previous releases — continuous mode's
    #: extra executables (masked row reset) only build when asked for.
    batching: str = "window"
    #: continuous mode only: dispatches a resident row's cursor may be
    #: skipped before it is forced (lower = tighter tail latency for
    #: long-horizon rows, higher = fatter fused batches)
    starve_limit: int = 4

    def buckets(self) -> Tuple[int, ...]:
        bs = tuple(sorted({int(b) for b in self.batch_sizes})) \
            if self.batch_sizes else default_buckets(self.max_batch)
        if any(b < 1 for b in bs):
            raise ValueError(f"batch_sizes {bs} must all be >= 1")
        return bs


class ScenarioEngine:
    """The warm scenario executor (device side; see module docstring).

    Thread contract: construct anywhere, then ``run()`` only from ONE
    thread at a time (the micro-batcher's single dispatch worker).
    """

    def __init__(self, sim_config: SimConfig,
                 batch_sizes: Sequence[int]):
        from tmhpvsim_tpu.engine.simulation import Simulation

        # On a 2-D (chains, scenario) mesh the what-if batch axis is
        # sharded over the scenario mesh dimension, so every bucket must
        # divide evenly: round each up to a multiple of M.  Padding rows
        # are bit-inert (see Simulation._block_step_scan_scenario), so a
        # rounded-up bucket answers the same requests identically.
        align = max(1, int(getattr(sim_config, "mesh_scenario", 0) or 1))
        self.batch_align = align
        self.buckets = tuple(sorted(
            {-(-int(b) // align) * align for b in batch_sizes}))
        cfg = dataclasses.replace(
            sim_config, output="reduce",
            serve_batch_sizes=self.buckets)
        if getattr(sim_config, "mesh_scenario", 0) >= 1:
            from tmhpvsim_tpu.parallel import ShardedSimulation
            self.sim = ShardedSimulation(cfg)
        else:
            self.sim = Simulation(cfg)
        self.dtype = self.sim.dtype
        self.max_horizon_s = cfg.duration_s
        self.params = self.sim.scenario_fleet_params()
        # site-selector bounds (schema.parse_scenario): a site_index is
        # only answerable when chains ARE distinct sites (multi-site
        # grid or fleet — for an exchangeable MC ensemble the "site"
        # would be an arbitrary replicate); cohorts need a fleet that
        # actually tags >1 of them.  Read from the RESOLVED config (the
        # Simulation derives grid/n_chains from the fleet).
        rcfg = self.sim.config
        fp = rcfg.fleet
        self.n_sites = (rcfg.n_chains
                        if (rcfg.site_grid is not None or fp is not None)
                        else None)
        self.n_cohorts = (fp.n_cohorts
                          if fp is not None and fp.n_cohorts > 1 else 0)
        #: device-resident base state, shared by every query via a
        #: non-donating device copy (engine/simulation.py _copy_jit)
        self._state0 = self.sim.init_state()
        #: per-block host inputs, computed once (host float64 work)
        self._inputs = [self.sim.host_inputs(bi)[0]
                        for bi in range(self.sim.n_blocks)]
        #: chain state at each block boundary, cached as continuous
        #: batching discovers it (see :meth:`block_state`); costs at
        #: most ``n_blocks`` extra state-sized device buffers
        self._block_states = {0: self._state0}

    def block_state(self, bi: int):
        """Chain state at the start of block ``bi``.

        The chain state is deterministic and scenario-INDEPENDENT —
        scenario knobs only perturb the per-row fold, never the RNG or
        model state (``Simulation._scenario_block_core``) — so states
        computed once are valid for every request.  Continuous batching
        leans on this: a row admitted mid-stream at block cursor 0 and
        a row already at cursor k both dispatch against the cached
        state of THEIR OWN block, which is bit-identical to the state a
        serial batch-of-1 run would have reached.  The cache fills in
        dispatch order, so any resident cursor's state is present by
        construction (a row only reaches cursor k after block k-1
        dispatched and stored state k)."""
        return self._block_states[bi]

    def store_block_state(self, bi: int, state) -> None:
        """Cache the post-block state a dispatch just produced (no-op
        when already known; the returned buffer is fresh, never a
        donated alias)."""
        if bi < self.sim.n_blocks and bi not in self._block_states:
            self._block_states[bi] = state

    def open_rolling(self, bucket: Optional[int] = None
                     ) -> "RollingSession":
        """One continuous-batching slot protocol over this engine
        (bucket defaults to the largest — already ``batch_align``
        rounded — compiled bucket)."""
        return RollingSession(
            self, max(self.buckets) if bucket is None else bucket)

    def run(self, requests: Sequence[Request]) -> List[dict]:
        """Answer a batch: one fused dispatch chain over the blocks the
        batch's longest horizon needs.  Row ``i`` of the padded batch is
        bit-identical to a batch-of-1 run of scenario ``i`` (see
        ``Simulation._block_step_scan_scenario``), so replies do not
        depend on which requests happened to share the window."""
        from tmhpvsim_tpu.engine.simulation import _copy_jit
        import jax

        scenarios = [r.scenario for r in requests]
        bucket = schema.pick_bucket(len(scenarios), self.buckets)
        scen = schema.encode_batch(scenarios, bucket, self.dtype)
        cfg = self.sim.config
        horizon = max(s.horizon_s for s in scenarios)
        n_blocks = min(self.sim.n_blocks,
                       -(-int(horizon) // cfg.block_s))
        state = _copy_jit(self._state0)
        acc = self.sim.init_scenario_acc(bucket)
        totals: List[Optional[dict]] = [None] * len(scenarios)
        for bi in range(n_blocks):
            state, acc, fdelta = self.sim.scenario_step(
                state, self._inputs[bi], acc, scen)
            fd = jax.device_get(fdelta)
            for i in range(len(scenarios)):
                totals[i] = flt.merge_host(
                    totals[i], {k: v[i] for k, v in fd.items()})
        acc_h = jax.device_get(acc)
        return [
            self._format(req, {k: np.asarray(v[i])
                               for k, v in acc_h.items()}, totals[i])
            for i, req in enumerate(requests)
        ]

    def _format(self, req: Request, row: dict,
                total: Optional[dict]) -> dict:
        """One request's mode-shaped result (plain JSON-safe python).

        Host reductions are fixed-order numpy ops on bit-identical
        arrays, and JSON float round-trips are exact (repr shortest
        round-trip), so equal scenarios give byte-equal replies through
        any transport."""
        h = int(req.scenario.horizon_s)

        def sel(out):
            # echo an active site selector so the reply is self-
            # describing; unselected replies stay byte-identical to the
            # pre-selector wire format
            if req.scenario.site_index >= 0:
                out["site_index"] = int(req.scenario.site_index)
            if req.scenario.cohort >= 0:
                out["cohort"] = int(req.scenario.cohort)
            return out

        if req.mode == "fleet":
            return sel({"mode": "fleet", "horizon_s": h,
                        "fleet": flt.summarize(total, self.params)})
        if req.mode == "quantiles":
            fleet = flt.summarize(total, self.params)
            return sel({"mode": "quantiles", "horizon_s": h,
                        "count": fleet["count"],
                        "residual": fleet["residual"]})
        ns = int(row["n_seconds"].sum())

        def tot(name):
            return float(row[name].astype(np.float64).sum())

        return sel({"mode": "reduce", "horizon_s": h, "stats": {
            "n_seconds": ns,
            "pv_sum_w": tot("pv_sum"),
            "meter_sum_w": tot("meter_sum"),
            "residual_sum_w": tot("residual_sum"),
            "pv_max_w": float(row["pv_max"].max()),
            "residual_min_w": float(row["residual_min"].min()),
            "residual_max_w": float(row["residual_max"].max()),
        }})


class RollingSession:
    """Device-side slot protocol of continuous batching (the scheduler
    is :class:`~tmhpvsim_tpu.serve.batcher.ContinuousBatcher`).

    One fixed ``bucket``-wide accumulator rolls forever.  Each resident
    request owns a slot; each fused dispatch folds ONE block index for
    the slots scheduled at that cursor.  Bit-identity with batch-of-1
    falls out of three established properties:

    * rows the dispatch does NOT schedule ride along with
      ``horizon_s=0`` — the bit-inert padding row
      (``Simulation._block_step_scan_scenario`` folds nothing for it),
      so their accumulator bits and everyone else's are untouched;
    * scheduled rows carry their TRUE horizon, and block ``bi`` covers
      global seconds ``[bi*block_s, (bi+1)*block_s)``, so the validity
      mask ``t < horizon_s`` folds exactly the seconds a serial run of
      that row would fold in that block — in the same block order,
      against the same cached chain state (:meth:`ScenarioEngine
      .block_state`);
    * a newly admitted slot's accumulator row is re-initialised on
      device by a masked select against the pristine init template —
      bit-equal to ``init_scenario_acc``'s values.

    Thread contract: all methods run on the batcher's single dispatch
    worker thread (same as ``ScenarioEngine.run``).
    """

    def __init__(self, engine: ScenarioEngine, bucket: int):
        import jax
        import jax.numpy as jnp

        self.engine = engine
        self.bucket = int(bucket)
        if self.bucket % engine.batch_align != 0:
            raise ValueError(
                f"rolling bucket {bucket} must be a multiple of "
                f"batch_align {engine.batch_align}")
        dt = np.dtype(engine.dtype)
        no_cap = float(np.finfo(dt).max)
        #: neutral (padding) fill per knob column — a free slot is a
        #: bit-inert padding row
        self._neutral = {
            "demand_scale": (dt, 1.0),
            "demand_shift_w": (dt, 0.0),
            "pv_scale": (dt, 1.0),
            "weather_bias": (dt, 1.0),
            "curtail_w": (dt, no_cap),
            "site_index": (np.int32, -1),
            "cohort": (np.int32, -1),
        }
        self._no_cap = no_cap
        self._cols = {k: np.full((self.bucket,), fill, d)
                      for k, (d, fill) in self._neutral.items()}
        self._horizons = np.zeros(self.bucket, np.int32)
        self._reqs: List[Optional[Request]] = [None] * self.bucket
        self._totals: List[Optional[dict]] = [None] * self.bucket
        #: pristine init accumulator — the masked row reset selects
        #: from it, so re-admitted rows start bit-equal to a fresh
        #: ``init_scenario_acc`` (never donated)
        self._acc0 = engine.sim.init_scenario_acc(self.bucket)
        self.acc = engine.sim.init_scenario_acc(self.bucket)

        def _reset(acc, acc0, mask):
            return jax.tree.map(
                lambda a, z: jnp.where(mask[:, None], z, a), acc, acc0)

        #: masked row re-init (donates ``acc``); compiled here so the
        #: serving START absorbs it and a warm worker's first admit
        #: compiles nothing
        self._reset = jax.jit(_reset, donate_argnums=(0,))
        self.acc = self._reset(self.acc, self._acc0,
                               np.zeros(self.bucket, bool))

    def blocks_for(self, request: Request) -> int:
        """Blocks this request's horizon needs (its retirement cursor)."""
        cfg = self.engine.sim.config
        return min(self.engine.sim.n_blocks,
                   -(-int(request.scenario.horizon_s) // cfg.block_s))

    def admit_rows(self, items: Sequence[Tuple[int, Request]]) -> None:
        """Bind requests to free slots: write their knob columns and
        re-initialise exactly their accumulator rows on device."""
        mask = np.zeros(self.bucket, bool)
        for slot, req in items:
            s = req.scenario
            self._cols["demand_scale"][slot] = s.demand_scale
            self._cols["demand_shift_w"][slot] = s.demand_shift_w
            self._cols["pv_scale"][slot] = s.dc_capacity_scale
            self._cols["weather_bias"][slot] = s.weather_bias
            self._cols["curtail_w"][slot] = (
                self._no_cap if s.curtail_w is None else s.curtail_w)
            self._cols["site_index"][slot] = s.site_index
            self._cols["cohort"][slot] = s.cohort
            self._horizons[slot] = s.horizon_s
            self._reqs[slot] = req
            self._totals[slot] = None
            mask[slot] = True
        self.acc = self._reset(self.acc, self._acc0, mask)

    def step_finish(self, bi: int, sched: Sequence[int],
                    retiring: Sequence[int]) -> dict:
        """One fused dispatch of block ``bi`` for the slots in
        ``sched``; returns ``{slot: formatted result}`` for the slots
        in ``retiring`` (their horizon completes with this block)."""
        import jax
        from tmhpvsim_tpu.engine.simulation import _copy_jit

        e = self.engine
        scen = dict(self._cols)
        # the per-dispatch horizon mask IS the scheduler: scheduled
        # rows fold their true horizon's share of this block, everyone
        # else is a horizon-0 padding row this round
        h = np.zeros(self.bucket, np.int32)
        for sl in sched:
            h[sl] = self._horizons[sl]
        scen["horizon_s"] = h
        state = _copy_jit(e.block_state(bi))
        state, self.acc, fdelta = e.sim.scenario_step(
            state, e._inputs[bi], self.acc, scen)
        e.store_block_state(bi + 1, state)
        fd = jax.device_get(fdelta)
        for sl in sched:
            self._totals[sl] = flt.merge_host(
                self._totals[sl], {k: v[sl] for k, v in fd.items()})
        out = {}
        if retiring:
            acc_h = jax.device_get(self.acc)
            for sl in retiring:
                row = {k: np.asarray(v[sl]) for k, v in acc_h.items()}
                out[sl] = e._format(self._reqs[sl], row,
                                    self._totals[sl])
                self._release(sl)
        return out

    def _release(self, slot: int) -> None:
        for k, (_d, fill) in self._neutral.items():
            self._cols[k][slot] = fill
        self._horizons[slot] = 0
        self._reqs[slot] = None
        self._totals[slot] = None

    def recover(self) -> None:
        """After a failed dispatch (the donated accumulator is gone):
        fresh accumulator, every slot back to padding."""
        self.acc = self.engine.sim.init_scenario_acc(self.bucket)
        for slot in range(self.bucket):
            self._release(slot)


class ScenarioServer:
    """The asyncio serving front (see module docstring)."""

    def __init__(self, cfg: ServeConfig, *, registry=None,
                 tracer: Optional[Tracer] = None):
        self.cfg = cfg
        self.registry = registry or obs_metrics.get_registry()
        self.tracer = tracer
        self.engine: Optional[ScenarioEngine] = None
        self.batcher: Optional[MicroBatcher] = None
        self._req_tx = None
        self._reply_tx: dict = {}
        self._consume_task: Optional[asyncio.Task] = None
        self._tasks: set = set()
        self._inflight_ids: set = set()
        self._recent_ids: OrderedDict = OrderedDict()
        self._draining = False
        self._stopped = False
        self._drain_event: Optional[asyncio.Event] = None
        #: set by :meth:`kill` (chaos): the fleet supervisor's respawn
        #: signal, the in-process analogue of SIGCHLD
        self.died = asyncio.Event()
        reg = self.registry
        self._c_requests = reg.counter("serve.requests_total")
        self._c_replies = reg.counter("serve.replies_total")
        self._c_rejected = reg.counter("serve.rejected_total")
        self._c_timeouts = reg.counter("serve.timeouts_total")
        self._c_replay_evict = reg.counter("serve.replay_evictions_total")
        self._g_inflight = reg.gauge("serve.in_flight")
        self._h_reply = reg.histogram("serve.reply_latency_s")
        #: reconnect-and-resubscribe for the request subscription — a
        #: dropped broker connection must not kill the server
        self._consume_policy = ResiliencePolicy(
            attempts=forever, base_delay_s=0.1, max_delay_s=2.0,
            name="serve.consume", registry=reg)
        #: bounded retries for reply publishes — a transient publish
        #: failure must not lose an accepted request's answer
        self._reply_policy = ResiliencePolicy(
            attempts=5, base_delay_s=0.05, max_delay_s=0.5,
            name="serve.publish_reply", registry=reg)

    @property
    def draining(self) -> bool:
        return self._draining

    def readiness(self) -> tuple:
        """``(ok, detail)`` for the live ops plane's ``/readyz``: ready
        iff the warm engine is built (AOT warm-up done), the server is
        not draining, and the dispatch circuit breaker is closed — an
        open OR half-open breaker reads not-ready until its probe batch
        actually succeeds, so a load balancer only routes to workers
        whose device path is proven."""
        warm = self.engine is not None
        breaker = self.batcher.breaker if self.batcher is not None \
            else None
        bstate = breaker.state if breaker is not None else "closed"
        ok = warm and not self._draining and bstate == "closed"
        return ok, {"warm": warm, "draining": self._draining,
                    "breaker": bstate}

    async def start(self) -> None:
        """Build the warm engine (compiles — possibly from the warm
        cache), open the request subscription, start the batcher."""
        if self.cfg.batching not in ("window", "continuous"):
            raise ValueError(
                f"batching {self.cfg.batching!r} not one of "
                "'window', 'continuous'")
        self._drain_event = asyncio.Event()
        with obs_metrics.use_registry(self.registry):
            self.engine = ScenarioEngine(self.cfg.sim,
                                         self.cfg.buckets())
            breaker = CircuitBreaker(
                "serve.dispatch",
                failure_threshold=self.cfg.breaker_threshold,
                reset_s=self.cfg.breaker_reset_s,
                registry=self.registry)
            if self.cfg.batching == "continuous":
                self.batcher = ContinuousBatcher(
                    self.engine.open_rolling(),
                    window_s=self.cfg.window_s,
                    queue_limit=self.cfg.queue_limit,
                    registry=self.registry,
                    breaker=breaker,
                    starve_limit=self.cfg.starve_limit)
            else:
                self.batcher = MicroBatcher(
                    self.engine.run,
                    window_s=self.cfg.window_s,
                    max_batch=max(self.engine.buckets),
                    queue_limit=self.cfg.queue_limit,
                    batch_align=self.engine.batch_align,
                    registry=self.registry,
                    breaker=breaker)
            self.batcher.start()
            self._req_tx = make_transport(self.cfg.url, self.cfg.exchange)
            await self._req_tx.__aenter__()
        self._consume_task = asyncio.create_task(self._consume())
        if self.tracer:
            self.tracer.instant("serve.start", "serve")
        logger.info(
            "scenario server listening on %s exchange %r "
            "(buckets %s, window %.0f ms, max horizon %d s)",
            self.cfg.url, self.cfg.exchange, list(self.engine.buckets),
            self.cfg.window_s * 1e3, self.engine.max_horizon_s)

    def install_signal_handlers(self) -> None:
        """SIGINT/SIGTERM -> begin draining (idempotent)."""
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            with contextlib.suppress(NotImplementedError):
                loop.add_signal_handler(sig, self.begin_drain)

    def begin_drain(self) -> None:
        """Stop accepting work: new requests get typed ``draining``
        replies; in-flight requests run to completion."""
        if not self._draining:
            logger.info("scenario server draining: rejecting new "
                        "requests, completing %d in flight",
                        len(self._inflight_ids))
            if self.tracer:
                self.tracer.instant("serve.drain", "serve")
        self._draining = True
        if self._drain_event is not None:
            self._drain_event.set()

    async def serve_forever(self) -> None:
        """Run until a signal (or :meth:`begin_drain`) starts the
        drain, then stop cleanly."""
        await self._drain_event.wait()
        await self.stop()

    async def stop(self) -> None:
        """Drain and shut down (idempotent): queued batches run,
        in-flight replies publish, then the subscription and reply
        transports close."""
        if self._stopped:
            return
        self._stopped = True
        self._draining = True
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.cfg.drain_timeout_s
        if self.batcher is not None:
            await self.batcher.stop(drain=True,
                                    timeout=self.cfg.drain_timeout_s)
        if self._tasks:
            # replies for everything the batcher just resolved (or
            # force-failed with typed 'draining' at the deadline); past
            # the deadline, stragglers are cancelled unreplied
            done, pending = await asyncio.wait(
                self._tasks,
                timeout=max(1.0, deadline - loop.time()))
            for t in pending:
                t.cancel()
            if pending:
                await asyncio.wait(pending, timeout=1.0)
        if self._consume_task is not None:
            self._consume_task.cancel()
            with contextlib.suppress(asyncio.CancelledError,
                                     ConnectionError):
                await self._consume_task
        for tx in [self._req_tx, *self._reply_tx.values()]:
            if tx is not None:
                with contextlib.suppress(Exception):
                    await tx.__aexit__(None, None, None)
        self._reply_tx.clear()
        if self.tracer:
            self.tracer.instant("serve.stop", "serve")

    async def kill(self) -> None:
        """Simulated SIGKILL (chaos tests): stop consuming, cancel
        every in-flight reply task, drop queued work unreplied and
        close transports — no drain, no ``draining`` rejections, no
        farewell replies.  A killed process says nothing; the fleet
        router's health loop and reroute path are what keep the
        requests alive.  Sets :attr:`died` for the fleet supervisor."""
        self._stopped = True
        self._draining = True
        self.died.set()
        if self._consume_task is not None:
            self._consume_task.cancel()
            with contextlib.suppress(asyncio.CancelledError,
                                     ConnectionError):
                await self._consume_task
            self._consume_task = None
        for t in list(self._tasks):
            t.cancel()
        if self._tasks:
            await asyncio.wait(list(self._tasks), timeout=1.0)
        if self.batcher is not None:
            self.batcher.kill()
        for tx in [self._req_tx, *self._reply_tx.values()]:
            if tx is not None:
                with contextlib.suppress(Exception):
                    await tx.__aexit__(None, None, None)
        self._req_tx = None
        self._reply_tx.clear()

    # ------------------------------------------------------------------
    # request path
    # ------------------------------------------------------------------

    async def _consume(self) -> None:
        async def run():
            # (re)build the request transport when the last subscription
            # died — reconnect AND re-subscribe, the fanout contract
            if self._req_tx is None:
                tx = make_transport(self.cfg.url, self.cfg.exchange)
                await tx.__aenter__()
                self._req_tx = tx
            try:
                async for item in self._req_tx.subscribe(with_meta=True):
                    _t, _v, meta = item
                    self._handle(meta)
            except BaseException:
                tx, self._req_tx = self._req_tx, None
                if tx is not None:
                    with contextlib.suppress(Exception):
                        await tx.__aexit__(None, None, None)
                raise

        await self._consume_policy.call(run)

    def _handle(self, meta) -> None:
        # non-request traffic on a shared exchange is not ours to judge
        if not isinstance(meta, dict) or \
                meta.get("op") != schema.OP_REQUEST:
            return
        # bind the request's propagated trace context (no-op when the
        # live ops plane is off): the scope covers the instants below
        # AND the tasks created inside it — contextvars follow
        # create_task, so _respond/_publish_reply inherit the ids
        with obs_trace.extracted(meta):
            self._handle_traced(meta)

    def _handle_traced(self, meta: dict) -> None:
        self._c_requests.inc()
        loop = asyncio.get_running_loop()
        t_recv = loop.time()
        rid = meta.get("id") if isinstance(meta.get("id"), str) else None
        reply_to = meta.get("reply_to") \
            if isinstance(meta.get("reply_to"), str) else None
        if self.tracer:
            self.tracer.instant("serve.request", "serve", id=rid)
        try:
            if self._draining:
                raise RequestError("draining",
                                   "server is draining; retry elsewhere")
            req = schema.parse_request(
                meta, max_horizon_s=self.engine.max_horizon_s,
                n_sites=self.engine.n_sites,
                n_cohorts=self.engine.n_cohorts)
            if req.id in self._inflight_ids or \
                    req.id in self._recent_ids:
                if req.id in self._recent_ids:  # true LRU: a replayed
                    self._recent_ids.move_to_end(req.id)  # id stays hot
                raise RequestError(
                    "duplicate", f"request id {req.id!r} already seen")
        except RequestError as err:
            tid = meta.get("trace_id")
            self._reject(reply_to, rid, err,
                         trace_id=tid if isinstance(tid, str) else None)
            return
        self._inflight_ids.add(req.id)
        self._g_inflight.set(len(self._inflight_ids))
        task = asyncio.create_task(self._respond(req, t_recv))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _reject(self, reply_to: Optional[str], rid: Optional[str],
                err: RequestError,
                trace_id: Optional[str] = None) -> None:
        self._c_rejected.inc()
        logger.warning("scenario request rejected (%s): %s",
                       err.code, err)
        if reply_to:  # no reply address -> counted, nothing to say
            task = asyncio.create_task(self._publish_reply(
                reply_to, schema.error_meta(
                    rid, err.code, str(err), trace_id=trace_id,
                    retry_after_ms=err.retry_after_ms)))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

    async def _respond(self, req: Request, t_recv: float) -> None:
        loop = asyncio.get_running_loop()
        try:
            try:
                fut = self.batcher.submit(req)
                result, info = await asyncio.wait_for(
                    fut, timeout=self.cfg.timeout_s)
            except asyncio.TimeoutError:
                self._c_timeouts.inc()
                await self._publish_reply(req.reply_to, schema.error_meta(
                    req.id, "timeout",
                    f"no result within {self.cfg.timeout_s:g} s",
                    trace_id=req.trace_id))
                return
            except RequestError as err:
                self._c_rejected.inc()
                await self._publish_reply(req.reply_to, schema.error_meta(
                    req.id, err.code, str(err), trace_id=req.trace_id,
                    retry_after_ms=err.retry_after_ms))
                return
            except Exception as err:  # engine bug: reply, do not wedge
                logger.exception("scenario request %s failed", req.id)
                await self._publish_reply(req.reply_to, schema.error_meta(
                    req.id, "internal", f"{type(err).__name__}: {err}",
                    trace_id=req.trace_id))
                return
            latency = loop.time() - t_recv
            await self._publish_reply(req.reply_to, schema.ok_meta(
                req.id, req.mode, result,
                timings={**info, "reply_latency_s": latency},
                trace_id=req.trace_id))
            self._c_replies.inc()
            self._h_reply.observe(latency)
            if self.tracer:
                self.tracer.instant("serve.reply", "serve", id=req.id,
                                    latency_s=latency)
        finally:
            self._inflight_ids.discard(req.id)
            self._recent_ids[req.id] = None
            while len(self._recent_ids) > self.cfg.recent_ids_cap:
                self._recent_ids.popitem(last=False)
                self._c_replay_evict.inc()
            self._g_inflight.set(len(self._inflight_ids))

    async def _publish_reply(self, exchange: str, meta: dict) -> None:
        """Publish on a per-``reply_to`` transport (cached: clients
        reuse their reply exchange across requests).  Retried under the
        reply policy, rebuilding the transport on failure — a transient
        broker error must not lose an accepted request's answer."""

        async def attempt():
            tx = self._reply_tx.get(exchange)
            if tx is None:
                tx = make_transport(self.cfg.url, exchange)
                await tx.__aenter__()
                self._reply_tx[exchange] = tx
            try:
                await tx.publish(0.0, _now(), meta=meta)
            except BaseException:
                self._reply_tx.pop(exchange, None)
                with contextlib.suppress(Exception):
                    await tx.__aexit__(None, None, None)
                raise

        await self._reply_policy.call(attempt)


class ScenarioClient:
    """Request/reply correlation over the fanout transports.

    One reply exchange per client, one subscription, replies resolved
    by ``id`` — so replies arriving out of order, or other clients'
    replies on a deliberately shared reply exchange, route correctly
    by construction.  ``batch()`` issues many requests concurrently
    (the server's micro-batch window sees them together).
    """

    def __init__(self, url: str, exchange: str = "scenario",
                 reply_to: Optional[str] = None,
                 policy: Optional[ResiliencePolicy] = None,
                 rejection_policy: Optional[ResiliencePolicy] = None):
        self._url = url
        self._exchange = exchange
        self.reply_to = reply_to or \
            f"scenario.reply.{uuid.uuid4().hex[:12]}"
        self._pending: dict = {}
        self._req_tx = None
        self._rep_tx = None
        self._task: Optional[asyncio.Task] = None
        #: bounded retry policy for request publishes (None = one shot);
        #: reply timeouts stay the caller's ``timeout`` budget
        self._policy = policy
        #: typed busy/unavailable replies re-issue the SAME request id
        #: under this policy (None = surface them as values).  The
        #: server's ``retry_after_ms`` hint, when present, REPLACES the
        #: policy's decorrelated jitter (resilience.py honours the
        #: ``retry_after_s`` exception attribute): the server knows its
        #: queue depth and breaker reset, the dice do not.  Safe by
        #: construction — busy/unavailable shed BEFORE execution, so a
        #: retried id can never double-execute or trip the replay LRU.
        self._rejection_policy = rejection_policy
        #: the reply subscription reconnects-and-resubscribes forever —
        #: a broker blip must not strand every pending future
        self._consume_policy = ResiliencePolicy(
            attempts=forever, base_delay_s=0.1, max_delay_s=2.0,
            name="ScenarioClient.consume")

    async def __aenter__(self):
        self._req_tx = make_transport(self._url, self._exchange)
        await self._req_tx.__aenter__()
        self._rep_tx = make_transport(self._url, self.reply_to)
        await self._rep_tx.__aenter__()
        self._task = asyncio.create_task(self._consume())
        # let the subscription register before the first publish (the
        # fanout contract only delivers to already-bound subscribers)
        await asyncio.sleep(0.05)
        return self

    async def __aexit__(self, *exc):
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError,
                                     ConnectionError):
                await self._task
        for tx in (self._rep_tx, self._req_tx):
            if tx is not None:
                with contextlib.suppress(Exception):
                    await tx.__aexit__(None, None, None)
        return False

    async def _consume(self) -> None:
        async def run():
            if self._rep_tx is None:
                tx = make_transport(self._url, self.reply_to)
                await tx.__aenter__()
                self._rep_tx = tx
            try:
                async for _t, _v, meta in \
                        self._rep_tx.subscribe(with_meta=True):
                    if not isinstance(meta, dict) or \
                            meta.get("op") != schema.OP_REPLY:
                        continue
                    fut = self._pending.pop(meta.get("id"), None)
                    if fut is not None and not fut.done():
                        fut.set_result(meta)
            except BaseException:
                tx, self._rep_tx = self._rep_tx, None
                if tx is not None:
                    with contextlib.suppress(Exception):
                        await tx.__aexit__(None, None, None)
                raise

        await self._consume_policy.call(run)

    async def request(self, scenario: Optional[dict] = None,
                      mode: str = "reduce", rid: Optional[str] = None,
                      timeout: float = 60.0,
                      tenant: Optional[str] = None) -> dict:
        """One scenario query -> the reply meta dict (``ok`` true or
        false — typed errors come back as values, not exceptions).
        With a ``rejection_policy``, typed busy/unavailable replies are
        retried under it (same id, server ``retry_after_ms`` hint
        honoured) and only the final reply surfaces."""
        rid = rid or uuid.uuid4().hex[:16]
        if self._rejection_policy is None:
            return await self._request_once(scenario, mode, rid,
                                            timeout, tenant)

        async def attempt():
            reply = await self._request_once(scenario, mode, rid,
                                             timeout, tenant)
            err = reply.get("error") if not reply.get("ok") else None
            if err and err.get("code") in ("busy", "unavailable"):
                exc = RequestError(err["code"],
                                   err.get("message", ""),
                                   retry_after_ms=err.get(
                                       "retry_after_ms"))
                exc.reply = reply  # surfaced on retry exhaustion
                raise exc
            return reply

        return await self._rejection_policy.call(
            attempt, name="ScenarioClient.rejected",
            fallback=lambda exc: getattr(
                exc, "reply",
                schema.error_meta(rid, "unavailable", str(exc))))

    async def _request_once(self, scenario: Optional[dict],
                            mode: str, rid: str, timeout: float,
                            tenant: Optional[str] = None) -> dict:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending[rid] = fut
        meta = schema.request_meta(rid, self.reply_to, mode, scenario)
        if tenant is not None:
            meta["tenant"] = tenant
        # one trace per logical request: mint here (when propagation is
        # on) so the publish instant, the transport stamp and the reply
        # all share the id
        tid = obs_trace.new_trace_id() \
            if obs_trace.propagation_enabled() else None
        try:
            with obs_trace.trace_scope(tid):
                tracer = obs_trace.get_tracer()
                if tracer:
                    tracer.instant("client.publish", "serve", id=rid)
                if self._policy is not None:
                    await self._policy.call(
                        self._req_tx.publish, 0.0, _now(), meta=meta,
                        name="ScenarioClient.request")
                else:
                    await self._req_tx.publish(0.0, _now(), meta=meta)
                reply = await asyncio.wait_for(fut, timeout)
                if tracer:
                    tracer.instant("client.reply", "serve", id=rid,
                                   ok=bool(reply.get("ok")))
                return reply
        finally:
            self._pending.pop(rid, None)

    async def batch(self, scenarios: Sequence[Optional[dict]],
                    mode: str = "reduce",
                    timeout: float = 60.0) -> List[dict]:
        """Concurrent requests (one window's worth of company)."""
        return list(await asyncio.gather(*[
            self.request(s, mode=mode, timeout=timeout)
            for s in scenarios]))


async def serve_main(cfg: ServeConfig, *,
                     compile_cache: Optional[str] = None,
                     trace: Optional[str] = None,
                     metrics_path: Optional[str] = None,
                     run_report_path: Optional[str] = None,
                     obs_port: Optional[int] = None,
                     obs_bind: str = "127.0.0.1",
                     install_signals: bool = True) -> None:
    """App orchestrator behind ``pvsim serve``: per-run registry +
    compile cache + flight recorder + run report, around one
    :class:`ScenarioServer` lifetime.  ``obs_port`` (``--obs-port``)
    additionally binds the live ops plane (obs/live.py) — bound BEFORE
    the warm-up compile so ``/readyz`` answers 503 while warming — and
    turns on cross-process trace propagation."""
    from tmhpvsim_tpu.obs.live import maybe_obs_server

    registry = obs_metrics.MetricsRegistry()
    sink = None
    if metrics_path:
        sink = obs_metrics.make_sink(metrics_path)
        registry.add_sink(sink)
    tracer = Tracer() if trace else None
    server = ScenarioServer(cfg, registry=registry, tracer=tracer)
    if obs_port is not None:
        obs_trace.enable_propagation(True)
    async with maybe_obs_server(obs_port, host=obs_bind, registry=registry,
                                tracer=tracer, ready=server.readiness):
        await _serve_main_inner(cfg, server, registry, sink, tracer,
                                compile_cache, trace, run_report_path,
                                install_signals)


async def _serve_main_inner(cfg, server, registry, sink, tracer,
                            compile_cache, trace, run_report_path,
                            install_signals) -> None:
    from tmhpvsim_tpu.engine import compilecache

    with obs_metrics.use_registry(registry):
        if compile_cache is not None:
            compilecache.configure(compile_cache)
        try:
            await server.start()
            if install_signals:
                server.install_signal_handlers()
            await server.serve_forever()
        except asyncio.CancelledError:
            raise  # orderly shutdown: no crash artifact
        except BaseException:
            if tracer:
                # the flight recorder's whole point: the last 30 s of
                # serving timeline survive an unhandled exception
                with contextlib.suppress(Exception):
                    tracer.dump_flight(trace + ".crash.json")
            raise
        finally:
            with contextlib.suppress(Exception):
                await server.stop()
            if tracer:
                with contextlib.suppress(Exception):
                    tracer.export(trace, process_name="pvsim-serve")
            if run_report_path:
                try:
                    from tmhpvsim_tpu.obs.report import RunReport

                    rep = RunReport(
                        "pvsim.serve",
                        config=(server.engine.sim.config
                                if server.engine else cfg.sim),
                        plan=(server.engine.sim.plan
                              if server.engine else None))
                    rep.attach_metrics(registry)
                    rep.write(run_report_path)
                except Exception as err:  # must not mask the outcome
                    logger.warning("run report write failed: %s", err)
            if sink is not None:
                registry.flush(event="end")
                registry.remove_sink(sink)
                with contextlib.suppress(Exception):
                    sink.close()
