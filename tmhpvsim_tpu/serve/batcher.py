"""Micro-batcher: coalesce concurrent scenario requests into one
fused dispatch.

The window protocol: the first pending request OPENS a window; the
batch dispatches when either ``window_s`` elapses or ``max_batch``
requests are pending, whichever comes first.  A lone request therefore
pays at most one window of added latency, and a burst of concurrent
clients rides one dispatch (batch occupancy > 1 — the serving win the
e2e acceptance test asserts).

The dispatch callable runs in a single worker thread: device access is
serialized by construction (one dispatch in flight at a time — exactly
the semantics of one accelerator) while the event loop stays free to
accept and reject traffic.  Results resolve per-request futures; a
future the server already abandoned (request timeout) is skipped, not
an error.

SLO metrics (``serve.*``, obs/metrics.py): ``queue_wait_s`` /
``dispatch_s`` histograms, a ``batch_occupancy`` histogram on dedicated
count buckets plus a last-batch gauge, and ``batches_total``.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextlib
import dataclasses
import logging
from typing import Callable, List, Optional, Sequence

from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs import trace as obs_trace
from tmhpvsim_tpu.runtime import faults
from tmhpvsim_tpu.runtime.resilience import CircuitBreaker
from tmhpvsim_tpu.serve.schema import Request, RequestError

log = logging.getLogger(__name__)

#: occupancy histogram buckets — request counts, not seconds
OCCUPANCY_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0,
                     32.0, 48.0, 64.0)


@dataclasses.dataclass
class _Pending:
    request: Request
    future: asyncio.Future
    t_enq: float  # loop.time() at submit


class MicroBatcher:
    """See module docstring.  ``dispatch(requests) -> results`` is a
    SYNCHRONOUS callable (it owns the device) returning one result per
    request, positionally."""

    _STOP = object()

    def __init__(self, dispatch: Callable[[List[Request]], Sequence],
                 *, window_s: float = 0.010, max_batch: int = 16,
                 queue_limit: int = 1024, registry=None,
                 breaker: Optional[CircuitBreaker] = None,
                 batch_align: int = 1):
        if max_batch < 1:
            raise ValueError(f"max_batch {max_batch} must be >= 1")
        if batch_align < 1:
            raise ValueError(
                f"batch_align {batch_align} must be >= 1")
        self._dispatch = dispatch
        self._window_s = float(window_s)
        self._max_batch = int(max_batch)
        #: soft alignment: at window close, top the batch up to the next
        #: multiple of this from requests ALREADY queued (non-blocking).
        #: On a 2-D (chains, scenario) mesh an aligned batch fills the
        #: scenario shards evenly instead of padding one of them.
        self._batch_align = int(batch_align)
        #: dispatch circuit breaker: consecutive dispatch failures open
        #: it and submit sheds with typed ``unavailable`` until a probe
        #: batch succeeds (None = never shed)
        self.breaker = breaker
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=queue_limit)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serve-dispatch")
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        reg = registry or obs_metrics.get_registry()
        self._c_batches = reg.counter("serve.batches_total")
        self._h_wait = reg.histogram("serve.queue_wait_s")
        self._h_dispatch = reg.histogram("serve.dispatch_s")
        self._h_occupancy = reg.histogram("serve.batch_occupancy",
                                          buckets=OCCUPANCY_BUCKETS)
        self._g_occupancy = reg.gauge("serve.last_batch_occupancy")

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    def submit(self, request: Request) -> asyncio.Future:
        """Enqueue one request; the returned future resolves with its
        result.  Raises a typed ``busy`` rejection when the pending
        queue is full and ``draining`` once the batcher is stopping."""
        if self._closed:
            raise RequestError("draining", "batcher is stopping")
        if self.breaker is not None and self.breaker.state == "open":
            # shed while open; once half-open, requests flow again and
            # the next batch is the probe that closes or re-opens it
            self.breaker.count_rejected()
            raise RequestError(
                "unavailable",
                "dispatch circuit breaker is open; retry with backoff")
        loop = asyncio.get_running_loop()
        pending = _Pending(request, loop.create_future(), loop.time())
        try:
            self._queue.put_nowait(pending)
        except asyncio.QueueFull:
            raise RequestError(
                "busy", f"pending queue full "
                f"({self._queue.maxsize} requests)") from None
        tracer = obs_trace.get_tracer()
        if tracer:  # queue-wait starts here; trace_id rides the context
            tracer.instant("batcher.admit", "serve", rid=request.id)
        return pending.future

    async def stop(self, drain: bool = True,
                   timeout: Optional[float] = None) -> None:
        """Stop the loop.  ``drain=True`` processes everything already
        queued first; ``drain=False`` fails queued requests with a
        typed ``draining`` error.  ``timeout`` bounds the drain: past
        the deadline the loop is force-closed and every request still
        queued fails with a typed ``draining`` rejection instead of
        hanging shutdown on a stuck dispatch."""
        self._closed = True
        if not drain:
            self._fail_queued("server shut down")
        await self._queue.put(self._STOP)
        timed_out = False
        if self._task is not None:
            try:
                if timeout is None:
                    await self._task
                else:
                    await asyncio.wait_for(
                        asyncio.shield(self._task), timeout)
            except asyncio.TimeoutError:
                timed_out = True
                log.warning(
                    "drain deadline (%.1f s) exceeded; force-closing "
                    "with typed 'draining' rejections for %d queued "
                    "request(s)", timeout, self._queue.qsize())
                self._task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await self._task
                self._fail_queued(
                    f"drain deadline ({timeout:g} s) exceeded")
            self._task = None
        # past the deadline a dispatch may still hold the worker thread;
        # waiting would defeat the deadline (the thread parks until the
        # device call returns)
        self._pool.shutdown(wait=not timed_out)

    def _fail_queued(self, why: str) -> None:
        while True:
            try:
                p = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            if p is not self._STOP and not p.future.done():
                p.future.set_exception(RequestError("draining", why))

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            first = await self._queue.get()
            if first is self._STOP:
                return
            batch = [first]
            stop_after = False
            deadline = loop.time() + self._window_s
            while len(batch) < self._max_batch:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                try:
                    nxt = await asyncio.wait_for(self._queue.get(),
                                                 remaining)
                except asyncio.TimeoutError:
                    break
                if nxt is self._STOP:
                    stop_after = True
                    break
                batch.append(nxt)
            # soft alignment: never wait past the window for it, but if
            # requests are already sitting in the queue, take just
            # enough to reach the next multiple of ``batch_align`` (the
            # padding bucket is the same either way, so this is free)
            while (not stop_after and self._batch_align > 1
                   and len(batch) < self._max_batch
                   and len(batch) % self._batch_align != 0):
                try:
                    nxt = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if nxt is self._STOP:
                    stop_after = True
                    break
                batch.append(nxt)
            await self._run_batch(batch, loop)
            if stop_after:
                return

    async def _run_batch(self, batch: List[_Pending], loop) -> None:
        now = loop.time()
        waits = [now - p.t_enq for p in batch]
        for w in waits:
            self._h_wait.observe(w)
        self._h_occupancy.observe(float(len(batch)))
        self._g_occupancy.set(len(batch))
        self._c_batches.inc()
        requests = [p.request for p in batch]
        tracer = obs_trace.get_tracer()
        span = contextlib.nullcontext()
        if tracer:
            # one fused dispatch serves many traces: the span carries
            # ALL of their ids so the stitcher can claim it for each
            tids = [r.trace_id for r in requests if r.trace_id]
            span = tracer.span("batcher.dispatch", "serve",
                               batch=len(batch),
                               **({"trace_ids": tids} if tids else {}))
        t0 = loop.time()
        try:
            with span:
                if faults.ACTIVE is not None:
                    await faults.afire("serve.dispatch")
                results = await loop.run_in_executor(
                    self._pool, self._dispatch, requests)
        except Exception as err:
            if self.breaker is not None:
                self.breaker.record_failure()
            log.exception("scenario dispatch failed (%d requests)",
                          len(batch))
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(
                        RequestError("internal",
                                     f"dispatch failed: {err}"))
            return
        if self.breaker is not None:
            self.breaker.record_success()
        dispatch_s = loop.time() - t0
        self._h_dispatch.observe(dispatch_s)
        if len(results) != len(batch):  # dispatch contract violation
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(RequestError(
                        "internal",
                        f"dispatch returned {len(results)} results "
                        f"for {len(batch)} requests"))
            return
        # resolve as (result, info): the server folds the per-request
        # timings into the reply's "t" section
        for p, r, w in zip(batch, results, waits):
            if not p.future.done():
                p.future.set_result((r, {
                    "batch": len(batch),
                    "queue_s": w,
                    "dispatch_s": dispatch_s,
                }))
