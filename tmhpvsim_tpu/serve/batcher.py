"""Request batchers: coalesce concurrent scenario requests into fused
dispatches.

Two schedulers share one submit/stop front (:class:`_BatcherCore`):

* :class:`MicroBatcher` — the window protocol.  The first pending
  request OPENS a window; the batch dispatches when either ``window_s``
  elapses or ``max_batch`` requests are pending, whichever comes first.
  Every row of a dispatch retires together: the batch pays the blocks
  of its LONGEST horizon, so a short request stuck behind a long one
  waits for blocks it does not need.
* :class:`ContinuousBatcher` — rolling (continuous) batching.  Requests
  occupy slots of ONE fixed-width device batch; each fused dispatch
  advances one block index for every resident row at that cursor, rows
  retire individually the moment their own horizon's blocks are folded,
  and freed slots are backfilled from the queue into the very next
  dispatch instead of waiting for the batch to drain.  Rows not
  scheduled in a dispatch ride along as ``horizon_s=0`` padding — the
  established bit-inert row (``Simulation._block_step_scan_scenario``)
  — so replies stay bit-identical to batch-of-1 runs.  The device-side
  slot protocol lives in :class:`~tmhpvsim_tpu.serve.server
  .RollingSession`; this class only schedules.

Both keep the ``batch_align``/bucket-rounding contract: the window
batcher tops a closing batch up to the next multiple from requests
already queued; the continuous batcher's slot count IS the engine's
aligned bucket, so every dispatch divides the 2-D scenario mesh evenly
by construction.

The dispatch callable runs in a single worker thread: device access is
serialized by construction (one dispatch in flight at a time — exactly
the semantics of one accelerator) while the event loop stays free to
accept and reject traffic.  Results resolve per-request futures; a
future the server already abandoned (request timeout) is skipped, not
an error.

Typed ``busy``/``unavailable`` rejections carry a ``retry_after_ms``
hint derived from the batcher window + queue depth (or the breaker's
remaining reset time), so clients back off by the server's own queue
arithmetic instead of blind jitter.

SLO metrics (``serve.*``, obs/metrics.py): ``queue_wait_s`` /
``dispatch_s`` histograms, a ``batch_occupancy`` histogram on dedicated
count buckets plus a last-batch gauge, and ``batches_total``.  The
continuous scheduler adds ``serve.backfilled_total`` (slots admitted
into an already-rolling batch) and a ``serve.resident_rows`` gauge.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextlib
import dataclasses
import logging
from typing import Callable, Dict, List, Optional, Sequence

from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs import trace as obs_trace
from tmhpvsim_tpu.runtime import faults
from tmhpvsim_tpu.runtime.resilience import CircuitBreaker
from tmhpvsim_tpu.serve.schema import Request, RequestError

log = logging.getLogger(__name__)

#: occupancy histogram buckets — request counts, not seconds
OCCUPANCY_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0,
                     32.0, 48.0, 64.0)

#: dispatches the continuous scheduler may skip the oldest resident
#: row's cursor before it is forced (anti-starvation)
STARVE_LIMIT = 4

#: ceiling on retry_after hints — past this the client should treat the
#: server as down, not slow
MAX_RETRY_AFTER_MS = 60_000


@dataclasses.dataclass
class _Pending:
    request: Request
    future: asyncio.Future
    t_enq: float  # loop.time() at submit


class _BatcherCore:
    """Shared submit/stop front of both schedulers (see module
    docstring).  ``capacity`` is the per-dispatch row budget the
    retry_after arithmetic divides the queue by."""

    _STOP = object()

    def __init__(self, *, window_s: float, capacity: int,
                 queue_limit: int = 1024, registry=None,
                 breaker: Optional[CircuitBreaker] = None):
        if capacity < 1:
            raise ValueError(f"batch capacity {capacity} must be >= 1")
        self._window_s = float(window_s)
        self._capacity = int(capacity)
        #: dispatch circuit breaker: consecutive dispatch failures open
        #: it and submit sheds with typed ``unavailable`` until a probe
        #: batch succeeds (None = never shed)
        self.breaker = breaker
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=queue_limit)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serve-dispatch")
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        #: EWMA of fused-dispatch device seconds (retry_after input)
        self._ewma_dispatch_s: Optional[float] = None
        reg = registry or obs_metrics.get_registry()
        self._c_batches = reg.counter("serve.batches_total")
        self._h_wait = reg.histogram("serve.queue_wait_s")
        self._h_dispatch = reg.histogram("serve.dispatch_s")
        self._h_occupancy = reg.histogram("serve.batch_occupancy",
                                          buckets=OCCUPANCY_BUCKETS)
        self._g_occupancy = reg.gauge("serve.last_batch_occupancy")

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    def retry_after_ms(self) -> int:
        """The honest backoff hint for a shedding rejection: how long
        until the queue ahead of a new request has likely dispatched
        (batches ahead x (window + EWMA dispatch)), or the breaker's
        remaining reset when it is open."""
        if self.breaker is not None and self.breaker.state == "open":
            ms = int(self.breaker.reset_remaining_s() * 1000.0)
            return max(1, min(MAX_RETRY_AFTER_MS, ms))
        per_batch = self._window_s + (self._ewma_dispatch_s
                                      if self._ewma_dispatch_s is not None
                                      else self._window_s)
        batches_ahead = -(-(self._queue.qsize() + 1) // self._capacity)
        ms = int(batches_ahead * per_batch * 1000.0)
        return max(1, min(MAX_RETRY_AFTER_MS, ms))

    def _note_dispatch(self, dispatch_s: float) -> None:
        e = self._ewma_dispatch_s
        self._ewma_dispatch_s = (dispatch_s if e is None
                                 else 0.2 * dispatch_s + 0.8 * e)

    def submit(self, request: Request) -> asyncio.Future:
        """Enqueue one request; the returned future resolves with its
        result.  Raises a typed ``busy`` rejection when the pending
        queue is full and ``draining`` once the batcher is stopping."""
        if self._closed:
            raise RequestError("draining", "batcher is stopping")
        if self.breaker is not None and self.breaker.state == "open":
            # shed while open; once half-open, requests flow again and
            # the next batch is the probe that closes or re-opens it
            self.breaker.count_rejected()
            raise RequestError(
                "unavailable",
                "dispatch circuit breaker is open; retry with backoff",
                retry_after_ms=self.retry_after_ms())
        loop = asyncio.get_running_loop()
        pending = _Pending(request, loop.create_future(), loop.time())
        try:
            self._queue.put_nowait(pending)
        except asyncio.QueueFull:
            raise RequestError(
                "busy", f"pending queue full "
                f"({self._queue.maxsize} requests)",
                retry_after_ms=self.retry_after_ms()) from None
        tracer = obs_trace.get_tracer()
        if tracer:  # queue-wait starts here; trace_id rides the context
            tracer.instant("batcher.admit", "serve", rid=request.id)
        return pending.future

    async def stop(self, drain: bool = True,
                   timeout: Optional[float] = None) -> None:
        """Stop the loop.  ``drain=True`` processes everything already
        queued first; ``drain=False`` fails queued requests with a
        typed ``draining`` error.  ``timeout`` bounds the drain: past
        the deadline the loop is force-closed and every request still
        queued fails with a typed ``draining`` rejection instead of
        hanging shutdown on a stuck dispatch."""
        self._closed = True
        if not drain:
            self._fail_queued("server shut down")
        await self._queue.put(self._STOP)
        timed_out = False
        if self._task is not None:
            try:
                if timeout is None:
                    await self._task
                else:
                    await asyncio.wait_for(
                        asyncio.shield(self._task), timeout)
            except asyncio.TimeoutError:
                timed_out = True
                log.warning(
                    "drain deadline (%.1f s) exceeded; force-closing "
                    "with typed 'draining' rejections for %d queued "
                    "request(s)", timeout, self._queue.qsize())
                self._task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await self._task
                self._fail_queued(
                    f"drain deadline ({timeout:g} s) exceeded")
            self._task = None
        # past the deadline a dispatch may still hold the worker thread;
        # waiting would defeat the deadline (the thread parks until the
        # device call returns)
        self._pool.shutdown(wait=not timed_out)

    def kill(self) -> None:
        """Simulated SIGKILL (chaos tests): drop everything on the
        floor — no drain, no rejections, queued and in-flight futures
        never resolve.  A killed process says nothing."""
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self._pool.shutdown(wait=False)

    def _fail_queued(self, why: str) -> None:
        while True:
            try:
                p = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            if p is not self._STOP and not p.future.done():
                p.future.set_exception(RequestError("draining", why))

    async def _run(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class MicroBatcher(_BatcherCore):
    """The window scheduler (see module docstring).
    ``dispatch(requests) -> results`` is a SYNCHRONOUS callable (it
    owns the device) returning one result per request, positionally."""

    def __init__(self, dispatch: Callable[[List[Request]], Sequence],
                 *, window_s: float = 0.010, max_batch: int = 16,
                 queue_limit: int = 1024, registry=None,
                 breaker: Optional[CircuitBreaker] = None,
                 batch_align: int = 1):
        if max_batch < 1:
            raise ValueError(f"max_batch {max_batch} must be >= 1")
        if batch_align < 1:
            raise ValueError(
                f"batch_align {batch_align} must be >= 1")
        super().__init__(window_s=window_s, capacity=max_batch,
                         queue_limit=queue_limit, registry=registry,
                         breaker=breaker)
        self._dispatch = dispatch
        self._max_batch = int(max_batch)
        #: soft alignment: at window close, top the batch up to the next
        #: multiple of this from requests ALREADY queued (non-blocking).
        #: On a 2-D (chains, scenario) mesh an aligned batch fills the
        #: scenario shards evenly instead of padding one of them.
        self._batch_align = int(batch_align)

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            first = await self._queue.get()
            if first is self._STOP:
                return
            batch = [first]
            stop_after = False
            deadline = loop.time() + self._window_s
            while len(batch) < self._max_batch:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                try:
                    nxt = await asyncio.wait_for(self._queue.get(),
                                                 remaining)
                except asyncio.TimeoutError:
                    break
                if nxt is self._STOP:
                    stop_after = True
                    break
                batch.append(nxt)
            # soft alignment: never wait past the window for it, but if
            # requests are already sitting in the queue, take just
            # enough to reach the next multiple of ``batch_align`` (the
            # padding bucket is the same either way, so this is free)
            while (not stop_after and self._batch_align > 1
                   and len(batch) < self._max_batch
                   and len(batch) % self._batch_align != 0):
                try:
                    nxt = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if nxt is self._STOP:
                    stop_after = True
                    break
                batch.append(nxt)
            await self._run_batch(batch, loop)
            if stop_after:
                return

    async def _run_batch(self, batch: List[_Pending], loop) -> None:
        now = loop.time()
        waits = [now - p.t_enq for p in batch]
        for w in waits:
            self._h_wait.observe(w)
        self._h_occupancy.observe(float(len(batch)))
        self._g_occupancy.set(len(batch))
        self._c_batches.inc()
        requests = [p.request for p in batch]
        tracer = obs_trace.get_tracer()
        span = contextlib.nullcontext()
        if tracer:
            # one fused dispatch serves many traces: the span carries
            # ALL of their ids so the stitcher can claim it for each
            tids = [r.trace_id for r in requests if r.trace_id]
            span = tracer.span("batcher.dispatch", "serve",
                               batch=len(batch),
                               **({"trace_ids": tids} if tids else {}))
        t0 = loop.time()
        try:
            with span:
                if faults.ACTIVE is not None:
                    await faults.afire("serve.dispatch")
                results = await loop.run_in_executor(
                    self._pool, self._dispatch, requests)
        except Exception as err:
            if self.breaker is not None:
                self.breaker.record_failure()
            log.exception("scenario dispatch failed (%d requests)",
                          len(batch))
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(
                        RequestError("internal",
                                     f"dispatch failed: {err}"))
            return
        if self.breaker is not None:
            self.breaker.record_success()
        dispatch_s = loop.time() - t0
        self._h_dispatch.observe(dispatch_s)
        self._note_dispatch(dispatch_s)
        if len(results) != len(batch):  # dispatch contract violation
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(RequestError(
                        "internal",
                        f"dispatch returned {len(results)} results "
                        f"for {len(batch)} requests"))
            return
        # resolve as (result, info): the server folds the per-request
        # timings into the reply's "t" section
        for p, r, w in zip(batch, results, waits):
            if not p.future.done():
                p.future.set_result((r, {
                    "batch": len(batch),
                    "queue_s": w,
                    "dispatch_s": dispatch_s,
                }))


class ContinuousBatcher(_BatcherCore):
    """The rolling scheduler (see module docstring).  ``session`` is a
    :class:`~tmhpvsim_tpu.serve.server.RollingSession`: ``bucket`` slots
    wide, with synchronous ``admit_rows`` / ``step_finish`` /
    ``recover`` methods that run on the single dispatch thread.

    Scheduling policy: each iteration backfills free slots from the
    queue (non-blocking), then dispatches the block cursor shared by
    the MOST resident rows (ties prefer the cursor closest to
    retirement, so slots free sooner).  A cursor skipped
    :data:`STARVE_LIMIT` times in a row while the oldest resident row
    waits at it is forced — no horizon mix can park a row forever.
    The window only applies while the batch is EMPTY (first fill):
    waiting for company while resident rows are runnable would stall
    them for nothing.
    """

    def __init__(self, session, *, window_s: float = 0.010,
                 queue_limit: int = 1024, registry=None,
                 breaker: Optional[CircuitBreaker] = None,
                 starve_limit: int = STARVE_LIMIT):
        super().__init__(window_s=window_s, capacity=session.bucket,
                         queue_limit=queue_limit, registry=registry,
                         breaker=breaker)
        self._session = session
        self._starve_limit = int(starve_limit)
        reg = registry or obs_metrics.get_registry()
        self._c_backfilled = reg.counter("serve.backfilled_total")
        self._g_resident = reg.gauge("serve.resident_rows")

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        s = self._session
        bucket = s.bucket
        free = list(range(bucket - 1, -1, -1))
        occupied: Dict[int, _Pending] = {}
        cursors: Dict[int, int] = {}
        need: Dict[int, int] = {}
        waits: Dict[int, float] = {}
        admit_at: Dict[int, float] = {}
        closing = False
        starve = 0
        while True:
            # ---- gather admissions -------------------------------------
            pend: List[_Pending] = []
            if not occupied:
                if closing:
                    return
                first = await self._queue.get()
                if first is self._STOP:
                    return
                pend.append(first)
                # the window protocol, empty-batch case only: a lone
                # request waits at most one window for company
                deadline = loop.time() + self._window_s
                while len(pend) < bucket and not closing:
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        break
                    try:
                        nxt = await asyncio.wait_for(self._queue.get(),
                                                     remaining)
                    except asyncio.TimeoutError:
                        break
                    if nxt is self._STOP:
                        closing = True
                        break
                    pend.append(nxt)
            else:
                # rolling: backfill free slots from the queue into the
                # very next dispatch, never waiting (resident rows are
                # runnable NOW)
                while len(pend) < len(free) and not closing:
                    try:
                        nxt = self._queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if nxt is self._STOP:
                        closing = True
                        break
                    pend.append(nxt)
                if pend:
                    self._c_backfilled.inc(len(pend))
            # ---- admit into slots --------------------------------------
            admits = []
            now = loop.time()
            for p in pend:
                if p.future.done():  # abandoned while queued
                    continue
                slot = free.pop()
                occupied[slot] = p
                cursors[slot] = 0
                need[slot] = s.blocks_for(p.request)
                waits[slot] = now - p.t_enq
                admit_at[slot] = now
                self._h_wait.observe(waits[slot])
                admits.append((slot, p.request))
            if admits:
                try:
                    await loop.run_in_executor(
                        self._pool, s.admit_rows, admits)
                except Exception as err:
                    await self._fail_resident(
                        occupied, cursors, need, waits, admit_at, free,
                        err)
                    continue
            self._g_resident.set(len(occupied))
            if not occupied:
                if closing:
                    return
                continue
            # ---- pick the cursor to advance ----------------------------
            counts: Dict[int, int] = {}
            for c in cursors.values():
                counts[c] = counts.get(c, 0) + 1
            bi = max(counts, key=lambda c: (counts[c], c))
            oldest = min(occupied, key=lambda sl: admit_at[sl])
            if starve >= self._starve_limit:
                bi = cursors[oldest]
            starve = 0 if cursors[oldest] == bi else starve + 1
            sched = sorted(sl for sl, c in cursors.items() if c == bi)
            retiring = [sl for sl in sched if cursors[sl] + 1 >= need[sl]]
            # ---- fused dispatch of block ``bi`` ------------------------
            self._h_occupancy.observe(float(len(sched)))
            self._g_occupancy.set(len(sched))
            self._c_batches.inc()
            tracer = obs_trace.get_tracer()
            span = contextlib.nullcontext()
            if tracer:
                tids = [occupied[sl].request.trace_id for sl in sched
                        if occupied[sl].request.trace_id]
                span = tracer.span(
                    "batcher.block", "serve", block=bi,
                    batch=len(sched), retiring=len(retiring),
                    **({"trace_ids": tids} if tids else {}))
            t0 = loop.time()
            try:
                with span:
                    if faults.ACTIVE is not None:
                        await faults.afire("serve.dispatch")
                    results = await loop.run_in_executor(
                        self._pool, s.step_finish, bi, sched, retiring)
            except Exception as err:
                if self.breaker is not None:
                    self.breaker.record_failure()
                log.exception(
                    "continuous dispatch failed (block %d, %d rows)",
                    bi, len(sched))
                await self._fail_resident(
                    occupied, cursors, need, waits, admit_at, free, err)
                continue
            if self.breaker is not None:
                self.breaker.record_success()
            dispatch_s = loop.time() - t0
            self._h_dispatch.observe(dispatch_s)
            self._note_dispatch(dispatch_s)
            # ---- advance & retire --------------------------------------
            for sl in sched:
                cursors[sl] += 1
            for sl, result in results.items():
                p = occupied.pop(sl)
                blocks = need.pop(sl)
                cursors.pop(sl)
                w = waits.pop(sl)
                admit_at.pop(sl)
                free.append(sl)
                if not p.future.done():
                    p.future.set_result((result, {
                        "batch": len(sched),
                        "queue_s": w,
                        "dispatch_s": dispatch_s,
                        "blocks": blocks,
                    }))
            self._g_resident.set(len(occupied))

    async def _fail_resident(self, occupied, cursors, need, waits,
                             admit_at, free, err) -> None:
        """A failed fused dispatch poisons the shared accumulator
        (donated buffers), so every resident row fails typed
        ``internal`` and the session recovers a fresh accumulator.
        Queued (not yet admitted) requests are untouched."""
        loop = asyncio.get_running_loop()
        for sl, p in list(occupied.items()):
            if not p.future.done():
                p.future.set_exception(
                    RequestError("internal", f"dispatch failed: {err}"))
        free.extend(sorted(occupied))
        occupied.clear()
        cursors.clear()
        need.clear()
        waits.clear()
        admit_at.clear()
        self._g_resident.set(0)
        with contextlib.suppress(Exception):
            await loop.run_in_executor(self._pool, self._session.recover)
