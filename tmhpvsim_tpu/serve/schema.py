"""Request/reply wire schema of the scenario-serving runtime.

Requests and replies ride the brokers' OUT-OF-BAND metadata channel
(``Message.meta`` / AMQP headers / the tcp wire's ``"m"`` key) so the
JSON-float body contract of the fanout exchanges is untouched —
reference-shaped consumers sharing a broker never see a non-float body.

Request meta (on the server's request exchange)::

    {"op": "scenario", "id": "<1..64 chars>", "reply_to": "<exchange>",
     "mode": "reduce" | "quantiles" | "fleet",     # default "reduce"
     "scenario": {                                 # all knobs optional
        "demand_scale":     float in [0, 8],       # default 1
        "demand_shift_w":   float in [-1e7, 1e7],  # default 0
        "dc_capacity_scale":float in [0, 8],       # default 1
        "weather_bias":     float in [0.25, 4],    # default 1
        "curtail_w":        float >= 0 or null,    # default null (no cap)
        "horizon_s":        int in [1, server max] # default server max
        "site_index":       int in [0, n_sites),   # default -1 (all sites)
        "cohort":           int in [0, n_cohorts)  # default -1 (all cohorts)
     }}

The two **site selectors** bound a what-if to one installation
(``site_index``, a chain-axis index into the served fleet) or to one
cohort tag (``cohort``, against the fleet's dense cohort-id space).
They are mutually exclusive, and each is only accepted when the served
config can answer it: ``site_index`` needs a multi-site run
(``n_sites`` known), ``cohort`` a heterogeneous fleet with >1 cohort.
A selected reply folds exactly the chains the selector names — bit
identical to running the equivalent single-site config on its own.

Reply meta (on ``reply_to``)::

    {"op": "scenario-reply", "id": ..., "ok": true,
     "mode": ..., "result": {...}, "t": {queue/dispatch/batch timings}}
    {"op": "scenario-reply", "id": ..., "ok": false,
     "error": {"code": "<ERROR_CODES>", "message": ...,
               "retry_after_ms": <optional int: busy/unavailable hint>}}

Validation is strict — unknown scenario knobs, non-finite values and
out-of-bounds values are typed ``invalid`` rejections, never silently
clamped: a serving fleet must not quietly answer a different question
than the one asked.

:func:`encode_batch` turns validated :class:`Scenario` rows into the
(batch,)-leaf knob pytree ``Simulation.scenario_step`` consumes
(``engine.simulation.SCENARIO_FLOAT_KNOBS`` + int32 ``horizon_s``);
the request-side ``dc_capacity_scale`` maps to the engine leaf
``pv_scale``, and a null curtailment cap encodes as the compute dtype's
finfo.max so ``min(pv, cap)`` is the identity.  Padding rows carry
``horizon_s=0`` and fold nothing.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import numpy as np

OP_REQUEST = "scenario"
OP_REPLY = "scenario-reply"

MODES = ("reduce", "quantiles", "fleet")

#: typed rejection codes a reply's ``error.code`` may carry
#: (``unavailable`` = the dispatch circuit breaker is open: the server
#: is shedding load until its probe succeeds — retry with backoff)
ERROR_CODES = ("invalid", "duplicate", "busy", "draining", "timeout",
               "internal", "unavailable")

#: request-side knob bounds: name -> (lo, hi, default).  Scales are
#: capped at 8x (a fleet scenario, not a numerics stress test) and the
#: weather-regime bias at [0.25, 4] so the perturbed pv stays within
#: the analytics sketch's dynamic range.
KNOB_BOUNDS = {
    "demand_scale": (0.0, 8.0, 1.0),
    "demand_shift_w": (-1e7, 1e7, 0.0),
    "dc_capacity_scale": (0.0, 8.0, 1.0),
    "weather_bias": (0.25, 4.0, 1.0),
}

_MAX_ID_LEN = 64
_MAX_EXCHANGE_LEN = 128


class RequestError(ValueError):
    """A typed request rejection: ``code`` is one of :data:`ERROR_CODES`
    and lands verbatim in the error reply.

    ``retry_after_ms`` (busy/unavailable rejections) is the server's
    load-derived hint for when a retry is worth sending — batcher
    window + queue depth, or the breaker's remaining reset time.  It
    rides the error reply and feeds ``ResiliencePolicy``'s backoff via
    the ``retry_after_s`` attribute hint instead of blind jitter.
    """

    def __init__(self, code: str, message: str,
                 retry_after_ms: Optional[int] = None):
        assert code in ERROR_CODES, code
        super().__init__(message)
        self.code = code
        self.retry_after_ms = (None if retry_after_ms is None
                               else max(0, int(retry_after_ms)))

    @property
    def retry_after_s(self) -> Optional[float]:
        if self.retry_after_ms is None:
            return None
        return self.retry_after_ms / 1000.0


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One validated scenario: the knob values a request perturbs.

    ``horizon_s=0`` marks a batch padding row — it folds nothing, so
    its presence never changes another row's answer.
    """

    demand_scale: float = 1.0
    demand_shift_w: float = 0.0
    dc_capacity_scale: float = 1.0
    weather_bias: float = 1.0
    curtail_w: Optional[float] = None
    horizon_s: int = 0
    #: chain-axis index to restrict the fold to (-1 = whole fleet)
    site_index: int = -1
    #: cohort tag to restrict the fold to (-1 = every cohort)
    cohort: int = -1


@dataclasses.dataclass(frozen=True)
class Request:
    """One validated scenario request.

    ``trace_id``/``span_id`` are the optional W3C-traceparent-style
    propagation ids (obs/trace.py): stamped by the client's transport
    when the live ops plane is on, echoed in the reply so one id
    correlates client → broker → batcher → dispatch → reply.  Absent ids
    parse as None — tracing is never a validity condition.
    """

    id: str
    reply_to: str
    mode: str
    scenario: Scenario
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    #: admission-control tenant tag (router token-bucket quotas);
    #: absent parses as None and the request draws the default quota
    tenant: Optional[str] = None


def _check_float(name: str, v, lo: float, hi: float) -> float:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise RequestError("invalid",
                           f"scenario.{name}: expected a number, "
                           f"got {type(v).__name__}")
    v = float(v)
    if not math.isfinite(v):
        raise RequestError("invalid", f"scenario.{name}: must be finite")
    if not (lo <= v <= hi):
        raise RequestError(
            "invalid", f"scenario.{name}={v:g} outside [{lo:g}, {hi:g}]")
    return v


def parse_scenario(doc, *, max_horizon_s: int,
                   n_sites: Optional[int] = None,
                   n_cohorts: int = 0) -> Scenario:
    """Validate one request's ``scenario`` value (may be None/absent:
    every knob has a neutral default and the horizon defaults to the
    server's maximum).  ``n_sites``/``n_cohorts`` bound the site
    selectors; a selector the served config cannot answer is a typed
    ``invalid`` rejection, never a silent whole-fleet answer."""
    if doc is None:
        doc = {}
    if not isinstance(doc, dict):
        raise RequestError("invalid",
                           f"scenario: expected an object, "
                           f"got {type(doc).__name__}")
    known = set(KNOB_BOUNDS) | {"curtail_w", "horizon_s",
                                "site_index", "cohort"}
    unknown = sorted(set(doc) - known)
    if unknown:
        raise RequestError(
            "invalid", f"scenario: unknown knob(s) {', '.join(unknown)} "
            f"(known: {', '.join(sorted(known))})")
    kw = {}
    for name, (lo, hi, default) in KNOB_BOUNDS.items():
        kw[name] = (_check_float(name, doc[name], lo, hi)
                    if name in doc else default)
    cap = doc.get("curtail_w")
    if cap is not None:
        cap = _check_float("curtail_w", cap, 0.0, float("inf"))
        if math.isinf(cap):  # pragma: no cover - isfinite already rejects
            cap = None
    kw["curtail_w"] = cap
    h = doc.get("horizon_s", max_horizon_s)
    if isinstance(h, bool) or not isinstance(h, int):
        raise RequestError("invalid",
                           "scenario.horizon_s: expected an integer")
    if not (1 <= h <= max_horizon_s):
        raise RequestError(
            "invalid",
            f"scenario.horizon_s={h} outside [1, {max_horizon_s}]")
    kw["horizon_s"] = h

    def _selector(name, limit, what):
        v = doc.get(name, -1)
        if isinstance(v, bool) or not isinstance(v, int):
            raise RequestError("invalid",
                               f"scenario.{name}: expected an integer")
        if v == -1:
            return -1
        if limit is None or limit <= 0:
            raise RequestError(
                "invalid",
                f"scenario.{name}: the served config has no {what}")
        if not 0 <= v < limit:
            raise RequestError(
                "invalid",
                f"scenario.{name}={v} outside [0, {limit})")
        return v

    kw["site_index"] = _selector("site_index", n_sites, "site axis")
    kw["cohort"] = _selector("cohort", n_cohorts or None, "cohort tags")
    if kw["site_index"] >= 0 and kw["cohort"] >= 0:
        raise RequestError(
            "invalid",
            "scenario: site_index and cohort are mutually exclusive")
    return Scenario(**kw)


def parse_request(meta, *, max_horizon_s: int,
                  n_sites: Optional[int] = None,
                  n_cohorts: int = 0) -> Request:
    """Validate one request meta dict (``op`` already checked by the
    caller's traffic filter).  Raises :class:`RequestError` with code
    ``invalid`` on any malformation."""
    if not isinstance(meta, dict):
        raise RequestError("invalid", "request meta must be an object")
    rid = meta.get("id")
    if not isinstance(rid, str) or not 1 <= len(rid) <= _MAX_ID_LEN:
        raise RequestError(
            "invalid", f"id: expected a 1..{_MAX_ID_LEN} char string")
    reply_to = meta.get("reply_to")
    if not isinstance(reply_to, str) or \
            not 1 <= len(reply_to) <= _MAX_EXCHANGE_LEN:
        raise RequestError(
            "invalid",
            f"reply_to: expected a 1..{_MAX_EXCHANGE_LEN} char "
            "exchange name")
    mode = meta.get("mode", "reduce")
    if mode not in MODES:
        raise RequestError(
            "invalid", f"mode {mode!r} not one of {', '.join(MODES)}")
    # "tenant" is the admission-control tag; "worker" is the router's
    # chosen-worker stamp (trace stitching) — both ride through workers
    unknown = sorted(set(meta) - {"op", "id", "reply_to", "mode",
                                  "scenario", "trace_id", "span_id",
                                  "tenant", "worker"})
    if unknown:
        raise RequestError(
            "invalid", f"unknown request field(s) {', '.join(unknown)}")
    tenant = meta.get("tenant")
    if tenant is not None and (not isinstance(tenant, str)
                               or not 1 <= len(tenant) <= _MAX_ID_LEN):
        raise RequestError(
            "invalid",
            f"tenant: expected a 1..{_MAX_ID_LEN} char string")
    scenario = parse_scenario(meta.get("scenario"),
                              max_horizon_s=max_horizon_s,
                              n_sites=n_sites, n_cohorts=n_cohorts)
    tid, sid = meta.get("trace_id"), meta.get("span_id")
    return Request(
        id=rid, reply_to=reply_to, mode=mode, scenario=scenario,
        trace_id=tid if isinstance(tid, str) and tid else None,
        span_id=sid if isinstance(sid, str) and sid else None,
        tenant=tenant)


def request_meta(rid: str, reply_to: str, mode: str = "reduce",
                 scenario: Optional[dict] = None) -> dict:
    """The client-side request meta (what :func:`parse_request` reads)."""
    meta = {"op": OP_REQUEST, "id": rid, "reply_to": reply_to,
            "mode": mode}
    if scenario is not None:
        meta["scenario"] = scenario
    return meta


def ok_meta(rid: str, mode: str, result: dict,
            timings: Optional[dict] = None,
            trace_id: Optional[str] = None) -> dict:
    meta = {"op": OP_REPLY, "id": rid, "ok": True, "mode": mode,
            "result": result}
    if timings:
        meta["t"] = timings
    if trace_id:  # echo the request's trace so the reply joins its trace
        meta["trace_id"] = trace_id
    return meta


def error_meta(rid: Optional[str], code: str, message: str,
               trace_id: Optional[str] = None,
               retry_after_ms: Optional[int] = None) -> dict:
    assert code in ERROR_CODES, code
    err = {"code": code, "message": message}
    if retry_after_ms is not None:
        err["retry_after_ms"] = max(0, int(retry_after_ms))
    meta = {"op": OP_REPLY, "id": rid, "ok": False, "error": err}
    if trace_id:
        meta["trace_id"] = trace_id
    return meta


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest configured batch bucket that fits ``n`` requests —
    the compiled-executable set stays finite (one shape per bucket)."""
    fits = [b for b in buckets if b >= n]
    if not fits:
        raise ValueError(
            f"batch of {n} exceeds largest bucket {max(buckets)}")
    return min(fits)


def encode_batch(scenarios: Sequence[Scenario], batch: int,
                 dtype) -> dict:
    """Validated scenarios -> the (batch,)-leaf knob pytree of
    ``Simulation.scenario_step`` (host numpy; rows past
    ``len(scenarios)`` are horizon-0 padding)."""
    if len(scenarios) > batch:
        raise ValueError(f"{len(scenarios)} scenarios > batch {batch}")
    dt = np.dtype(dtype)
    no_cap = float(np.finfo(dt).max)
    pad = batch - len(scenarios)

    def col(vals, fill):
        return np.asarray(list(vals) + [fill] * pad, dt)

    return {
        "demand_scale": col((s.demand_scale for s in scenarios), 1.0),
        "demand_shift_w": col((s.demand_shift_w for s in scenarios), 0.0),
        "pv_scale": col((s.dc_capacity_scale for s in scenarios), 1.0),
        "weather_bias": col((s.weather_bias for s in scenarios), 1.0),
        "curtail_w": col((no_cap if s.curtail_w is None else s.curtail_w
                          for s in scenarios), no_cap),
        "horizon_s": np.asarray(
            [s.horizon_s for s in scenarios] + [0] * pad, np.int32),
        "site_index": np.asarray(
            [s.site_index for s in scenarios] + [-1] * pad, np.int32),
        "cohort": np.asarray(
            [s.cohort for s in scenarios] + [-1] * pad, np.int32),
    }
