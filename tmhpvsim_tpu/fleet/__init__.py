"""Heterogeneous fleet subsystem: per-site parameters as a first-class
batched pytree on the chain axis (see fleet/params.py)."""

from tmhpvsim_tpu.fleet.params import (  # noqa: F401
    COLUMN_RANGES,
    N_REGIMES,
    NO_AC_LIMIT,
    FleetParams,
    check_range,
    slice_fleet,
)
