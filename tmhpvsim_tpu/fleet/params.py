"""Per-site fleet parameters: the batched pytree on the chain axis.

``SiteGrid`` (config.py) made *geometry* per-chain; everything else —
DC capacity, inverter limit, cloud climate, demand profile — stayed a
global scalar, which is the gap between "one site, many Monte-Carlo
replicas" and "millions of distinct installations".  :class:`FleetParams`
closes it: one row per site, chain i simulates site i, and the
heterogeneous columns ride the simulation as ``state["fleet"]`` leaves
of shape (n_chains,) — exactly like ``state["site"]`` — so sharding,
chain slabs, checkpoints and the scenario batch path all carry them
with zero extra plumbing.

Broadcast rules (the HLO-identity contract, tested in
tests/test_fleet.py):

* a column left at its neutral value (capacity scale 1, no AC limit,
  regime 0, demand scale 1 / shift 0) contributes NO state leaf and NO
  per-second transform — the engine's host-side gating compiles the
  exact program a no-fleet config compiles;
* a homogeneous fleet (every row equal, all columns neutral) therefore
  lowers to byte-identical HLO vs the scalar ``Site`` path;
* a heterogeneous column becomes one (n_chains,) leaf consumed inside
  the per-chain body (wide impl) or bound as a block-setup vector
  (scan family) — one multiply/add/min per second per active column.

Per-second transforms (engine/simulation.py):

* demand:  ``meter_i = meter_i * demand_scale_i + demand_shift_w_i``
* power:   ``pv_i    = min(pv_i * dc_capacity_scale_i, ac_limit_w_i)``
* weather: the hourly Markov step draws from the regime table
  ``weather_regime_i`` selects (data/parameters.py
  ``MARKOV_STEP_PARAMS_REGIMES``; regime 0 is the vendored Munich fit,
  byte-identical rows).

``cohort`` is a small-integer site-class tag (tariff group, DSO area,
hardware generation ...) consumed by the per-cohort group-by reductions
in obs/analytics.py and by the serve site-selector; it never changes
the simulated physics.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional

import numpy as np

from tmhpvsim_tpu.config import Site, SiteGrid
from tmhpvsim_tpu.data import (LINKE_TURBIDITY_MONTHLY_MUNICH,
                               MARKOV_STEP_PARAMS_REGIMES)

#: validation ranges, shared with ``SiteGrid.from_csv``: column ->
#: (lo, hi), inclusive.  Out-of-range rows are configuration errors a
#: fleet build must refuse by line, never propagate into the geometry
#: chain as NaN/garbage.
COLUMN_RANGES = {
    "latitude": (-90.0, 90.0),
    "longitude": (-180.0, 180.0),
    "altitude": (-430.0, 9000.0),       # Dead Sea shore .. above Everest BC
    "surface_tilt": (0.0, 90.0),
    "surface_azimuth": (0.0, 360.0),
    "albedo": (0.0, 1.0),
    "dc_capacity_scale": (0.0, 1e6),
    "ac_limit_w": (0.0, float("inf")),
    "demand_scale": (0.0, 1e6),
    "demand_shift_w": (-1e9, 1e9),
}

#: number of vendored weather-regime step tables
N_REGIMES = len(MARKOV_STEP_PARAMS_REGIMES)

#: columns ``FleetParams.from_csv`` reads beyond the SiteGrid geometry set
_FLEET_CSV_COLUMNS = frozenset(COLUMN_RANGES) | {"weather_regime", "cohort"}

#: the no-AC-limit sentinel (encodes as the compute dtype's finfo.max on
#: device, so ``min(pv, limit)`` is the identity for unlimited rows)
NO_AC_LIMIT = float("inf")


def check_range(name: str, value: float, *, where: str = "") -> None:
    """Raise ValueError when ``value`` falls outside ``name``'s range
    (or is non-finite for a bounded column); ``where`` prefixes the
    message (e.g. ``"fleet.csv line 7: "``)."""
    rng = COLUMN_RANGES.get(name)
    if rng is None:
        return
    lo, hi = rng
    ok = lo <= value <= hi if np.isfinite(value) else (
        name == "ac_limit_w" and value > 0)
    if not ok:
        raise ValueError(
            f"{where}{name}={value!r} outside [{lo:g}, {hi:g}]")


@dataclasses.dataclass(frozen=True)
class FleetParams:
    """One row per installation; every per-site field is a length-n
    sequence.  Geometry columns mirror ``SiteGrid``; the electrical /
    stochastic columns default to their neutral values (see module
    docstring for what "neutral" buys).  The timezone and turbidity
    climatology are shared across the fleet, like ``SiteGrid``.
    """

    latitude: tuple
    longitude: tuple
    altitude: tuple = None
    surface_tilt: tuple = None
    surface_azimuth: tuple = None
    albedo: tuple = None
    #: DC nameplate relative to the reference module string (1.0 = the
    #: vendored 250 W class)
    dc_capacity_scale: tuple = None
    #: inverter AC clip [W]; ``inf`` = no clip (the neutral value)
    ac_limit_w: tuple = None
    #: index into data/parameters.py MARKOV_STEP_PARAMS_REGIMES
    weather_regime: tuple = None
    #: demand profile affine map applied to the uniform meter draw
    demand_scale: tuple = None
    demand_shift_w: tuple = None
    #: site-class tag for group-by analytics / the serve selector
    cohort: tuple = None
    timezone: str = "Europe/Berlin"
    linke_turbidity_monthly: tuple = LINKE_TURBIDITY_MONTHLY_MUNICH
    #: cohort-id space of the NOTIONAL fleet: set by ``slice_fleet`` so a
    #: chain slab / autotune probe containing only low-numbered cohorts
    #: still folds into full-width (n_cohorts,) accumulator leaves —
    #: slab merges need equal shapes.  None = ``max(cohort) + 1``.
    n_cohorts_hint: Optional[int] = None

    def __post_init__(self):
        n = len(self.latitude)
        if n == 0:
            raise ValueError("FleetParams needs at least one site")
        defaults = {
            "altitude": 100.0,
            "surface_tilt": None,        # -> latitude (tilt-equals-latitude)
            "surface_azimuth": 180.0,
            "albedo": 0.25,
            "dc_capacity_scale": 1.0,
            "ac_limit_w": NO_AC_LIMIT,
            "weather_regime": 0,
            "demand_scale": 1.0,
            "demand_shift_w": 0.0,
            "cohort": 0,
        }
        for f, dflt in defaults.items():
            v = getattr(self, f)
            if v is None:
                if f == "surface_tilt":
                    v = tuple(self.latitude)
                else:
                    v = (dflt,) * n
                object.__setattr__(self, f, v)
            elif len(v) != n:
                raise ValueError(f"FleetParams.{f} must have length {n}")
        for i, (r, c) in enumerate(zip(self.weather_regime, self.cohort)):
            if not 0 <= int(r) < N_REGIMES:
                raise ValueError(
                    f"FleetParams.weather_regime[{i}]={r!r} outside "
                    f"[0, {N_REGIMES})")
            if int(c) < 0:
                raise ValueError(
                    f"FleetParams.cohort[{i}]={c!r} must be >= 0")
        for name in COLUMN_RANGES:
            for i, v in enumerate(getattr(self, name)):
                check_range(name, float(v),
                            where=f"FleetParams.{name}[{i}]: ")

    def __len__(self):
        return len(self.latitude)

    # -- derived views ---------------------------------------------------

    @property
    def n_cohorts(self) -> int:
        """Cohort-id space size: ``max(cohort) + 1`` (dense small ints),
        or the notional fleet's width when this is a slice."""
        n = int(max(self.cohort)) + 1
        return max(n, self.n_cohorts_hint or 0)

    @property
    def het_demand(self) -> bool:
        """Any row's demand transform differs from the identity."""
        return any(s != 1.0 for s in self.demand_scale) or \
            any(s != 0.0 for s in self.demand_shift_w)

    @property
    def het_power(self) -> bool:
        """Any row's power transform differs from the identity."""
        return any(s != 1.0 for s in self.dc_capacity_scale) or \
            any(np.isfinite(v) for v in self.ac_limit_w)

    @property
    def het_regime(self) -> bool:
        """Any row draws from a non-default weather-regime table."""
        return any(int(r) != 0 for r in self.weather_regime)

    @property
    def uniform_geometry(self) -> bool:
        """Every site shares one geometry row — the fleet lowers onto
        the scalar ``Site`` path instead of a per-chain grid."""
        return all(
            len(set(getattr(self, f))) == 1
            for f in ("latitude", "longitude", "altitude", "surface_tilt",
                      "surface_azimuth", "albedo")
        )

    def site_grid(self) -> SiteGrid:
        """The geometry columns as a ``SiteGrid`` (the engine derives
        this when the fleet's geometry is non-uniform)."""
        return SiteGrid(
            latitude=tuple(self.latitude),
            longitude=tuple(self.longitude),
            altitude=tuple(self.altitude),
            surface_tilt=tuple(self.surface_tilt),
            surface_azimuth=tuple(self.surface_azimuth),
            albedo=tuple(self.albedo),
            timezone=self.timezone,
            linke_turbidity_monthly=self.linke_turbidity_monthly,
        )

    def uniform_site(self) -> Site:
        """Row 0 as a scalar ``Site`` (valid when ``uniform_geometry``)."""
        return Site(
            latitude=float(self.latitude[0]),
            longitude=float(self.longitude[0]),
            altitude=float(self.altitude[0]),
            surface_tilt=float(self.surface_tilt[0]),
            surface_azimuth=float(self.surface_azimuth[0]),
            albedo=float(self.albedo[0]),
            timezone=self.timezone,
            linke_turbidity_monthly=self.linke_turbidity_monthly,
        )

    def digest(self) -> str:
        """Stable content hash of every parameter row — the fleet's
        identity in the checkpoint config echo and the autotune plan
        key.  Two fleets with equal rows digest equal regardless of how
        they were built (CSV, synthetic, literal)."""
        doc = {f.name: getattr(self, f.name)
               for f in dataclasses.fields(self)}
        blob = json.dumps(doc, sort_keys=True, separators=(",", ":"),
                          default=float)
        return hashlib.sha256(blob.encode()).hexdigest()

    # -- builders --------------------------------------------------------

    @classmethod
    def from_site_grid(cls, grid: SiteGrid, **kw) -> "FleetParams":
        """A fleet with the grid's geometry and neutral electrical /
        stochastic columns (override any via ``kw``)."""
        return cls(
            latitude=tuple(grid.latitude),
            longitude=tuple(grid.longitude),
            altitude=tuple(grid.altitude),
            surface_tilt=tuple(grid.surface_tilt),
            surface_azimuth=tuple(grid.surface_azimuth),
            albedo=tuple(grid.albedo),
            timezone=grid.timezone,
            linke_turbidity_monthly=grid.linke_turbidity_monthly,
            **kw,
        )

    @classmethod
    def from_csv(cls, path: str, **kw) -> "FleetParams":
        """A fleet from an asset-register CSV with header.  Required
        columns ``latitude``, ``longitude``; every other per-site column
        is optional with its neutral default (``surface_tilt`` defaults
        to the row's latitude; blank ``ac_limit_w`` cells mean no clip).
        Extra columns are ignored.  Out-of-range and unparsable values
        are refused with the offending CSV line number."""
        import csv as _csv

        rows = []
        with open(path, newline="") as f:
            reader = _csv.DictReader(f)
            cols = set(reader.fieldnames or ()) & _FLEET_CSV_COLUMNS
            missing = {"latitude", "longitude"} - cols
            if missing:
                raise ValueError(
                    f"{path}: missing required column(s) {sorted(missing)}")
            for row in reader:
                vals = {}
                for k in cols:
                    v = row.get(k)
                    if v is None or v == "":   # ragged row / blank cell
                        continue
                    try:
                        vals[k] = int(v) if k in ("weather_regime",
                                                  "cohort") else float(v)
                    except ValueError:
                        raise ValueError(
                            f"{path} line {reader.line_num}: bad value "
                            f"{v!r} for {k}") from None
                    if k == "weather_regime" and \
                            not 0 <= vals[k] < N_REGIMES:
                        raise ValueError(
                            f"{path} line {reader.line_num}: "
                            f"weather_regime={vals[k]} outside "
                            f"[0, {N_REGIMES})")
                    if k == "cohort" and vals[k] < 0:
                        raise ValueError(
                            f"{path} line {reader.line_num}: "
                            f"cohort={vals[k]} must be >= 0")
                    check_range(k, float(vals[k]),
                                where=f"{path} line {reader.line_num}: ")
                if "latitude" not in vals or "longitude" not in vals:
                    raise ValueError(
                        f"{path} line {reader.line_num}: latitude and "
                        "longitude are required in every row")
                rows.append(vals)
        if not rows:
            raise ValueError(f"{path}: no data rows")

        def col(name, default=None):
            return tuple(
                r.get(name, r["latitude"] if default == "latitude"
                      else default) for r in rows)

        return cls(
            latitude=col("latitude"),
            longitude=col("longitude"),
            altitude=col("altitude", 100.0),
            surface_tilt=col("surface_tilt", "latitude"),
            surface_azimuth=col("surface_azimuth", 180.0),
            albedo=col("albedo", 0.25),
            dc_capacity_scale=col("dc_capacity_scale", 1.0),
            ac_limit_w=col("ac_limit_w", NO_AC_LIMIT),
            weather_regime=col("weather_regime", 0),
            demand_scale=col("demand_scale", 1.0),
            demand_shift_w=col("demand_shift_w", 0.0),
            cohort=col("cohort", 0),
            **kw,
        )

    @classmethod
    def synthetic(cls, n: int, seed: int = 0, *,
                  n_cohorts: int = 3, **kw) -> "FleetParams":
        """A seeded national-fleet sampler for bench/test use: ``n``
        rooftop installations over a Germany-like bounding box, capacity
        log-normal around the reference class, ~30 % inverter-clipped,
        regimes banded north (maritime) / south (continental-dry) with
        the temperate default in between, demand profiles scattered
        around the reference meter.  Same (n, seed) -> same fleet,
        bit-for-bit (numpy Generator with a fixed bit stream)."""
        if n < 1:
            raise ValueError(f"synthetic fleet needs n >= 1, got {n}")
        rng = np.random.default_rng((seed, 0xF1EE7))
        lat = rng.uniform(47.3, 55.0, n)
        lon = rng.uniform(6.0, 15.0, n)
        alt = np.clip(rng.gamma(2.0, 150.0, n), 0.0, 2500.0)
        tilt = np.clip(lat + rng.normal(0.0, 8.0, n), 5.0, 75.0)
        azi = np.clip(rng.normal(180.0, 35.0, n), 90.0, 270.0)
        albedo = np.clip(rng.normal(0.25, 0.05, n), 0.1, 0.6)
        cap = np.clip(rng.lognormal(0.0, 0.4, n), 0.2, 6.0)
        # ~30 % of sites clip: limit at 70-95 % of scaled nameplate
        # (250 W reference class), the rest unlimited
        clip = rng.uniform(size=n) < 0.3
        limit = np.where(clip,
                         cap * 250.0 * rng.uniform(0.7, 0.95, n),
                         np.inf)
        # regime bands: north of 53.5N maritime, south of 48.5N
        # continental-dry, temperate (regime 0) in between
        regime = np.where(lat > 53.5, 1, np.where(lat < 48.5, 2, 0))
        dem_scale = np.clip(rng.lognormal(0.0, 0.3, n), 0.2, 5.0)
        dem_shift = rng.normal(0.0, 200.0, n)
        cohort = rng.integers(0, max(1, n_cohorts), n)
        return cls(
            latitude=tuple(round(v, 5) for v in lat),
            longitude=tuple(round(v, 5) for v in lon),
            altitude=tuple(round(v, 1) for v in alt),
            surface_tilt=tuple(round(v, 2) for v in tilt),
            surface_azimuth=tuple(round(v, 2) for v in azi),
            albedo=tuple(round(v, 3) for v in albedo),
            dc_capacity_scale=tuple(round(v, 4) for v in cap),
            ac_limit_w=tuple(float(v) if np.isfinite(v) else NO_AC_LIMIT
                             for v in np.round(limit, 1)),
            weather_regime=tuple(int(v) for v in regime),
            demand_scale=tuple(round(v, 4) for v in dem_scale),
            demand_shift_w=tuple(round(v, 1) for v in dem_shift),
            cohort=tuple(int(v) for v in cohort),
            **kw,
        )


def slice_fleet(fleet: Optional[FleetParams], off: int, n: int
                ) -> Optional[FleetParams]:
    """``fleet`` restricted to sites [off, off+n) — the rows a chain
    slab (or an autotune probe) of those chains simulates; the slicing
    twin of ``config.slice_grid``.  None passes through."""
    if fleet is None:
        return None
    per_site = ("latitude", "longitude", "altitude", "surface_tilt",
                "surface_azimuth", "albedo", "dc_capacity_scale",
                "ac_limit_w", "weather_regime", "demand_scale",
                "demand_shift_w", "cohort")
    return dataclasses.replace(
        fleet, n_cohorts_hint=fleet.n_cohorts,
        **{f: tuple(getattr(fleet, f)[off:off + n])
           for f in per_site})
