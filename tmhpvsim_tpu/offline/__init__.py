"""Offline tools (parameter fitting) — never imported by the runtime."""
