"""Markov-step shape-parameter fitting — a *working* offline pipeline.

The reference ships an MCMC fitting pipeline for the hourly cloud-cover
step distributions that is broken end to end (undefined names, impossible
bins, wrong call signatures; SURVEY.md §2.2: cloud_cover_hourly.py:118-267)
— its only surviving artifact is the shipped CSV of fitted shapes.  This
module re-implements the pipeline so the vendored parameters
(data/parameters.py MARKOV_STEP_PARAMS) can actually be re-derived from
data:

1. bin an hourly cloud-cover series by *current* state into the six
   model bins (cloud_cover_hourly.py:1-21 module docstring semantics —
   the broken code's ``bins=[-2e-4, -1.0, ...]`` is nonsense and its
   ``shift(-2)`` contradicts the documented one-step process);
2. collect the one-hour steps taken from each bin;
3. fit an asymmetric-Laplace and a location-scale Student-t to each bin's
   steps by maximum likelihood (scipy.optimize — deterministic and
   dependency-light, replacing 8000-draw NUTS chains);
4. select per bin by AIC and emit rows in the MARKOV_STEP_PARAMS layout
   ``(loc, scale, kappa, df, is_t)``.

Input series can come from any source; ``load_total_cloud_cover`` reads
ERA-5 netcdf when xarray is available (gated import — the runtime never
needs it), or a plain CSV of hourly values in [0, 1].
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import numpy as np

from tmhpvsim_tpu.data import MARKOV_STEP_BINS

_LOG2PI = math.log(2.0 * math.pi)


# ---------------------------------------------------------------------------
# data loading / binning
# ---------------------------------------------------------------------------


#: The reference's ERA-5 request footprint (cloud_cover_hourly.py:41-91):
#: hourly total cloud cover for the grid cell around the Munich site.
ERA5_DATASET = "reanalysis-era5-single-levels"
ERA5_VARIABLE = "total_cloud_cover"
ERA5_AREA_MUNICH = (48.25, 11.5, 48.0, 11.75)  # N, W, S, E


def retrieve_total_cloud_cover(target: str,
                               years: Sequence[int] = (2019,),
                               area: Tuple[float, float, float, float]
                               = ERA5_AREA_MUNICH) -> str:
    """Download hourly ERA-5 total cloud cover to ``target`` (netcdf).

    The working replacement for the reference's ``get_total_cloud_cover``
    download step (cloud_cover_hourly.py:41-91): same dataset, variable and
    caching contract (an existing ``target`` short-circuits the download).
    Gated on ``cdsapi`` — offline-only, the runtime never needs it; needs
    Copernicus CDS credentials in ``~/.cdsapirc`` exactly like the
    reference.  Returns ``target``.
    """
    import os

    if os.path.exists(target):
        return target  # cache hit (cloud_cover_hourly.py:59-64)
    try:
        import cdsapi
    except ImportError as err:
        raise RuntimeError(
            "ERA-5 retrieval requires cdsapi (offline tooling only); "
            "install it or supply an already-downloaded file"
        ) from err
    client = cdsapi.Client()
    client.retrieve(
        ERA5_DATASET,
        {
            "product_type": "reanalysis",
            "format": "netcdf",
            "variable": ERA5_VARIABLE,
            "year": [str(y) for y in years],
            "month": [f"{m:02d}" for m in range(1, 13)],
            "day": [f"{d:02d}" for d in range(1, 32)],
            "time": [f"{h:02d}:00" for h in range(24)],
            "area": list(area),
        },
        target,
    )
    return target


def load_total_cloud_cover(path: str) -> np.ndarray:
    """Hourly total cloud cover in [0, 1] from a .nc (ERA-5 'tcc') or a
    single-column CSV file."""
    if path.endswith(".nc"):
        try:
            import xarray as xr
        except ImportError as err:
            raise RuntimeError(
                "reading netcdf requires xarray; convert to CSV instead"
            ) from err
        ds = xr.open_dataset(path)
        name = "tcc" if "tcc" in ds else list(ds.data_vars)[0]
        values = np.asarray(ds[name]).ravel()
    else:
        values = np.loadtxt(path, delimiter=",", ndmin=1).ravel()
    values = values[np.isfinite(values)]
    if values.size and values.max() > 1.5:
        values = values / 100.0  # percent-encoded cloud cover
    return np.clip(values, 0.0, 1.0)


def bin_steps(series: np.ndarray,
              bins: Sequence[float] = MARKOV_STEP_BINS):
    """Per-bin one-hour step samples.

    Returns a list (one entry per bin) of arrays of ``x[i+1] - x[i]`` for
    all i whose *current* state x[i] falls in that bin — the documented
    Markov semantics (cloud_cover_hourly.py:1-21) with the same
    ``searchsorted(side='left')`` membership the runtime chain uses.
    """
    series = np.asarray(series, dtype=np.float64)
    steps = np.diff(series)
    state = series[:-1]
    idx = np.searchsorted(np.asarray(bins), state, side="left")
    idx = np.clip(idx, 0, len(bins) - 1)
    return [steps[idx == b] for b in range(len(bins))]


# ---------------------------------------------------------------------------
# maximum-likelihood fits
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Fit:
    loc: float
    scale: float
    kappa: float       # AL only (1.0 for t)
    df: float          # t only (1.0 for AL)
    is_t: bool
    nll: float         # negative log-likelihood at the optimum
    n: int

    @property
    def aic(self) -> float:
        return 2 * 3 + 2 * self.nll  # both families have 3 parameters

    def as_row(self) -> Tuple[float, float, float, float, float]:
        """(loc, scale, kappa, df, is_t) — MARKOV_STEP_PARAMS layout."""
        return (self.loc, self.scale, self.kappa, self.df,
                1.0 if self.is_t else 0.0)


def _al_nll(params, x):
    """Negative log-likelihood of the asymmetric Laplace in the reference's
    parameterisation (cloud_cover_hourly.py:93-106): density
    exp(-kappa*z) for z >= 0, exp(z/kappa) for z < 0, z=(x-loc)/scale,
    normalised by 1/(scale*(kappa + 1/kappa))."""
    loc, log_scale, log_kappa = params
    scale, kappa = math.exp(log_scale), math.exp(log_kappa)
    z = (x - loc) / scale
    expo = np.where(z >= 0, kappa * z, -z / kappa)
    return x.size * math.log(scale * (kappa + 1.0 / kappa)) + expo.sum()


def fit_asymmetric_laplace(x: np.ndarray) -> Fit:
    from scipy.optimize import minimize

    x = np.asarray(x, dtype=np.float64)
    med, mad = np.median(x), np.median(np.abs(x - np.median(x))) + 1e-9
    best = None
    for kappa0 in (0.5, 1.0, 2.0):
        res = minimize(
            _al_nll, x0=[med, math.log(mad), math.log(kappa0)], args=(x,),
            method="Nelder-Mead",
            options={"xatol": 1e-10, "fatol": 1e-10, "maxiter": 4000},
        )
        if best is None or res.fun < best.fun:
            best = res
    loc, log_scale, log_kappa = best.x
    return Fit(loc=float(loc), scale=math.exp(log_scale),
               kappa=math.exp(log_kappa), df=1.0, is_t=False,
               nll=float(best.fun), n=x.size)


def _t_nll(params, x):
    from scipy.special import gammaln

    loc, log_scale, log_df = params
    scale, df = math.exp(log_scale), math.exp(log_df)
    z = (x - loc) / scale
    return -(
        x.size * (
            gammaln((df + 1) / 2) - gammaln(df / 2)
            - 0.5 * math.log(df * math.pi) - math.log(scale)
        )
        - (df + 1) / 2 * np.log1p(z * z / df).sum()
    )


def fit_student_t(x: np.ndarray) -> Fit:
    from scipy.optimize import minimize

    x = np.asarray(x, dtype=np.float64)
    med, mad = np.median(x), np.median(np.abs(x - np.median(x))) + 1e-9
    res = minimize(
        _t_nll, x0=[med, math.log(mad), math.log(5.0)], args=(x,),
        method="Nelder-Mead",
        options={"xatol": 1e-10, "fatol": 1e-10, "maxiter": 4000},
    )
    loc, log_scale, log_df = res.x
    return Fit(loc=float(loc), scale=math.exp(log_scale), kappa=1.0,
               df=math.exp(log_df), is_t=True, nll=float(res.fun), n=x.size)


def fit_bin(x: np.ndarray, min_samples: int = 30) -> Optional[Fit]:
    """Best-AIC fit of one bin's steps; None when the bin is too thin."""
    if x.size < min_samples:
        return None
    al, st = fit_asymmetric_laplace(x), fit_student_t(x)
    return al if al.aic <= st.aic else st


def fit_all(series: np.ndarray,
            bins: Sequence[float] = MARKOV_STEP_BINS,
            min_samples: int = 30):
    """Fit every bin; returns list of Optional[Fit] aligned with ``bins``."""
    return [fit_bin(x, min_samples) for x in bin_steps(series, bins)]


def format_params_table(fits, bins: Sequence[float] = MARKOV_STEP_BINS
                        ) -> str:
    """Render fits as a MARKOV_STEP_PARAMS-style Python tuple literal,
    ready to paste into data/parameters.py (the modern equivalent of the
    reference's shapes.csv artifact)."""
    lines = ["MARKOV_STEP_PARAMS = ("]
    prev = -1e-4
    for edge, fit in zip(bins, fits):
        lines.append(f"    # ({prev:g}, {edge:g}]  "
                     + ("Student-t" if fit and fit.is_t
                        else "asymmetric Laplace" if fit else "UNFIT"))
        if fit is None:
            lines.append("    # (insufficient samples)")
        else:
            loc, scale, kappa, df, is_t = fit.as_row()
            lines.append(
                f"    ({loc!r}, {scale!r}, {kappa!r}, {df!r}, {is_t!r}),"
            )
        prev = edge
    lines.append(")")
    return "\n".join(lines)
