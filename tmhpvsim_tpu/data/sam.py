"""SAM database loaders: exact hardware rows, when you have the files.

The reference pins its hardware to two concrete SAM database rows fetched
through pvlib at construction time (pvmodel.py:13-17):

* module:   ``Hanwha_HSL60P6_PA_4_250T__2013_``  (Sandia module library)
* inverter: ``ABB__MICRO_0_25_I_OUTD_US_208_208V__CEC_2014_`` (CEC library)

This framework vendors nominal same-hardware-class coefficients instead
(data/parameters.py) because neither pvlib nor the SAM CSVs exist in the
runtime image and the build environment has no network egress — the exact
rows are *public* data but unobtainable here, and inventing 40 six-digit
coefficients would be worse than honest nominals.

This module closes the gap from the other side: it parses the standard SAM
library CSVs (``sam-library-sandia-modules-*.csv``, ``CEC Inverters.csv``
— the exact files pvlib ships and ``retrieve_sam`` reads) into the dict
shape ``models/pv.py`` consumes.  Point the env vars

    TMHPVSIM_SAM_MODULES=/path/to/sam-library-sandia-modules-2015-6-30.csv
    TMHPVSIM_SAM_INVERTERS=/path/to/sam-library-cec-inverters-2019-03-05.csv

at the files (optionally ``TMHPVSIM_SAM_MODULE_NAME`` /
``TMHPVSIM_SAM_INVERTER_NAME`` to pick different rows) and every consumer
— engine, golden model, apps — runs with the exact reference hardware,
giving absolute-watt parity with the reference stack.
"""

from __future__ import annotations

import csv
import re

#: The rows the reference selects (pvmodel.py:13-17), in pvlib's
#: normalised-name form.
REFERENCE_MODULE_NAME = "Hanwha_HSL60P6_PA_4_250T__2013_"
REFERENCE_INVERTER_NAME = "ABB__MICRO_0_25_I_OUTD_US_208_208V__CEC_2014_"


def _norm(name: str) -> str:
    """Name canonicalisation for row lookup.

    pvlib's retrieve_sam maps each punctuation character to '_'
    one-for-one, which makes the underscore *count* depend on the exact
    spacing in a given library vintage.  Both the lookup key and the CSV
    names are therefore canonicalised the same way — non-alphanumerics to
    '_', runs collapsed, ends stripped — so every historical spelling of
    the same product matches.
    """
    return re.sub(r"_+", "_", re.sub(r"[^A-Za-z0-9]", "_", name)).strip("_")


def _read_rows(path: str):
    """Yield (name, {normalised_column: raw_value}) for each data row.

    SAM CSVs have a header row, then a units row, then data; some variants
    insert a ``[0]/[1]/[2]`` type row.  Non-data rows are filtered by
    failing to parse any numeric field.
    """
    with open(path, newline="") as f:
        reader = csv.reader(f)
        header = next(reader)
        cols = [_norm(c).lower() for c in header]
        for row in reader:
            if not row or not row[0]:
                continue
            rec = dict(zip(cols, row))
            yield row[0], rec


def _pick(path: str, name: str, kind: str) -> dict:
    want = _norm(name)
    names = []
    for raw_name, rec in _read_rows(path):
        if _norm(raw_name) == want:
            return rec
        names.append(raw_name)
    raise KeyError(
        f"{kind} {name!r} not found in {path}; rows present: "
        f"{names[:5]}... ({len(names)} total)"
    )


def _f(rec: dict, *candidates: str, default=None) -> float:
    for c in candidates:
        v = rec.get(c.lower())
        if v not in (None, ""):
            try:
                return float(v)
            except ValueError:
                continue
    if default is not None:
        return default
    raise KeyError(f"none of {candidates} present/numeric in SAM row")


def load_sam_module(path: str, name: str = REFERENCE_MODULE_NAME) -> dict:
    """A Sandia-library module row -> the SAPM dict models/pv.py reads.

    Column synonyms cover the header variations across SAM library vintages
    (e.g. ``BVmpo`` vs ``Bvmpo``, ``DTC`` for the cell/back temperature
    delta, ``A``/``B`` for the thermal-model coefficients).
    """
    rec = _pick(path, name, "module")
    return {
        "Cells_in_Series": int(_f(rec, "Cells_in_Series", "Cells in Series",
                                  "Serial_Cells")),
        "Isco": _f(rec, "Isco"),
        "Voco": _f(rec, "Voco"),
        "Impo": _f(rec, "Impo"),
        "Vmpo": _f(rec, "Vmpo"),
        "Aisc": _f(rec, "Aisc", "AIsc"),
        "Aimp": _f(rec, "Aimp", "AImp"),
        "Bvoco": _f(rec, "Bvoco", "BVoco", "BVoc0"),
        "Mbvoc": _f(rec, "Mbvoc", "MBVoc", default=0.0),
        "Bvmpo": _f(rec, "Bvmpo", "BVmpo", "BVmp0"),
        "Mbvmp": _f(rec, "Mbvmp", "MBVmp", default=0.0),
        "N": _f(rec, "N"),
        "C0": _f(rec, "C0"),
        "C1": _f(rec, "C1"),
        "C2": _f(rec, "C2"),
        "C3": _f(rec, "C3"),
        "A0": _f(rec, "A0"), "A1": _f(rec, "A1"), "A2": _f(rec, "A2"),
        "A3": _f(rec, "A3"), "A4": _f(rec, "A4"),
        "B0": _f(rec, "B0"), "B1": _f(rec, "B1"), "B2": _f(rec, "B2"),
        "B3": _f(rec, "B3"), "B4": _f(rec, "B4"), "B5": _f(rec, "B5"),
        "FD": _f(rec, "FD", default=1.0),
        "T_a": _f(rec, "A"),
        "T_b": _f(rec, "B"),
        "T_deltaT": _f(rec, "DTC"),
    }


def load_sam_inverter(path: str,
                      name: str = REFERENCE_INVERTER_NAME) -> dict:
    """A CEC-library inverter row -> the Sandia-inverter dict."""
    rec = _pick(path, name, "inverter")
    return {
        "Paco": _f(rec, "Paco"),
        "Pdco": _f(rec, "Pdco"),
        "Vdco": _f(rec, "Vdco"),
        "Pso": _f(rec, "Pso"),
        "C0": _f(rec, "C0"),
        "C1": _f(rec, "C1"),
        "C2": _f(rec, "C2"),
        "C3": _f(rec, "C3"),
        "Pnt": _f(rec, "Pnt"),
    }


def env_overrides() -> tuple:
    """(module|None, inverter|None) from the TMHPVSIM_SAM_* env vars."""
    import os

    module = inverter = None
    mpath = os.environ.get("TMHPVSIM_SAM_MODULES")
    if mpath:
        module = load_sam_module(
            mpath, os.environ.get("TMHPVSIM_SAM_MODULE_NAME",
                                  REFERENCE_MODULE_NAME))
    ipath = os.environ.get("TMHPVSIM_SAM_INVERTERS")
    if ipath:
        inverter = load_sam_inverter(
            ipath, os.environ.get("TMHPVSIM_SAM_INVERTER_NAME",
                                  REFERENCE_INVERTER_NAME))
    return module, inverter
