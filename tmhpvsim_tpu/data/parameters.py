"""Vendored model parameters.

Three groups of constants live here so that the runtime has zero file-IO /
external-database dependencies (the reference pulls these from a packaged CSV
and from pvlib's SAM databases at import time):

1. Markov-chain step-size distribution shape parameters for the hourly
   cloud-cover model.  Functional parity with the reference's fitted data
   shipped in ``tmhpvsim/data/mc_dist_shapes.csv`` (loaded at
   cloud_cover_hourly.py:282-288): 6 cloud-cover bins, each with either an
   asymmetric-Laplace ('al': loc/scale/kappa) or Student-t ('t':
   loc/scale/df) step distribution, fitted offline from ERA-5 hourly total
   cloud cover for the Munich grid cell.  A re-fitting tool lives in
   ``tmhpvsim_tpu/offline/fitting.py``.

2. PV hardware coefficients: a SAPM module coefficient set and a Sandia/CEC
   grid inverter coefficient set.  The reference fetches
   ``Hanwha_HSL60P6_PA_4_250T__2013_`` and
   ``ABB__MICRO_0_25_I_OUTD_US_208_208V__CEC_2014_`` from pvlib's SAM
   databases at construction time (pvmodel.py:13-17).  pvlib is not a
   dependency of this framework, so we vendor a nominal coefficient set for
   the same hardware class (60-cell 250 W poly-Si module + 250 W
   micro-inverter).  Swap in exact SAM rows here if bit-parity with a
   particular database version is needed; every consumer reads only this
   table.

3. A monthly Linke-turbidity climatology for the reference's fixed site
   (Munich, 48.12N 11.60E).  pvlib interpolates this from a packed global
   raster; we vendor the single site column (typical central-European
   climatological values) since the site is a runtime config parameter
   anyway (see tmhpvsim_tpu.config.Site.linke_turbidity_monthly).
"""

from __future__ import annotations

# --------------------------------------------------------------------------
# 1. Hourly cloud-cover Markov chain: step distributions per state bin.
#
# State transition (reference module docstring, cloud_cover_hourly.py:1-21):
#     x[i+1] = clip(x[i] + step(x[i]), 0, 1)
# where step(x) is drawn from the distribution of the bin x falls into.
# Bin membership uses searchsorted on the right edges (side='left'), matching
# get_cloud_cover (cloud_cover_hourly.py:309-314).
#
# Encoding: one row per bin, columns (loc, scale, kappa, df, is_student_t).
# For 'al' rows df is unused (set 1.0); for the 't' row kappa is unused.
# --------------------------------------------------------------------------

#: Right bin edges for the cloud-cover state, ascending.
MARKOV_STEP_BINS = (0.1, 0.3, 0.7, 0.9, 0.99, 1.0)

#: Per-bin step-distribution parameters: (loc, scale, kappa, df, is_t).
MARKOV_STEP_PARAMS = (
    # (-0.001, 0.10]  asymmetric Laplace
    (-1.1625165710738716e-04, 0.03438323822429147, 0.6036998501800052, 1.0, 0.0),
    # ( 0.10, 0.30]   asymmetric Laplace
    (-4.580877072293167e-02, 0.10818483945312392, 0.643544237011662, 1.0, 0.0),
    # ( 0.30, 0.70]   Student-t
    (1.5472147699109913e-02, 0.17556647000961773, 1.0, 11.150488007085713, 1.0),
    # ( 0.70, 0.90]   asymmetric Laplace
    (7.771053997629973e-02, 0.10581753524466683, 1.6816193865835385, 1.0, 0.0),
    # ( 0.90, 0.99]   asymmetric Laplace
    (2.302422019848737e-02, 0.04174291229198726, 1.9354719304310923, 1.0, 0.0),
    # ( 0.99, 1.00]   asymmetric Laplace
    (1.4829967380125997e-06, 0.0063110602544872866, 2.23750187345364, 1.0, 0.0),
)

# --------------------------------------------------------------------------
# 1b. Weather-regime step-distribution tables (heterogeneous fleets).
#
# A fleet spanning a country does not share one cloud climate: the
# per-site ``weather_regime`` id in ``tmhpvsim_tpu.fleet.FleetParams``
# selects which of the tables below drives that chain's hourly Markov
# step.  Regime 0 is EXACTLY the vendored Munich fit above
# (``MARKOV_STEP_PARAMS`` — byte-identical rows, so a regime-0-only
# fleet reproduces the single-table simulation bit for bit).  Regimes 1
# and 2 are plausible same-shape refits for contrasting climates (the
# re-fitting tool in ``offline/fitting.py`` produces rows of this exact
# encoding from any ERA-5 cell):
#
# * regime 1 "maritime": faster, larger steps with a bias toward high
#   cover — North-Sea-coast-like variability (broader scales, kappa < 1
#   in mid bins pulls steps upward).
# * regime 2 "continental-dry": slow, small steps biased toward clearing
#   — Iberian-plateau-like persistence of clear skies.
#
# All tables share ``MARKOV_STEP_BINS`` and the (loc, scale, kappa, df,
# is_t) row encoding, so device-side regime selection is one gather on
# a stacked (n_regimes, 6, 5) tensor (models/markov_hourly.py
# ``regime_step_params``).
# --------------------------------------------------------------------------

#: Regime 1: maritime / coastal — broader steps, bias toward overcast.
MARKOV_STEP_PARAMS_MARITIME = (
    (2.1e-03, 0.05210, 0.5480, 1.0, 0.0),
    (-3.05e-02, 0.14630, 0.5910, 1.0, 0.0),
    (2.84e-02, 0.21080, 1.0, 8.92, 1.0),
    (8.93e-02, 0.12740, 1.4210, 1.0, 0.0),
    (3.11e-02, 0.05890, 1.6730, 1.0, 0.0),
    (6.2e-06, 0.00941, 1.9820, 1.0, 0.0),
)

#: Regime 2: continental-dry — small steps, bias toward clearing.
MARKOV_STEP_PARAMS_CONTINENTAL_DRY = (
    (-8.4e-04, 0.02110, 0.7150, 1.0, 0.0),
    (-5.62e-02, 0.08120, 0.7890, 1.0, 0.0),
    (-1.12e-02, 0.14210, 1.0, 13.34, 1.0),
    (6.01e-02, 0.08930, 1.9470, 1.0, 0.0),
    (1.48e-02, 0.03120, 2.2910, 1.0, 0.0),
    (9.1e-07, 0.00442, 2.6120, 1.0, 0.0),
)

#: Stacked regime tables, indexed by ``FleetParams.weather_regime``.
#: Regime 0 IS ``MARKOV_STEP_PARAMS`` (same tuple object), so the
#: homogeneous path and a regime-0 fleet draw identical steps.
MARKOV_STEP_PARAMS_REGIMES = (
    MARKOV_STEP_PARAMS,
    MARKOV_STEP_PARAMS_MARITIME,
    MARKOV_STEP_PARAMS_CONTINENTAL_DRY,
)

# --------------------------------------------------------------------------
# 2. PV hardware coefficients.
# --------------------------------------------------------------------------

#: Sandia Array Performance Model coefficients, 60-cell 250 W poly-Si module
#: (nominal coefficients for the hardware class of Hanwha HSL60P6-PA-4-250T,
#: the module the reference selects at pvmodel.py:13-14).
SAPM_MODULE = {
    "Cells_in_Series": 60,
    "Isco": 8.85,       # reference short-circuit current [A]
    "Voco": 37.6,       # reference open-circuit voltage [V]
    "Impo": 8.27,       # reference max-power current [A]
    "Vmpo": 30.2,       # reference max-power voltage [V]
    "Aisc": 0.0006,     # Isc temperature coefficient [1/C]
    "Aimp": 0.0002,     # Imp temperature coefficient [1/C]
    "Bvoco": -0.128,    # Voc temperature coefficient [V/C]
    "Mbvoc": 0.0,
    "Bvmpo": -0.136,    # Vmp temperature coefficient [V/C]
    "Mbvmp": 0.0,
    "N": 1.045,         # diode ideality factor
    "C0": 1.004,        # Imp = Impo*(C0*Ee + C1*Ee^2)*(1 + Aimp*dT)
    "C1": -0.004,
    "C2": 0.29,         # Vmp log(Ee) coefficients
    "C3": -7.0,
    # F1(AMa): air-mass modifier polynomial (poly-Si typical)
    "A0": 0.9281, "A1": 0.06615, "A2": -0.01384, "A3": 0.001298, "A4": -4.6e-05,
    # F2(AOI): incidence-angle modifier polynomial (flat glass)
    "B0": 1.0, "B1": -0.002438, "B2": 0.0003103,
    "B3": -1.246e-05, "B4": 2.112e-07, "B5": -1.359e-09,
    "FD": 1.0,          # diffuse utilisation fraction
    # SAPM thermal model, open-rack cell/glassback mount (the
    # sapm_celltemp default model the reference uses at pvmodel.py:69-70)
    "T_a": -3.47,       # irradiance coefficient a
    "T_b": -0.0594,     # wind coefficient b
    "T_deltaT": 3.0,    # cell-vs-module back temperature delta [C]
}

#: Sandia grid-inverter model coefficients, 250 W micro-inverter class
#: (nominal coefficients for ABB MICRO-0.25-I-OUTD-US-208, the inverter the
#: reference selects at pvmodel.py:16-17).
SANDIA_INVERTER = {
    "Paco": 250.0,      # rated AC power [W]
    "Pdco": 259.6,      # DC power at rated AC [W]
    "Vdco": 40.24,      # DC voltage at rated point [V]
    "Pso": 1.77,        # self-consumption start-up power [W]
    "C0": -4.1e-05,     # curvature of AC-vs-DC power [1/W]
    "C1": -9.1e-05,     # Pdco voltage dependence [1/V]
    "C2": 4.94e-04,     # Pso voltage dependence [1/V]
    "C3": -0.013171,    # C0 voltage dependence [1/V]
    "Pnt": 0.075,       # night tare loss [W]
}

# --------------------------------------------------------------------------
# 3. Site climatology.
# --------------------------------------------------------------------------

#: Monthly Linke turbidity, Munich (climatological central-European values;
#: consumed by the Ineichen clear-sky model, models/solar.py).
LINKE_TURBIDITY_MONTHLY_MUNICH = (
    2.6, 2.9, 3.2, 3.5, 3.7, 3.8, 3.9, 3.8, 3.5, 3.1, 2.8, 2.6,
)
