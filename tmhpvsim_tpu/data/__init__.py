"""Vendored numeric data for tmhpvsim-tpu (no runtime file/IO dependencies)."""

from tmhpvsim_tpu.data.parameters import (  # noqa: F401
    MARKOV_STEP_BINS,
    MARKOV_STEP_PARAMS,
    SAPM_MODULE,
    SANDIA_INVERTER,
    LINKE_TURBIDITY_MONTHLY_MUNICH,
)
