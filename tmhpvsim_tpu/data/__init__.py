"""Vendored numeric data for tmhpvsim-tpu (no runtime file/IO dependencies).

``SAPM_MODULE`` / ``SANDIA_INVERTER`` default to the vendored nominal
coefficient sets (parameters.py) and are replaced wholesale at import time
by exact SAM database rows when the ``TMHPVSIM_SAM_MODULES`` /
``TMHPVSIM_SAM_INVERTERS`` env vars point at the library CSVs (data/sam.py)
— the path to absolute-watt parity with the reference's pinned hardware.
"""

from tmhpvsim_tpu.data.parameters import (  # noqa: F401
    MARKOV_STEP_BINS,
    MARKOV_STEP_PARAMS,
    MARKOV_STEP_PARAMS_REGIMES,
    SAPM_MODULE,
    SANDIA_INVERTER,
    LINKE_TURBIDITY_MONTHLY_MUNICH,
)

from tmhpvsim_tpu.data.sam import env_overrides as _env_overrides

# A bad override file must fail loudly at import, never half-load: silently
# continuing on nominal coefficients would defeat the parity the override
# exists for.
_sam_module, _sam_inverter = _env_overrides()
if _sam_module is not None:
    SAPM_MODULE = _sam_module
if _sam_inverter is not None:
    SANDIA_INVERTER = _sam_inverter
del _sam_module, _sam_inverter
