"""Live ops plane: an embeddable HTTP endpoint for scrape-time telemetry.

Everything observability built so far is end-of-run (RunReport) or
file-shaped (JSONL/Prometheus sinks, trace exports).  This module is the
*live* side: a tiny asyncio HTTP/1.1 server (``--obs-port``, off by
default) that ``pvsim``, ``pvsim serve`` and ``metersim`` embed, serving

* ``GET /metrics`` — the run's :class:`~..obs.metrics.MetricsRegistry`
  in OpenMetrics 1.0 text exposition (device telemetry / fleet gauges
  update at block granularity mid-run, so a scrape sees the live run).
  Under multi-process jax every sample carries a ``process="<idx>"``
  label (obs/pod.py ``process_labels``) so a federated scrape of all
  hosts stays distinguishable; single-process output is byte-identical
  to the unlabelled exposition;
* ``GET /podmetrics`` — the pod-wide view (obs/pod.py): aggregates
  (host count, median block wall, straggler total) next to per-host
  rows from the latest heartbeat gather, so ONE scrape of process 0
  sees the whole fleet; 404 until a multi-process run with
  ``pod_obs='on'`` reaches a block boundary;
* ``GET /healthz`` — liveness: 200 whenever the event loop turns;
* ``GET /readyz`` — readiness wired to real state via an injectable
  callable (serve: AOT warm-up done AND not draining AND circuit breaker
  not open); 503 + JSON detail otherwise, so the PR-8 breaker and the
  drain path are load-balancer-visible;
* ``GET /flight`` — the flight-recorder window of the run's tracer as a
  Chrome-trace JSON document, on demand (404 when tracing is off).

No third-party HTTP stack: raw ``asyncio.start_server`` with a minimal
GET-only parser and ``Connection: close`` semantics — scrapers
(Prometheus, curl, load balancers) all speak this.  Two lifecycles:

* ``await start()`` / ``await stop()`` inside the asyncio apps
  (pvsim_main, metersim_main, serve_main);
* ``start_threaded()`` / ``close_threaded()`` for the synchronous
  device path (``pvsim --backend=jax``): a daemon thread runs a private
  event loop; ``start_threaded`` returns only once the socket is bound
  (or raises the bind error in the caller).

Port 0 binds an ephemeral port; the resolved one is in ``.port`` (the
same pattern as ``runtime/tcpbroker.py``).  The default path is inert:
no ``--obs-port``, no object constructed, no socket bound.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import threading
from typing import Callable, Optional

from .metrics import (MetricsRegistry, OPENMETRICS_CONTENT_TYPE,
                      get_registry)
from .trace import Tracer

logger = logging.getLogger(__name__)

_REASONS = {200: "OK", 404: "Not Found", 405: "Method Not Allowed",
            503: "Service Unavailable"}

#: ready callable contract: () -> (ok, detail-dict)
ReadyFn = Callable[[], tuple]


def ready_always() -> tuple:
    """Default readiness: ready as soon as the socket answers (apps with
    no warm-up/drain machinery: metersim, asyncio pvsim)."""
    return True, {}


class ObsServer:
    """The embeddable ops endpoint; see module docstring.

    ``registry`` defaults to the process-default registry *at request
    time* when not pinned, so apps that install a per-run registry after
    constructing the server still expose the right one.  ``ready`` is
    the injectable readiness probe; ``tracer`` (optional) backs
    ``/flight``.
    """

    def __init__(self, port: int, host: str = "127.0.0.1", *,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 ready: Optional[ReadyFn] = None,
                 prefix: str = "tmhpvsim"):
        self.host = host
        self.port = int(port)
        self.prefix = prefix
        self._registry = registry
        self.tracer = tracer
        self.ready = ready or ready_always
        self._server: Optional[asyncio.base_events.Server] = None
        self._thread: Optional[threading.Thread] = None
        self._thread_loop: Optional[asyncio.AbstractEventLoop] = None

    @property
    def registry(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else get_registry()

    # -- asyncio lifecycle -----------------------------------------------

    async def start(self) -> "ObsServer":
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("obs endpoint on http://%s:%d (/metrics /podmetrics "
                    "/healthz /readyz /flight)", self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._server is None:
            return
        self._server.close()
        await self._server.wait_closed()
        self._server = None

    # -- threaded lifecycle (synchronous device path) ----------------------

    def start_threaded(self) -> "ObsServer":
        """Run the endpoint on a daemon thread with a private event loop;
        returns once the socket is bound (bind errors raise here, in the
        caller, not on the thread)."""
        bound = threading.Event()
        boot_err: list = []

        def _run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._thread_loop = loop
            try:
                loop.run_until_complete(self.start())
            except Exception as e:  # surface the bind error to the caller
                boot_err.append(e)
                bound.set()
                loop.close()
                return
            bound.set()
            try:
                loop.run_forever()
                loop.run_until_complete(self.stop())
            finally:
                loop.close()

        self._thread = threading.Thread(
            target=_run, name="obs-live", daemon=True)
        self._thread.start()
        bound.wait(timeout=10.0)
        if boot_err:
            self._thread = None
            self._thread_loop = None
            raise boot_err[0]
        return self

    def close_threaded(self) -> None:
        loop, thread = self._thread_loop, self._thread
        self._thread_loop = self._thread = None
        if loop is not None:
            loop.call_soon_threadsafe(loop.stop)
        if thread is not None:
            thread.join(timeout=5.0)

    # -- request handling --------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=5.0)
            parts = request.decode("latin-1", "replace").split()
            method = parts[0] if parts else ""
            path = (parts[1] if len(parts) > 1 else "/").split("?", 1)[0]
            # drain headers (Connection: close — nothing in them matters)
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            if method != "GET":
                status, ctype, body = 405, "text/plain; charset=utf-8", \
                    b"method not allowed\n"
            else:
                status, ctype, body = self._route(path)
            head = (f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    f"Connection: close\r\n\r\n")
            writer.write(head.encode("ascii") + body)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass  # a rude scraper must never hurt the run it observes
        finally:
            with contextlib.suppress(Exception):
                writer.close()
                await writer.wait_closed()

    def _route(self, path: str) -> tuple:
        reg = self.registry
        reg.counter("obs.live.requests").inc()
        if path == "/metrics":
            # labels resolved at scrape time: jax.distributed may not
            # be initialised yet when the server is constructed
            from tmhpvsim_tpu.obs.pod import process_labels

            text = reg.openmetrics_text(prefix=self.prefix,
                                        labels=process_labels())
            return 200, OPENMETRICS_CONTENT_TYPE, text.encode("utf-8")
        if path == "/podmetrics":
            from tmhpvsim_tpu.obs.pod import podmetrics_text

            text = podmetrics_text(self.prefix)
            if text is None:
                return 404, "text/plain; charset=utf-8", \
                    b"no pod snapshot (pod observability off, or no " \
                    b"block boundary gathered yet)\n"
            return 200, OPENMETRICS_CONTENT_TYPE, text.encode("utf-8")
        if path == "/healthz":
            return 200, "text/plain; charset=utf-8", b"ok\n"
        if path == "/readyz":
            try:
                ok, detail = self.ready()
            except Exception as e:  # a broken probe reads as not-ready
                ok, detail = False, {"error": repr(e)}
            body = json.dumps({"ready": bool(ok), **(detail or {})},
                              sort_keys=True).encode("utf-8") + b"\n"
            return (200 if ok else 503), \
                "application/json; charset=utf-8", body
        if path == "/flight":
            if self.tracer is None or not self.tracer.enabled:
                return 404, "text/plain; charset=utf-8", \
                    b"tracing off (run with --trace)\n"
            doc = self.tracer.flight_doc()
            return 200, "application/json; charset=utf-8", \
                json.dumps(doc).encode("utf-8")
        return 404, "text/plain; charset=utf-8", b"not found\n"


@contextlib.asynccontextmanager
async def maybe_obs_server(port: Optional[int], **kw):
    """``async with maybe_obs_server(args.obs_port, ...) as obs:`` — the
    app-side guard: None port yields None and binds nothing."""
    if port is None:
        yield None
        return
    obs = ObsServer(port, **kw)
    await obs.start()
    try:
        yield obs
    finally:
        await obs.stop()
