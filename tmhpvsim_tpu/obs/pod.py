"""Pod-scale observability: heartbeats, stragglers, comm attribution.

Every observability plane built so far — ``/metrics``, the RunReport,
the cost roofline, the tracer — sees ONE process at a time, so a
``--hosts K`` pod run is a fleet of mutually-blind workers.  This
module is the pod-wide view, in four pieces:

* :class:`PodMonitor` — on every block boundary of a multi-process run,
  gathers a fixed-width per-host heartbeat row (process id, chain
  range, block index, steady block wall, blocks/s) over the existing
  ``process_allgather`` path (parallel/distributed.py
  :func:`~tmhpvsim_tpu.parallel.distributed.gather_rows`), computes the
  pod-median block wall, and flags stragglers: a host whose block wall
  exceeds ``straggler_factor`` × the pod median logs a WARNING and
  increments ``pod.straggler_total`` — on EVERY host, since the gather
  is symmetric, so every report agrees on the verdict.  ``doc()``
  renders the RunReport v14 ``pod`` section.
* :func:`comm_split` — collective-vs-compute device-time attribution
  from a ``jax.profiler`` device trace (the PR-2 ``device_trace``
  manifest path): the ``*.trace.json.gz`` Chrome-trace export is parsed
  with stdlib gzip+json, XLA op events are split by name into
  collective ops (all-reduce / all-gather / reduce-scatter / ... — the
  DCN/ICI story at pod scale) vs compute, and the collective fraction
  comes back as ``comm_frac`` (also published as the
  ``device.pod.comm_frac`` gauge and folded into the ``pod`` section).
* :func:`podmetrics_text` — the ``/podmetrics`` exposition
  (obs/live.py): pod-wide aggregates next to per-host rows, derived
  from the latest gathered snapshot, so ONE scrape of process 0 sees
  the whole fleet.
* :func:`process_labels` — the ``{"process": "<idx>"}`` OpenMetrics
  label set a multi-process ``/metrics`` scrape stamps on every sample;
  empty (byte-identical output) for single-process runs.

Off by default: ``SimConfig.pod_obs="off"`` constructs no monitor, runs
no gathers, stamps nothing — the lowered HLO is byte-identical with the
axis on vs off (asserted, like every other obs axis).  The heartbeat
gather itself is host-side numpy over ``process_allgather`` at block
boundaries where the sharded collectives already synchronise, so it
never perturbs the compiled graph.
"""

from __future__ import annotations

import logging
import statistics
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)

#: XLA op-name prefixes counted as collective (communication) time.
#: HLO collective instructions lower to ops named like ``all-reduce.1``
#: / ``all-gather-start`` — prefix match covers the fused/started
#: variants on every backend.
COLLECTIVE_PREFIXES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "ragged-all-to-all",
)

#: trace events that run on XLA executor threads but are dispatch
#: plumbing, not ops
_EVENT_DENYLIST = {"D2D Dispatch"}

#: latest gathered pod snapshot (host rows + aggregates), shared with
#: the ``/podmetrics`` endpoint; guarded because the ObsServer thread
#: reads while the engine thread writes
_latest_lock = threading.Lock()
_latest_snapshot: Optional[dict] = None


def _set_latest(snap: Optional[dict]) -> None:
    global _latest_snapshot
    with _latest_lock:
        _latest_snapshot = snap


def latest_snapshot() -> Optional[dict]:
    """The most recent pod heartbeat snapshot in this process (None
    before the first gather / when pod observability is off)."""
    with _latest_lock:
        return _latest_snapshot


def process_labels() -> dict:
    """OpenMetrics labels identifying this process in a federated
    scrape: ``{"process": "<index>"}`` under multi-process jax, ``{}``
    (byte-identical exposition) otherwise — including when jax is not
    importable at all (pure-host tooling)."""
    try:
        import jax

        if jax.process_count() > 1:
            return {"process": str(jax.process_index())}
    except Exception:
        pass
    return {}


class PodMonitor:
    """Per-host heartbeat gather + straggler verdicts at block
    granularity; see module docstring.

    COLLECTIVE: in a multi-process run :meth:`observe_block` must be
    called by every process at the same block boundary (the engine's
    per-block loop guarantees this — the sharded dispatch already
    synchronised the pod).  Single-process runs take a local-only path
    with no collective, so the monitor is safe everywhere.
    """

    def __init__(self, *, n_chains: int, block_s: int,
                 straggler_factor: float = 2.0,
                 registry=None, chain_start: int = 0,
                 chain_stop: Optional[int] = None):
        self.n_chains = int(n_chains)
        self.block_s = int(block_s)
        self.straggler_factor = float(straggler_factor)
        self.registry = registry
        self.chain_start = int(chain_start)
        self.chain_stop = int(n_chains if chain_stop is None
                              else chain_stop)
        try:
            import jax

            self.process_index = int(jax.process_index())
            self.process_count = int(jax.process_count())
        except Exception:
            self.process_index, self.process_count = 0, 1
        self.blocks_observed = 0
        self.straggler_total = 0
        self._max_over_median = 0.0
        self._last_over_median = 0.0
        self._sum_over_median = 0.0
        self._last_hosts: list = []
        self.comm: Optional[dict] = None
        # the heartbeat gather is a barrier: a fast host waits there for
        # the pod's slowest, and that wait lands in its NEXT
        # dispatch-to-dispatch block wall — which would launder every
        # host's wall up to the straggler's and hide persistent skew.
        # Timing the gather and subtracting it from the next wall keeps
        # the reported walls genuine per-host compute time.
        self._prev_gather_wait_s = 0.0

    # -- per-block path ----------------------------------------------------

    def observe_block(self, block_index: int, block_wall_s: float,
                      blocks_per_s: float) -> Optional[dict]:
        """Gather every host's heartbeat for one completed block and
        update the straggler/skew accounting; returns the snapshot."""
        import numpy as np

        from tmhpvsim_tpu.parallel.distributed import gather_rows

        wall = max(0.0, float(block_wall_s) - self._prev_gather_wait_s)
        bps = (1.0 / wall) if wall > 0 else float(blocks_per_s)
        row = np.asarray([
            float(self.process_index), float(self.chain_start),
            float(self.chain_stop), float(block_index),
            wall, bps,
        ], dtype=np.float64)
        t0 = time.perf_counter()
        try:
            rows = gather_rows(row)
        except Exception as e:  # a failed gather must not kill the run
            logger.warning("pod heartbeat gather failed at block %d: %s",
                           block_index, e)
            return None
        self._prev_gather_wait_s = time.perf_counter() - t0
        hosts = [{
            "process": int(r[0]),
            "chain_start": int(r[1]),
            "chain_stop": int(r[2]),
            "block": int(r[3]),
            "block_wall_s": round(float(r[4]), 6),
            "blocks_per_s": round(float(r[5]), 4),
        } for r in rows]
        hosts.sort(key=lambda h: h["process"])
        walls = [h["block_wall_s"] for h in hosts]
        # median_low, not median: with an even host count (2 hosts
        # especially) the interpolating median averages the straggler's
        # own wall in, bounding every over-median ratio below 2.0 — the
        # default factor could never fire.  The low median compares
        # against the faster half instead.
        median = statistics.median_low(walls) if walls else 0.0
        stragglers = []
        my_ratio = 1.0
        for h in hosts:
            ratio = (h["block_wall_s"] / median) if median > 0 else 1.0
            h["over_median"] = round(ratio, 4)
            if h["process"] == self.process_index:
                my_ratio = ratio
            if median > 0 and ratio > self.straggler_factor:
                stragglers.append(h["process"])
        self.blocks_observed += 1
        self._last_over_median = my_ratio
        self._max_over_median = max(self._max_over_median,
                                    max((h["over_median"] for h in hosts),
                                        default=1.0))
        self._sum_over_median += my_ratio
        self._last_hosts = hosts
        if stragglers:
            self.straggler_total += len(stragglers)
            logger.warning(
                "pod straggler at block %d: host(s) %s exceeded %.2fx "
                "the pod-median block wall (%.3f s); walls=%s",
                block_index, stragglers, self.straggler_factor, median,
                ["%.3f" % w for w in walls],
            )
        if self.registry is not None:
            if stragglers:
                self.registry.counter("pod.straggler_total").inc(
                    len(stragglers))
            self.registry.gauge("pod.hosts").set(float(len(hosts)))
            self.registry.gauge("pod.block_wall_median_s").set(median)
            self.registry.gauge("pod.over_median").set(my_ratio)
        snap = {
            "block": int(block_index),
            "median_block_wall_s": round(median, 6),
            "straggler_factor": self.straggler_factor,
            "stragglers": stragglers,
            "straggler_total": self.straggler_total,
            "hosts": hosts,
        }
        _set_latest(snap)
        return snap

    # -- comm attribution --------------------------------------------------

    def attach_comm(self, comm: Optional[dict]) -> None:
        """Fold a :func:`comm_split` result into the section (and the
        ``device.pod.comm_frac`` gauge)."""
        if comm is None:
            return
        self.comm = comm
        if self.registry is not None and \
                comm.get("comm_frac") is not None:
            self.registry.gauge("device.pod.comm_frac").set(
                float(comm["comm_frac"]))

    # -- report section ----------------------------------------------------

    def doc(self) -> Optional[dict]:
        """The RunReport v14 ``pod`` section (None before any block)."""
        if not self.blocks_observed:
            return None
        out = {
            "process_count": self.process_count,
            "process_index": self.process_index,
            "straggler_factor": self.straggler_factor,
            "blocks_observed": self.blocks_observed,
            "straggler_total": self.straggler_total,
            "skew": {
                "max_over_median": round(self._max_over_median, 4),
                "last_over_median": round(self._last_over_median, 4),
                "mean_over_median": round(
                    self._sum_over_median / self.blocks_observed, 4),
            },
            "hosts": [dict(h) for h in self._last_hosts],
            "comm_frac": (None if self.comm is None
                          else self.comm.get("comm_frac")),
        }
        if self.comm is not None:
            out["comm"] = dict(self.comm)
        return out


# -- collective-vs-compute attribution ------------------------------------


def _is_xla_op(name: str, thread: str, process: str) -> bool:
    """Heuristic: a Chrome-trace duration event that is an XLA op
    execution (vs runtime plumbing, Python frames, or host threads).
    XLA executor threads are named ``tf_XLA...`` on CPU; device planes
    carry ``/device:...`` process names on TPU/GPU exports."""
    if not (thread.startswith("tf_XLA") or "/device:" in process):
        return False
    if not name or name in _EVENT_DENYLIST:
        return False
    if "::" in name:        # C++ infra frames (ThunkExecutor::Execute...)
        return False
    if name.startswith("$"):  # interpreter/bridge frames
        return False
    return True


def is_collective(op_name: str) -> bool:
    """Whether one XLA op name is a collective (communication) op."""
    return op_name.startswith(COLLECTIVE_PREFIXES)


def comm_split(log_dir: str) -> Optional[dict]:
    """Collective-vs-compute device-time split of a ``device_trace``
    capture in ``log_dir``.

    Parses every ``*.trace.json.gz`` Chrome-trace export under the
    profiler's ``plugins/profile/<ts>/`` layout (stdlib gzip + json —
    no protobuf walker), classifies XLA op duration events by name
    prefix, and returns::

        {"collective_s": ..., "compute_s": ..., "comm_frac": ...,
         "n_events": ..., "n_collective_events": ..., "top_collectives":
         {name: seconds, ...}}

    None when the directory holds no parsable trace or no XLA op events
    — callers treat that as "no attribution available", never an error.

    The event walk lives in ``obs.attribution.iter_xla_op_events`` —
    this two-bucket split is the degenerate case of that module's
    phase-level taxonomy (and inherits its plain-``.trace.json``
    fixture support alongside the profiler's gzip exports).
    """
    # lazy: attribution imports this module's filters at import time
    from tmhpvsim_tpu.obs.attribution import iter_xla_op_events

    coll_us = 0.0
    comp_us = 0.0
    n_events = 0
    n_coll = 0
    by_coll: dict = {}
    for name, _hlo_op, dur in iter_xla_op_events(log_dir):
        n_events += 1
        if is_collective(name):
            n_coll += 1
            coll_us += dur
            base = name.split(".", 1)[0]
            by_coll[base] = by_coll.get(base, 0.0) + dur
        else:
            comp_us += dur
    total_us = coll_us + comp_us
    if n_events == 0 or total_us <= 0:
        return None
    return {
        "collective_s": round(coll_us / 1e6, 6),
        "compute_s": round(comp_us / 1e6, 6),
        "comm_frac": round(coll_us / total_us, 6),
        "n_events": n_events,
        "n_collective_events": n_coll,
        "top_collectives": {k: round(v / 1e6, 6)
                            for k, v in sorted(by_coll.items(),
                                               key=lambda kv: -kv[1])[:8]},
    }


# -- /podmetrics exposition ------------------------------------------------


def podmetrics_text(prefix: str = "tmhpvsim") -> Optional[str]:
    """The ``/podmetrics`` OpenMetrics exposition: pod-wide aggregates
    next to per-host rows, from the latest gathered snapshot.  None
    when no snapshot exists yet (pod observability off, or no block
    boundary reached) — obs/live.py answers 404."""
    snap = latest_snapshot()
    if snap is None:
        return None
    p = f"{prefix}_pod" if prefix else "pod"
    lines = [
        f"# TYPE {p}_hosts gauge",
        f"{p}_hosts {len(snap['hosts'])}",
        f"# TYPE {p}_block gauge",
        f"{p}_block {snap['block']}",
        f"# TYPE {p}_block_wall_median_seconds gauge",
        f"{p}_block_wall_median_seconds {snap['median_block_wall_s']}",
        f"# TYPE {p}_straggler gauge",
        f"{p}_straggler {snap['straggler_total']}",
        f"# TYPE {p}_host_block_wall_seconds gauge",
    ]
    for h in snap["hosts"]:
        lines.append(
            f'{p}_host_block_wall_seconds{{process="{h["process"]}"}} '
            f'{h["block_wall_s"]}')
    lines.append(f"# TYPE {p}_host_blocks_per_second gauge")
    for h in snap["hosts"]:
        lines.append(
            f'{p}_host_blocks_per_second{{process="{h["process"]}"}} '
            f'{h["blocks_per_s"]}')
    lines.append(f"# TYPE {p}_host_over_median gauge")
    for h in snap["hosts"]:
        lines.append(
            f'{p}_host_over_median{{process="{h["process"]}"}} '
            f'{h.get("over_median", 1.0)}')
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# -- validation ------------------------------------------------------------


def validate_pod_section(sec) -> list:
    """Shape-check the v14 ``pod`` section; returns a list of error
    strings (empty = valid).  Shared by obs/report.py and
    tools/pod_report.py."""
    _NUM = (int, float)
    errors = []
    if not isinstance(sec, dict):
        return [f"pod: expected dict, got {type(sec).__name__}"]
    for key in ("process_count", "process_index", "blocks_observed",
                "straggler_total"):
        v = sec.get(key)
        if not isinstance(v, int) or v < 0:
            errors.append(f"{key}: expected an int >= 0")
    if isinstance(sec.get("process_count"), int) and \
            isinstance(sec.get("process_index"), int) and \
            sec["process_count"] >= 1 and \
            sec["process_index"] >= sec["process_count"]:
        errors.append("process_index: outside [0, process_count)")
    sf = sec.get("straggler_factor")
    if not isinstance(sf, _NUM) or sf <= 0:
        errors.append("straggler_factor: expected a number > 0")
    skew = sec.get("skew")
    if not isinstance(skew, dict):
        errors.append("skew: expected an object")
    else:
        for key in ("max_over_median", "last_over_median",
                    "mean_over_median"):
            v = skew.get(key)
            if not isinstance(v, _NUM) or v <= 0:
                errors.append(f"skew.{key}: expected a number > 0")
    hosts = sec.get("hosts")
    if not isinstance(hosts, list) or not hosts:
        errors.append("hosts: expected a non-empty list")
    else:
        if isinstance(sec.get("process_count"), int) and \
                len(hosts) != sec["process_count"]:
            errors.append(f"hosts: {len(hosts)} row(s) != process_count "
                          f"{sec['process_count']}")
        for i, h in enumerate(hosts):
            if not isinstance(h, dict):
                errors.append(f"hosts[{i}]: expected an object")
                continue
            for key in ("process", "chain_start", "chain_stop", "block"):
                if not isinstance(h.get(key), int):
                    errors.append(f"hosts[{i}].{key}: expected an int")
            for key in ("block_wall_s", "blocks_per_s"):
                if not isinstance(h.get(key), _NUM):
                    errors.append(f"hosts[{i}].{key}: expected a number")
            if isinstance(h.get("chain_start"), int) and \
                    isinstance(h.get("chain_stop"), int) and \
                    not 0 <= h["chain_start"] <= h["chain_stop"]:
                errors.append(f"hosts[{i}]: chain range inverted")
    cf = sec.get("comm_frac")
    if cf is not None and (not isinstance(cf, _NUM)
                           or not 0.0 <= cf <= 1.0):
        errors.append(f"comm_frac: expected a number in [0, 1] or null, "
                      f"got {cf!r}")
    if "comm" in sec and not isinstance(sec["comm"], (dict, type(None))):
        errors.append("comm: expected an object or null")
    return errors
