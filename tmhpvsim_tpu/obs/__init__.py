"""Observability subsystem: metrics registry, run reports, profiler.

Three host-side modules (nothing here ever runs inside jit):

* :mod:`~tmhpvsim_tpu.obs.metrics` — low-overhead counters / gauges /
  histograms with pluggable sinks (JSONL, Prometheus text exposition);
* :mod:`~tmhpvsim_tpu.obs.report` — the schema-versioned ``RunReport``
  emitted at the end of every engine/app/bench run;
* :mod:`~tmhpvsim_tpu.obs.profiler` — block timing, ``jax.profiler``
  trace annotations, and platform-guarded device traces (the round-5
  retraction happened because a CPU-fallback trace was committed as
  device evidence; the guard makes that impossible to miss again).

``engine/profiling.py`` remains as a compatibility shim re-exporting
the profiler names.
"""

from tmhpvsim_tpu.obs.metrics import (  # noqa: F401
    JsonlSink,
    MetricsRegistry,
    PrometheusSink,
    get_registry,
    make_sink,
    use_registry,
)
from tmhpvsim_tpu.obs.profiler import (  # noqa: F401
    BlockTimer,
    PlatformMismatchError,
    annotate,
    device_trace,
    read_manifest,
)
from tmhpvsim_tpu.obs.report import (  # noqa: F401
    REPORT_SCHEMA_VERSION,
    RunReport,
    validate_report,
)
