"""Observability subsystem: metrics, reports, profiler, telemetry,
drift sentinel, streaming tracer.

Host-side modules (plus one device-side fold):

* :mod:`~tmhpvsim_tpu.obs.metrics` — low-overhead counters / gauges /
  histograms with pluggable sinks (JSONL, Prometheus text exposition);
* :mod:`~tmhpvsim_tpu.obs.report` — the schema-versioned ``RunReport``
  emitted at the end of every engine/app/bench run;
* :mod:`~tmhpvsim_tpu.obs.profiler` — block timing, ``jax.profiler``
  trace annotations, and platform-guarded device traces (the round-5
  retraction happened because a CPU-fallback trace was committed as
  device evidence; the guard makes that impossible to miss again);
* :mod:`~tmhpvsim_tpu.obs.telemetry` — the in-graph numerics
  accumulator that rides the device scan carry (the one part of obs that
  DOES run inside jit; lazily imported here because it needs jax);
* :mod:`~tmhpvsim_tpu.obs.analytics` — the in-graph fleet-risk
  accumulator (residual quantile sketch, exceedance curve, LOLP,
  ramp-rate extrema); same jit-resident carry pattern as telemetry,
  same lazy import;
* :mod:`~tmhpvsim_tpu.obs.sentinel` — the drift sentinel comparing
  leading-block means against the float64 golden models
  (``DriftSentinel``, ``DriftError``);
* :mod:`~tmhpvsim_tpu.obs.trace` — the asyncio-task-aware streaming
  event tracer + flight recorder (Chrome-trace JSON export), plus the
  cross-process trace-context propagation layer (trace_id/span_id over
  broker message meta);
* :mod:`~tmhpvsim_tpu.obs.live` — the live ops plane: the embeddable
  ``--obs-port`` HTTP endpoint (``/metrics`` OpenMetrics, ``/healthz``,
  ``/readyz``, ``/flight``);
* :mod:`~tmhpvsim_tpu.obs.cost` — the static per-plan device cost model
  behind the ``device.cost.*`` gauges and the RunReport v10 ``cost``
  section (achieved FLOPs, roofline fraction, north-star fraction).
"""

from tmhpvsim_tpu.obs.metrics import (  # noqa: F401
    JsonlSink,
    MetricsRegistry,
    PrometheusSink,
    get_registry,
    make_sink,
    use_registry,
)
from tmhpvsim_tpu.obs.profiler import (  # noqa: F401
    BlockTimer,
    PlatformMismatchError,
    annotate,
    device_trace,
    read_manifest,
)
from tmhpvsim_tpu.obs.report import (  # noqa: F401
    REPORT_SCHEMA_VERSION,
    RunReport,
    validate_report,
)
from tmhpvsim_tpu.obs.sentinel import (  # noqa: F401
    DriftError,
    DriftSentinel,
)
from tmhpvsim_tpu.obs.trace import (  # noqa: F401
    Tracer,
    get_tracer,
    set_tracer,
    use_tracer,
)
from tmhpvsim_tpu.obs.live import ObsServer  # noqa: F401
from tmhpvsim_tpu.obs import cost  # noqa: F401


def __getattr__(name):
    # obs.telemetry/obs.analytics import jax at module scope (they build
    # jit-resident accumulators); the runtime layers import this package
    # from jax-free contexts, so those submodules load lazily on first
    # touch
    if name in ("telemetry", "analytics"):
        import importlib

        return importlib.import_module(f"tmhpvsim_tpu.obs.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
