"""Low-overhead host-side metrics: counters, gauges, histograms, sinks.

Everything here runs on the host, outside jit — an instrumented call is
a dict lookup plus a float add, so the engine's per-block hooks cost
microseconds against block walls of milliseconds to seconds (asserted by
tests/test_obs.py's 65536-chain overhead test).  A registry with no
sinks attached never touches the filesystem; a disabled registry
(``MetricsRegistry(enabled=False)``) hands out shared no-op metric
objects so instrumented code needs no conditionals.

Sinks (``registry.add_sink``) receive the registry on every ``flush()``:

* :class:`JsonlSink` — appends one JSON snapshot line per flush (the
  ``--metrics PATH`` artifact: greppable time series of the run);
* :class:`PrometheusSink` — rewrites a text-exposition snapshot file
  atomically (point a node_exporter textfile collector at it).

``make_sink(path)`` picks by suffix: ``.prom`` -> Prometheus, anything
else JSONL.  The process-default registry (:func:`get_registry`) is what
the engine/runtime layers instrument against; apps install a fresh one
per run via :func:`use_registry` so reports never mix runs.
"""

from __future__ import annotations

import bisect
import contextlib
import json
import logging
import os
import re
import threading
import time
from typing import Iterable, Optional

logger = logging.getLogger(__name__)

#: content type an OpenMetrics 1.0 scraper negotiates for (what
#: ``obs/live.py`` answers ``/metrics`` with)
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8")

#: histogram bucket upper bounds (seconds-flavoured log-ish grid; the
#: +Inf bucket is implicit).  Wide enough for µs-scale host hooks and
#: minute-scale compile times alike.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0,
)


class Counter:
    """Monotonically increasing value (floats allowed: cumulative
    seconds are counters too)."""

    __slots__ = ("name", "_v")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease "
                             f"(inc({amount}))")
        self._v += amount

    @property
    def value(self) -> float:
        return self._v


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_v")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0

    def set(self, value: float) -> None:
        self._v = float(value)

    def add(self, delta: float) -> None:
        self._v += float(delta)

    @property
    def value(self) -> float:
        return self._v


class Histogram:
    """Count/sum/min/max plus cumulative bucket counts (Prometheus
    semantics: ``buckets[i]`` counts observations <= ``bounds[i]``)."""

    __slots__ = ("name", "bounds", "bucket_counts", "count", "sum",
                 "min", "max")

    def __init__(self, name: str, buckets: Iterable[float] = DEFAULT_BUCKETS):
        self.name = name
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self.bucket_counts = [0] * len(self.bounds)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value
        i = bisect.bisect_left(self.bounds, value)
        if i < len(self.bucket_counts):
            self.bucket_counts[i] += 1

    def snapshot(self) -> dict:
        cum = []
        running = 0
        for bound, n in zip(self.bounds, self.bucket_counts):
            running += n
            cum.append([bound, running])
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": (self.sum / self.count) if self.count else None,
            "buckets": cum,
        }


def quantile_from_snapshot(snap: Optional[dict], q: float) -> Optional[float]:
    """Quantile estimate from a :meth:`Histogram.snapshot` dict by linear
    interpolation within the cumulative buckets (Prometheus
    ``histogram_quantile`` semantics), clamped to the observed [min, max]
    so a handful of sub-bucket latencies cannot report a bucket-bound
    worth of latency.  None when the histogram is empty/absent — an
    empty/zero-count/bucketless snapshot is a valid "nothing observed"
    answer, never an exception (report assembly calls this on whatever
    the run left behind).

    Deterministic edge rules (pinned by tests/test_liveops.py):

    * when every observation landed in one bucket the grid carries no
      interior geometry, so the estimate interpolates the observed span
      directly — ``min + q * (max - min)`` — falling back to that
      bucket's upper bound when min/max were lost (snapshots rebuilt
      from sparse JSON);
    * a quantile landing exactly on a cumulative bucket boundary
      (``q * count == cum``) returns that bucket's upper bound, never an
      interpolation between neighbours;
    * beyond the last finite bucket the answer is the observed ``max``.
    """
    if not snap or not snap.get("count"):
        return None
    count = snap["count"]
    target = q * count
    smin, smax = snap.get("min"), snap.get("max")
    # `or ()`: snapshots rebuilt from JSON may carry buckets=null
    buckets = [(b, c) for b, c in (snap.get("buckets") or ())]
    occupied = [i for i, (b, c) in enumerate(buckets)
                if c > (buckets[i - 1][1] if i else 0)]
    value = None
    if len(occupied) == 1 and buckets[occupied[0]][1] == count:
        if smin is not None and smax is not None:
            return smin + q * (smax - smin)
        value = buckets[occupied[0]][0]
    else:
        lo_bound, lo_cum = 0.0, 0
        for bound, cum in buckets:
            if cum >= target:
                if cum == target:
                    value = bound
                else:
                    frac = (target - lo_cum) / (cum - lo_cum)
                    value = lo_bound + frac * (bound - lo_bound)
                break
            lo_bound, lo_cum = bound, cum
    if value is None:  # beyond the last finite bucket (+Inf territory)
        value = smax
    if value is None:
        return None
    if smin is not None:
        value = max(value, smin)
    if smax is not None:
        value = min(value, smax)
    return value


class _NullMetric:
    """Shared no-op stand-in handed out by a disabled registry."""

    name = "<disabled>"
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def add(self, delta: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass


_NULL = _NullMetric()


class MetricsRegistry:
    """Named metrics + sinks.  Creation is locked (threads share the
    process-default registry); the hot-path mutators are plain float ops
    under the GIL — single-writer-per-metric is the expected pattern."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._metrics: dict = {}
        self._sinks: list = []
        self._lock = threading.Lock()

    # -- metric accessors ------------------------------------------------

    def _get(self, name: str, cls, **kw):
        if not self.enabled:
            return _NULL
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.setdefault(name, cls(name, **kw))
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}"
            )
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, Histogram, buckets=buckets)

    @contextlib.contextmanager
    def timed(self, name: str):
        """Wall-time a block into histogram ``name`` (nests naturally:
        inner scopes are separate metrics and the outer span includes
        them)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.histogram(name).observe(time.perf_counter() - t0)

    # -- snapshots & sinks -----------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able state of every metric."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(self._metrics.items()):
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            else:
                out["histograms"][name] = m.snapshot()
        return out

    def prometheus_text(self, prefix: str = "tmhpvsim") -> str:
        """The registry in Prometheus text exposition format."""
        lines = []
        for name, m in sorted(self._metrics.items()):
            pname = _prom_name(f"{prefix}_{name}" if prefix else name)
            if isinstance(m, Counter):
                lines += [f"# TYPE {pname} counter",
                          f"{pname} {_prom_num(m.value)}"]
            elif isinstance(m, Gauge):
                lines += [f"# TYPE {pname} gauge",
                          f"{pname} {_prom_num(m.value)}"]
            else:
                lines.append(f"# TYPE {pname} histogram")
                running = 0
                for bound, n in zip(m.bounds, m.bucket_counts):
                    running += n
                    lines.append(
                        f'{pname}_bucket{{le="{_prom_num(bound)}"}} '
                        f"{running}"
                    )
                lines.append(f'{pname}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{pname}_sum {_prom_num(m.sum)}")
                lines.append(f"{pname}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def openmetrics_text(self, prefix: str = "tmhpvsim",
                         labels: Optional[dict] = None) -> str:
        """The registry in OpenMetrics 1.0 text exposition format (what
        ``obs/live.py`` serves at ``/metrics``).  Differs from
        :meth:`prometheus_text` exactly where the specs diverge: counter
        samples carry the ``_total`` suffix and the exposition ends with
        the mandatory ``# EOF`` terminator.

        ``labels`` stamps every sample with a constant label set —
        obs/live.py passes ``{"process": "<idx>"}`` under multi-process
        jax so federated pod scrapes can tell the hosts apart
        (obs/pod.py ``process_labels``).  Histogram buckets merge the
        extra labels after ``le``.  None/empty keeps the output
        byte-identical to the unlabelled exposition."""
        extra = ",".join(f'{k}="{v}"'
                         for k, v in sorted((labels or {}).items()))
        lbl = "{" + extra + "}" if extra else ""
        lines = []
        for name, m in sorted(self._metrics.items()):
            pname = _prom_name(f"{prefix}_{name}" if prefix else name)
            if isinstance(m, Counter):
                lines += [f"# TYPE {pname} counter",
                          f"{pname}_total{lbl} {_prom_num(m.value)}"]
            elif isinstance(m, Gauge):
                lines += [f"# TYPE {pname} gauge",
                          f"{pname}{lbl} {_prom_num(m.value)}"]
            else:
                lines.append(f"# TYPE {pname} histogram")
                bext = ("," + extra) if extra else ""
                running = 0
                for bound, n in zip(m.bounds, m.bucket_counts):
                    running += n
                    lines.append(
                        f'{pname}_bucket{{le="{_prom_num(bound)}"'
                        f"{bext}}} {running}"
                    )
                lines.append(f'{pname}_bucket{{le="+Inf"{bext}}} '
                             f"{m.count}")
                lines.append(f"{pname}_sum{lbl} {_prom_num(m.sum)}")
                lines.append(f"{pname}_count{lbl} {m.count}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def add_sink(self, sink) -> None:
        self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        if sink in self._sinks:
            self._sinks.remove(sink)

    def flush(self, event: Optional[str] = None) -> None:
        """Emit the current state to every sink (no-op with no sinks)."""
        for sink in self._sinks:
            try:
                sink.emit(self, event)
            except Exception as e:
                # a sink must never kill the run it observes (closed
                # fd -> ValueError, full disk -> OSError)
                logger.warning("metrics sink %r failed: %s", sink, e)

    def close(self) -> None:
        for sink in self._sinks:
            close = getattr(sink, "close", None)
            if close is not None:
                close()
        self._sinks.clear()


def _prom_name(name: str) -> str:
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return name if not name[:1].isdigit() else "_" + name


def _prom_num(v: float) -> str:
    # integral values render without the trailing '.0' Prometheus text
    # parsers tolerate but humans grep for
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class JsonlSink:
    """Appends ``{"ts": ..., "event": ..., "metrics": snapshot}`` as one
    JSON line per flush."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def emit(self, registry: MetricsRegistry, event: Optional[str]) -> None:
        doc = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "event": event,
            "metrics": registry.snapshot(),
        }
        self._f.write(json.dumps(doc) + "\n")

    def close(self) -> None:
        self._f.close()


class PrometheusSink:
    """Rewrites ``path`` with a full text-exposition snapshot on every
    flush (atomic tmp + rename: a scraper never reads a torn file)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def emit(self, registry: MetricsRegistry, event: Optional[str]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(registry.prometheus_text())
        os.replace(tmp, self.path)

    def close(self) -> None:
        pass


def make_sink(path: str):
    """Sink for ``--metrics PATH``: ``.prom`` -> Prometheus snapshot,
    anything else JSONL append."""
    return PrometheusSink(path) if path.endswith(".prom") \
        else JsonlSink(path)


#: process-default registry: what library layers (engine, runtime.clock,
#: checkpoint, slab) instrument against
_default = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default


@contextlib.contextmanager
def use_registry(registry: MetricsRegistry):
    """Install ``registry`` as the process default for the scope — apps
    wrap each run so a run's report only sees that run's metrics.
    NB: library code that cached ``get_registry()`` at construction time
    keeps its registry; construct Simulations inside the scope."""
    global _default
    prev = _default
    _default = registry
    try:
        yield registry
    finally:
        _default = prev
