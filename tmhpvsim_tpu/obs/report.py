"""RunReport: the schema-versioned JSON artifact every run emits.

One report per engine/app/bench run, so every performance claim is
backed by a machine-checkable record of what ran where: config + the
resolved autotune ``Plan``, device platform/kind/process count, HBM
stats, per-block walls split compile-vs-steady, checkpoint save/restore
timings, slab progress, pacing slip, the headline site-s/s figure, and
(when a device trace was captured) the trace's platform-guard manifest.
Retraction-proofing is the point: round 5's roofline had to be
withdrawn because none of this was recorded (VERDICT.md §5).

The validator is hand-rolled (no jsonschema dependency): required keys,
per-field types, no unknown top-level keys, and the document must be
JSON-serialisable.  Consumers match on ``schema_version`` /
``kind`` — bump :data:`REPORT_SCHEMA_VERSION` on breaking changes.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Optional

logger = logging.getLogger(__name__)

#: v1: PR-2 sections.  v2: adds the optional ``telemetry`` section
#: (drift-sentinel verdict + per-field worst z-scores, obs/sentinel.py).
#: v3: adds the optional ``streaming`` section (publish→join / join→csv
#: latency quantiles, funnel pending high-water + eviction / stall /
#: backpressure counters, retry + broker connect counts — the asyncio
#: streaming path's aggregate view, obs/trace.py holds the timeline).
#: v4: adds the optional ``executor`` section (warm/cold compile counts
#: from the persistent compilation cache, dispatch counts and the
#: blocks-per-dispatch factor, AOT warm-up stats — engine/compilecache.py)
#: and the ``blocks_per_dispatch`` field to the plan echo.
#: v5: adds the optional ``fleet`` section (on-device fleet-risk
#: analytics: residual quantile sketch, exceedance curve,
#: loss-of-load probability, ramp-rate extrema, per-regime conditional
#: means — obs/analytics.py ``summarize``).
#: v6: adds the optional ``serving`` section (the scenario server's SLO
#: view: request/reply/rejection/timeout counts, in-flight gauge,
#: micro-batch occupancy, queue-wait / dispatch / reply-latency
#: quantiles — serve/, derived from the ``serve.*`` metric names).
#: v7: adds the optional ``resilience`` section (recovery outcomes:
#: checkpoint resumes + supervised restart count, retry/giveup
#: aggregates, circuit-breaker opens/rejections/state, injected-fault
#: counts by chokepoint — runtime/resilience.py, runtime/faults.py).
#: v8: adds the optional ``precision`` section (the mixed-precision /
#: tabulated-kernel axes: resolved compute_dtype + kernel_impl, the
#: sentinel-gate outcome for non-default picks, per-variant rates in
#: bench documents — engine/autotune.py, models/tables.py), the
#: optional ``probe`` section (bench.py backend-probe attempt/timeout
#: accounting under runtime/resilience.ResiliencePolicy), and the
#: ``compute_dtype`` / ``kernel_impl`` fields in the plan echo.
#: v9: enriches the optional ``checkpoint`` section with the
#: preemption-safe subsystem's accounting (engine/checkpoint.py):
#: generation rotation (``generations`` on disk, ``latest_generation``),
#: integrity outcomes (``verify_failures``, ``fallbacks`` to an older
#: generation), the async writer (``async_saves``, ``async_dropped``
#: latest-wins supersessions, ``async_write_failures``, peak
#: ``async_queue_depth``) and ``preempt_snapshots`` (SIGTERM-grace /
#: chaos-preempt final snapshots).  All additive — v8 readers of the
#: section's original four keys are unaffected.
#: v10: adds the optional ``cost`` section (per-dispatch device cost
#: attribution, obs/cost.py ``cost_doc``: static flops/bytes-per-
#: site-second pricing of the resolved plan cell × the measured
#: site-s/s rate → achieved GFLOP/s / GB/s, roofline fractions against
#: the chip's published peaks, north-star fraction; ``basis`` records
#: whether the per-site costs were measured via XLA cost_analysis or
#: priced by the static model).
#: v11: adds the ``rng_batch`` / ``geom_stride`` fields to the plan
#: echo (the scan-restructuring axes: whole-block RNG pre-generation
#: and strided solar geometry — engine/autotune.py, models/solar.py)
#: and prices them in the ``cost`` section (obs/cost.py static-v1
#: factors).  Both additive — a v10 reader of the plan echo's original
#: keys is unaffected, and documents omitting them mean the historical
#: scan/1 path.
#: v12: the heterogeneous-fleet subsystem (fleet/params.py).  The
#: ``fleet`` section gains the optional ``cohorts`` list (per-cohort
#: group-by reductions: count, residual extrema + quantiles, mean
#: meter/pv/residual — obs/analytics.py ``summarize``) and the config
#: echo gains the optional ``fleet`` identity (site count + content
#: digest, mirroring the checkpoint echo).  All additive — a v11
#: reader of the fleet section's original keys is unaffected, and
#: documents omitting them mean a homogeneous (fleet-less) run.
#: v13: adds the optional ``mesh`` section (pod-scale execution,
#: parallel/mesh.py + parallel/distributed.py ``mesh_doc``): the device
#: grid's ``shape`` and ``axis_names`` (1D ``["chains"]`` or 2D
#: ``["chains", "scenario"]``), ``n_devices``, the process topology
#: (``process_count``/``process_index``) and, when known, the chain
#: layout (``n_chains``, ``chains_per_device``, this process's
#: ``chain_start``/``chain_stop``).  Additive — unsharded runs omit it
#: (None), and a v12 reader ignores the extra key only if it reads
#: leniently; strict v12 readers should bump.
#: v14: adds the optional ``pod`` section (pod-scale observability,
#: obs/pod.py ``PodMonitor.doc()``): per-host heartbeat rows gathered
#: at block boundaries (process, chain range, block index, block wall,
#: blocks/s), skew statistics against the pod-median block wall,
#: ``straggler_total`` (block walls exceeding ``straggler_factor`` ×
#: the pod median), and the collective-vs-compute device-time split's
#: ``comm_frac`` when a device trace was captured.  The ``cost``
#: section gains the optional ``model_error`` sub-doc (obs/cost.py):
#: measured-vs-static flops/bytes ratios and per-factor implied
#: corrections, present only under ``basis: "measured"``.  All
#: additive — unsharded/off runs omit the section (None).
#: v15: adds the optional ``attribution`` section (semantic phase
#: attribution, obs/attribution.py ``attribute``): per-phase device-time
#: split from a scoped trace — ``basis`` ("scope" when ph__* phase
#: scopes mapped the ops, "opname-heuristic" when only op-name
#: heuristics applied, "unavailable" when the trace carried nothing
#: attributable), ``total_device_s``, per-phase ``seconds``/``frac``,
#: and the ``unattributed`` residual.  The ``cost`` section's
#: ``model_error`` sub-doc gains optional per-axis ``phases`` /
#: ``measured_phase_frac`` keys checking each static-v1 factor axis
#: against the measured share of the phase it claims to scale.  All
#: additive — runs without ``phase_obs`` omit the section (None).
#: v16: the ``serving`` section gains the optional additive ``fleet``
#: sub-doc (:func:`fleet_serving_section`) when the run served through
#: the horizontally-scaled tier (serve/router.py + serve/fleet.py):
#: ``router`` totals (routed/replies/rerouted/dup_replies/quota_
#: rejected/shed + reply-latency) and per-worker rows (requests/
#: replies/batches/backfilled/occupancy/compile counters/restarts)
#: that partition the router's routed totals — tools/serve_report.py
#: checks the partition.  Single-worker serves omit the key — their
#: reports stay byte-compatible with v15 emitters.
#: The validator accepts any version in [1, REPORT_SCHEMA_VERSION] —
#: prior-version documents stay loadable (tested).
REPORT_SCHEMA_VERSION = 16
REPORT_KIND = "tmhpvsim_tpu.run_report"

_NUM = (int, float)
_OPT_DICT = (dict, type(None))

#: top-level schema: name -> (required, allowed types).  Optional dict
#: sections are None when the run had nothing to report there.
_TOP_SCHEMA = {
    "schema_version": (True, int),
    "kind": (True, str),
    "app": (True, str),
    "created_utc": (True, str),
    "device": (True, dict),
    "config": (False, _OPT_DICT),
    "plan": (False, _OPT_DICT),
    "timing": (False, _OPT_DICT),
    "checkpoint": (False, _OPT_DICT),
    "slabs": (False, _OPT_DICT),
    "realtime": (False, _OPT_DICT),
    "headline": (False, _OPT_DICT),
    "metrics": (False, _OPT_DICT),
    "profile": (False, _OPT_DICT),
    "processes": (False, (list, type(None))),
    "telemetry": (False, _OPT_DICT),
    "streaming": (False, _OPT_DICT),
    "executor": (False, _OPT_DICT),
    "fleet": (False, _OPT_DICT),
    "serving": (False, _OPT_DICT),
    "resilience": (False, _OPT_DICT),
    "precision": (False, _OPT_DICT),
    "probe": (False, _OPT_DICT),
    "cost": (False, _OPT_DICT),
    "mesh": (False, _OPT_DICT),
    "pod": (False, _OPT_DICT),
    "attribution": (False, _OPT_DICT),
}

_DEVICE_SCHEMA = {
    "platform": (True, (str, type(None))),
    "device_kind": (False, (str, type(None))),
    "n_devices": (False, int),
    "process_count": (False, int),
    "process_index": (False, int),
    "memory_stats": (False, _OPT_DICT),
}

_TIMING_SCHEMA = {
    "compile_s": (False, _NUM + (type(None),)),
    "steady_block_s": (False, _NUM + (type(None),)),
    "first_block_s": (False, _NUM + (type(None),)),
    "n_blocks_timed": (False, int),
    "site_seconds_per_s": (False, _NUM + (type(None),)),
    "rate_includes_compile": (False, bool),
}


def _check_fields(doc: dict, schema: dict, where: str,
                  closed: bool = False) -> None:
    for key, (required, types) in schema.items():
        if key not in doc:
            if required:
                raise ValueError(f"run report {where}: missing required "
                                 f"key {key!r}")
            continue
        if not isinstance(doc[key], types):
            raise ValueError(
                f"run report {where}: {key!r} has type "
                f"{type(doc[key]).__name__}, expected "
                f"{'/'.join(t.__name__ for t in (types if isinstance(types, tuple) else (types,)))}"
            )
    if closed:
        unknown = set(doc) - set(schema)
        if unknown:
            raise ValueError(f"run report {where}: unknown keys "
                             f"{sorted(unknown)}")


def validate_fleet_section(sec: dict) -> list:
    """Shape-check the v12 additions to the ``fleet`` section; returns
    a list of error strings (empty = valid).  Pre-v12 sections (no
    ``cohorts`` key) and homogeneous runs (``cohorts: null``) are
    valid by construction."""
    errors = []
    co = sec.get("cohorts")
    if co is None:
        return errors
    if not isinstance(co, list):
        return [f"cohorts: expected a list or null, "
                f"got {type(co).__name__}"]
    for i, row in enumerate(co):
        if not isinstance(row, dict):
            errors.append(f"cohorts[{i}]: expected an object")
            continue
        for key in ("cohort", "count"):
            if not isinstance(row.get(key), int):
                errors.append(f"cohorts[{i}].{key}: expected an integer")
        for key in ("residual_min", "residual_max", "meter_mean",
                    "pv_mean", "residual_mean"):
            if key in row and not isinstance(
                    row[key], _NUM + (type(None),)):
                errors.append(f"cohorts[{i}].{key}: expected a number "
                              "or null")
        if "quantiles" in row and not isinstance(row["quantiles"],
                                                 _OPT_DICT):
            errors.append(f"cohorts[{i}].quantiles: expected an object "
                          "or null")
    return errors


def validate_mesh_section(sec: dict) -> list:
    """Shape-check the v13 ``mesh`` section; returns a list of error
    strings (empty = valid).  Checks internal consistency too: the
    shape's product must equal ``n_devices`` and pair up with
    ``axis_names``, and a chain layout (when present) must divide
    evenly and bound the process's slice."""
    errors = []
    shape = sec.get("shape")
    axes = sec.get("axis_names")
    if not (isinstance(shape, list) and shape
            and all(isinstance(s, int) and s >= 1 for s in shape)):
        errors.append("shape: expected a non-empty list of ints >= 1")
        shape = None
    if not (isinstance(axes, list) and axes
            and all(isinstance(a, str) for a in axes)):
        errors.append("axis_names: expected a non-empty list of strings")
        axes = None
    if shape is not None and axes is not None and len(shape) != len(axes):
        errors.append(f"shape/axis_names: rank mismatch "
                      f"({len(shape)} vs {len(axes)})")
    n_dev = sec.get("n_devices")
    if not isinstance(n_dev, int) or n_dev < 1:
        errors.append("n_devices: expected an int >= 1")
    elif shape is not None:
        prod = 1
        for s in shape:
            prod *= s
        if prod != n_dev:
            errors.append(f"n_devices: {n_dev} != product(shape) {prod}")
    for key in ("process_count", "process_index"):
        if key in sec and (not isinstance(sec[key], int) or sec[key] < 0):
            errors.append(f"{key}: expected an int >= 0")
    if isinstance(sec.get("process_count"), int) and \
            isinstance(sec.get("process_index"), int) and \
            sec["process_index"] >= sec["process_count"] >= 1:
        errors.append("process_index: outside [0, process_count)")
    nc = sec.get("n_chains")
    if nc is not None:
        if not isinstance(nc, int) or nc < 1:
            errors.append("n_chains: expected an int >= 1 or absent")
        elif isinstance(n_dev, int) and n_dev >= 1:
            if nc % n_dev != 0:
                errors.append(f"n_chains: {nc} not divisible by "
                              f"n_devices {n_dev}")
            cpd = sec.get("chains_per_device")
            if cpd is not None and cpd != nc // n_dev:
                errors.append(f"chains_per_device: {cpd} != "
                              f"{nc // n_dev}")
        lo, hi = sec.get("chain_start"), sec.get("chain_stop")
        if lo is not None and hi is not None and isinstance(nc, int):
            if not (isinstance(lo, int) and isinstance(hi, int)
                    and 0 <= lo <= hi <= nc):
                errors.append("chain_start/chain_stop: expected "
                              f"0 <= start <= stop <= n_chains ({nc})")
    return errors


def validate_report(doc) -> dict:
    """Validate ``doc`` against the versioned schema; returns it.

    Raises ValueError on: non-dict, wrong kind/schema_version, missing
    required fields, mistyped fields, unknown top-level keys, or a
    document json.dumps cannot serialise.
    """
    if not isinstance(doc, dict):
        raise ValueError(f"run report must be a dict, got "
                         f"{type(doc).__name__}")
    _check_fields(doc, _TOP_SCHEMA, "top level", closed=True)
    if doc["kind"] != REPORT_KIND:
        raise ValueError(f"run report kind {doc['kind']!r} != "
                         f"{REPORT_KIND!r}")
    if not 1 <= doc["schema_version"] <= REPORT_SCHEMA_VERSION:
        raise ValueError(
            f"run report schema_version {doc['schema_version']!r} outside "
            f"[1, {REPORT_SCHEMA_VERSION}] (this build); newer documents "
            "need a newer reader"
        )
    _check_fields(doc["device"], _DEVICE_SCHEMA, "device")
    if isinstance(doc.get("timing"), dict):
        _check_fields(doc["timing"], _TIMING_SCHEMA, "timing")
    if isinstance(doc.get("cost"), dict):
        from tmhpvsim_tpu.obs.cost import validate_cost

        errors = validate_cost(doc["cost"])
        if errors:
            raise ValueError("run report cost: " + "; ".join(errors))
    if isinstance(doc.get("fleet"), dict):
        errors = validate_fleet_section(doc["fleet"])
        if errors:
            raise ValueError("run report fleet: " + "; ".join(errors))
    if isinstance(doc.get("mesh"), dict):
        errors = validate_mesh_section(doc["mesh"])
        if errors:
            raise ValueError("run report mesh: " + "; ".join(errors))
    if isinstance(doc.get("pod"), dict):
        from tmhpvsim_tpu.obs.pod import validate_pod_section

        errors = validate_pod_section(doc["pod"])
        if errors:
            raise ValueError("run report pod: " + "; ".join(errors))
    if isinstance(doc.get("attribution"), dict):
        from tmhpvsim_tpu.obs.attribution import validate_attribution_section

        errors = validate_attribution_section(doc["attribution"])
        if errors:
            raise ValueError("run report attribution: " + "; ".join(errors))
    try:
        json.dumps(doc)
    except (TypeError, ValueError) as e:
        raise ValueError(f"run report is not JSON-serialisable: {e}") from e
    return doc


def device_info() -> dict:
    """Platform/device/process facts, every query individually guarded —
    a report must never die on a backend that cannot answer (the wedged
    tunnel answers nothing; the watchdog path still needs its report)."""
    out = {"platform": None, "device_kind": None, "n_devices": 0,
           "process_count": 1, "process_index": 0, "memory_stats": None}
    try:
        import jax
    except Exception as e:
        logger.warning("device_info: jax unavailable (%s)", e)
        return out
    for key, query in (
        ("platform", lambda: jax.default_backend()),
        ("device_kind", lambda: jax.local_devices()[0].device_kind),
        ("n_devices", lambda: jax.device_count()),
        ("process_count", lambda: jax.process_count()),
        ("process_index", lambda: jax.process_index()),
    ):
        try:
            out[key] = query()
        except Exception:
            pass
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats is not None:
            # plain ints only (device stats can carry numpy scalars)
            out["memory_stats"] = {k: int(v) for k, v in stats.items()}
    except Exception:
        pass  # CPU backends have no memory_stats
    return out


def _config_doc(config) -> Optional[dict]:
    """JSON-able echo of a SimConfig (or a prepared dict, passed
    through).  Dataclass-based so the echo tracks config growth; tuples
    normalised to lists for stable comparisons."""
    if config is None or isinstance(config, dict):
        return config
    try:
        doc = dataclasses.asdict(config)
    except TypeError:
        doc = {k: getattr(config, k) for k in dir(config)
               if not k.startswith("_")
               and isinstance(getattr(config, k), (str, int, float,
                                                   bool, type(None)))}
    grid = doc.get("site_grid")
    if isinstance(grid, dict):  # 10k-site grids: echo the size, not rows
        doc["site_grid"] = {"n_sites": len(grid.get("latitude", ()))}
    if getattr(config, "fleet", None) is not None:
        # million-row fleets: echo the identity (size + content digest +
        # cohort width), never the parameter columns (schema v12)
        fp = config.fleet
        doc["fleet"] = {"n_sites": len(fp), "n_cohorts": fp.n_cohorts,
                        "digest": fp.digest()}
    return json.loads(json.dumps(doc, default=_jsonable))


def _jsonable(v):
    for cast in (int, float, str):
        try:
            return cast(v)
        except (TypeError, ValueError):
            continue
    return repr(v)


def _plan_doc(plan) -> Optional[dict]:
    if plan is None or isinstance(plan, dict):
        return plan
    return {"block_impl": plan.block_impl,
            "scan_unroll": plan.scan_unroll,
            "stats_fusion": plan.stats_fusion,
            "slab_chains": plan.slab_chains,
            # getattr: plan dicts rebuilt from pre-v4 documents / old
            # autotune cache entries may predate the field
            "blocks_per_dispatch": int(getattr(plan, "blocks_per_dispatch",
                                               1)),
            # getattr: pre-v8 plans predate the precision axes
            "compute_dtype": str(getattr(plan, "compute_dtype", "f32")),
            "kernel_impl": str(getattr(plan, "kernel_impl", "exact")),
            # getattr: pre-v11 plans predate the scan-restructuring axes
            "rng_batch": str(getattr(plan, "rng_batch", "scan")),
            "geom_stride": int(getattr(plan, "geom_stride", 1)),
            "source": plan.source}


def _latency_doc(snap: Optional[dict]) -> Optional[dict]:
    """Quantile summary of one latency histogram snapshot."""
    from tmhpvsim_tpu.obs.metrics import quantile_from_snapshot

    if not snap or not snap.get("count"):
        return None
    return {
        "count": snap["count"],
        "mean_s": snap.get("mean"),
        "min_s": snap.get("min"),
        "max_s": snap.get("max"),
        "p50_s": quantile_from_snapshot(snap, 0.50),
        "p90_s": quantile_from_snapshot(snap, 0.90),
        "p99_s": quantile_from_snapshot(snap, 0.99),
    }


def _sum_prefixed(counters: dict, prefix: str) -> float:
    return sum(v for k, v in counters.items() if k.startswith(prefix))


def _streaming_section(snap: dict) -> Optional[dict]:
    """The ``streaming`` report section from the well-known metric names
    the instrumented runtime layers use (funnel, retry, brokers, the
    pvsim join-latency accounting).  None when the run streamed nothing
    (pure jax-backend runs keep their reports v2-shaped)."""
    hists = snap.get("histograms", {})
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    streamed = (
        "streaming.publish_to_join_s" in hists
        or "streaming.join_to_csv_s" in hists
        or any(k.startswith(("funnel.", "broker.", "retry."))
               for k in list(counters) + list(gauges))
    )
    if not streamed:
        return None
    return {
        "publish_to_join": _latency_doc(
            hists.get("streaming.publish_to_join_s")),
        "join_to_csv": _latency_doc(hists.get("streaming.join_to_csv_s")),
        "rows_written": int(counters.get("pvsim.rows_written_total", 0)),
        "funnel": {
            "pending_high_water":
                int(gauges.get("funnel.pending_high_water", 0)),
            "evictions": int(counters.get("funnel.evicted_total", 0)),
            "stall_suspends":
                int(counters.get("funnel.stall_suspends_total", 0)),
            "backpressure_waits":
                int(counters.get("funnel.backpressure_waits_total", 0)),
        },
        "retry": {
            "attempts": int(_sum_prefixed(counters, "retry.attempts.")),
            "exhausted": int(_sum_prefixed(counters, "retry.exhausted.")),
        },
        "broker": {
            "connects": int(counters.get("broker.connects_total", 0)),
            "reconnects": int(counters.get("broker.reconnects_total", 0)),
            "published": int(counters.get("broker.published_total", 0)),
            "delivered": int(counters.get("broker.delivered_total", 0)),
        },
    }


def executor_section(snap: dict) -> Optional[dict]:
    """The ``executor`` report section (schema v4) from the well-known
    ``executor.*`` metric names the warm-start layer records
    (engine/compilecache.py listener + the Simulation dispatch loops).
    None when the run recorded nothing executor-related (older-style
    runs keep their reports free of the section)."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    if not any(k.startswith("executor.")
               for k in list(counters) + list(gauges)):
        return None
    out = {
        "compile_warm": int(counters.get("executor.compile_warm_total", 0)),
        "compile_cold": int(counters.get("executor.compile_cold_total", 0)),
        "dispatches": int(counters.get("executor.dispatches_total", 0)),
        "aot_warmup": int(counters.get("executor.aot_warmup_total", 0)),
        "aot_warmup_errors":
            int(counters.get("executor.aot_warmup_errors_total", 0)),
    }
    if "executor.aot_warmup_s" in gauges:
        out["aot_warmup_s"] = float(gauges["executor.aot_warmup_s"])
    if "executor.blocks_per_dispatch" in gauges:
        out["blocks_per_dispatch"] = \
            int(gauges["executor.blocks_per_dispatch"])
    return out


def serving_section(snap: dict) -> Optional[dict]:
    """The ``serving`` report section (schema v6) from the well-known
    ``serve.*`` metric names the scenario server + micro-batcher record
    (serve/server.py, serve/batcher.py).  None when the run served
    nothing — batch and app runs keep their reports section-free."""
    from tmhpvsim_tpu.obs.metrics import quantile_from_snapshot

    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    hists = snap.get("histograms", {})
    if not any(k.startswith("serve.")
               for k in list(counters) + list(gauges) + list(hists)):
        return None
    occ = hists.get("serve.batch_occupancy")
    occupancy = None
    if occ and occ.get("count"):
        occupancy = {
            "batches": occ["count"],
            "mean": occ.get("mean"),
            "max": occ.get("max"),
            "p50": quantile_from_snapshot(occ, 0.50),
        }
    return {
        "requests": int(counters.get("serve.requests_total", 0)),
        "replies": int(counters.get("serve.replies_total", 0)),
        "rejected": int(counters.get("serve.rejected_total", 0)),
        "timeouts": int(counters.get("serve.timeouts_total", 0)),
        "batches": int(counters.get("serve.batches_total", 0)),
        "in_flight": int(gauges.get("serve.in_flight", 0)),
        "occupancy": occupancy,
        "queue_wait": _latency_doc(hists.get("serve.queue_wait_s")),
        "dispatch": _latency_doc(hists.get("serve.dispatch_s")),
        "reply_latency": _latency_doc(hists.get("serve.reply_latency_s")),
    }


def fleet_serving_section(router_snap: dict,
                          workers: list) -> Optional[dict]:
    """The v16 ``serving.fleet`` sub-doc from a router registry
    snapshot plus ``[(worker_name, worker_snapshot), ...]`` (one
    snapshot per worker, counters summed across its lives by the
    caller).  None when the router saw no traffic AND no workers were
    given — a single-worker serve never gains the key.

    Invariant the tools check: the per-worker ``requests`` rows
    partition the router's forwarded totals
    (``sum(workers[].requests) == router.routed + router.rerouted``)
    — every routed request landed on exactly one worker per forward.
    """
    from tmhpvsim_tpu.obs.metrics import quantile_from_snapshot

    counters = router_snap.get("counters", {})
    gauges = router_snap.get("gauges", {})
    hists = router_snap.get("histograms", {})
    if not workers and not any(k.startswith("router.")
                               for k in list(counters) + list(gauges)):
        return None

    def c(name):
        return int(counters.get(name, 0))

    rows = []
    for name, snap in workers:
        wc = snap.get("counters", {})
        wg = snap.get("gauges", {})
        wh = snap.get("histograms", {})
        occ = wh.get("serve.batch_occupancy")
        occupancy = None
        if occ and occ.get("count"):
            occupancy = {"batches": occ["count"],
                         "mean": occ.get("mean"),
                         "max": occ.get("max"),
                         "p50": quantile_from_snapshot(occ, 0.50)}
        rows.append({
            "name": name,
            "requests": int(wc.get("serve.requests_total", 0)),
            "replies": int(wc.get("serve.replies_total", 0)),
            "rejected": int(wc.get("serve.rejected_total", 0)),
            "timeouts": int(wc.get("serve.timeouts_total", 0)),
            "batches": int(wc.get("serve.batches_total", 0)),
            "backfilled": int(wc.get("serve.backfilled_total", 0)),
            "occupancy": occupancy,
            "compile_cold":
                int(wc.get("executor.compile_cold_total", 0)),
            "compile_warm":
                int(wc.get("executor.compile_warm_total", 0)),
            "restarts": int(gauges.get(
                f"resilience.supervised_restarts.{name}", 0)),
        })
    return {
        "router": {
            "requests": c("router.requests_total"),
            "routed": c("router.routed_total"),
            "replies": c("router.replies_total"),
            "rejected": c("router.rejected_total"),
            "quota_rejected": c("router.quota_rejected_total"),
            "shed": c("router.shed_total"),
            "rerouted": c("router.rerouted_total"),
            "dup_replies": c("router.dup_replies_total"),
            "timeouts": c("router.timeouts_total"),
            "worker_down": c("router.worker_down_total"),
            "workers_ready": int(gauges.get("router.workers_ready", 0)),
            "pending": int(gauges.get("router.pending", 0)),
            "reply_latency":
                _latency_doc(hists.get("router.reply_latency_s")),
        },
        "workers": rows,
    }


def resilience_section(snap: dict) -> Optional[dict]:
    """The ``resilience`` report section (schema v7) from the
    well-known ``resilience.*`` / ``faults.*`` metric names
    (runtime/resilience.py policies + breakers, runtime/faults.py
    chokepoints, the checkpoint-resume markers in apps/pvsim.py).
    None when the run recorded none of them — healthy chaos-free runs
    keep their reports section-free."""
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    if not any(k.startswith(("resilience.", "faults."))
               for k in list(counters) + list(gauges)):
        return None
    state_names = {0: "closed", 1: "half_open", 2: "open"}
    breaker_states = {
        k[len("resilience.breaker_state."):]:
            state_names.get(int(v), str(v))
        for k, v in gauges.items()
        if k.startswith("resilience.breaker_state.")
    }
    out = {
        "resumes": int(counters.get("resilience.resumed_total", 0)),
        "restarts":
            int(gauges.get("resilience.supervised_restarts", 0)),
        "retries": int(counters.get("resilience.retries_total", 0)),
        "giveups": int(counters.get("resilience.giveups_total", 0)),
        "breaker": {
            "opens": int(_sum_prefixed(
                counters, "resilience.breaker_open_total.")),
            "rejected": int(_sum_prefixed(
                counters, "resilience.breaker_rejected_total.")),
            "states": breaker_states,
        },
        "faults_injected": int(counters.get("faults.injected_total", 0)),
        "faults_by_point": {
            k[len("faults.injected."):]: int(v)
            for k, v in counters.items()
            if k.startswith("faults.injected.")
        },
    }
    if "resilience.resumed_block" in gauges:
        out["resumed_block"] = int(gauges["resilience.resumed_block"])
    return out


class RunReport:
    """Incremental builder for one run's report.

    Sections start as None and are filled by the run path that owns
    them; ``doc()`` assembles + validates, ``write()`` lands the JSON
    atomically.  ``device`` is collected at build time unless the
    caller set it (bench's pure-host doc builder injects its own).
    """

    def __init__(self, app: str, config=None, plan=None):
        self.app = app
        self.config = _config_doc(config)
        self.plan = _plan_doc(plan)
        self.device: Optional[dict] = None
        self.timing: Optional[dict] = None
        self.checkpoint: Optional[dict] = None
        self.slabs: Optional[dict] = None
        self.realtime: Optional[dict] = None
        self.headline: Optional[dict] = None
        self.metrics: Optional[dict] = None
        self.profile: Optional[dict] = None
        self.processes: Optional[list] = None
        #: drift-sentinel section (obs/sentinel.py DriftSentinel.report())
        self.telemetry: Optional[dict] = None
        #: streaming-join section, derived from the well-known streaming
        #: metric names by :meth:`attach_metrics`
        self.streaming: Optional[dict] = None
        #: warm-start executor section (schema v4): compile cache
        #: warm/cold counts + dispatch stats, derived from the
        #: ``executor.*`` metric names by :meth:`attach_metrics` (or set
        #: directly from ``engine.compilecache.executor_doc()``)
        self.executor: Optional[dict] = None
        #: fleet-analytics section (schema v5): the host summary of the
        #: run's merged FleetAcc (obs/analytics.py ``summarize``)
        self.fleet: Optional[dict] = None
        #: scenario-serving SLO section (schema v6), derived from the
        #: ``serve.*`` metric names by :meth:`attach_metrics`
        self.serving: Optional[dict] = None
        #: recovery/chaos section (schema v7), derived from the
        #: ``resilience.*`` / ``faults.*`` metric names by
        #: :meth:`attach_metrics`
        self.resilience: Optional[dict] = None
        #: precision section (schema v8): the resolved
        #: compute_dtype/kernel_impl axes, their sentinel-gate outcome,
        #: and — in bench documents — the per-variant rate pricing
        self.precision: Optional[dict] = None
        #: backend-probe section (schema v8): bench.py probe attempt /
        #: timeout accounting under runtime.resilience.ResiliencePolicy
        self.probe: Optional[dict] = None
        #: device cost-attribution section (schema v10): set from
        #: ``obs.cost.cost_doc`` by every path that measures a site-s/s
        #: rate (apps/pvsim.py jax wrapper, bench.py, serve shutdown)
        self.cost: Optional[dict] = None
        #: mesh/topology section (schema v13): set from
        #: ``parallel.distributed.mesh_doc`` by sharded runs — device
        #: grid shape + axis names, process topology, chain layout
        self.mesh: Optional[dict] = None
        #: pod observability section (schema v14): set from
        #: ``obs.pod.PodMonitor.doc()`` — per-host heartbeat rows, skew
        #: stats, straggler counts, collective-vs-compute comm_frac
        self.pod: Optional[dict] = None
        #: phase-attribution section (schema v15): set from
        #: ``obs.attribution.attribute`` when a phase-scoped device
        #: trace was captured — per-phase device seconds/fractions plus
        #: the unattributed residual
        self.attribution: Optional[dict] = None

    def set_timing(self, timer_summary: dict) -> None:
        """Adopt a ``BlockTimer.summary()`` dict as the timing section."""
        keys = ("compile_s", "first_block_s", "steady_block_s",
                "n_blocks_timed", "site_seconds_per_s",
                "rate_includes_compile")
        self.timing = {k: timer_summary[k] for k in keys
                       if k in timer_summary}

    def attach_metrics(self, registry) -> None:
        """Snapshot a metrics registry and derive the checkpoint / slab
        / realtime sections from the well-known metric names the
        instrumented layers use."""
        snap = registry.snapshot()
        self.metrics = snap
        hists = snap.get("histograms", {})
        gauges = snap.get("gauges", {})
        counters = snap.get("counters", {})
        save = hists.get("checkpoint.save_s")
        restore = hists.get("checkpoint.restore_s")
        ck_extra = {name for src in (counters, gauges) for name in src
                    if name.startswith("checkpoint.")}
        if save or restore or ck_extra:
            self.checkpoint = {
                "saves": (save or {}).get("count", 0),
                "save_total_s": (save or {}).get("sum", 0.0),
                "restores": (restore or {}).get("count", 0),
                "restore_total_s": (restore or {}).get("sum", 0.0),
            }
            # v9 additive keys, present only when the subsystem used
            # the corresponding feature (engine/checkpoint.py)
            for key, src, metric in (
                ("generations", gauges, "checkpoint.generations"),
                ("latest_generation", gauges,
                 "checkpoint.latest_generation"),
                ("verify_failures", counters,
                 "checkpoint.verify_fail_total"),
                ("fallbacks", counters, "checkpoint.fallback_total"),
                ("async_saves", counters, "checkpoint.async_saves_total"),
                ("async_dropped", counters,
                 "checkpoint.async_dropped_total"),
                ("async_write_failures", counters,
                 "checkpoint.async_write_failures_total"),
                ("async_queue_depth", gauges,
                 "checkpoint.async_queue_depth"),
                ("preempt_snapshots", counters,
                 "checkpoint.preempt_snapshots_total"),
            ):
                if metric in src:
                    self.checkpoint[key] = int(src[metric])
        if "slab.total" in gauges:
            self.slabs = {"completed": int(gauges.get("slab.completed", 0)),
                          "total": int(gauges["slab.total"])}
        if "clock.pacing_slip_total_s" in gauges or \
                "clock.pacing_lag_s" in gauges:
            self.realtime = {
                "pacing_lag_s": gauges.get("clock.pacing_lag_s", 0.0),
                "pacing_slip_total_s":
                    gauges.get("clock.pacing_slip_total_s", 0.0),
            }
        streaming = _streaming_section(snap)
        if streaming is not None:
            self.streaming = streaming
        executor = executor_section(snap)
        if executor is not None:
            # preserve fields the caller set directly (e.g. cache_dir
            # from engine.compilecache.executor_doc())
            self.executor = {**executor, **(self.executor or {})}
        serving = serving_section(snap)
        if serving is not None:
            # a fleet sub-doc attached earlier survives the re-derive
            fleet = (self.serving or {}).get("fleet")
            self.serving = serving
            if fleet is not None:
                self.serving["fleet"] = fleet
        resilience = resilience_section(snap)
        if resilience is not None:
            self.resilience = resilience

    def attach_fleet_serving(self, router_snap: dict,
                             workers: list) -> None:
        """Attach the v16 ``serving.fleet`` sub-doc (see
        :func:`fleet_serving_section`); merges into whatever
        ``serving`` section :meth:`attach_metrics` derived."""
        fleet = fleet_serving_section(router_snap, workers)
        if fleet is None:
            return
        if self.serving is None:
            self.serving = serving_section(router_snap)
        if self.serving is None:
            # router registries carry no serve.* names: synthesize the
            # base section from the fleet totals so the serving shape
            # stays the documented v6 one with the additive fleet key
            r = fleet["router"]
            self.serving = {
                "requests": r["requests"],
                "replies": r["replies"],
                "rejected": r["rejected"],
                "timeouts": r["timeouts"],
                "batches": sum(w["batches"] for w in fleet["workers"]),
                "in_flight": r["pending"],
                "occupancy": None,
                "queue_wait": None,
                "dispatch": None,
                "reply_latency": r["reply_latency"],
            }
        self.serving["fleet"] = fleet

    def doc(self, validate: bool = True) -> dict:
        out = {
            "schema_version": REPORT_SCHEMA_VERSION,
            "kind": REPORT_KIND,
            "app": self.app,
            "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
            "device": self.device if self.device is not None
            else device_info(),
            "config": self.config,
            "plan": self.plan,
            "timing": self.timing,
            "checkpoint": self.checkpoint,
            "slabs": self.slabs,
            "realtime": self.realtime,
            "headline": self.headline,
            "metrics": self.metrics,
            "profile": self.profile,
            "processes": self.processes,
            "telemetry": self.telemetry,
            "streaming": self.streaming,
            "executor": self.executor,
            "fleet": self.fleet,
            "serving": self.serving,
            "resilience": self.resilience,
            "precision": self.precision,
            "probe": self.probe,
            "cost": self.cost,
            "mesh": self.mesh,
            "pod": self.pod,
            "attribution": self.attribution,
        }
        return validate_report(out) if validate else out

    def write(self, path: str) -> dict:
        """Validate + write the report JSON (atomic tmp + rename)."""
        doc = self.doc()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return doc
