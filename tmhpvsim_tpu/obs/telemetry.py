"""In-graph numerics telemetry: device-side accumulators on the scan carry.

The block step loops (``engine/simulation.py``'s ``_block_step_scan*``)
already carry per-chain state and reduced statistics through
``lax.scan``; this module adds a third passenger, a ``TelemetryAcc`` —
a flat pytree (dict of scalars / tiny vectors) of health reductions
folded *inside* the scan so raw per-second samples never leave the
device:

* per-field NaN / Inf counters over ``meter``, ``csi``, ``pv`` and
  ``residual`` (int32 — any nonzero value trips the sentinel, so
  saturation in a pathological all-NaN run is irrelevant);
* running min / max / sum / sum-of-squares moments per field in the
  compute dtype (the count-weighted float32 sums carry a relative
  error of order ``block_s * eps`` ~ 5e-4, well inside the sentinel's
  tolerance bands);
* at level ``full``: a fixed 8-bin csi histogram (bin width 0.25,
  last bin open) and Markov cloud-state occupancy counts.

The accumulator is zero-initialised *inside* the block jit, so each
block's telemetry is a pure per-block delta: the mesh aggregation in
``parallel/distributed.psum_telemetry`` can psum/pmin/pmax shard
contributions without double-counting history, and the drift sentinel
(``obs/sentinel.py``) gets per-block moments it can localise failures
with.  The host sees roughly thirty scalars once per block, piggybacked
on the existing per-block device->host sync.

Levels: ``off`` (telemetry structurally absent from the traced graph —
byte-identical HLO, asserted by tests), ``light`` (counters + moments),
``full`` (light + histogram + occupancy).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

#: valid values for SimConfig.telemetry / Plan.telemetry / --telemetry
TELEMETRY_LEVELS = ("off", "light", "full")

#: fields with NaN/Inf counters and moment accumulators
TELEMETRY_FIELDS = ("meter", "csi", "pv", "residual")

#: csi histogram: CSI_HIST_BINS bins of width CSI_HIST_WIDTH starting
#: at 0; the last bin is open (clear-sky index rarely exceeds ~1.5)
CSI_HIST_BINS = 8
CSI_HIST_WIDTH = 0.25


def init_acc(level: str, dtype=jnp.float32, n_chains=None) -> dict:
    """Fresh zeroed TelemetryAcc pytree for one block.

    Flat dict so shard_map specs and psum kind dispatch stay trivial.
    min/max start at +/-finfo.max (not inf: inf survives pmin/pmax but
    poisons the ``observed`` heuristic in :func:`summarize`).

    With ``n_chains`` the per-field leaves are **per-chain vectors**:
    the scan-body fold (:func:`fold_second`) then accumulates purely
    elementwise — no cross-chain reduction per second, so on the
    bandwidth-bound accelerator scan body the ops fuse into the
    existing per-chain loop instead of adding a reduction pass per
    field per second.  (On a compute-bound 1-core CPU host every
    elementwise op still costs; there the autotuner resolves large
    chain counts to the wide impl, whose :func:`fold_wide` is a few
    bulk reductions measured ~1 % — the 2 % acceptance arm.)
    :func:`reduce_chainwise` collapses the per-chain acc to the scalar
    form once per block, after the scan.  Per-chain accs carry a
    non-finite counter ``nf_{field}`` instead of ``inf_{field}`` (one
    fewer mask in the hot fold); the reduction derives
    ``inf = nf - nan``.
    """
    if level not in ("light", "full"):
        raise ValueError(f"init_acc: telemetry level {level!r} must be "
                         f"'light' or 'full'")
    dt = jnp.dtype(dtype)
    big = jnp.asarray(jnp.finfo(dt).max, dt)
    acc = {"count": jnp.zeros((), dt)}
    shape = () if n_chains is None else (int(n_chains),)
    for f in TELEMETRY_FIELDS:
        acc[f"nan_{f}"] = jnp.zeros(shape, jnp.int32)
        if n_chains is None:
            acc[f"inf_{f}"] = jnp.zeros(shape, jnp.int32)
        else:
            acc[f"nf_{f}"] = jnp.zeros(shape, jnp.int32)
        acc[f"min_{f}"] = jnp.full(shape, big, dt)
        acc[f"max_{f}"] = jnp.full(shape, -big, dt)
        acc[f"sum_{f}"] = jnp.zeros(shape, dt)
        acc[f"sumsq_{f}"] = jnp.zeros(shape, dt)
    if level == "full":
        acc["csi_hist"] = jnp.zeros((CSI_HIST_BINS,), dt)
        if n_chains is None:
            acc["occupancy"] = jnp.zeros((2,), dt)  # [clear, covered]
        else:
            acc["occ_cov"] = jnp.zeros(shape, jnp.int32)
    return acc


def leaf_kinds(acc: dict) -> dict:
    """Cross-shard reduction kind per leaf: 'min' | 'max' | 'sum'."""
    return {
        k: ("min" if k.startswith("min_")
            else "max" if k.startswith("max_")
            else "sum")
        for k in acc
    }


def fold_second(acc: dict, level: str, *, meter, pv, csi, residual,
                covered, valid) -> dict:
    """Fold one second of per-chain ``(n_chains,)`` vectors into a
    **per-chain** acc (``init_acc(..., n_chains=n)``).

    Purely elementwise — every op here fuses into the scan body's
    existing per-chain loop, so the hot-path cost is a handful of
    compares/adds per chain per second, not a reduction pass.  ``valid``
    is the scalar duration mask the stats fold already computes (padding
    seconds past ``duration_s`` contribute nothing).  Non-finite samples
    are excluded from the moments (counted in the NaN / non-finite
    counters instead) so a single NaN localises to its counter rather
    than poisoning every moment in the block.
    """
    dt = acc["count"].dtype
    big = jnp.asarray(jnp.finfo(dt).max, dt)
    vz = jnp.where(valid, 1.0, 0.0).astype(dt)
    n = meter.shape[0]
    out = dict(acc)
    out["count"] = acc["count"] + vz * n
    for name, v in (("meter", meter), ("csi", csi), ("pv", pv),
                    ("residual", residual)):
        v = v.astype(dt)  # no-op for fields already in the compute dtype
        isn = v != v
        fin = jnp.isfinite(v)
        use = fin & valid
        out[f"nan_{name}"] = acc[f"nan_{name}"] + (isn & valid)
        # valid & ~fin == valid ^ use (use is a subset of valid)
        out[f"nf_{name}"] = acc[f"nf_{name}"] + (valid ^ use)
        v0 = jnp.where(use, v, jnp.zeros_like(v))
        out[f"min_{name}"] = jnp.minimum(acc[f"min_{name}"],
                                         jnp.where(use, v, big))
        out[f"max_{name}"] = jnp.maximum(acc[f"max_{name}"],
                                         jnp.where(use, v, -big))
        out[f"sum_{name}"] = acc[f"sum_{name}"] + v0
        out[f"sumsq_{name}"] = acc[f"sumsq_{name}"] + v0 * v0
    if level == "full":
        fin_c = jnp.isfinite(csi)
        bins = jnp.clip(csi / CSI_HIST_WIDTH, 0, CSI_HIST_BINS - 1)
        idx = jnp.where(fin_c, bins, 0).astype(jnp.int32)
        w = vz * jnp.where(fin_c, 1.0, 0.0).astype(dt)
        out["csi_hist"] = acc["csi_hist"].at[idx].add(w)
        # covered arrives as the model's 0/1 float mask, not bool
        out["occ_cov"] = acc["occ_cov"] + ((covered != 0) & valid)
    return out


def reduce_chainwise(acc: dict) -> dict:
    """Collapse a per-chain TelemetryAcc to the scalar (shard-level)
    form — called once per block, after the scan, inside the same jit.
    Leaf names/shapes of the result match ``init_acc(level, dtype)``,
    so psum dispatch, :func:`summarize` and :func:`publish` see one
    format regardless of how the block was folded.
    """
    out = {}
    for k, v in acc.items():
        if k.startswith("nan_"):
            out[k] = v.sum(dtype=jnp.int32)
        elif k.startswith("nf_"):
            f = k[3:]
            out[f"inf_{f}"] = (v.sum(dtype=jnp.int32)
                               - acc[f"nan_{f}"].sum(dtype=jnp.int32))
        elif k.startswith("min_"):
            out[k] = v.min()
        elif k.startswith("max_"):
            out[k] = v.max()
        elif k.startswith(("sum_", "sumsq_")):
            out[k] = v.sum()
        elif k == "occ_cov":
            cov = v.sum().astype(acc["count"].dtype)
            out["occupancy"] = jnp.stack([acc["count"] - cov, cov])
        else:  # count, csi_hist: already shard-level
            out[k] = v
    return out


def fold_wide(acc: dict, level: str, *, meter, pv, t, duration_s) -> dict:
    """Fold materialised ``(n_chains, T)`` block arrays into ``acc``.

    The wide formulation never materialises csi, so only meter / pv /
    residual are folded; csi stays unobserved (and :func:`summarize`
    reports it as such).  ``level`` is accepted for signature parity —
    the histogram/occupancy extras need csi and are likewise skipped.
    """
    del level
    dt = acc["count"].dtype
    big = jnp.asarray(jnp.finfo(dt).max, dt)
    valid = t < duration_s                       # (T,)
    vz = jnp.where(valid, 1.0, 0.0).astype(dt)   # (T,)
    n = meter.shape[0]
    residual = meter - pv
    out = dict(acc)
    out["count"] = acc["count"] + vz.sum() * n
    for name, v in (("meter", meter), ("pv", pv), ("residual", residual)):
        isn = jnp.isnan(v)
        fin = jnp.isfinite(v)
        vmask = valid[None, :]
        v0 = jnp.where(fin, v, jnp.zeros_like(v)) * vz[None, :]
        out[f"nan_{name}"] = acc[f"nan_{name}"] + (isn & vmask).sum(
            dtype=jnp.int32)
        out[f"inf_{name}"] = acc[f"inf_{name}"] + ((~fin) & (~isn)
                                                   & vmask).sum(
            dtype=jnp.int32)
        out[f"min_{name}"] = jnp.minimum(
            acc[f"min_{name}"], jnp.where(fin & vmask, v, big).min().astype(dt))
        out[f"max_{name}"] = jnp.maximum(
            acc[f"max_{name}"],
            jnp.where(fin & vmask, v, -big).max().astype(dt))
        out[f"sum_{name}"] = acc[f"sum_{name}"] + v0.sum().astype(dt)
        out[f"sumsq_{name}"] = acc[f"sumsq_{name}"] + (v0 * v0).sum().astype(dt)
    return out


def summarize(acc: dict) -> dict:
    """Host-side reduction of a (fetched) TelemetryAcc into plain floats.

    A field that was never folded (e.g. csi under the wide impl) keeps
    its +/-big min/max sentinels and zero sums — reported with
    ``observed: False`` so the drift sentinel skips its bands.
    """
    host = {k: np.asarray(v) for k, v in acc.items()}
    big = float(np.finfo(host["count"].dtype).max)
    count = float(host["count"])
    fields = {}
    for f in TELEMETRY_FIELDS:
        mn = float(host[f"min_{f}"])
        mx = float(host[f"max_{f}"])
        s = float(host[f"sum_{f}"])
        ss = float(host[f"sumsq_{f}"])
        nan = int(host[f"nan_{f}"])
        inf = int(host[f"inf_{f}"])
        observed = not (mn > 0.5 * big and mx < -0.5 * big
                        and s == 0.0 and nan == 0 and inf == 0)
        mean = s / count if count else 0.0
        var = max(ss / count - mean * mean, 0.0) if count else 0.0
        fields[f] = {
            "nan": nan,
            "inf": inf,
            "observed": observed,
            "min": mn if mn < 0.5 * big else None,
            "max": mx if mx > -0.5 * big else None,
            "mean": mean,
            "std": math.sqrt(var),
        }
    out = {"count": count, "fields": fields}
    if "csi_hist" in host:
        out["csi_hist"] = [float(x) for x in host["csi_hist"]]
    if "occupancy" in host:
        out["cloud_occupancy"] = {
            "clear": float(host["occupancy"][0]),
            "covered": float(host["occupancy"][1]),
        }
    return out


def publish(registry, summary: dict) -> None:
    """Flush one block summary into the metrics registry (``device.*``).

    Counters accumulate across blocks (NaN/Inf totals, histogram mass,
    occupancy seconds); gauges hold the latest block's moments.
    """
    registry.counter("device.telemetry.blocks_total").inc()
    for f, s in summary["fields"].items():
        registry.counter(f"device.nan_total.{f}").inc(s["nan"])
        registry.counter(f"device.inf_total.{f}").inc(s["inf"])
        if not s["observed"]:
            continue
        registry.gauge(f"device.{f}.mean").set(s["mean"])
        registry.gauge(f"device.{f}.std").set(s["std"])
        if s["min"] is not None:
            registry.gauge(f"device.{f}.min").set(s["min"])
        if s["max"] is not None:
            registry.gauge(f"device.{f}.max").set(s["max"])
    for i, v in enumerate(summary.get("csi_hist") or ()):
        if v:
            registry.counter(f"device.csi_hist.bin{i}").inc(v)
    for k, v in (summary.get("cloud_occupancy") or {}).items():
        if v:
            registry.counter(f"device.cloud_occupancy.{k}").inc(v)


def repl_view(acc: dict, repl_view_fn) -> dict:
    """Fetch every leaf to host numpy via the sim's replicated-view
    helper (handles non-addressable sharded arrays)."""
    return {k: np.asarray(repl_view_fn(v)) for k, v in acc.items()}
