"""Semantic phase attribution: per-phase device-time split of a trace.

PR 15's ``obs.pod.comm_split`` separates collective from compute time —
one bit of taxonomy.  This module generalises that event walk into a
phase-level one: when the engine traces under ``SimConfig.phase_obs``
(engine/simulation.py ``_phase`` / obs/profiler.py :func:`phase_scope`),
every HLO op carries a ``ph__<phase>`` component in its ``op_name``
metadata, and a device trace of such a build can be bucketed into the
~9 semantic stages of the per-second chain (rng, markov, csi, geometry,
physics, fleet, telemetry, analytics, collectives) plus an
``unattributed`` residual.

The join is indirect, by necessity: Chrome-trace op events do NOT carry
scope metadata — they carry the *optimized-HLO instruction name*
(``args.hlo_op``, e.g. ``fusion.1``).  The scope path lives in the
compiled HLO text (``jit.lower(...).compile().as_text()``), where every
instruction's ``metadata={op_name="jit(f)/.../ph__geometry/sin"}``
records the scopes it was traced under.  So attribution is a two-file
protocol:

1. at capture time, :func:`write_phase_map` parses the compiled HLO of
   the active block jit into ``{instruction name: phase}`` — fusions
   inherit their root op's scope, falling back to a majority vote over
   the fused computation's members — and drops ``phase_map.json`` next
   to the trace;
2. :func:`attribute` walks the trace's XLA op events (gzip or plain
   Chrome JSON) and joins durations against that map
   (``basis: "scope"``), degrading to op-name heuristics — collectives
   by prefix, rng by name — when no map or no scoped ops are present
   (``basis: "opname-heuristic"``), and to ``basis: "unavailable"``
   with a rate-limited WARN when nothing at all can be attributed
   (older jax, scope-less builds): never an exception.

The result feeds the RunReport v15 ``attribution`` section, the
``device.phase.*`` gauges, bench.py's per-lever attribution diffs
(:func:`diff_attribution`) and obs/cost.py's ``model_error`` phase
checks (each static-v1 factor axis names the phase it claims to scale).
"""

from __future__ import annotations

import glob
import gzip
import json
import logging
import os
import re
import time
from typing import Iterator, Optional

from tmhpvsim_tpu.obs.pod import (COLLECTIVE_PREFIXES, _is_xla_op)
from tmhpvsim_tpu.obs.profiler import PHASE_PREFIX

logger = logging.getLogger(__name__)

ATTRIBUTION_SCHEMA_VERSION = 1

#: sidecar written next to a scoped trace by :func:`write_phase_map`
PHASE_MAP_NAME = "phase_map.json"

#: the semantic stages of the per-second chain, in pipeline order
#: (engine/simulation.py wraps each in ``phase_scope``)
PHASES = ("rng", "markov", "csi", "geometry", "physics", "fleet",
          "telemetry", "analytics", "collectives")

#: recognised ``basis`` values of an attribution doc
BASES = ("scope", "opname-heuristic", "unavailable")

#: op-name fragments attributed to the rng phase when no scope map is
#: available (threefry/philox hash chains dominate the draw cost)
_RNG_NAME_PATTERNS = ("rng", "threefry", "philox")

#: control-flow CONTAINER instructions: their trace events re-span the
#: body thunks' events on the same thread (a ``while`` duration is the
#: whole loop including every member op), so counting them alongside
#: the member events double-counts ~every scan body.  Excluded from
#: the op walk; the loop's own bookkeeping overhead lands nowhere,
#: which is the conservative choice.
_CONTAINER_OPS = ("while", "conditional", "call")

#: min seconds between "no scope metadata" WARNs (a bench sweep calls
#: attribute() once per variant; one warning carries the message)
_WARN_INTERVAL_S = 60.0
_last_warn = [0.0]


# -- trace event walk ------------------------------------------------------


def _iter_trace_files(log_dir: str) -> Iterator[str]:
    """Every Chrome-trace export under ``log_dir`` — the profiler's
    ``plugins/profile/<ts>/*.trace.json.gz`` layout plus plain
    ``*.trace.json`` (hand-built fixtures, other exporters)."""
    for pattern in ("*.trace.json.gz", "*.trace.json"):
        for path in sorted(glob.glob(
                os.path.join(log_dir, "**", pattern), recursive=True)):
            yield path


def _load_trace(path: str) -> Optional[dict]:
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt", encoding="utf-8",
                           errors="replace") as f:
                return json.load(f)
        with open(path, encoding="utf-8", errors="replace") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, EOFError) as e:
        logger.warning("unparsable device trace %s: %s", path, e)
        return None


def iter_xla_op_events(log_dir: str) -> Iterator[tuple]:
    """``(op_name, hlo_op, dur_us)`` for every XLA op duration event in
    every parsable trace under ``log_dir``.

    ``hlo_op`` is the optimized-HLO instruction name jax stamps into
    ``args.hlo_op`` (the :func:`attribute` join key); None when the
    export carries no HLO metadata.  The op/thread/process filtering is
    ``obs.pod._is_xla_op`` — this iterator is the generalised event
    walk ``comm_split`` grew from — plus the :data:`_CONTAINER_OPS`
    exclusion (a ``while`` event spans its whole body's events, so
    keeping it would double-count every scan iteration).
    """
    for path in _iter_trace_files(log_dir):
        trace = _load_trace(path)
        if trace is None:
            continue
        events = trace.get("traceEvents") or []
        proc_names: dict = {}
        thread_names: dict = {}
        for ev in events:
            if ev.get("ph") != "M":
                continue
            args = ev.get("args") or {}
            if ev.get("name") == "process_name":
                proc_names[ev.get("pid")] = str(args.get("name", ""))
            elif ev.get("name") == "thread_name":
                thread_names[(ev.get("pid"), ev.get("tid"))] = \
                    str(args.get("name", ""))
        for ev in events:
            if ev.get("ph") != "X":
                continue
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur <= 0:
                continue
            name = str(ev.get("name", ""))
            thread = thread_names.get((ev.get("pid"), ev.get("tid")), "")
            process = proc_names.get(ev.get("pid"), "")
            if not _is_xla_op(name, thread, process):
                continue
            args = ev.get("args") or {}
            hlo_op = args.get("hlo_op")
            op = str(hlo_op) if hlo_op else name
            if op.split(".", 1)[0] in _CONTAINER_OPS:
                continue
            yield name, (str(hlo_op) if hlo_op else None), float(dur)


# -- phase classification --------------------------------------------------


_SCOPE_RE = re.compile(re.escape(PHASE_PREFIX) + r"([A-Za-z0-9_]+)")


def phase_of_scope_path(op_name: str) -> Optional[str]:
    """The phase named by the INNERMOST ``ph__<phase>`` occurrence in an
    HLO ``op_name`` scope path (``jit(f)/jit(main)/ph__geometry/sin``
    -> ``"geometry"``), or None when no phase scope encloses the op.

    Matched by substring, not path component: transforms wrap the scope
    name in brackets — under vmap/while the path reads
    ``.../vmap(ph__markov)/while/body/...`` — and the thunk-level
    instructions of a scanned graph live almost entirely inside such
    wrapped components."""
    m = _SCOPE_RE.findall(op_name)
    return m[-1] if m else None


def phase_of_op_name(name: str) -> Optional[str]:
    """Scope-less fallback: the phase an optimized-HLO op name alone
    reveals — collectives by instruction-name prefix (the
    ``comm_split`` taxonomy), rng by hash-chain fragments.  Everything
    else is unattributable without a scope map."""
    if name.startswith(COLLECTIVE_PREFIXES):
        return "collectives"
    base = name.lower()
    if any(p in base for p in _RNG_NAME_PATTERNS):
        return "rng"
    return None


# -- compiled-HLO phase map ------------------------------------------------

_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=")
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->.*\{")
_OP_NAME_RE = re.compile(r'metadata=\{[^}]*?op_name="([^"]*)"')
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")


def parse_hlo_phase_map(hlo_text: str) -> dict:
    """``{optimized-HLO instruction name: phase}`` from one compiled
    module's text (``lowered.compile().as_text()``).

    An instruction's phase is the innermost ``ph__*`` scope in its
    ``op_name`` metadata.  A fusion whose own metadata names no phase
    (or a root op traced outside any scope) falls back to the majority
    phase among its fused computation's member instructions — XLA fuses
    across scope boundaries freely, and charging the whole fusion to
    the dominant member is the honest first-order split.  An unscoped
    instruction (copies, converts, tuple plumbing — inserted by late
    passes with no metadata) inside a computation whose scoped members
    UNANIMOUSLY name one phase inherits that phase: a rejection
    sampler's while-body carry copies are that sampler's work
    (measured: they were >60% of a CPU trace's device time before this
    rule).  Instructions with no phase anywhere are omitted (they land
    in the residual) — in particular plumbing inside MIXED-phase
    computations, like the main scan body's carries, stays
    unattributed rather than being charged to the dominant phase.
    """
    instr_phase: dict = {}
    comp_counts: dict = {}          # computation -> {phase: n_members}
    comp_unscoped: dict = {}        # computation -> [unscoped names]
    fusion_calls: dict = {}         # instr -> (containing, called comp)
    current_comp = None
    for line in hlo_text.splitlines():
        mc = _COMP_RE.match(line)
        if mc:
            current_comp = mc.group(1)
            continue
        if line.startswith("}"):
            current_comp = None
            continue
        mi = _INSTR_RE.match(line)
        if not mi:
            continue
        name = mi.group(1)
        mo = _OP_NAME_RE.search(line)
        phase = phase_of_scope_path(mo.group(1)) if mo else None
        if phase is not None:
            instr_phase[name] = phase
            if current_comp is not None:
                counts = comp_counts.setdefault(current_comp, {})
                counts[phase] = counts.get(phase, 0) + 1
        elif current_comp is not None and " parameter(" not in line:
            comp_unscoped.setdefault(current_comp, []).append(name)
        mcall = _CALLS_RE.search(line)
        if mcall:
            fusion_calls[name] = (current_comp, mcall.group(1))
    # second pass: fusions without their own phase inherit the majority
    # phase of the computation they call (ties stay unattributed).  The
    # inherited phase counts toward the CONTAINING computation's phase
    # mix, so the unanimity pass below sees a computation holding, say,
    # one rng op and one geometry fusion as mixed — not unanimous rng.
    for name, (container, comp) in fusion_calls.items():
        if name in instr_phase:
            continue
        counts = comp_counts.get(comp)
        if not counts:
            continue
        ranked = sorted(counts.items(), key=lambda kv: -kv[1])
        if len(ranked) == 1 or ranked[0][1] > ranked[1][1]:
            phase = ranked[0][0]
            instr_phase[name] = phase
            if container is not None:
                ccounts = comp_counts.setdefault(container, {})
                ccounts[phase] = ccounts.get(phase, 0) + 1
    # third pass: unscoped members of a single-phase computation inherit
    # its phase (setdefault — a fusion-majority assignment wins)
    for comp, members in comp_unscoped.items():
        counts = comp_counts.get(comp)
        if not counts or len(counts) != 1:
            continue
        phase = next(iter(counts))
        for name in members:
            instr_phase.setdefault(name, phase)
    return instr_phase


def write_phase_map(log_dir: str, hlo_texts) -> dict:
    """Parse each compiled-HLO text and write the merged
    ``phase_map.json`` sidecar into ``log_dir`` (next to the trace the
    map explains).  Returns the merged ``{instruction: phase}`` map."""
    merged: dict = {}
    for text in hlo_texts:
        merged.update(parse_hlo_phase_map(text))
    os.makedirs(log_dir, exist_ok=True)
    doc = {
        "schema_version": ATTRIBUTION_SCHEMA_VERSION,
        "n_mapped": len(merged),
        "op_phase": merged,
    }
    with open(os.path.join(log_dir, PHASE_MAP_NAME), "w") as f:
        json.dump(doc, f)
    return merged


def read_phase_map(log_dir: str) -> Optional[dict]:
    """The ``{instruction: phase}`` map of a capture directory, or None
    when no sidecar exists (scope-less capture — attribute() degrades
    to op-name heuristics)."""
    path = os.path.join(log_dir, PHASE_MAP_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    op_phase = doc.get("op_phase")
    return op_phase if isinstance(op_phase, dict) else None


# -- attribution -----------------------------------------------------------


def _warn_rate_limited(msg: str, *args) -> None:
    now = time.monotonic()
    if now - _last_warn[0] >= _WARN_INTERVAL_S:
        _last_warn[0] = now
        logger.warning(msg, *args)


def attribute(log_dir: str, phase_map: Optional[dict] = None
              ) -> Optional[dict]:
    """Per-phase device-time split of a ``device_trace`` capture.

    Returns the RunReport v15 ``attribution`` section::

        {"schema_version": 1, "basis": "scope",
         "total_device_s": ..., "n_events": ...,
         "phases": {"geometry": {"seconds": ..., "frac": ...}, ...},
         "unattributed_s": ..., "unattributed_frac": ...}

    ``phases`` holds only phases with nonzero observed time; fractions
    are of total XLA op time, so ``sum(frac) + unattributed_frac == 1``
    (the fractions-sum invariant tests assert).  ``basis`` records the
    evidence class: ``"scope"`` (joined against a compiled-HLO phase
    map — see :func:`write_phase_map`), ``"opname-heuristic"`` (no map;
    collectives/rng recognised by op name only) or ``"unavailable"``
    (XLA events exist but nothing could be attributed — rate-limited
    WARN, never an exception).  None only when the directory holds no
    parsable trace or no XLA op events at all, mirroring
    ``obs.pod.comm_split``.
    """
    pm = phase_map if phase_map is not None else read_phase_map(log_dir)
    per_phase_us: dict = {}
    total_us = 0.0
    n_events = 0
    scope_hits = 0
    heuristic_hits = 0
    for name, hlo_op, dur in iter_xla_op_events(log_dir):
        n_events += 1
        total_us += dur
        phase = None
        if pm:
            phase = pm.get(hlo_op) if hlo_op else None
            if phase is None:
                phase = pm.get(name)
            if phase is not None:
                scope_hits += 1
        if phase is None:
            phase = phase_of_op_name(name)
            if phase is not None:
                heuristic_hits += 1
        if phase is not None:
            per_phase_us[phase] = per_phase_us.get(phase, 0.0) + dur
    if n_events == 0 or total_us <= 0:
        return None
    if scope_hits:
        basis = "scope"
    elif heuristic_hits:
        basis = "opname-heuristic"
    else:
        basis = "unavailable"
        _warn_rate_limited(
            "phase attribution unavailable for %s: %d XLA op events but "
            "no phase map matched and no op name was recognisable — "
            "capture with SimConfig.phase_obs='on' and write_phase_map() "
            "to get a scoped split", log_dir, n_events)
    attributed_us = sum(per_phase_us.values())
    phases = {
        name: {"seconds": round(us / 1e6, 6),
               "frac": round(us / total_us, 6)}
        for name, us in sorted(per_phase_us.items(),
                               key=lambda kv: -kv[1])
    }
    return {
        "schema_version": ATTRIBUTION_SCHEMA_VERSION,
        "basis": basis,
        "total_device_s": round(total_us / 1e6, 6),
        "n_events": n_events,
        "n_scope_events": scope_hits,
        "phases": phases,
        "unattributed_s": round((total_us - attributed_us) / 1e6, 6),
        "unattributed_frac": round((total_us - attributed_us) / total_us,
                                   6),
    }


def phase_fractions(doc: Optional[dict]) -> Optional[dict]:
    """``{phase: frac}`` of an attribution doc when it carries a usable
    split (basis != 'unavailable'), else None — the shape
    ``obs.cost.model_error_doc`` takes for its per-axis phase checks."""
    if not isinstance(doc, dict) or doc.get("basis") == "unavailable":
        return None
    phases = doc.get("phases")
    if not isinstance(phases, dict):
        return None
    return {name: float(v.get("frac", 0.0))
            for name, v in phases.items() if isinstance(v, dict)}


# -- lever diffs -----------------------------------------------------------


def diff_attribution(base: Optional[dict], variant: Optional[dict]
                     ) -> Optional[dict]:
    """Per-phase share shift of a lever variant vs the all-defaults
    baseline: ``{"phases": {name: {"base_frac", "variant_frac",
    "delta_frac"}}, "basis": ...}``.  None when either side is missing
    or unavailable (a diff against heuristic-only evidence would
    mislead more than it informs)."""
    bf = phase_fractions(base)
    vf = phase_fractions(variant)
    if bf is None or vf is None:
        return None
    out = {}
    for name in sorted(set(bf) | set(vf)):
        b, v = bf.get(name, 0.0), vf.get(name, 0.0)
        out[name] = {
            "base_frac": round(b, 6),
            "variant_frac": round(v, 6),
            "delta_frac": round(v - b, 6),
        }
    return {
        "basis": "scope" if (base.get("basis") == "scope"
                             and variant.get("basis") == "scope")
        else "opname-heuristic",
        "phases": out,
    }


def describe_diff(label: str, diff: Optional[dict],
                  min_delta: float = 0.01) -> list:
    """Human lines for a lever diff — one per phase whose share moved
    by at least ``min_delta`` ("<label> cut geometry share from 31.2%
    to 12.4%")."""
    if not diff:
        return []
    lines = []
    for name, d in sorted(diff["phases"].items(),
                          key=lambda kv: kv[1]["delta_frac"]):
        delta = d["delta_frac"]
        if abs(delta) < min_delta:
            continue
        verb = "cut" if delta < 0 else "raised"
        lines.append(
            "%s %s %s share from %.1f%% to %.1f%%" % (
                label, verb, name,
                100.0 * d["base_frac"], 100.0 * d["variant_frac"]))
    return lines


# -- /metrics exposition ---------------------------------------------------


def publish_phase_gauges(registry, doc: Optional[dict]) -> None:
    """Surface an attribution doc as ``device.phase.*`` gauges on a
    metrics registry (obs/metrics.py), where the live ``/metrics``
    endpoint and RunReport's metrics dump pick them up.  No-op on
    None/unavailable docs."""
    if registry is None or not isinstance(doc, dict):
        return
    if doc.get("basis") == "unavailable":
        return
    registry.gauge("device.phase.total_s").set(doc.get(
        "total_device_s", 0.0))
    for name, d in (doc.get("phases") or {}).items():
        registry.gauge(f"device.phase.{name}.frac").set(d.get("frac", 0.0))
        registry.gauge(f"device.phase.{name}.seconds").set(
            d.get("seconds", 0.0))
    registry.gauge("device.phase.unattributed.frac").set(
        doc.get("unattributed_frac", 0.0))


# -- validation ------------------------------------------------------------


def validate_attribution_section(sec) -> list:
    """Schema errors of a RunReport ``attribution`` section (empty list
    == valid).  Checks the fractions-sum invariant: phase fractions
    plus the unattributed residual must cover total time to within
    rounding (<= 1 + eps each way)."""
    errors: list = []
    if not isinstance(sec, dict):
        return [f"attribution: expected dict, got {type(sec).__name__}"]
    basis = sec.get("basis")
    if basis not in BASES:
        errors.append(f"attribution.basis: {basis!r} not in {BASES}")
    for key in ("total_device_s", "unattributed_s", "unattributed_frac"):
        v = sec.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            errors.append(f"attribution.{key}: non-negative number "
                          f"required, got {v!r}")
    n_events = sec.get("n_events")
    if not isinstance(n_events, int) or isinstance(n_events, bool) \
            or n_events < 0:
        errors.append(f"attribution.n_events: non-negative int required, "
                      f"got {n_events!r}")
    phases = sec.get("phases")
    if not isinstance(phases, dict):
        errors.append(f"attribution.phases: dict required, "
                      f"got {type(phases).__name__}")
        return errors
    frac_sum = 0.0
    for name, d in phases.items():
        if not isinstance(d, dict):
            errors.append(f"attribution.phases[{name!r}]: dict required")
            continue
        for key in ("seconds", "frac"):
            v = d.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                errors.append(f"attribution.phases[{name!r}].{key}: "
                              f"non-negative number required, got {v!r}")
        frac = d.get("frac")
        if isinstance(frac, (int, float)) and not isinstance(frac, bool):
            if frac > 1 + 1e-6:
                errors.append(f"attribution.phases[{name!r}].frac: "
                              f"{frac} > 1")
            frac_sum += float(frac)
    uf = sec.get("unattributed_frac")
    if isinstance(uf, (int, float)) and not isinstance(uf, bool):
        total = frac_sum + float(uf)
        if total > 1 + 1e-3:
            errors.append(f"attribution: phase fractions + unattributed "
                          f"residual sum to {total:.6f} > 1")
    return errors
