"""Block timing, trace annotations, and platform-guarded device traces.

Absorbs the late ``engine/profiling.py`` (its re-export shim warned for
one release and is now removed — see MIGRATION.md) and
hardens it around the round-5 failure mode: the "device" traces in
``benchmarks/profile_r05`` were silently CPU-fallback captures — the
env-pinned TPU tunnel had flipped the process to CPU before the trace
started — and the roofline claim built on them had to be retracted
(VERDICT.md §5).  :func:`device_trace` therefore records the platform
that actually executed inside a sidecar manifest
(``trace_manifest.json``) next to the trace, logs a WARNING whenever it
differs from the caller's expectation, and can refuse outright
(``strict=True``).  A trace directory without a manifest, or with
``platform_mismatch: true``, is not device evidence.

:func:`annotate` wraps ``jax.profiler.TraceAnnotation`` so the engine's
block step, slab, checkpoint and autotune-probe regions are navigable
spans in Perfetto/TensorBoard instead of one undifferentiated wall of
XLA ops.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from typing import Optional

logger = logging.getLogger(__name__)

#: sidecar written into every trace directory by :func:`device_trace`
MANIFEST_NAME = "trace_manifest.json"
MANIFEST_SCHEMA_VERSION = 1

#: env override for the expected platform when the caller passes none
#: (battery scripts export it so ad-hoc captures inherit the guard)
EXPECT_ENV = "TMHPVSIM_EXPECT_PLATFORM"


class PlatformMismatchError(RuntimeError):
    """A ``strict`` device trace executed on a platform other than the
    expected one (e.g. TPU expected, CPU traced)."""


class BlockTimer:
    """Accumulates per-block wall times and derives throughput.

    The first tick is kept apart as the compile-inclusive block
    (``compile_s``); steady-state statistics come only from later
    blocks, and ``summary()`` reports ``steady_block_s=None`` rather
    than passing the compile block off as steady state when it is all
    there is (the pre-obs version conflated them).

    Usage::

        timer = BlockTimer(n_chains=cfg.n_chains, block_s=cfg.block_s)
        for blk in sim.run_blocks():
            timer.tick()        # call once per completed block
        timer.summary()         # dict; also logged at INFO

    ``log=False`` silences the per-tick/summary INFO lines (the engine's
    internal timer runs quiet so apps' own timers stay the single log
    voice).  With ``registry=`` every steady block also lands in
    ``<prefix>.block_wall_s`` and the compile block in
    ``<prefix>.compile_s`` on that metrics registry.
    """

    def __init__(self, n_chains: int, block_s: int, log: bool = True,
                 registry=None, prefix: str = "blocks"):
        self.n_chains = n_chains
        self.block_s = block_s
        self._log = log
        self._registry = registry
        self._prefix = prefix
        self._last = time.perf_counter()
        self._first_dt = None
        self.block_times = []

    def reset_clock(self) -> None:
        """Restart the tick reference without discarding history — call
        at loop entry when construction and first block are separated by
        unrelated work (autotune probes, checkpoint loads)."""
        self._last = time.perf_counter()

    def tick(self, n_blocks: int = 1) -> float:
        """Record the wall since the previous tick.

        ``n_blocks > 1`` credits one multi-block fused dispatch
        (engine/simulation.py ``blocks_per_dispatch``): the dispatch
        wall is split into ``n_blocks`` equal per-block-equivalent
        entries so ``summary()``'s steady statistics and site-s/s rate
        stay comparable with per-block dispatch.  The first entry of a
        timer's life still absorbs the whole compile.
        """
        now = time.perf_counter()
        dt = now - self._last
        self._last = now
        per_block = dt / max(1, n_blocks)
        remaining = n_blocks
        if self._first_dt is None:
            self._first_dt = per_block  # includes compile; kept separately
            remaining -= 1
            if self._registry is not None:
                self._registry.gauge(
                    f"{self._prefix}.compile_s").set(per_block)
        for _ in range(remaining):
            self.block_times.append(per_block)
        if self._registry is not None and remaining:
            for _ in range(remaining):
                self._registry.histogram(
                    f"{self._prefix}.block_wall_s").observe(per_block)
        if self._log:
            rate = self.n_chains * self.block_s * n_blocks / dt
            logger.info(
                "%s done in %.3f s (%.3g site-s/s)%s",
                "block" if n_blocks == 1 else f"{n_blocks}-block dispatch",
                dt, rate,
                " [first: includes compile]"
                if len(self.block_times) < n_blocks else "",
            )
        return dt

    def last_block_s(self) -> float:
        """The most recent per-block(-equivalent) wall: the latest
        steady entry, else the compile-inclusive first block, else 0.0
        before any tick.  What the pod heartbeat reports as this
        host's block wall (obs/pod.py)."""
        if self.block_times:
            return self.block_times[-1]
        return self._first_dt or 0.0

    def rate(self) -> float:
        """Current site-s/s throughput, quiet — same preference order as
        :meth:`summary` (steady blocks, else the compile-inclusive
        first block) but safe to call once per block without logging.
        0.0 before the first tick."""
        steady = self.block_times
        total = sum(steady)
        if total:
            return self.n_chains * self.block_s * len(steady) / total
        if self._first_dt:
            return self.n_chains * self.block_s / self._first_dt
        return 0.0

    def summary(self) -> dict:
        """Timing split compile-vs-steady.

        ``compile_s`` is the first (compile-inclusive) block wall —
        upper bound on compile, includes one block of steady work;
        ``steady_block_s`` averages the remaining blocks and is None
        when none exist.  ``site_seconds_per_s`` prefers steady blocks
        and falls back to the compile-inclusive one, flagged by
        ``rate_includes_compile``.  ``first_block_s`` is kept as an
        alias of ``compile_s`` for older consumers.
        """
        steady = self.block_times
        total = sum(steady)
        n_timed = len(steady) + (1 if self._first_dt is not None else 0)
        if total:
            rate = self.n_chains * self.block_s * len(steady) / total
        elif self._first_dt:
            rate = self.n_chains * self.block_s / self._first_dt
        else:
            rate = 0.0
        out = {
            "n_blocks_timed": n_timed,
            "first_block_s": self._first_dt,
            "compile_s": self._first_dt,
            "steady_block_s": (total / len(steady)) if steady else None,
            "site_seconds_per_s": rate,
            "rate_includes_compile": not steady,
        }
        if self._log:
            if steady:
                logger.info(
                    "throughput: %(site_seconds_per_s).3g site-s/s "
                    "(steady block %(steady_block_s).3f s)", out)
            elif self._first_dt is not None:
                logger.info(
                    "throughput: %(site_seconds_per_s).3g site-s/s "
                    "(single block %(compile_s).3f s, includes compile; "
                    "no steady blocks timed)", out)
        return out


#: scope-name prefix marking a semantic phase in HLO ``op_name``
#: metadata; obs/attribution.py keys on it when bucketing device time
PHASE_PREFIX = "ph__"


def phase_scope(name: str):
    """In-graph semantic-phase scope: a ``jax.named_scope`` whose name
    (``ph__<name>``) survives lowering into every enclosed HLO op's
    ``op_name`` metadata, where obs/attribution.py can bucket device
    time by phase.  Unlike :func:`annotate` (a host-side span around a
    dispatch) this is TRACE-time scoping — it must wrap the traced
    computation itself and it changes lowered-text metadata, which is
    why the engine only enters it when ``SimConfig.phase_obs`` is on
    (off stays byte-identical HLO).  Degrades to a no-op without jax.
    """
    try:
        import jax

        return jax.named_scope(PHASE_PREFIX + name)
    except Exception:  # no jax — host-side callers still compose
        return contextlib.nullcontext()


@contextlib.contextmanager
def annotate(name: str):
    """Host-side ``jax.profiler.TraceAnnotation`` span (a named region in
    Perfetto); degrades to a no-op when jax/profiling is unavailable."""
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:  # no jax, or profiling backend unavailable
        ctx = contextlib.nullcontext()
    with ctx:
        yield


def read_manifest(log_dir: str) -> Optional[dict]:
    """The trace sidecar manifest, or None when absent/unreadable (an
    absent manifest means the capture predates the platform guard — do
    not treat it as device evidence)."""
    try:
        with open(os.path.join(log_dir, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _start_trace(log_dir: str, python_tracer: bool) -> None:
    """``jax.profiler.start_trace``, optionally with the Python-frame
    tracer disabled.

    The Chrome-trace export caps at ~1M events; over a minutes-long
    capture the Python tracer's per-frame events alone exceed the cap
    and the XLA op events — the part ``obs.pod.comm_split`` needs — are
    the ones dropped.  jax's public ``start_trace`` hardcodes default
    profiler options, so the opt-out builds the ``ProfilerSession``
    with ``python_tracer_level=0`` through the same profile-state slot
    ``stop_trace`` reads; any internals mismatch (other jax versions)
    falls back to the public path, which is always correct, just
    noisier."""
    import jax

    if python_tracer:
        jax.profiler.start_trace(log_dir)
        return
    try:
        from jax._src.lib import xla_client
        from jax._src.profiler import _profile_state

        with _profile_state.lock:
            if _profile_state.profile_session is not None:
                raise RuntimeError("Profile has already been started. "
                                   "Only one profile may be run at a time.")
            opts = xla_client.profiler.ProfileOptions()
            opts.python_tracer_level = 0
            _profile_state.profile_session = \
                xla_client.profiler.ProfilerSession(opts)
            _profile_state.create_perfetto_link = False
            _profile_state.create_perfetto_trace = False
            _profile_state.log_dir = str(log_dir)
    except RuntimeError:
        raise
    except Exception as e:
        logger.warning("python-tracer opt-out unavailable on this jax "
                       "(%s); capturing with default options", e)
        jax.profiler.start_trace(log_dir)


@contextlib.contextmanager
def device_trace(log_dir: str, expect_platform: Optional[str] = None,
                 strict: bool = False, python_tracer: bool = True):
    """``jax.profiler`` trace scope with a platform-guarded sidecar.

    On exit, ``trace_manifest.json`` in ``log_dir`` records the backend
    that actually executed (``jax.default_backend()``), the expected
    platform, and ``platform_mismatch``.  A mismatch logs at WARNING —
    and raises :class:`PlatformMismatchError` under ``strict=True`` — so
    a CPU-fallback capture can never again be committed as a device
    trace unnoticed.  ``expect_platform`` defaults to the
    ``TMHPVSIM_EXPECT_PLATFORM`` env var; None/unset disables the guard
    (the platform is still recorded).

    ``python_tracer=False`` drops Python-frame events from the capture
    (see :func:`_start_trace`) — pass it when the trace feeds op-level
    analysis (``obs.pod.comm_split``) rather than a human timeline, or
    when the capture spans minutes (frame events otherwise crowd the
    XLA ops out of the ~1M-event export cap).
    """
    import jax

    if expect_platform is None:
        expect_platform = os.environ.get(EXPECT_ENV) or None
    t0 = time.perf_counter()
    started = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    _start_trace(log_dir, python_tracer)
    body_ok = True
    try:
        yield
    except BaseException:
        body_ok = False
        raise
    finally:
        jax.profiler.stop_trace()
        traced = None
        device_kind = None
        try:
            traced = jax.default_backend()
            device_kind = jax.devices()[0].device_kind
        except Exception as e:  # never lose the trace over a query
            logger.warning("could not query traced platform: %s", e)
        mismatch = (expect_platform is not None and traced is not None
                    and traced != expect_platform)
        manifest = {
            "schema_version": MANIFEST_SCHEMA_VERSION,
            "traced_platform": traced,
            "device_kind": device_kind,
            "expected_platform": expect_platform,
            "platform_mismatch": mismatch,
            "started_utc": started,
            "wall_s": round(time.perf_counter() - t0, 3),
        }
        try:
            os.makedirs(log_dir, exist_ok=True)
            with open(os.path.join(log_dir, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f, indent=1)
        except OSError as e:
            logger.warning("trace manifest write failed (%s): %s",
                           log_dir, e)
        if mismatch:
            logger.warning(
                "platform_mismatch: device trace in %s captured backend "
                "%r but %r was expected — this capture is NOT %s "
                "evidence (see %s)", log_dir, traced, expect_platform,
                expect_platform, MANIFEST_NAME,
            )
            if strict and body_ok:
                raise PlatformMismatchError(
                    f"trace in {log_dir} executed on {traced!r}, "
                    f"expected {expect_platform!r}"
                )
