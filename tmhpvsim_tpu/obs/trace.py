"""Structured event tracer + flight recorder for the streaming path.

The batch engine got metrics and run reports in the observability
rounds; the *streaming* half of the paper's artifact (metersim → broker
→ funnel → CSV) stayed dark: a stalled join or a reconnect storm was
invisible until the CSV went quiet.  This module is the timeline side of
the answer (obs/metrics.py is the aggregate side): monotonic-clock spans
and instant events with categories, tagged with the *asyncio task* that
emitted them, kept in a bounded in-memory ring.

Two ways out of the ring:

* :meth:`Tracer.export` — the whole ring as a Chrome-trace-event JSON
  (``{"traceEvents": [...]}``), loadable in Perfetto
  (https://ui.perfetto.dev) or ``chrome://tracing``.  Events carry this
  process's real pid, so a ``jax.profiler`` device trace of the same run
  (``--profile``) merges as a separate process row by concatenating the
  two files' ``traceEvents`` lists.
* :meth:`Tracer.dump_flight` — the last-N-seconds slice, written when
  something already went wrong: unhandled app exceptions and the
  bench.py watchdog's rc=3 salvage path dump here so a wedged run
  finally leaves a timeline behind.  The dump is itself a valid trace
  file (tools/trace_stats.py validates both).

Cost model: tracing defaults OFF.  Call sites hold an
``Optional[Tracer]`` and guard with ``if tracer:`` (``__bool__`` is
``enabled``), so a disabled/absent tracer costs one truth test on the
hot path; an enabled one costs a dict build + deque append per event
(the ring never allocates past ``ring_capacity``).
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Optional

#: default ring size — at the apps' 1 Hz × ~4 events/record this is
#: hours of history; free-run tests churn it in seconds, which is the
#: point of a ring
TRACE_RING_CAPACITY = 65_536

#: seconds of history a flight dump keeps by default
FLIGHT_WINDOW_S = 30.0


# -- cross-process trace-context propagation ---------------------------
#
# W3C-traceparent-style ids carried in broker ``Message.meta`` and the
# serve request/reply schema: ``trace_id`` (32 hex chars, one per
# logical request) and ``span_id`` (16 hex chars, one per hop).  The
# layer is OFF by default — ``stamp``/``extract`` are no-ops until an
# app turns it on (``--obs-port`` does), so the default wire format is
# byte-identical to pre-propagation builds.  The bound context rides a
# ``contextvars.ContextVar``, so it follows asyncio tasks (set before
# ``create_task`` → inherited by the task) and is restored on scope
# exit; spans/instants recorded while a context is bound carry the
# trace_id in their args, which is what ``tools/trace_stats.py
# --stitch`` groups the multi-process timeline by.

_propagate = False
_context: contextvars.ContextVar = contextvars.ContextVar(
    "tmhpvsim_trace_context", default=None)


def enable_propagation(on: bool = True) -> None:
    """Turn trace-context stamping/extraction on (or back off)."""
    global _propagate
    _propagate = bool(on)


def propagation_enabled() -> bool:
    return _propagate


@contextlib.contextmanager
def use_propagation(on: bool = True):
    """Scoped :func:`enable_propagation` (tests)."""
    global _propagate
    prev = _propagate
    _propagate = bool(on)
    try:
        yield
    finally:
        _propagate = prev


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def current_trace() -> Optional[tuple]:
    """The bound ``(trace_id, span_id)``, or None."""
    return _context.get()


@contextlib.contextmanager
def trace_scope(trace_id: Optional[str], span_id: Optional[str] = None):
    """Bind ``(trace_id, span_id)`` as the current trace context for the
    scope.  ``trace_id=None`` binds nothing (callers can pass a maybe-id
    straight through)."""
    if trace_id is None:
        yield None
        return
    ctx = (trace_id, span_id or new_span_id())
    token = _context.set(ctx)
    try:
        yield ctx
    finally:
        _context.reset(token)


def stamp(meta: Optional[dict]) -> Optional[dict]:
    """Return ``meta`` with ``trace_id``/``span_id`` added (a fresh dict;
    the input is never mutated).  Continues the bound trace when one is
    set, else mints a new trace.  When propagation is off, returns
    ``meta`` unchanged — the transports call this unconditionally and
    the off path must not alter the wire format."""
    if not _propagate:
        return meta
    ctx = _context.get()
    out = dict(meta) if meta else {}
    out.setdefault("trace_id", ctx[0] if ctx else new_trace_id())
    out.setdefault("span_id", new_span_id())
    return out


def extract(meta: Optional[dict]) -> Optional[tuple]:
    """``(trace_id, span_id)`` carried by a message's meta, or None (off,
    absent, or malformed — a foreign publisher's meta never raises)."""
    if not _propagate or not isinstance(meta, dict):
        return None
    tid = meta.get("trace_id")
    if not isinstance(tid, str) or not tid:
        return None
    sid = meta.get("span_id")
    return (tid, sid if isinstance(sid, str) and sid else None)


@contextlib.contextmanager
def extracted(meta: Optional[dict]):
    """Bind the trace context carried by ``meta`` for the scope (the
    consume-side counterpart of :func:`stamp`); binds nothing when the
    meta carries no context."""
    ctx = extract(meta)
    if ctx is None:
        yield None
        return
    token = _context.set(ctx)
    try:
        yield ctx
    finally:
        _context.reset(token)


def _with_trace_id(args: dict) -> dict:
    """Merge the bound trace_id into span/instant args (recording side of
    propagation: this is what lets the stitcher claim an event)."""
    if _propagate:
        ctx = _context.get()
        if ctx is not None and "trace_id" not in args:
            return {**args, "trace_id": ctx[0]}
    return args


def _task_or_thread() -> str:
    """Track label for the current execution context: the asyncio task
    name when inside a running loop (the apps are task soups — 'Task-3'
    tells you nothing less than which coroutine stalled), else the
    thread name (bench's watchdog monitor, jax worker threads)."""
    try:
        task = asyncio.current_task()
    except RuntimeError:  # no running event loop in this thread
        task = None
    if task is not None:
        return f"task:{task.get_name()}"
    return f"thread:{threading.current_thread().name}"


class _Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._t0 = 0

    def __enter__(self):
        self._t0 = self._tracer.now_us()
        self._args = _with_trace_id(self._args)
        return self

    def __exit__(self, *exc):
        t = self._tracer
        ev = {"name": self._name, "cat": self._cat, "ph": "X",
              "ts": self._t0, "dur": t.now_us() - self._t0,
              "tid": _task_or_thread()}
        if self._args:
            ev["args"] = self._args
        t._events.append(ev)
        return False


class Tracer:
    """Bounded ring of Chrome-trace events; see module docstring.

    ``clock`` is injectable for tests (monotonic nanoseconds).  The ring
    (``collections.deque(maxlen=...)``) is append-safe across threads.
    """

    def __init__(self, enabled: bool = True,
                 ring_capacity: int = TRACE_RING_CAPACITY,
                 clock=time.monotonic_ns):
        self.enabled = enabled
        self._clock = clock
        self._events: deque = deque(maxlen=ring_capacity)

    def __bool__(self) -> bool:
        return self.enabled

    def now_us(self) -> int:
        return self._clock() // 1000

    # -- recording -------------------------------------------------------

    def span(self, name: str, cat: str = "app", **args):
        """Context manager: one complete ("X") event with duration."""
        if not self.enabled:
            return contextlib.nullcontext()
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "app", **args) -> None:
        """One instant ("i") event, thread-scoped."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": self.now_us(), "tid": _task_or_thread()}
        args = _with_trace_id(args)
        if args:
            ev["args"] = args
        self._events.append(ev)

    def events(self) -> list:
        """Snapshot of the ring, oldest first."""
        return list(self._events)

    def __len__(self) -> int:
        return len(self._events)

    # -- export ----------------------------------------------------------

    def render(self, events: Optional[list] = None,
               process_name: str = "tmhpvsim") -> dict:
        """The ring (or ``events``) as a Chrome-trace document dict —
        what :meth:`export` writes and what ``obs/live.py`` serves at
        ``/flight``."""
        evs = self.events() if events is None else events
        pid = os.getpid()
        # string track labels -> small int tids + "thread_name" metadata,
        # the encoding chrome://tracing and Perfetto expect
        tids: dict = {}
        out = []
        for ev in evs:
            label = ev.get("tid", "thread:?")
            tid = tids.setdefault(label, len(tids) + 1)
            out.append({**ev, "pid": pid, "tid": tid})
        meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                 "args": {"name": process_name}}]
        for label, tid in tids.items():
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": label}})
        return {"traceEvents": meta + out, "displayTimeUnit": "ms"}

    def flight_doc(self, last_s: float = FLIGHT_WINDOW_S) -> dict:
        """The last ``last_s`` seconds of the ring as a trace document
        (no file written).  A span that *started* before the window but
        overlaps it is kept (that long span is usually the story)."""
        cut = self.now_us() - int(last_s * 1e6)
        evs = [e for e in self.events()
               if e["ts"] + e.get("dur", 0) >= cut]
        return self.render(events=evs)

    def export(self, path: str, process_name: str = "tmhpvsim",
               events: Optional[list] = None) -> dict:
        """Write the ring (or ``events``) as a Chrome-trace JSON; returns
        the document.  Atomic tmp+rename: a killed process never leaves a
        torn trace for the salvage tooling to choke on."""
        doc = self.render(events=events, process_name=process_name)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return doc

    def dump_flight(self, path: str,
                    last_s: float = FLIGHT_WINDOW_S) -> dict:
        """Export only the last ``last_s`` seconds of the ring — the
        crash/watchdog artifact (see :meth:`flight_doc`)."""
        cut = self.now_us() - int(last_s * 1e6)
        evs = [e for e in self.events()
               if e["ts"] + e.get("dur", 0) >= cut]
        return self.export(path, events=evs)


#: process-default tracer: None means "tracing off everywhere".  Library
#: code never installs one; apps/bench do when asked to (``--trace``),
#: and pass Tracer instances explicitly where two app mains share one
#: process (the e2e tests) — a global swap would race there.
_default: Optional[Tracer] = None


def get_tracer() -> Optional[Tracer]:
    return _default


def set_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install (or clear, with None) the process-default tracer; returns
    the previous one.  bench.py installs a ring at headline start so the
    watchdog has something to dump."""
    global _default
    prev = _default
    _default = tracer
    return prev


@contextlib.contextmanager
def use_tracer(tracer: Optional[Tracer]):
    """Scoped :func:`set_tracer` (tests)."""
    prev = set_tracer(tracer)
    try:
        yield tracer
    finally:
        set_tracer(prev)
