"""Drift sentinel: streamed device telemetry vs golden CPU reference.

Consumes the per-block summaries produced by ``obs/telemetry.py`` and
answers two questions the host otherwise cannot, until a wrong CSV
surfaces hours later:

* **Is the graph numerically healthy?**  Any nonzero NaN/Inf counter in
  a block summary trips the sentinel immediately (WARN, or
  :class:`DriftError` under ``strict``), localised to field and block.
* **Is the ensemble drifting?**  Per-block ensemble means of csi / pv /
  meter / residual are compared against reference bands derived from
  the float64 golden models (``engine/golden.py``).  The golden stream
  is a *realization*, not an expectation, so the band half-width is
  estimated from the spread of several independent golden realizations
  (plus an analytic band for the uniform meter) rather than a
  per-second std — robust at small block sizes where realization-to-
  realization variance dominates.

Reference moments are computed lazily on first use (a few golden
block-seconds on the host, once per run) and only for the first
``ref_blocks`` blocks — later blocks get NaN/Inf checks only, which is
the cheap steady-state contract.  Reference failures (exotic configs
the golden path cannot mirror) degrade to NaN/Inf-only checking with a
WARN; they never kill the run they observe.
"""

from __future__ import annotations

import datetime as _dt
import logging
import math
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

#: golden realizations per reference block (band = spread of their means)
REF_REALIZATIONS = 4

#: floors for the band half-width, per field (units of the field) — a
#: zero spread (e.g. pv overnight: all realizations exactly 0) must not
#: produce a zero-width band
_BAND_FLOORS = {"csi": 0.02, "pv": 1.0}


class DriftError(RuntimeError):
    """Raised under ``strict`` on NaN/Inf appearance or band escape."""


def _golden_reference(config, n_blocks: int,
                      realizations: int = REF_REALIZATIONS) -> list:
    """Per-block reference bands from ``realizations`` golden streams.

    Returns a list (one entry per block) of ``{field: (mean, band)}``
    where ``band`` is the 1-sigma-equivalent tolerance denominator.
    Fields: csi always; pv/residual only for single-site configs (the
    golden physics chain models one site); meter is analytic and
    handled at observe time (its band depends on the observed count).
    """
    from tmhpvsim_tpu.engine.golden import GoldenClearskyIndex
    from tmhpvsim_tpu.models import pv as pvmod
    from tmhpvsim_tpu.models import solar
    from tmhpvsim_tpu.data import SANDIA_INVERTER, SAPM_MODULE

    start = _dt.datetime.fromisoformat(config.start)
    total_s = min(n_blocks * config.block_s, config.duration_s)
    n_blocks = -(-total_s // config.block_s)
    fp = getattr(config, "fleet", None)
    # heterogeneous per-site power transforms move the ensemble pv mean
    # away from the one-site golden chain, so those bands are dropped the
    # same way multi-site geometry drops them
    single_site = config.site_grid is None and (
        fp is None or not fp.het_power)
    # chains on non-default weather regimes draw from step tables the
    # golden chain does not model — the csi ensemble mean is a regime
    # mixture, so its band is dropped too (NaN/Inf checks remain)
    with_csi = fp is None or not fp.het_regime

    times = [start + _dt.timedelta(seconds=i) for i in range(total_s)]
    if single_site:
        from zoneinfo import ZoneInfo

        tz = ZoneInfo(config.site.timezone)
        epoch = np.asarray(
            [int(t.replace(tzinfo=tz).timestamp()) for t in times],
            dtype=np.float64)
        doy = np.asarray([t.timetuple().tm_yday for t in times],
                         dtype=np.float64)
        geom = solar.block_geometry(epoch, doy, config.site, xp=np)

    # per-realization, per-block means: [realization][block][field]
    csi_means = np.empty((realizations, n_blocks))
    pv_means = np.empty((realizations, n_blocks)) if single_site else None
    for k in range(realizations):
        rng = np.random.default_rng((config.seed, 7700 + k))
        model = GoldenClearskyIndex(start, config.options, rng)
        csi = np.empty(total_s)
        for i, t in enumerate(times):
            csi[i] = model.next(t)
        if single_site:
            ac = pvmod.power_from_csi(csi, geom, SAPM_MODULE,
                                      SANDIA_INVERTER, xp=np)
        for b in range(n_blocks):
            sl = slice(b * config.block_s,
                       min((b + 1) * config.block_s, total_s))
            csi_means[k, b] = csi[sl].mean()
            if single_site:
                pv_means[k, b] = ac[sl].mean()

    def band(means_col, floor):
        spread = float(means_col.std(ddof=1)) if realizations > 1 else 0.0
        # inflate for the sampled-mean's own uncertainty about the true
        # expectation (K realizations estimate it with SE spread/sqrt(K))
        return max(spread * math.sqrt(1.0 + 1.0 / realizations), floor)

    refs = []
    for b in range(n_blocks):
        entry = {}
        if with_csi:
            entry["csi"] = (float(csi_means[:, b].mean()),
                            band(csi_means[:, b], _BAND_FLOORS["csi"]))
        if single_site:
            entry["pv"] = (float(pv_means[:, b].mean()),
                           band(pv_means[:, b], _BAND_FLOORS["pv"]))
        refs.append(entry)
    return refs


class DriftSentinel:
    """Streaming per-block health verdicts against golden references.

    Parameters
    ----------
    config : SimConfig
        The run's config (start / block_s / seed / site drive the
        golden reference).
    level : str
        Telemetry level ('light' | 'full') — recorded in the report.
    strict : bool
        Raise :class:`DriftError` instead of WARN-and-continue.
    tol_std : float
        Band-escape threshold in band units (the band is a 1-sigma
        equivalent; 4.0 keeps the false-positive rate negligible while
        catching the order-of-magnitude drifts that matter).
    ref_blocks : int
        Number of leading blocks with full moment bands; later blocks
        get NaN/Inf checks only.
    """

    def __init__(self, config, *, level: str = "light",
                 strict: bool = False, tol_std: float = 4.0,
                 ref_blocks: int = 2):
        self.config = config
        self.level = level
        self.strict = bool(strict)
        self.tol_std = float(tol_std)
        self.ref_blocks = int(ref_blocks)
        self.blocks_checked = 0
        self.worst_z: dict = {}
        self.nan_event: Optional[dict] = None
        self.drift_events: list = []
        self._verdict = "ok"
        self._ref = None
        self._ref_failed = False

    # -- reference -------------------------------------------------------

    def _reference(self) -> list:
        if self._ref is None and not self._ref_failed:
            try:
                self._ref = _golden_reference(self.config, self.ref_blocks)
            except Exception as e:
                self._ref_failed = True
                self._ref = []
                logger.warning(
                    "drift sentinel: golden reference unavailable (%s); "
                    "falling back to NaN/Inf checks only", e)
        return self._ref

    # -- per-block observation -------------------------------------------

    def observe_block(self, block_idx: int, summary: dict) -> str:
        """Check one block summary; returns the verdict so far."""
        self.blocks_checked += 1

        # 1. finiteness: any nonzero counter is an immediate event
        for f, s in summary["fields"].items():
            bad = s["nan"] + s["inf"]
            if bad and self.nan_event is None:
                self.nan_event = {
                    "field": f, "block": int(block_idx),
                    "nan": s["nan"], "inf": s["inf"],
                }
                self._verdict = "nan"
                msg = (f"drift sentinel: non-finite values in field "
                       f"{f!r} at block {block_idx} "
                       f"(nan={s['nan']}, inf={s['inf']})")
                if self.strict:
                    raise DriftError(msg)
                logger.warning(msg)

        # 2. moment bands for the leading reference blocks
        ref = self._reference()
        if block_idx < len(ref):
            self._check_bands(block_idx, summary, ref[block_idx])
        return self._verdict

    def _check_bands(self, block_idx: int, summary: dict,
                     ref_entry: dict) -> None:
        count = summary["count"]
        bands = dict(ref_entry)
        # meter: analytic uniform[0, meter_max_w) moments; the ensemble
        # mean over `count` samples has SE = std / sqrt(count)
        mmax = float(self.config.meter_max_w)
        if count > 0:
            fp = getattr(self.config, "fleet", None)
            if fp is not None and fp.het_demand:
                # per-site affine demand: meter_i ~ scale_i*U(0,mmax)
                # + shift_i, so the ensemble mean recenters on the
                # fleet-average transform and the SE widens by the RMS
                # of the scales (cohort-aware widening — every site's
                # variance contributes, not the nominal one)
                sc = np.asarray(fp.demand_scale, dtype=np.float64)
                sh = np.asarray(fp.demand_shift_w, dtype=np.float64)
                center = float(sc.mean()) * mmax / 2.0 + float(sh.mean())
                m_se = (mmax * math.sqrt(float((sc * sc).mean()) / 12.0)
                        / math.sqrt(count))
            else:
                center = mmax / 2.0
                m_se = (mmax / math.sqrt(12.0)) / math.sqrt(count)
            bands["meter"] = (center, max(m_se, 1e-9 * max(mmax, 1.0)))
            if "pv" in ref_entry:
                pv_mean, pv_band = ref_entry["pv"]
                bands["residual"] = (
                    center - pv_mean,
                    math.sqrt(pv_band ** 2 + m_se ** 2),
                )
        for f, (ref_mean, band) in bands.items():
            s = summary["fields"].get(f)
            if s is None or not s["observed"] or s["nan"] or s["inf"]:
                continue  # unobserved or already flagged non-finite
            z = abs(s["mean"] - ref_mean) / band
            if z > self.worst_z.get(f, 0.0):
                self.worst_z[f] = z
            if z > self.tol_std:
                event = {"field": f, "block": int(block_idx),
                         "z": z, "mean": s["mean"], "ref_mean": ref_mean,
                         "band": band}
                self.drift_events.append(event)
                if self._verdict == "ok":
                    self._verdict = "drift"
                msg = (f"drift sentinel: field {f!r} escaped its band at "
                       f"block {block_idx}: mean={s['mean']:.6g} vs "
                       f"ref={ref_mean:.6g} (z={z:.2f} > "
                       f"tol={self.tol_std})")
                if self.strict:
                    raise DriftError(msg)
                logger.warning(msg)

    # -- report ----------------------------------------------------------

    @property
    def verdict(self) -> str:
        return self._verdict

    def report(self) -> dict:
        """JSON-able section for RunReport.telemetry."""
        return {
            "level": self.level,
            "strict": self.strict,
            "verdict": self._verdict,
            "blocks_checked": self.blocks_checked,
            "tolerance_std": self.tol_std,
            "worst_z": {f: round(z, 4) for f, z in self.worst_z.items()},
            "nan": self.nan_event,
            "drift": self.drift_events or None,
        }
