"""On-device fleet analytics: risk statistics folded inside the scan.

Where ``obs/telemetry.py`` answers "is the simulation healthy?", this
module answers the grid operator's question — "what is the risk?" — with
the same machinery: a ``FleetAcc``, a flat pytree of fixed-size sketches
riding the scan carry next to the reduce statistics and the
``TelemetryAcc``, folded per second *inside* the jit so a million-site
year leaves the device as a few KB of decision-ready numbers instead of
per-second arrays:

* a **residual-load quantile sketch**: a fixed equi-width histogram of
  ``residual = meter - pv`` over ``[lo, hi)`` with explicit under/overflow
  slots plus exact running min/max — :func:`summarize` interpolates
  p1/p5/p50/p95/p99 from it.  Rank error is bounded by the mass of the
  quantile's bin: with the default 2048 bins over ``[-meter_max_w,
  +meter_max_w)`` the reference 1e6-sample acceptance run sits well
  inside the 0.5 % rank-error budget (tests/test_analytics.py);
* an **exceedance curve** over a configurable threshold grid: seconds
  with ``residual > threshold_j`` for each threshold, folded as one
  searchsorted + scatter-add per second;
* **loss-of-load probability**: seconds (and distinct events) in which
  ``residual > capacity_w`` has persisted for ``>= lolp_k`` consecutive
  seconds, via an in-carry run-length counter;
* **ramp-rate extremes**: ``max |Δresidual|`` over 1 s / 60 s / 3600 s
  windows.  Each window keeps one previous-sample ring slot per chain in
  the carry (the sample grid is every w-th second), so the 3600 s window
  costs one ``(n_chains,)`` vector, not a 3600-deep ring buffer;
* at level ``full``: per-Markov-regime (cloud covered / clear)
  conditional means of meter, pv and residual.

**Exactness contract** (what makes the sketches merge associatively):
every ``risk``-level leaf is either an int32 count or a running extremum
— both exactly associative — so slab partitions, ``blocks_per_dispatch``
mega-blocks and ``psum``/``pmin``/``pmax`` across the mesh
(``parallel/distributed.psum_fleet``) produce *bit-identical* fleet
sections regardless of merge order.  Only the ``full``-level
conditional-mean float sums reassociate (relative error of order
``block_s * eps``).  int32 bound: one block's per-shard counts stay
exact while ``n_chains * block_s < 2**31`` (~248k chains at the default
8640 s block); the host-side run totals (:func:`merge_host`) widen to
int64 / float64.

Like the TelemetryAcc, the accumulator is zero-initialised *inside* the
block jit, so each block is a pure per-block delta and mesh psums never
double-count.  Consequence: the LOLP run-length counter and the ramp
previous-sample slots reset at block (and slab) boundaries — a loss run
or ramp pair spanning a boundary is split.  Runs no longer than one
block match a NumPy oracle exactly (the acceptance test's regime); at
operational block sizes the seam bias is a conservative undercount of
order ``lolp_k / block_s``.

Levels: ``off`` (analytics structurally absent from the traced graph —
byte-identical HLO, asserted by tests), ``risk`` (sketch + exceedance +
LOLP + ramps), ``full`` (risk + per-regime conditional means).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

#: valid values for SimConfig.analytics / Plan.analytics / --analytics
ANALYTICS_LEVELS = ("off", "risk", "full")

#: sample-grid windows [s] for the ramp-rate extrema
RAMP_WINDOWS = (1, 60, 3600)


@dataclasses.dataclass(frozen=True)
class FleetParams:
    """Static sketch geometry: resolved once per run, baked into the jit.

    Everything here is a compile-time constant of the block step (python
    floats/tuples closed over by the fold), so two shards/slabs of one
    run always classify a given residual sample identically — the
    premise of the bit-identical-merge contract.
    """

    #: residual histogram support [W): samples outside land in the
    #: explicit under/overflow slots
    lo: float
    hi: float
    #: interior histogram bins (equi-width over [lo, hi))
    bins: int
    #: exceedance thresholds [W], strictly ascending
    thresholds: tuple
    #: loss-of-load capacity [W]: residual above this is a loss second
    capacity_w: float
    #: consecutive loss seconds before a run counts as loss of load
    lolp_k: int
    ramp_windows: tuple = RAMP_WINDOWS

    def __post_init__(self):
        if not self.hi > self.lo:
            raise ValueError(f"FleetParams: hi {self.hi} must be > lo {self.lo}")
        if self.bins < 1:
            raise ValueError(f"FleetParams: bins {self.bins} must be >= 1")
        if self.lolp_k < 1:
            raise ValueError(f"FleetParams: lolp_k {self.lolp_k} must be >= 1")
        th = tuple(float(t) for t in self.thresholds)
        if not th:
            raise ValueError("FleetParams: thresholds must be non-empty")
        if any(b <= a for a, b in zip(th, th[1:])):
            raise ValueError(
                f"FleetParams: thresholds {th} must be strictly ascending")
        object.__setattr__(self, "thresholds", th)
        rw = tuple(int(w) for w in self.ramp_windows)
        if any(w < 1 for w in rw) or any(
                b <= a for a, b in zip(rw, rw[1:])):
            raise ValueError(
                f"FleetParams: ramp_windows {rw} must be strictly "
                "ascending positive ints")
        object.__setattr__(self, "ramp_windows", rw)


def params_from_config(config) -> FleetParams:
    """Resolve sketch geometry from a SimConfig.

    Defaults size everything off ``meter_max_w`` (the demand upper
    bound): residual lives in roughly ``(-pv_max, meter_max_w)``, so the
    sketch spans ``[-meter_max_w, +meter_max_w)``; the threshold grid is
    the 1/8..7/8 fractions of max demand; LOLP capacity defaults to 80 %
    of max demand with a 60 s persistence requirement.
    """
    mx = float(config.meter_max_w)
    th = getattr(config, "analytics_thresholds", None)
    cap = getattr(config, "analytics_capacity_w", None)
    return FleetParams(
        lo=-mx,
        hi=mx,
        bins=int(getattr(config, "analytics_bins", 2048)),
        thresholds=(tuple(th) if th
                    else tuple(mx * f / 8.0 for f in range(1, 8))),
        capacity_w=(float(cap) if cap is not None else 0.8 * mx),
        lolp_k=int(getattr(config, "analytics_lolp_k", 60)),
    )


def init_acc(level: str, dtype=jnp.float32, n_chains=None, *,
             params: FleetParams, cohorts: int = 0) -> dict:
    """Fresh zeroed FleetAcc pytree for one block.

    Flat dict, mirroring ``telemetry.init_acc``: with ``n_chains`` the
    extremum/LOLP/ramp/regime leaves are per-chain vectors folded
    elementwise by :func:`fold_second` (plus carry-only ring slots
    ``prev_ramp_*`` / ``seen_ramp_*`` / ``lol_run`` that
    :func:`reduce_chainwise` drops); the histogram and exceedance
    sketches are shared scatter-add targets either way.  Without
    ``n_chains`` this is the scalar (shard-level) form that
    :func:`fold_wide`, ``psum_fleet`` and :func:`summarize` consume.
    min/max start at +/-finfo.max (not inf — inf survives pmin/pmax but
    poisons the observed heuristic in :func:`summarize`).

    ``cohorts`` (heterogeneous fleets, fleet/params.py): with C >= 2 the
    acc additionally carries per-cohort group-by leaves — count, sum of
    meter/pv/residual, residual min/max and a (C, bins+2) grouped
    residual histogram.  Like the shared sketches they are scatter-add /
    scatter-extremum targets WITHOUT a chain axis, identical in both acc
    forms, so they pass through :func:`reduce_chainwise` unchanged and
    merge associatively (int leaves and extrema bit-exactly) across
    slabs, shards and mega-blocks.  C is a host-static property of the
    whole fleet (``FleetParams.n_cohorts``; slices keep the parent's
    width via ``n_cohorts_hint``), so every partition allocates the same
    shapes.
    """
    if level not in ("risk", "full"):
        raise ValueError(f"init_acc: analytics level {level!r} must be "
                         f"'risk' or 'full'")
    dt = jnp.dtype(dtype)
    big = jnp.asarray(jnp.finfo(dt).max, dt)
    shape = () if n_chains is None else (int(n_chains),)
    acc = {
        "count": jnp.zeros((), jnp.int32),
        "res_hist": jnp.zeros((params.bins + 2,), jnp.int32),
        "exceed": jnp.zeros((len(params.thresholds) + 1,), jnp.int32),
        "min_res": jnp.full(shape, big, dt),
        "max_res": jnp.full(shape, -big, dt),
        "lol_seconds": jnp.zeros(shape, jnp.int32),
        "lol_events": jnp.zeros(shape, jnp.int32),
    }
    for w in params.ramp_windows:
        acc[f"max_ramp_{w}s"] = jnp.full(shape, -big, dt)
    if n_chains is not None:
        acc["lol_run"] = jnp.zeros(shape, jnp.int32)
        for w in params.ramp_windows:
            acc[f"prev_ramp_{w}s"] = jnp.zeros(shape, dt)
            acc[f"seen_ramp_{w}s"] = jnp.zeros(shape, jnp.int32)
    if cohorts:
        c = int(cohorts)
        acc["cohort_count"] = jnp.zeros((c,), jnp.int32)
        acc["cohort_hist"] = jnp.zeros((c, params.bins + 2), jnp.int32)
        acc["min_cohort_res"] = jnp.full((c,), big, dt)
        acc["max_cohort_res"] = jnp.full((c,), -big, dt)
        for f in ("meter", "pv", "residual"):
            acc[f"cohort_sum_{f}"] = jnp.zeros((c,), dt)
    if level == "full":
        acc["regime_observed"] = jnp.zeros((), jnp.int32)
        acc["cov_count"] = jnp.zeros(shape, jnp.int32)
        for f in ("meter", "pv", "residual"):
            acc[f"sum_{f}"] = jnp.zeros(shape, dt)
            acc[f"cov_sum_{f}"] = jnp.zeros(shape, dt)
    return acc


def leaf_kinds(acc: dict) -> dict:
    """Cross-shard reduction kind per leaf: 'min' | 'max' | 'sum'.

    ``regime_observed`` is a seen-flag, not a count: max keeps it 0/1
    under psum-style merges of any width.
    """
    return {
        k: ("min" if k.startswith("min_")
            else "max" if k.startswith("max_") or k == "regime_observed"
            else "sum")
        for k in acc
    }


def fold_second(acc: dict, level: str, params: FleetParams, *, meter, pv,
                residual, covered, t, valid, cohort=None) -> dict:
    """Fold one second of per-chain ``(n_chains,)`` vectors into a
    **per-chain** acc (``init_acc(..., n_chains=n)``).

    ``t`` is the scalar global second index the scan body already
    carries (``x["t"]``) — it drives the ramp sample grids.  ``valid``
    is the scalar duration mask (a per-chain vector is also accepted —
    the scenario path's site-selector mask).  A non-finite residual
    sample drops the whole second from every statistic (``use`` mask);
    by IEEE semantics a finite residual implies finite meter and pv, so
    the single mask is sufficient for the conditional means too.
    ``cohort``: per-chain int32 group ids for the per-cohort leaves
    (required when the acc was built with ``cohorts``; the masked-out
    samples scatter zero / the extremum identity, so partial partitions
    merge bit-exactly).
    """
    dt = acc["min_res"].dtype
    big = jnp.asarray(jnp.finfo(dt).max, dt)
    r = residual.astype(dt)
    use = valid & jnp.isfinite(r)
    uz = use.astype(jnp.int32)
    out = dict(acc)
    out["count"] = acc["count"] + uz.sum(dtype=jnp.int32)
    # residual histogram: clip in float BEFORE the int cast (out-of-range
    # float->int conversion is target-defined), under/overflow -> slots
    # 0 / bins+1, interior [lo, hi) -> slots 1..bins
    inv_w = params.bins / (params.hi - params.lo)
    b = jnp.clip(jnp.where(use, (r - params.lo) * inv_w, 0.0),
                 -1.0, float(params.bins))
    idx = jnp.floor(b).astype(jnp.int32) + 1
    out["res_hist"] = acc["res_hist"].at[idx].add(uz)
    # exceedance: slot i counts seconds with exactly i thresholds below
    # r (searchsorted 'left' == #{th_j < r}); summarize suffix-sums
    th = jnp.asarray(params.thresholds, dt)
    rg = jnp.where(use, r, params.lo)
    slot = jnp.searchsorted(th, rg, side="left").astype(jnp.int32)
    out["exceed"] = acc["exceed"].at[slot].add(uz)
    out["min_res"] = jnp.minimum(acc["min_res"], jnp.where(use, r, big))
    out["max_res"] = jnp.maximum(acc["max_res"], jnp.where(use, r, -big))
    # loss of load: in-carry run length of consecutive exceedance seconds
    exc = (r > params.capacity_w) & use
    run = jnp.where(exc, acc["lol_run"] + 1, 0)
    out["lol_events"] = acc["lol_events"] + (run == params.lolp_k)
    out["lol_seconds"] = acc["lol_seconds"] + (run >= params.lolp_k)
    out["lol_run"] = run
    # ramp extrema: sample grid S_w = {t : (t+1) % w == 0}; a pair
    # counts only when BOTH endpoints are usable (seen resets on an
    # unusable grid sample — identical semantics to fold_wide's slices)
    for w in params.ramp_windows:
        at = ((t + 1) % w) == 0 if w > 1 else jnp.asarray(True)
        prev = acc[f"prev_ramp_{w}s"]
        seen = acc[f"seen_ramp_{w}s"]
        d = jnp.abs(r - prev)
        ok = at & use & (seen > 0)
        out[f"max_ramp_{w}s"] = jnp.where(
            ok, jnp.maximum(acc[f"max_ramp_{w}s"], d),
            acc[f"max_ramp_{w}s"])
        out[f"prev_ramp_{w}s"] = jnp.where(at & use, r, prev)
        out[f"seen_ramp_{w}s"] = jnp.where(at, uz, seen)
    if "cohort_count" in acc and cohort is not None:
        # per-cohort group-by: one scatter per leaf, keyed by the chain's
        # cohort id.  Same histogram slot ``idx`` as the shared sketch,
        # so the grouped histogram's column sums equal ``res_hist``.
        out["cohort_count"] = acc["cohort_count"].at[cohort].add(uz)
        out["cohort_hist"] = acc["cohort_hist"].at[cohort, idx].add(uz)
        out["min_cohort_res"] = acc["min_cohort_res"].at[cohort].min(
            jnp.where(use, r, big))
        out["max_cohort_res"] = acc["max_cohort_res"].at[cohort].max(
            jnp.where(use, r, -big))
        for name, v in (("meter", meter), ("pv", pv), ("residual", r)):
            v = v.astype(dt)
            out[f"cohort_sum_{name}"] = acc[f"cohort_sum_{name}"].at[
                cohort].add(jnp.where(use, v, jnp.zeros_like(v)))
    if level == "full":
        # covered arrives as the model's 0/1 float mask, not bool
        cov = (covered != 0) & use
        out["regime_observed"] = jnp.ones_like(acc["regime_observed"])
        out["cov_count"] = acc["cov_count"] + cov
        for name, v in (("meter", meter), ("pv", pv), ("residual", r)):
            v = v.astype(dt)
            v0 = jnp.where(use, v, jnp.zeros_like(v))
            out[f"sum_{name}"] = acc[f"sum_{name}"] + v0
            out[f"cov_sum_{name}"] = acc[f"cov_sum_{name}"] + jnp.where(
                cov, v, jnp.zeros_like(v))
    return out


def reduce_chainwise(acc: dict) -> dict:
    """Collapse a per-chain FleetAcc to the scalar (shard-level) form —
    once per block, after the scan, inside the same jit.  Drops the
    carry-only ring slots; the result's leaf set matches
    ``init_acc(level, dtype, params=...)`` so psum dispatch,
    :func:`merge_host` and :func:`summarize` see one format.
    """
    out = {}
    for k, v in acc.items():
        if k == "lol_run" or k.startswith(("prev_ramp_", "seen_ramp_")):
            continue
        if "cohort" in k:
            out[k] = v  # (C,)-grouped scatter targets: already shard-level
        elif k.startswith("min_"):
            out[k] = v.min()
        elif k.startswith("max_"):
            out[k] = v.max()
        elif k in ("count", "res_hist", "exceed", "regime_observed"):
            out[k] = v  # already shard-level
        elif v.dtype == jnp.int32:
            out[k] = v.sum(dtype=jnp.int32)
        else:
            out[k] = v.sum()
    return out


def fold_wide(acc: dict, level: str, params: FleetParams, *, meter, pv,
              t, duration_s, cohort=None) -> dict:
    """Fold materialised ``(n_chains, T)`` block arrays into a
    **scalar-form** acc.

    Same per-second classification as :func:`fold_second` (bit-identical
    int leaves), vectorised: run lengths via a cummax trick, ramp grids
    as static strided slices.  The wide impl never materialises the
    Markov cloud state, so the ``full`` regime leaves stay unfolded and
    ``regime_observed`` stays 0 (:func:`summarize` reports regimes as
    unobserved) — mirroring telemetry's unobserved csi.
    """
    del level
    dt = acc["min_res"].dtype
    big = jnp.asarray(jnp.finfo(dt).max, dt)
    T = meter.shape[1]
    r = (meter - pv).astype(dt)
    valid = t < duration_s                     # (T,)
    use = valid[None, :] & jnp.isfinite(r)     # (n, T)
    uz = use.astype(jnp.int32)
    out = dict(acc)
    out["count"] = acc["count"] + uz.sum(dtype=jnp.int32)
    inv_w = params.bins / (params.hi - params.lo)
    b = jnp.clip(jnp.where(use, (r - params.lo) * inv_w, 0.0),
                 -1.0, float(params.bins))
    idx = jnp.floor(b).astype(jnp.int32) + 1
    out["res_hist"] = acc["res_hist"].at[idx.ravel()].add(uz.ravel())
    th = jnp.asarray(params.thresholds, dt)
    rg = jnp.where(use, r, params.lo)
    slot = jnp.searchsorted(th, rg.ravel(), side="left").astype(jnp.int32)
    out["exceed"] = acc["exceed"].at[slot].add(uz.ravel())
    out["min_res"] = jnp.minimum(
        acc["min_res"], jnp.where(use, r, big).min().astype(dt))
    out["max_res"] = jnp.maximum(
        acc["max_res"], jnp.where(use, r, -big).max().astype(dt))
    # run length ending at column i = i - (last non-loss column <= i)
    exc = (r > params.capacity_w) & use
    tidx = jnp.arange(T, dtype=jnp.int32)
    last_not = jax.lax.cummax(
        jnp.where(exc, jnp.int32(-1), tidx[None, :]), axis=1)
    runlen = tidx[None, :] - last_not
    out["lol_seconds"] = acc["lol_seconds"] + (
        exc & (runlen >= params.lolp_k)).sum(dtype=jnp.int32)
    out["lol_events"] = acc["lol_events"] + (
        exc & (runlen == params.lolp_k)).sum(dtype=jnp.int32)
    for w in params.ramp_windows:
        key = f"max_ramp_{w}s"
        if w >= T:  # no intra-block pair exists at this block size
            continue
        at = ((t + 1) % w) == 0 if w > 1 else jnp.ones((T,), bool)
        d = jnp.abs(r[:, w:] - r[:, :-w])
        pair_ok = at[w:][None, :] & use[:, w:] & use[:, :-w]
        cand = jnp.where(pair_ok, d, -big).max().astype(dt)
        out[key] = jnp.maximum(acc[key], cand)
    if "cohort_count" in acc and cohort is not None:
        # same per-sample classification as fold_second's cohort scatter,
        # vectorised over the block: int leaves fold bit-identically
        cid = jnp.broadcast_to(cohort[:, None], r.shape).ravel()
        out["cohort_count"] = acc["cohort_count"].at[cid].add(uz.ravel())
        out["cohort_hist"] = acc["cohort_hist"].at[
            cid, idx.ravel()].add(uz.ravel())
        out["min_cohort_res"] = acc["min_cohort_res"].at[cid].min(
            jnp.where(use, r, big).ravel())
        out["max_cohort_res"] = acc["max_cohort_res"].at[cid].max(
            jnp.where(use, r, -big).ravel())
        for name, v in (("meter", meter), ("pv", pv), ("residual", r)):
            v = v.astype(dt)
            out[f"cohort_sum_{name}"] = acc[f"cohort_sum_{name}"].at[
                cid].add(jnp.where(use, v, jnp.zeros_like(v)).ravel())
    return out


def merge_host(total: Optional[dict], delta: dict) -> Optional[dict]:
    """Host-side run-total merge of (fetched) scalar-form FleetAccs.

    Widens int32 counts to int64 and float sums to float64 so run totals
    stay exact past the per-block int32 bound; extrema keep their
    compute dtype (selection is exact at any width).  ``total=None``
    starts a fresh total from ``delta``.
    """
    kinds = leaf_kinds(delta)

    def widen(k, v):
        v = np.asarray(v)
        if kinds[k] in ("min", "max"):
            return v.copy()
        if v.dtype.kind in "iu":
            return v.astype(np.int64)
        return v.astype(np.float64)

    if total is None:
        return {k: widen(k, v) for k, v in delta.items()}
    op = {"sum": np.add, "min": np.minimum, "max": np.maximum}
    return {k: op[kinds[k]](total[k], widen(k, v))
            for k, v in delta.items()}


def _quantile(q: float, cum, edges_lo, edges_hi, counts, mn, mx,
              count: int) -> float:
    """Linear-interpolation quantile from cumulative histogram mass.

    Deterministic host float64 math on the (identical) integer counts,
    so equal sketches give bit-equal quantiles.
    """
    target = q * count
    i = int(np.searchsorted(cum, target, side="left"))
    i = min(i, len(counts) - 1)
    below = cum[i] - counts[i]
    frac = (target - below) / counts[i] if counts[i] else 0.0
    v = edges_lo[i] + frac * (edges_hi[i] - edges_lo[i])
    return float(min(max(v, mn), mx))


def summarize(acc: dict, params: FleetParams) -> dict:
    """Host-side reduction of a (fetched or host-merged) scalar-form
    FleetAcc into the plain-python ``fleet`` report section."""
    host = {k: np.asarray(v) for k, v in acc.items()}
    dt = host["min_res"].dtype
    big = float(np.finfo(dt).max)
    count = int(host["count"])
    mn = float(host["min_res"])
    mx = float(host["max_res"])
    observed = count > 0 and mn < 0.5 * big and mx > -0.5 * big
    level = "full" if "cov_count" in host else "risk"

    quantiles = None
    hist = host["res_hist"].astype(np.int64)
    if observed:
        width = (params.hi - params.lo) / params.bins
        interior_lo = params.lo + width * np.arange(params.bins)
        # under/overflow slots span [min, lo] and [hi, max] (clamped so
        # a degenerate all-interior run keeps monotone edges)
        edges_lo = np.concatenate(
            [[min(mn, params.lo)], interior_lo, [params.hi]])
        edges_hi = np.concatenate(
            [[params.lo], interior_lo + width, [max(mx, params.hi)]])
        cum = np.cumsum(hist)
        quantiles = {
            f"p{int(q * 100)}": _quantile(
                q, cum, edges_lo, edges_hi, hist, mn, mx, count)
            for q in (0.01, 0.05, 0.50, 0.95, 0.99)
        }

    exceed = host["exceed"].astype(np.int64)
    # slot i = seconds with exactly i thresholds below r, so seconds
    # with r > th_j = total mass in slots j+1..
    suffix = np.cumsum(exceed[::-1])[::-1]
    exceedance = [
        {"threshold_w": float(th),
         "seconds": int(suffix[j + 1]),
         "prob": float(suffix[j + 1] / count) if count else 0.0}
        for j, th in enumerate(params.thresholds)
    ]

    loss_s = int(host["lol_seconds"])
    events = int(host["lol_events"])
    ramp = {}
    for w in params.ramp_windows:
        v = float(host[f"max_ramp_{w}s"])
        ramp[f"{w}s"] = v if v > -0.5 * big else None

    out = {
        "level": level,
        "count": count,
        "residual": {
            "min": mn if observed else None,
            "max": mx if observed else None,
            "quantiles": quantiles,
        },
        "exceedance": exceedance,
        "lolp": {
            "capacity_w": float(params.capacity_w),
            "k_s": int(params.lolp_k),
            "loss_seconds": loss_s,
            "events": events,
            "prob": float(loss_s / count) if count else 0.0,
        },
        "ramp": ramp,
        "sketch": {
            "bins": int(params.bins),
            "lo_w": float(params.lo),
            "hi_w": float(params.hi),
            "width_w": float((params.hi - params.lo) / params.bins),
            "underflow": int(hist[0]),
            "overflow": int(hist[-1]),
        },
        "regimes": None,
        "cohorts": None,
    }
    if "cohort_count" in host:
        counts = host["cohort_count"].astype(np.int64)
        ghist = host["cohort_hist"].astype(np.int64)
        mins = host["min_cohort_res"].astype(np.float64)
        maxs = host["max_cohort_res"].astype(np.float64)
        width = (params.hi - params.lo) / params.bins
        interior_lo = params.lo + width * np.arange(params.bins)
        cohorts = []
        for c in range(len(counts)):
            n = int(counts[c])
            c_mn, c_mx = float(mins[c]), float(maxs[c])
            seen = n > 0 and c_mn < 0.5 * big and c_mx > -0.5 * big
            q = None
            if seen:
                e_lo = np.concatenate(
                    [[min(c_mn, params.lo)], interior_lo, [params.hi]])
                e_hi = np.concatenate(
                    [[params.lo], interior_lo + width,
                     [max(c_mx, params.hi)]])
                ccum = np.cumsum(ghist[c])
                q = {f"p{int(p * 100)}": _quantile(
                    p, ccum, e_lo, e_hi, ghist[c], c_mn, c_mx, n)
                    for p in (0.05, 0.50, 0.95)}
            means = {
                f"{f}_mean": (float(host[f"cohort_sum_{f}"][c]) / n
                              if n else None)
                for f in ("meter", "pv", "residual")
            }
            cohorts.append({
                "cohort": c,
                "count": n,
                "residual_min": c_mn if seen else None,
                "residual_max": c_mx if seen else None,
                "quantiles": q,
                **means,
            })
        out["cohorts"] = cohorts
    if level == "full" and int(host["regime_observed"]):
        cov_n = int(host["cov_count"])
        clr_n = count - cov_n
        regimes = {}
        for name, n in (("covered", cov_n), ("clear", clr_n)):
            means = {}
            for f in ("meter", "pv", "residual"):
                s = float(host[f"cov_sum_{f}"]) if name == "covered" else (
                    float(host[f"sum_{f}"]) - float(host[f"cov_sum_{f}"]))
                means[f"{f}_mean"] = s / n if n else None
            regimes[name] = {"seconds": n, **means}
        out["regimes"] = regimes
    return out


def publish(registry, summary: dict) -> None:
    """Flush one block summary into the metrics registry
    (``device.fleet.*``).  Counters accumulate across blocks; gauges
    hold the latest block's values."""
    registry.counter("device.fleet.blocks_total").inc()
    registry.counter("device.fleet.samples_total").inc(summary["count"])
    lolp = summary["lolp"]
    registry.counter("device.fleet.loss_seconds_total").inc(
        lolp["loss_seconds"])
    registry.counter("device.fleet.lol_events_total").inc(lolp["events"])
    registry.gauge("device.fleet.lolp").set(lolp["prob"])
    res = summary["residual"]
    for k in ("min", "max"):
        if res[k] is not None:
            registry.gauge(f"device.fleet.residual.{k}").set(res[k])
    for k in ("p50", "p95", "p99"):
        if res["quantiles"] is not None:
            registry.gauge(f"device.fleet.residual.{k}").set(
                res["quantiles"][k])
    for w, v in summary["ramp"].items():
        if v is not None:
            registry.gauge(f"device.fleet.ramp.{w}").set(v)


def repl_view(acc: dict, repl_view_fn) -> dict:
    """Fetch every leaf to host numpy via the sim's replicated-view
    helper (handles non-addressable sharded arrays)."""
    return {k: np.asarray(repl_view_fn(v)) for k, v in acc.items()}
