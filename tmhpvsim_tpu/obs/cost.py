"""Static per-plan device cost model → live ``device.cost.*`` gauges.

The roofline argument for this workload (ROADMAP item 3: ~390
flops/site-second on the scan path, achieved GFLOP/s far below VPU
peak, 0.183 north-star fraction) has so far been computed by hand from
one bench artifact.  This module makes the pricing automatic and live:

* :func:`model_cost` — a *static* table of flops/bytes per simulated
  site-second for each ``block_impl`` × ``compute_dtype`` ×
  ``kernel_impl`` plan cell, anchored to the round-5 XLA
  ``cost_analysis`` of the hot per-block jit (``bench.py
  _hot_jit_cost``) on the scan/f32/exact path and scaled by documented
  per-axis factors.  Static means it prices a plan *without a device*:
  the CPU tier-1 suite and the live ops plane both get real numbers.
* :func:`cost_doc` — the static model joined with a *measured*
  site-seconds/s rate (and, when a device ran, the measured XLA
  flops/bytes) into the RunReport v10 ``cost`` section: achieved
  GFLOP/s / GB/s, roofline fractions against the chip's peaks, and the
  north-star fraction.
* :func:`publish_gauges` — the same numbers as ``device.cost.*`` gauges
  on a :class:`~.metrics.MetricsRegistry`, refreshed at block
  granularity by the engine's ``on_block`` hooks so a live ``/metrics``
  scrape (obs/live.py) prices the run mid-flight.

``NORTH_STAR`` and ``PEAKS`` moved here from bench.py (bench imports
them back) so the one definition serves bench artifacts, live gauges
and report validation alike.

Static-model provenance (``model: static-v1``): the base point is the
round-5 partial battery's ``cost_analysis`` on scan/threefry/f32/exact —
~390 flops and ~96 HBM bytes per site-second.  Axis factors are
estimates, not measurements, and are labelled as such in the doc:

* ``block_impl``: scan2 fuses the accumulator fold into the same scan
  (slightly fewer carry round-trips); wide trades flops for layout;
  split re-materialises between stages (more HBM traffic).
* ``compute_dtype=bf16``: flop *count* is unchanged (the graph is the
  same arithmetic) but activation traffic roughly halves; f32 carries
  and reductions keep the bytes factor above 0.5.
* ``kernel_impl=table``: the transcendental-heavy solar/pv polynomial
  chains collapse into LUT gather + lerp (flops well under half) at the
  price of LUT traffic.

When a run measured the real thing (``cost_analysis`` flops/bytes per
block), :func:`cost_doc` prefers the measurement for the achieved rates
and keeps the static prediction alongside — the gap between the two is
itself a model-quality signal the trend tooling can watch.
"""

from __future__ import annotations

from typing import Optional

#: the ROADMAP's north star: 100k users × 1 simulated year / 1 min wall
#: on 8 chips, in simulated site-seconds per wall-second per chip
NORTH_STAR = 100_000 * 365.25 * 86400 / 60.0 / 8.0

#: per-chip peak rates for device kinds we have numbers for (VPU f32
#: GFLOP/s is an estimate for v5e — marked so artifacts say so)
PEAKS = {
    "TPU v5 lite": {"hbm_gbs": 819.0, "vpu_f32_gops": 6100.0,
                    "vpu_is_estimate": True},
}

#: static model version tag embedded in every doc this module emits
MODEL = "static-v1"

#: round-5 anchor: XLA cost_analysis of the hot block jit on the
#: scan/f32/exact path, normalised per simulated site-second
BASE_FLOPS_PER_SITE_S = 390.0
BASE_BYTES_PER_SITE_S = 96.0

#: per-axis (flops_factor, bytes_factor) multipliers on the anchor
_BLOCK_IMPL_FACTORS = {
    "scan": (1.0, 1.0),
    "scan2": (0.98, 0.97),
    "wide": (1.05, 1.08),
    "fused": (1.0, 1.0),
    "split": (1.02, 1.12),
}
_DTYPE_FACTORS = {
    "f32": (1.0, 1.0),
    "bf16": (1.0, 0.55),
}
_KERNEL_FACTORS = {
    "exact": (1.0, 1.0),
    "table": (0.45, 1.15),
}
#: rng_batch='block' hoists every threefry hash out of the scan body
#: into one batched counter-mode tensor: the per-second flop budget
#: loses the per-minute hash amortisation (~100 ALU ops / 64 bits,
#: SimConfig.prng_impl) but the pre-generated streams round-trip HBM
#: once at (block_s, n_chains) — flops drop, bytes rise slightly.
_RNG_BATCH_FACTORS = {
    "scan": (1.0, 1.0),
    "block": (0.80, 1.06),
}
#: geom_stride=s runs the transcendental PSA/irradiance chain once per
#: s seconds and replaces the other s-1 evaluations with a lerp (two
#: multiply-adds per interpolated field); traffic is unchanged — the
#: per-second xs rows still flow.  Keyed by str(stride) so the doc's
#: string fields stay uniform; unknown strides price as 1.0.
_GEOM_STRIDE_FACTORS = {
    "1": (1.0, 1.0),
    "30": (0.72, 1.0),
    "60": (0.70, 1.0),
}

#: which semantic phases (obs/attribution.py PHASES) each static-v1
#: factor axis claims to scale.  Used by :func:`model_error_doc` to
#: check a factor against the *measured* device-time share of its
#: phase: a factor promising a big flop cut on an axis whose phase is
#: 2% of device time cannot move the total — the phase share bounds
#: the achievable effect (Amdahl).  block_impl restructures the whole
#: loop rather than one phase, so it maps to no phase.
_FACTOR_PHASES = {
    "block_impl": (),
    "compute_dtype": ("physics", "csi"),
    "kernel_impl": ("geometry", "physics"),
    "rng_batch": ("rng",),
    "geom_stride": ("geometry",),
}


def _resolve(value: Optional[str], default: str) -> str:
    return default if value in (None, "", "auto") else str(value)


def model_cost(block_impl: Optional[str] = None,
               compute_dtype: Optional[str] = None,
               kernel_impl: Optional[str] = None,
               rng_batch: Optional[str] = None,
               geom_stride=None) -> dict:
    """Static flops/bytes per site-second for one plan cell.  Unknown
    axis values price as the default cell (factor 1.0) rather than
    raising — a future plan axis must not break old pricing."""
    bi = _resolve(block_impl, "scan")
    dt = _resolve(compute_dtype, "f32")
    ki = _resolve(kernel_impl, "exact")
    rb = _resolve(rng_batch, "scan")
    gs = _resolve(None if geom_stride in (None, "", "auto", 0, "0")
                  else str(geom_stride), "1")
    f1, b1 = _BLOCK_IMPL_FACTORS.get(bi, (1.0, 1.0))
    f2, b2 = _DTYPE_FACTORS.get(dt, (1.0, 1.0))
    f3, b3 = _KERNEL_FACTORS.get(ki, (1.0, 1.0))
    f4, b4 = _RNG_BATCH_FACTORS.get(rb, (1.0, 1.0))
    f5, b5 = _GEOM_STRIDE_FACTORS.get(gs, (1.0, 1.0))
    return {
        "model": MODEL,
        "block_impl": bi,
        "compute_dtype": dt,
        "kernel_impl": ki,
        "rng_batch": rb,
        "geom_stride": int(gs),
        "flops_per_site_s": round(
            BASE_FLOPS_PER_SITE_S * f1 * f2 * f3 * f4 * f5, 2),
        "bytes_per_site_s": round(
            BASE_BYTES_PER_SITE_S * b1 * b2 * b3 * b4 * b5, 2),
    }


def cost_doc(*, site_s_per_s: Optional[float],
             block_impl: Optional[str] = None,
             compute_dtype: Optional[str] = None,
             kernel_impl: Optional[str] = None,
             rng_batch: Optional[str] = None,
             geom_stride=None,
             device_kind: Optional[str] = None,
             measured_flops_per_site_s: Optional[float] = None,
             measured_bytes_per_site_s: Optional[float] = None,
             phase_fractions: Optional[dict] = None) -> dict:
    """The RunReport ``cost`` section (v10; v11 adds the rng_batch /
    geom_stride axes): static model × measured rate (→ achieved
    GFLOP/s, GB/s, north-star fraction), plus roofline fractions when
    the device kind has published peaks.  Measured XLA per-site costs,
    when provided, take precedence over the static prediction for the
    achieved rates; the prediction stays in the doc either way.

    When the caller passes no measurement, the auto-harvested basis
    from the AOT warm-up is used (engine/compilecache.py
    ``measured_cost()`` — ``compiled.cost_analysis()`` of the hot
    per-block jit, normalised per site-second).  That is what makes
    ``basis: "measured"`` appear with NO manual plumbing on every run
    that warmed the compile cache.  Under a measured basis the doc also
    carries the ``model_error`` sub-doc (:func:`model_error_doc`):
    each static-v1 factor priced against the measurement.

    ``phase_fractions`` — optional measured per-phase device-time
    shares (obs/attribution.py ``phase_fractions``); when present the
    ``model_error`` factor rows also carry the measured share of the
    phase each axis claims to scale (v15)."""
    doc = model_cost(block_impl, compute_dtype, kernel_impl,
                     rng_batch, geom_stride)
    if measured_flops_per_site_s is None and \
            measured_bytes_per_site_s is None:
        try:
            from tmhpvsim_tpu.engine.compilecache import measured_cost

            mc = measured_cost()
        except Exception:
            mc = None
        if mc:
            measured_flops_per_site_s = mc.get("flops_per_site_s")
            measured_bytes_per_site_s = mc.get("bytes_per_site_s")
            if measured_flops_per_site_s and mc.get("target"):
                doc["measured_target"] = str(mc["target"])
    flops_ss = (measured_flops_per_site_s
                if measured_flops_per_site_s else doc["flops_per_site_s"])
    bytes_ss = (measured_bytes_per_site_s
                if measured_bytes_per_site_s else doc["bytes_per_site_s"])
    if measured_flops_per_site_s:
        doc["measured_flops_per_site_s"] = round(
            float(measured_flops_per_site_s), 2)
    if measured_bytes_per_site_s:
        doc["measured_bytes_per_site_s"] = round(
            float(measured_bytes_per_site_s), 2)
    doc["basis"] = "measured" if measured_flops_per_site_s else "model"
    if doc["basis"] == "measured":
        doc["model_error"] = model_error_doc(
            doc, measured_flops_per_site_s, measured_bytes_per_site_s,
            phase_fractions=phase_fractions)
    if site_s_per_s:
        rate = float(site_s_per_s)
        doc["site_s_per_s"] = round(rate, 1)
        doc["achieved_gflops"] = round(flops_ss * rate / 1e9, 3)
        doc["achieved_gbs"] = round(bytes_ss * rate / 1e9, 3)
        doc["north_star_frac"] = round(rate / NORTH_STAR, 4)
        peaks = PEAKS.get(device_kind or "")
        if peaks:
            doc["device_kind"] = device_kind
            doc["roofline_frac_vpu"] = round(
                doc["achieved_gflops"] / peaks["vpu_f32_gops"], 5)
            doc["roofline_frac_hbm"] = round(
                doc["achieved_gbs"] / peaks["hbm_gbs"], 5)
            doc["peaks"] = dict(peaks)
    return doc


def model_error_doc(doc: dict,
                    measured_flops_per_site_s: Optional[float],
                    measured_bytes_per_site_s: Optional[float],
                    phase_fractions: Optional[dict] = None) -> dict:
    """Price each static-v1 factor against measurement — ROADMAP item
    2's "say which factor model terms were wrong", computable only
    under a measured basis.

    ``flops_ratio`` / ``bytes_ratio`` are measured ÷ static (1.0 =
    perfect model); the ``_err_pct`` twins are the same as signed
    percentages.  ``factors`` then carries, per plan axis, the factor
    the static table actually used and the *implied* factor — the
    value that axis would need for the model to match measurement if
    IT alone absorbed the whole error.  An implied factor far from its
    table entry on exactly one axis names the term to re-anchor.

    ``phase_fractions`` (v15, optional) — measured per-phase
    device-time shares from a scoped trace (obs/attribution.py).  When
    present, each factor row also carries ``phases`` (the semantic
    phases that axis claims to scale, :data:`_FACTOR_PHASES`) and
    ``measured_phase_frac`` (the summed measured share of those
    phases) — the Amdahl bound on how much of the device time that
    factor can actually move."""
    out = {}
    sf = float(doc["flops_per_site_s"])
    fr = (float(measured_flops_per_site_s) / sf
          if measured_flops_per_site_s and sf else None)
    sb = float(doc["bytes_per_site_s"])
    br = (float(measured_bytes_per_site_s) / sb
          if measured_bytes_per_site_s and sb else None)
    out["flops_ratio"] = round(fr, 4) if fr is not None else None
    out["flops_err_pct"] = (round((fr - 1.0) * 100.0, 2)
                            if fr is not None else None)
    out["bytes_ratio"] = round(br, 4) if br is not None else None
    out["bytes_err_pct"] = (round((br - 1.0) * 100.0, 2)
                            if br is not None else None)
    factors = {}
    for axis, table, key in (
        ("block_impl", _BLOCK_IMPL_FACTORS, doc["block_impl"]),
        ("compute_dtype", _DTYPE_FACTORS, doc["compute_dtype"]),
        ("kernel_impl", _KERNEL_FACTORS, doc["kernel_impl"]),
        ("rng_batch", _RNG_BATCH_FACTORS, doc.get("rng_batch", "scan")),
        ("geom_stride", _GEOM_STRIDE_FACTORS,
         str(doc.get("geom_stride", 1))),
    ):
        f, b = table.get(key, (1.0, 1.0))
        row = {"value": str(key), "flops_factor": f, "bytes_factor": b}
        if fr is not None:
            row["implied_flops_factor"] = round(f * fr, 4)
        if br is not None:
            row["implied_bytes_factor"] = round(b * br, 4)
        if phase_fractions:
            phases = _FACTOR_PHASES.get(axis, ())
            row["phases"] = list(phases)
            row["measured_phase_frac"] = round(
                sum(float(phase_fractions.get(p, 0.0)) for p in phases),
                4)
        factors[axis] = row
    out["factors"] = factors
    return out


#: the gauge keys publish_gauges mirrors out of a cost doc (numeric
#: scalars only — strings don't gauge)
GAUGE_KEYS = (
    "flops_per_site_s", "bytes_per_site_s", "site_s_per_s",
    "achieved_gflops", "achieved_gbs",
    "roofline_frac_vpu", "roofline_frac_hbm", "north_star_frac",
)


def publish_gauges(registry, doc: dict, prefix: str = "device.cost.") -> None:
    """Mirror a cost doc's numeric fields as ``device.cost.*`` gauges —
    what a live ``/metrics`` scrape and the report's gauge-derived
    fallback section read."""
    for key in GAUGE_KEYS:
        v = doc.get(key)
        if isinstance(v, (int, float)):
            registry.gauge(prefix + key).set(float(v))


def validate_cost(doc) -> list:
    """Schema errors (empty when valid) for a v10 ``cost`` section —
    shared by obs/report.py and tools/cost_report.py."""
    errors = []
    if not isinstance(doc, dict):
        return [f"cost: expected dict, got {type(doc).__name__}"]
    for key in ("model", "block_impl", "compute_dtype", "kernel_impl"):
        if not isinstance(doc.get(key), str):
            errors.append(f"cost.{key}: expected str, got "
                          f"{type(doc.get(key)).__name__}")
    # v11 axes — optional, so v10 documents keep validating
    if "rng_batch" in doc and not isinstance(doc["rng_batch"], str):
        errors.append(f"cost.rng_batch: expected str, got "
                      f"{type(doc['rng_batch']).__name__}")
    if "geom_stride" in doc and not isinstance(doc["geom_stride"], int):
        errors.append(f"cost.geom_stride: expected int, got "
                      f"{type(doc['geom_stride']).__name__}")
    for key in ("flops_per_site_s", "bytes_per_site_s"):
        if not isinstance(doc.get(key), (int, float)):
            errors.append(f"cost.{key}: expected number, got "
                          f"{type(doc.get(key)).__name__}")
    for key in ("site_s_per_s", "achieved_gflops", "achieved_gbs",
                "north_star_frac", "roofline_frac_vpu",
                "roofline_frac_hbm", "measured_flops_per_site_s",
                "measured_bytes_per_site_s"):
        if key in doc and not isinstance(doc[key], (int, float)):
            errors.append(f"cost.{key}: expected number, got "
                          f"{type(doc[key]).__name__}")
    if "basis" in doc and doc["basis"] not in ("model", "measured"):
        errors.append(f"cost.basis: expected 'model'|'measured', got "
                      f"{doc['basis']!r}")
    if "peaks" in doc and not isinstance(doc["peaks"], dict):
        errors.append("cost.peaks: expected dict")
    # v14 additions — optional, so pre-v14 documents keep validating
    if "measured_target" in doc and \
            not isinstance(doc["measured_target"], str):
        errors.append("cost.measured_target: expected str")
    me = doc.get("model_error")
    if "model_error" in doc and me is not None:
        if not isinstance(me, dict):
            errors.append(f"cost.model_error: expected object or null, "
                          f"got {type(me).__name__}")
        else:
            for key in ("flops_ratio", "flops_err_pct", "bytes_ratio",
                        "bytes_err_pct"):
                v = me.get(key)
                if v is not None and not isinstance(v, (int, float)):
                    errors.append(f"cost.model_error.{key}: expected "
                                  "number or null")
            fx = me.get("factors")
            if fx is not None and not isinstance(fx, dict):
                errors.append("cost.model_error.factors: expected "
                              "object or null")
            elif isinstance(fx, dict):
                for axis, row in fx.items():
                    if not isinstance(row, dict):
                        errors.append(f"cost.model_error.factors."
                                      f"{axis}: expected object")
                        continue
                    for key in ("flops_factor", "bytes_factor"):
                        if not isinstance(row.get(key), (int, float)):
                            errors.append(
                                f"cost.model_error.factors.{axis}."
                                f"{key}: expected number")
                    # v15 phase-check keys — optional, so v14
                    # documents keep validating
                    if "phases" in row and \
                            not isinstance(row["phases"], list):
                        errors.append(
                            f"cost.model_error.factors.{axis}."
                            "phases: expected list")
                    if "measured_phase_frac" in row and not isinstance(
                            row["measured_phase_frac"], (int, float)):
                        errors.append(
                            f"cost.model_error.factors.{axis}."
                            "measured_phase_frac: expected number")
    frac = doc.get("north_star_frac")
    if isinstance(frac, (int, float)) and frac < 0:
        errors.append(f"cost.north_star_frac: negative ({frac})")
    return errors
