"""Composed clear-sky-index model — multi-rate TPU formulation.

The reference (clearskyindexmodel.py:44-160, after Bright et al. 2015) keeps
seven "interpolated samplers" — (before, after) pairs of random draws,
linearly interpolated by the fraction of the current day/hour/minute — and
advances them in a rollover cascade as wall time crosses day/hour/minute
boundaries, composing per second:

    csi(t) = base(t) * (minute_noise(t) + second_noise(t))

with base/minute samplers chosen by whether the binary renewal process says
the sky is covered.

TPU-first re-design (the heart of SURVEY.md §7 steps 3-5): instead of
advancing stateful samplers second by second, every sampler *value* gets a
global interval index (precomputed on the host: models/timegrid.py) and is
generated on-device at its own natural rate:

  * hourly cloud cover  — `lax.scan` over hours (models/markov_hourly.py),
    the only sequential dependency above 1 s resolution;
  * hourly cloudy-csi, daily clear-csi, daily windspeed — index-keyed
    i.i.d. draws (`fold_in(key, value_index)`), randomly accessible, so
    any time block can be generated without replaying history;
  * minute-noise values — index-keyed draws whose sigma depends on the
    hourly cloud cover interpolated at their *draw instant*
    (clearskyindexmodel.py:86-95), gathered from the hourly array;
  * the per-second renewal + composition — one `lax.scan` over the seconds
    of a block with an O(1) carry (models/renewal.py), vmapped over chains.

Sampler-advance semantics preserved exactly (clearskyindexmodel.py:101-126):
the clear-sky-day sampler advances on *both* hour and day rollovers (its
pair index is hour_idx + day_idx), windspeed on day rollovers, cloud cover
and cloudy-csi on hour rollovers, minute noise on minute rollovers.

Reference-bug policies (see config.ModelOptions):
  * cloudy-csi sampler: the reference *never* advances it (no `next` call
    anywhere in the cascade, clearskyindexmodel.py:101-111), so it
    interpolates between the same two construction-time draws forever.
    Default here: advance on hour rollovers (the evident intent);
    `ModelOptions.advance_cloudy_hour=False` reproduces the frozen pair.
  * the 6/8<=cc<7/8 cloudy draw calls `gamma.pdf(x, ...)` with undefined
    `x` (NameError, clearskyindexmodel.py:80); fixed to a Gamma(5, 0.1)
    *sample*, per the comment above that line.
  * `covered` selects the clear-sky samplers and vice versa
    (clearskyindexmodel.py:149-160); kept by default for parity,
    `ModelOptions.swap_covered_branches=True` applies the evident intent.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tmhpvsim_tpu.config import ModelOptions
from tmhpvsim_tpu.models import distributions as dist
from tmhpvsim_tpu.models import markov_hourly, renewal
from tmhpvsim_tpu.models.timegrid import TimeGridSpec

# Bright et al. 2015 parameters as used by the reference
# (clearskyindexmodel.py:64-95,146-147)
CSI_CLEAR_DAY_LOC = 0.99
CSI_CLEAR_DAY_SCALE = 0.08
CSI_CLOUDY_NORM_LOC = 0.6784
CSI_CLOUDY_NORM_SCALE = 0.2046
CSI_CLOUDY_GAMMA_MID = (5.0, 0.1)      # 6/8 <= cc < 7/8 (bug-fixed draw)
CSI_CLOUDY_GAMMA_HIGH = (3.5624, 0.0867)  # cc >= 7/8
SIGMA_MIN_FACTOR = np.sqrt(0.9)        # minute-noise variance split
SIGMA_SEC_FACTOR = np.sqrt(0.1 * 60)   # second-noise variance split
NOISE_CLOUDY = (0.01, 0.003)           # (sigma0, sigma1) minute, cloudy
NOISE_CLEAR = (0.001, 0.0015)          # minute, clear — also used per-second
                                       # by *both* branches
                                       # (clearskyindexmodel.py:152,158)


@dataclasses.dataclass
class HostFeatures:
    """Host-precomputed, chain-independent arrays for one simulation run."""

    n_hours: int          # hour-interval count (sampler needs n_hours+1 values)
    n_days: int
    n_minutes: int
    f0_hour: float        # hour fraction at the grid start (primer draw instant)

    @classmethod
    def from_spec(cls, spec: TimeGridSpec):
        b0 = spec.block(0, 1)
        return cls(
            n_hours=spec.n_hour_intervals,
            n_days=spec.n_day_intervals,
            n_minutes=spec.n_minute_intervals,
            f0_hour=float(b0.hour_fraction[0]),
        )


# ---------------------------------------------------------------------------
# Per-run sampler value arrays (one chain; vmap over keys for a batch)
# ---------------------------------------------------------------------------


def _cloudy_csi_draw(key, cc, dtype):
    """One cloudy-csi sample given the cloud cover at the draw instant
    (clearskyindexmodel.py:68-84, with the NameError band fixed to rvs)."""
    k_n, k_g = jax.random.split(key)
    z = dist.normal(k_n, CSI_CLOUDY_NORM_LOC, CSI_CLOUDY_NORM_SCALE,
                    jnp.shape(cc), dtype)
    a = jnp.where(cc < 7 / 8, CSI_CLOUDY_GAMMA_MID[0], CSI_CLOUDY_GAMMA_HIGH[0])
    scale = jnp.where(cc < 7 / 8, CSI_CLOUDY_GAMMA_MID[1], CSI_CLOUDY_GAMMA_HIGH[1])
    g = scale * jax.random.gamma(k_g, a, jnp.shape(cc), dtype)
    return jnp.where(cc < 6 / 8, z, g)


def cc_window(k_cc, lo, n, carry, options: ModelOptions, dtype=jnp.float32,
              params=None):
    """Hourly cloud-cover values for global indices [lo, lo+n).

    ``carry`` is the chain state before transition ``lo`` (ignored in the
    iid-compat mode).  Returns (values[n], new_carry).  Every draw is
    keyed by its global index (markov_hourly.chain_window/iid_window), so
    any window regenerates identically — the foundation of the engine's
    O(window) state (SURVEY.md §5 checkpoint note).  ``params``
    overrides the step-distribution table (heterogeneous fleets pass a
    per-chain regime gather, markov_hourly.select_regime; None = the
    vendored Munich table, byte-identical draws)."""
    if options.persistent_cloud_chain:
        return markov_hourly.chain_window(k_cc, lo, n, carry, dtype,
                                          params=params)
    return markov_hourly.iid_window(k_cc, lo, n, dtype,
                                    params=params), carry


def cloudy_window(k_cloudy, lo, n, cc_vals, cc_lo, cc0, dtype=jnp.float32):
    """Cloudy-csi values for global indices [lo, lo+n).

    Value k >= 2 is drawn at hour rollover k-1 (hour_fraction == 0), so it
    sees cc == cc[k-1]; the two primer values (k < 2) see the
    construction-time interpolation ``cc0`` = lerp(cc[0], cc[1], f0_hour).
    ``cc_vals``/``cc_lo`` supply the hourly window covering [lo-1, lo+n-2]
    (entries outside it are never consumed: the windowed caller's window
    always starts one hour early, and the k < 2 branch covers the rest).
    """
    idx = lo + jnp.arange(n)
    cc_at = jnp.where(
        idx < 2, cc0,
        cc_vals[jnp.clip(idx - 1 - cc_lo, 0, cc_vals.shape[0] - 1)],
    )
    keys = jax.vmap(lambda i: jax.random.fold_in(k_cloudy, i))(idx)
    return jax.vmap(lambda k, c: _cloudy_csi_draw(k, c, dtype))(keys, cc_at)


def clear_day_window(k_day, lo, n, dtype=jnp.float32):
    """Clear-sky-day values for global pair indices [lo, lo+n) (the pair
    index is hour_idx + day_idx: the sampler advances on both rollovers).
    Index-keyed i.i.d. draws — randomly accessible."""
    idx = lo + jnp.arange(n)
    return jax.vmap(
        lambda i: dist.normal(jax.random.fold_in(k_day, i),
                              CSI_CLEAR_DAY_LOC, CSI_CLEAR_DAY_SCALE,
                              (), dtype)
    )(idx)


def ws_window(k_ws, lo, n, dtype=jnp.float32):
    """Daily windspeed values for global day indices [lo, lo+n)."""
    idx = lo + jnp.arange(n)
    return jax.vmap(
        lambda i: dist.windspeed(jax.random.fold_in(k_ws, i), (), dtype)
    )(idx)


def build_chain_arrays(key, feats: HostFeatures, options: ModelOptions,
                       dtype=jnp.float32):
    """All above-second-rate sampler values for ONE chain, full run — the
    window functions above evaluated over the whole grid (tests and small
    runs; the engine generates per-block windows instead).

    Returns dict of arrays:
      cc     [n_hours+1]           hourly cloud cover (Markov chain states)
      cloudy [n_hours+1]           cloudy-csi values (frozen pair if compat)
      clear_day [n_hours+n_days+1] clear-sky-day values (advances hour+day)
      ws     [n_days+1]            daily windspeed
    """
    k_cc, k_cloudy, k_day, k_ws = jax.random.split(key, 4)

    cc, _ = cc_window(k_cc, 0, feats.n_hours + 1, jnp.asarray(1.0, dtype),
                      options, dtype)
    cc0 = cc[0] * (1 - feats.f0_hour) + cc[1] * feats.f0_hour
    cloudy = cloudy_window(k_cloudy, 0, feats.n_hours + 1, cc, 0, cc0,
                           dtype)
    # (reference-compat frozen pair is handled at gather time in
    # csi_scan_block: the pair index is pinned to 0 so (cloudy[0], cloudy[1])
    # interpolate forever, exactly like a sampler that never advances)
    clear_day = clear_day_window(k_day, 0, feats.n_hours + feats.n_days + 1,
                                 dtype)
    ws = ws_window(k_ws, 0, feats.n_days + 1, dtype)
    return {"cc": cc, "cloudy": cloudy, "clear_day": clear_day, "ws": ws}


def minute_noise_values(key, cc, spec: TimeGridSpec, lo: int, hi: int,
                        dtype=jnp.float32):
    """Minute-noise sampler values with indices [lo, hi) for one chain.

    Host-convenience wrapper over :func:`minute_noise_values_device`.
    """
    h_idx, h_frac = spec.minute_value_features(lo, hi)
    feats = (jnp.asarray(h_idx), jnp.asarray(h_frac, dtype=dtype))
    return minute_noise_values_device(key, cc, lo, feats, dtype)


def minute_noise_values_device(key, cc, lo, feats, dtype=jnp.float32):
    """Device-side minute-noise values; jit-safe (``lo`` may be traced).

    Index-keyed draws: value i uses fold_in(key, i), so any block of the run
    can regenerate its minute values without history.  sigma depends on the
    hourly cloud cover interpolated at the value's draw instant
    (clearskyindexmodel.py:86-95): sigma = sqrt(0.9)*(s0 + s1*8*cc).

    ``feats`` is the (hour_idx, hour_frac) pair from
    ``TimeGridSpec.minute_value_features(lo, hi)`` — host-precomputed, its
    static length fixes hi - lo.
    """
    h_idx, h_frac = feats
    h_frac = h_frac.astype(dtype)
    cc_at = cc[h_idx] * (1 - h_frac) + cc[h_idx + 1] * h_frac

    i = lo + jnp.arange(h_idx.shape[0])
    keys = jax.vmap(lambda j: jax.random.fold_in(key, j))(i)
    k_cloudy = jax.vmap(lambda k: jax.random.fold_in(k, 0))(keys)
    k_clear = jax.vmap(lambda k: jax.random.fold_in(k, 1))(keys)

    def draw(kz, s0, s1):
        sigma = SIGMA_MIN_FACTOR * (s0 + s1 * 8.0 * cc_at)
        z = jax.vmap(lambda k: jax.random.normal(k, (), dtype))(kz)
        return 1.0 + sigma * z

    return {
        "noise_min_cloudy": draw(k_cloudy, *NOISE_CLOUDY),
        "noise_min_clear": draw(k_clear, *NOISE_CLEAR),
    }


# ---------------------------------------------------------------------------
# Per-second scan over one time block (single chain; vmap over chains)
# ---------------------------------------------------------------------------


def init_renewal(key, arrays, dtype=jnp.float32):
    """Initial renewal carry, matching the reference's construction: the
    binary process starts from interpolate(0) == the *before* values of the
    cloud-cover and windspeed samplers (clearskyindexmodel.py:98-99)."""
    return renewal.init(key, arrays["cc"][0], arrays["ws"][0], dtype)


def minute_grouped_keys(key, t):
    """Per-minute threefry keys covering the seconds ``t`` (contiguous,
    any alignment): key i belongs to global minute ``t[0]//60 + i``.
    Returns (keys[n_groups], offsets[T]) with ``offsets`` indexing second
    t into the flattened (n_groups, 60) draw table."""
    g0 = t[0] // 60
    n_groups = (t.shape[0] + 119) // 60  # covers any mid-minute alignment
    tg = g0 + jnp.arange(n_groups)
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(tg)
    return keys, t - g0 * 60


def meter_block(key, t, max_w, dtype=jnp.float32):
    """Uniform [0, max_w) demand per second of ``t``, minute-grouped keys —
    THE meter stream derivation, shared by the engine's per-chain stream
    (engine/simulation.py ``_block_step``) and the standalone jax metersim
    producer (apps/metersim.py) so the two can never diverge."""
    kg, off = minute_grouped_keys(key, t)
    draws = jax.vmap(lambda k: jax.random.uniform(k, (60,), dtype))(kg)
    return max_w * draws.reshape(-1)[off]


def scan_draws_tmajor(keys, g0, n_groups, dtype):
    """Batched (u_cycle, z_sec) for a minute-ALIGNED block, time-major.

    ``keys`` is the (n_chains,) stacked ``k_scan`` key array; returns two
    (n_groups*60, n_chains) arrays whose row t is the per-chain draw for
    local second t.  Values are bit-identical to the per-chain
    :func:`_minute_grouped_draws` stream (same fold_in indices, same
    counter slots) — only the memory layout differs, which is what the
    scan-fused engine path needs (engine/simulation.py): the per-second
    scan consumes row slices, so nothing is gathered or transposed.
    """
    n = keys.shape[0]

    def per_group(g):
        def per_chain(k):
            kg = jax.random.fold_in(k, g)
            u = jax.random.uniform(jax.random.fold_in(kg, 0), (60,), dtype)
            z = jax.random.normal(jax.random.fold_in(kg, 1), (60,), dtype)
            return u, z
        return jax.vmap(per_chain, out_axes=1)(keys)   # (60, n) each

    u, z = jax.vmap(per_group)(g0 + jnp.arange(n_groups))
    return u.reshape(-1, n), z.reshape(-1, n)


def meter_block_tmajor(keys, g0, n_groups, max_w, dtype):
    """Time-major batched meter stream for a minute-aligned block:
    (n_groups*60, n_chains), row t = per-chain demand at local second t.
    Bit-identical values to :func:`meter_block` (same fold_in/counter
    indexing), laid out for the scan-fused engine path."""
    n = keys.shape[0]

    def per_group(g):
        return jax.vmap(
            lambda k: jax.random.uniform(
                jax.random.fold_in(k, g), (60,), dtype
            ),
            out_axes=1,
        )(keys)

    u = jax.vmap(per_group)(g0 + jnp.arange(n_groups))
    return max_w * u.reshape(-1, n)


def _minute_grouped_draws(key, t, dtype):
    """(uniform, normal) per second of ``t``, one hash per minute."""
    kg, off = minute_grouped_keys(key, t)
    u = jax.vmap(
        lambda k: jax.random.uniform(jax.random.fold_in(k, 0), (60,), dtype)
    )(kg).reshape(-1)
    z = jax.vmap(
        lambda k: jax.random.normal(jax.random.fold_in(k, 1), (60,), dtype)
    )(kg).reshape(-1)
    return u[off], z[off]


def block_draws(key, t, dtype=jnp.float32):
    """Whole-block (uniform, normal) pre-generation for ONE chain — the
    ``rng_batch='block'`` hoist (Plan.rng_batch): exactly the draws
    :func:`csi_scan_block` would make internally (same per-minute
    ``fold_in`` keys, same counter slots, so values are bit-identical —
    asserted by tests/test_rng_batch.py), generated as one batched
    counter-mode tensor BEFORE the consumer instead of inside it.
    Batch across chains with ``jax.vmap`` and feed the result back via
    ``csi_scan_block(..., draws=...)``."""
    return _minute_grouped_draws(key, t, dtype)


def csi_scan_block(key, arrays, minute_vals, minute_lo, carry, block_idx,
                   options: ModelOptions, dtype=jnp.float32, unroll=8,
                   cloudy_pair=None, draws=None):
    """One block of per-second csi for one chain.

    TPU layout: the *only* sequential dependency is the renewal carry, so
    the ``lax.scan`` body is ~15 flops consuming pre-drawn uniforms; all
    RNG hashing (one threefry per global second index — counter-based, so
    results are block-partition invariant) and the whole sampler-
    interpolation/composition pipeline run as batched elementwise ops
    outside the scan, where the VPU parallelises them across lanes instead
    of serialising them across simulated seconds.

    Parameters
    ----------
    key : per-chain scan key; draw t uses fold_in(key, global second index)
    arrays : per-chain sampler arrays (build_chain_arrays)
    minute_vals : per-chain minute-noise values covering the block
    minute_lo : global index of minute_vals[0] (for gather rebasing)
    carry : renewal carry (init_renewal or previous block's)
    block_idx : dict of shared int32/float arrays over the block's seconds:
        t (global second), hour_idx, day_idx, min_idx, hour_frac, day_frac,
        min_frac
    draws : optional pre-generated (u_cycle, z_sec) pair from
        :func:`block_draws` (Plan.rng_batch='block'); None — the
        default — draws internally, leaving the historical graph
        byte-identical.
    Returns (carry', csi[T], covered[T]).
    """
    cc, cloudy, clear_day, ws = (
        arrays["cc"], arrays["cloudy"], arrays["clear_day"], arrays["ws"],
    )
    mc = minute_vals["noise_min_cloudy"]
    ml = minute_vals["noise_min_clear"]
    t, h, d, m = (block_idx["t"], block_idx["hour_idx"],
                  block_idx["day_idx"], block_idx["min_idx"])
    hf, df, mf = (block_idx["hour_frac"], block_idx["day_frac"],
                  block_idx["min_frac"])
    cd = h + d

    # --- batched counter-based RNG: one threefry key per GLOBAL minute,
    # with the 60 per-second values drawn in counter mode from it.  Cost:
    # ~1 hash per simulated second instead of the ~4 a per-second
    # fold_in+split+uniform+normal costs — the csi scan's dominant expense
    # on TPU (measured: the whole block step is RNG-hash-bound).  Second s
    # always reads value s % 60 of minute s // 60, so results stay
    # invariant under ANY block partition or alignment; blocks that start
    # or end mid-minute (free-standing callers — Simulation itself always
    # aligns) just draw up to two spare groups.
    if draws is None:
        u_cycle, z_sec = _minute_grouped_draws(key, t, dtype)
    else:
        u_cycle, z_sec = draws

    # --- elementwise sampler interpolation over the block
    cc_t = cc[h] * (1 - hf) + cc[h + 1] * hf
    ws_t = ws[d] * (1 - df) + ws[d + 1] * df

    # second-scale noise: both branches use the *clear* sigmas
    # (clearskyindexmodel.py:146-147,152,158)
    s0, s1 = NOISE_CLEAR
    noise_sec = SIGMA_SEC_FACTOR * (s0 + s1 * 8.0 * cc_t) * z_sec

    base_clear = clear_day[cd] * (1 - df) + clear_day[cd + 1] * df
    if options.advance_cloudy_hour:
        base_cloudy = cloudy[h] * (1 - hf) + cloudy[h + 1] * hf
    else:
        # reference-compat: the cloudy sampler never advances, so the two
        # CONSTRUCTION-TIME values (global indices 0 and 1) interpolate
        # forever (clearskyindexmodel.py:101-111 advances every sampler
        # except this one).  Windowed callers pass them as ``cloudy_pair``
        # (the window need not contain global index 0); full-run callers
        # leave None and they are cloudy[:2].
        pair = cloudy[:2] if cloudy_pair is None else cloudy_pair
        base_cloudy = pair[0] * (1 - hf) + pair[1] * hf
    mrel = m - minute_lo
    nmin_clear = ml[mrel] * (1 - mf) + ml[mrel + 1] * mf
    nmin_cloudy = mc[mrel] * (1 - mf) + mc[mrel + 1] * mf

    # --- minimal sequential core: the renewal compare/select alone.  The
    # candidate cycles are carry-independent, so the power-law inverse-CDF
    # is batched here (one vectorised sweep over the block) instead of
    # running inside every scan step; unroll=8 keeps the 3-scalar carry in
    # registers across iterations instead of round-tripping HBM (both
    # measured on TPU; together ~2x block throughput)
    cloud_cand, total_cand = renewal.cycle_from_u(u_cycle, cc_t, ws_t)

    def body(c, x):
        return renewal.step_from_cycle(c, x["cl"], x["to"], dtype)

    carry, covered = jax.lax.scan(
        body, carry, {"cl": cloud_cand, "to": total_cand}, unroll=unroll
    )

    is_cov = covered > 0.5
    use_clear = is_cov if not options.swap_covered_branches else ~is_cov
    base = jnp.where(use_clear, base_clear, base_cloudy)
    nmin = jnp.where(use_clear, nmin_clear, nmin_cloudy)
    return carry, base * (nmin + noise_sec), covered


def value_major_tables(arrays, minute_vals):
    """Sampler tables transposed to value-major (n_values, n_chains) for
    the scan-fused path: the per-second body indexes ROWS by the step's
    scalar interval index (a dynamic-slice), instead of the wide path's
    per-chain (n_chains, block_s) gathers — the single biggest HBM-traffic
    term of the wide formulation (measured on TPU v5e: the wide block step
    is bandwidth-bound, engine/simulation.py)."""
    return {
        "cc": arrays["cc"].T,
        "cloudy": arrays["cloudy"].T,
        "clear_day": arrays["clear_day"].T,
        "ws": arrays["ws"].T,
        "ml": minute_vals["noise_min_clear"].T,
        "mc": minute_vals["noise_min_cloudy"].T,
    }


def csi_compose_step(tables, x, carry, options: ModelOptions,
                     dtype=jnp.float32):
    """One simulated second of csi for ALL chains (the scan-fused body).

    Same math as :func:`csi_scan_block`, evaluated per step on (n_chains,)
    vectors: ``tables`` from :func:`value_major_tables`; ``x`` carries the
    step's scalar calendar indices/fractions (h, d, m, hf, df, mf) and the
    per-chain pre-drawn (u, z); ``carry`` is the renewal carry.  Returns
    (carry', csi, covered).  Consumes the identical RNG stream as the wide
    path (scan_draws_tmajor), so both formulations produce the same
    simulation up to float reassociation.
    """
    h, d, m = x["h"], x["d"], x["m"]
    hf, df, mf = x["hf"], x["df"], x["mf"]

    cc_t = tables["cc"][h] * (1 - hf) + tables["cc"][h + 1] * hf
    ws_t = tables["ws"][d] * (1 - df) + tables["ws"][d + 1] * df

    s0, s1 = NOISE_CLEAR
    noise_sec = SIGMA_SEC_FACTOR * (s0 + s1 * 8.0 * cc_t) * x["z"]

    cd = h + d
    base_clear = (tables["clear_day"][cd] * (1 - df)
                  + tables["clear_day"][cd + 1] * df)
    if options.advance_cloudy_hour:
        base_cloudy = (tables["cloudy"][h] * (1 - hf)
                       + tables["cloudy"][h + 1] * hf)
    else:
        # construction-time frozen pair (see csi_scan_block); windowed
        # callers supply it under "cloudy_pair" in value-major (2, chains)
        pair = tables.get("cloudy_pair")
        pair = tables["cloudy"][:2] if pair is None else pair
        base_cloudy = pair[0] * (1 - hf) + pair[1] * hf
    nmin_clear = tables["ml"][m] * (1 - mf) + tables["ml"][m + 1] * mf
    nmin_cloudy = tables["mc"][m] * (1 - mf) + tables["mc"][m + 1] * mf

    cloud_cand, total_cand = renewal.cycle_from_u(x["u"], cc_t, ws_t)
    carry, covered = renewal.step_from_cycle(
        carry, cloud_cand, total_cand, dtype
    )

    is_cov = covered > 0.5
    use_clear = is_cov if not options.swap_covered_branches else ~is_cov
    base = jnp.where(use_clear, base_clear, base_cloudy)
    nmin = jnp.where(use_clear, nmin_clear, nmin_cloudy)
    return carry, base * (nmin + noise_sec), covered


def host_block_index(spec: TimeGridSpec, offset: int, length: int,
                     dtype=jnp.float32, blk=None):
    """Shared (chain-independent) scan inputs for one block, as HOST
    (numpy) arrays: the jit call transfers them at dispatch, which skips
    ~26 eager per-leaf jnp.asarray dispatches per block (~70% of the
    measured host_inputs cost — the host side co-limits the pipeline at
    scan-fused device rates, PERF_ANALYSIS §4b).  numpy leaves have the
    same avals as the previous device arrays, so no jit recompiles and
    bit-identical values.  ``blk`` reuses an already-computed
    ``spec.block(offset, length)`` — the O(block_s) float64 calendar
    precompute is the per-block host cost, so callers that need the
    TimeBlock anyway (engine host_inputs) pass it in instead of paying
    it twice."""
    if blk is None:
        blk = spec.block(offset, length)
    return {
        "t": np.asarray(blk.offset + np.arange(len(blk.epoch)), np.int32),
        "hour_idx": np.asarray(blk.hour_idx, np.int32),
        "day_idx": np.asarray(blk.day_idx, np.int32),
        "min_idx": np.asarray(blk.min_idx, np.int32),
        "hour_frac": np.asarray(blk.hour_fraction, dtype),
        "day_frac": np.asarray(blk.day_fraction, dtype),
        "min_frac": np.asarray(blk.min_fraction, dtype),
    }, (int(blk.min_idx[0]), int(blk.min_idx[-1]) + 2)
