"""Hourly cloud-cover Markov chain as a branchless `lax.scan`.

Reference semantics (cloud_cover_hourly.py:1-21, 290-316): the hourly cloud
cover x in [0, 1] evolves as

    x[i+1] = clip(x[i] + step(x[i]), 0, 1)

where the step is drawn from one of six fitted distributions selected by
which bin x[i] falls into (searchsorted over the right bin edges).  Five bins
use an asymmetric-Laplace step, one a Student-t (data/parameters.py).

TPU-first formulation: per transition we gather the bin's parameters with a
`searchsorted` + take (no data-dependent Python branching), draw *both* an
asymmetric-Laplace variate (closed-form inverse CDF of one uniform) and a
Student-t variate from independent key splits, and `where`-select by the
bin's distribution mark.  One transition is ~20 scalar flops, so a year of
hourly states for a million chains is ~1e10 flops — `vmap` over chains and
`lax.scan` over hours maps this straight onto the VPU.

Reference-bug note: the reference's hourly *sampler* accidentally rebuilds
the chain generator on every draw (clearskyindexmodel.py:61-63), so in
practice it emits i.i.d. single steps from state 1.0 rather than a persistent
chain.  `chain()` implements the documented persistent behaviour;
`iid_from_one()` reproduces the accidental behaviour for compatibility
(selected via ModelOptions.persistent_cloud_chain).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tmhpvsim_tpu.data import (MARKOV_STEP_BINS, MARKOV_STEP_PARAMS,
                               MARKOV_STEP_PARAMS_REGIMES)
from tmhpvsim_tpu.models import distributions as dist


def step_params(dtype=jnp.float32, table=MARKOV_STEP_PARAMS):
    """Stacked per-bin step-distribution parameters for device-side gathers."""
    p = np.asarray(table, dtype=np.float64)
    return {
        "bins": jnp.asarray(MARKOV_STEP_BINS, dtype=dtype),
        "loc": jnp.asarray(p[:, 0], dtype=dtype),
        "scale": jnp.asarray(p[:, 1], dtype=dtype),
        "kappa": jnp.asarray(p[:, 2], dtype=dtype),
        "df": jnp.asarray(p[:, 3], dtype=dtype),
        "is_t": jnp.asarray(p[:, 4], dtype=dtype),
    }


def regime_step_params(dtype=jnp.float32):
    """Every vendored regime table stacked on a leading regime axis:
    each per-bin leaf becomes (n_regimes, 6), ``bins`` stays shared.
    Row 0 is the Munich fit byte-for-byte (``MARKOV_STEP_PARAMS_REGIMES``
    aliases it), so ``select_regime(regime_step_params(dt), 0)`` equals
    ``step_params(dt)`` exactly — heterogeneous-fleet chains pinned at
    regime 0 draw the same steps as the homogeneous path."""
    p = np.asarray(MARKOV_STEP_PARAMS_REGIMES, dtype=np.float64)
    return {
        "bins": jnp.asarray(MARKOV_STEP_BINS, dtype=dtype),
        "loc": jnp.asarray(p[:, :, 0], dtype=dtype),
        "scale": jnp.asarray(p[:, :, 1], dtype=dtype),
        "kappa": jnp.asarray(p[:, :, 2], dtype=dtype),
        "df": jnp.asarray(p[:, :, 3], dtype=dtype),
        "is_t": jnp.asarray(p[:, :, 4], dtype=dtype),
    }


def select_regime(regime_params, regime):
    """One chain's (6,)-leaf parameter dict gathered from the stacked
    regime tables; ``regime`` may be a traced int scalar (a per-chain
    leaf inside a vmapped block body)."""
    return {k: (v if k == "bins" else v[regime])
            for k, v in regime_params.items()}


def transition(key, state, params, dtype=jnp.float32):
    """One Markov transition; `state` may be any shape, keys broadcast over it."""
    idx = jnp.searchsorted(params["bins"], state, side="left")
    idx = jnp.clip(idx, 0, params["loc"].shape[0] - 1)
    loc = params["loc"][idx]
    scale = params["scale"][idx]
    kappa = params["kappa"][idx]
    df = params["df"][idx]
    is_t = params["is_t"][idx]

    k_al, k_t = jax.random.split(key)
    shape = jnp.shape(state)
    d_al = dist.asymmetric_laplace(k_al, loc, scale, kappa, shape, dtype)
    d_t = dist.student_t(k_t, loc, scale, df, shape, dtype)
    step = jnp.where(is_t > 0.5, d_t, d_al)
    return jnp.clip(state + step, 0.0, 1.0)


def chain_window(key, start, n, state, dtype=jnp.float32, params=None):
    """``n`` successive chain states for global value indices
    [start, start+n), continuing from ``state`` (the state the chain held
    before transition ``start``).

    Transition i is keyed by ``fold_in(key, i)`` — a pure function of the
    global index — so ANY window of the chain can be generated from (key,
    start, carry) without replaying history: the property that makes
    simulation state O(window) instead of O(run duration)
    (engine/simulation.py "windowed sampler arrays").  ``start`` may be a
    traced scalar; ``n`` must be static.  Returns (values[n], new_state)
    where new_state == values[n-1].
    """
    if params is None:
        params = step_params(dtype)

    def body(s, i):
        nxt = transition(jax.random.fold_in(key, i), s, params, dtype)
        return nxt, nxt

    final, samples = jax.lax.scan(body, state, start + jnp.arange(n))
    return samples, final


def chain(key, n_samples, initial_state=1.0, dtype=jnp.float32):
    """Persistent chain: `n_samples` successive states after `initial_state`.

    Returns shape (n_samples,).  vmap over keys for independent chains.
    The full-run convenience form of :func:`chain_window`.
    """
    init = jnp.asarray(np.clip(initial_state, 0.0, 1.0), dtype=dtype)
    samples, _ = chain_window(key, 0, n_samples, init, dtype)
    return samples


def iid_window(key, start, n, dtype=jnp.float32, params=None):
    """Reference-compat mode, windowed: value i is one i.i.d. step from
    state 1.0 (the accidental behaviour of clearskyindexmodel.py:61-63),
    keyed by global index — randomly accessible like
    :func:`chain_window`, no carry."""
    if params is None:
        params = step_params(dtype)
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        start + jnp.arange(n)
    )
    ones = jnp.ones((n,), dtype=dtype)
    return jax.vmap(lambda k, s: transition(k, s, params, dtype))(keys, ones)


def iid_from_one(key, n_samples, dtype=jnp.float32):
    """Full-run convenience form of :func:`iid_window`."""
    return iid_window(key, 0, n_samples, dtype)


# ---------------------------------------------------------------------------
# numpy golden implementation (float64, same formulas, independent code path)
# ---------------------------------------------------------------------------


_BINS64 = np.asarray(MARKOV_STEP_BINS, dtype=np.float64)
_PARAMS64 = np.asarray(MARKOV_STEP_PARAMS, dtype=np.float64)


def transition_numpy(rng: np.random.Generator, state: float) -> float:
    """One float64 transition — shared by the golden streaming model
    (engine/golden.py) and `chain_numpy`.

    Independent implementation of the same mathematical model (inverse-CDF
    sampling from numpy uniforms / standard_t), *not* the same RNG stream
    as `transition` — comparisons are distributional (SURVEY.md §7 hard
    part (c)).
    """
    idx = np.searchsorted(_BINS64, state, side="left")
    loc, scale, kappa, df, is_t = _PARAMS64[min(idx, len(_PARAMS64) - 1)]
    if is_t > 0.5:
        step = loc + scale * rng.standard_t(df)
    else:
        u = rng.uniform()
        k2 = kappa * kappa
        if u < k2 / (1 + k2):
            x = kappa * np.log((1 + k2) / k2 * u)
        else:
            x = -np.log((1 + k2) * (1 - u)) / kappa
        step = loc + scale * x
    return float(np.clip(state + step, 0.0, 1.0))


def chain_numpy(rng: np.random.Generator, n_samples, initial_state=1.0):
    """Pure-numpy persistent chain for distributional parity tests."""
    state = float(np.clip(initial_state, 0.0, 1.0))
    out = np.empty(n_samples)
    for i in range(n_samples):
        state = transition_numpy(rng, state)
        out[i] = state
    return out
