"""Keyed JAX samplers for the stochastic weather models.

Every random draw in the reference is a scipy/numpy global-RNG ``rvs`` call
(e.g. clearskyindexmodel.py:65-97, cloud_cover_binary.py:23,40); here each
becomes a pure function of an explicit `jax.random` key so draws are
counter-based, reproducible, vmap-able across millions of chains, and legal
inside `lax.scan`.  Where scipy uses generic machinery we use closed-form
inverse-CDF transforms — branchless, transcendental-light, and TPU-friendly.

Conventions: all samplers take `key` first, accept broadcastable parameter
arrays, and return an array of `shape` (default: broadcast of the params).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# --------------------------------------------------------------------------
# Asymmetric Laplace
# --------------------------------------------------------------------------


def asymmetric_laplace_ppf(q, kappa):
    """Percent-point function of the standard asymmetric Laplace distribution.

    Density f(x) = 1/(kappa + 1/kappa) * exp(-kappa*x) for x >= 0 and
    exp(x/kappa) for x < 0 — the parameterisation of the reference's custom
    scipy distribution (cloud_cover_hourly.py:93-106).  Closed form:

        q <  k^2/(1+k^2):  x =  kappa  * log((1+k^2)/k^2 * q)
        q >= k^2/(1+k^2):  x = -1/kappa * log((1+k^2) * (1-q))
    """
    k2 = kappa * kappa
    split = k2 / (1.0 + k2)
    # Guard both logs' arguments so the unselected branch never produces nan.
    lo = kappa * jnp.log(jnp.maximum((1.0 + k2) / k2 * q, 1e-38))
    hi = -(1.0 / kappa) * jnp.log(jnp.maximum((1.0 + k2) * (1.0 - q), 1e-38))
    return jnp.where(q < split, lo, hi)


def asymmetric_laplace(key, loc, scale, kappa, shape=None, dtype=jnp.float32):
    """Draw loc + scale * AL(kappa) via inverse-CDF of a uniform."""
    if shape is None:
        shape = jnp.broadcast_shapes(
            jnp.shape(loc), jnp.shape(scale), jnp.shape(kappa)
        )
    u = jax.random.uniform(
        key, shape, dtype=dtype, minval=jnp.finfo(dtype).tiny, maxval=1.0
    )
    return loc + scale * asymmetric_laplace_ppf(u, kappa)


# --------------------------------------------------------------------------
# Student-t (location-scale)
# --------------------------------------------------------------------------


def student_t(key, loc, scale, df, shape=None, dtype=jnp.float32):
    """loc + scale * t(df)."""
    if shape is None:
        shape = jnp.broadcast_shapes(jnp.shape(loc), jnp.shape(scale), jnp.shape(df))
    return loc + scale * jax.random.t(key, df, shape, dtype=dtype)


# --------------------------------------------------------------------------
# Truncated power law (cloud horizontal sizes, Wood & Field 2011)
# --------------------------------------------------------------------------

CLOUD_LENGTH_BETA = 1.66
CLOUD_LENGTH_XMIN_M = 0.1e3
CLOUD_LENGTH_XMAX_M = 1e6


def truncated_powerlaw_from_u(u, xmin, xmax, beta):
    """Inverse CDF of P(x) ~ x**(-beta) on [xmin, xmax] applied to
    uniforms ``u`` — the transform the reference applies for cloud lengths
    (cloud_cover_binary.py:25-40): with a = xmax^(1-beta),
    d = xmin^(1-beta) - a, x = (a + d*U)^(1/(1-beta)).

    Exposed separately so hot scans can consume *pre-generated* uniform
    arrays (batched counter-based RNG outside the scan) instead of hashing
    keys inside the sequential body (models/clearsky_index.py).
    """
    one_m_beta = 1.0 - beta
    a = xmax**one_m_beta
    d = xmin**one_m_beta - a
    return (a + d * u) ** (1.0 / one_m_beta)


def truncated_powerlaw(key, xmin, xmax, beta, shape=(), dtype=jnp.float32):
    """Keyed sampling via :func:`truncated_powerlaw_from_u`."""
    u = jax.random.uniform(key, shape, dtype=dtype)
    return truncated_powerlaw_from_u(u, xmin, xmax, beta)


def cloud_length_seconds_from_u(u, windspeed, xmax_m=CLOUD_LENGTH_XMAX_M):
    """Cloud transit time [s] from a pre-drawn uniform: power-law length [m]
    / windspeed [m/s].

    ``xmax_m`` may be an array — the TPU renewal kernel truncates the length
    distribution instead of rejection-sampling (see models/renewal.py); the
    clamp keeps the truncation bound above the distribution's support floor.
    """
    xmax_m = jnp.maximum(xmax_m, 2.0 * CLOUD_LENGTH_XMIN_M)
    return truncated_powerlaw_from_u(
        u, CLOUD_LENGTH_XMIN_M, xmax_m, CLOUD_LENGTH_BETA
    ) / windspeed


def cloud_length_seconds(key, windspeed, xmax_m=CLOUD_LENGTH_XMAX_M, shape=None,
                         dtype=jnp.float32):
    """Keyed wrapper over :func:`cloud_length_seconds_from_u`."""
    if shape is None:
        shape = jnp.broadcast_shapes(jnp.shape(windspeed), jnp.shape(xmax_m))
    u = jax.random.uniform(key, shape, dtype=dtype)
    return cloud_length_seconds_from_u(u, windspeed, xmax_m)


# --------------------------------------------------------------------------
# Windspeed (Mathiesen et al. 2013)
# --------------------------------------------------------------------------

WINDSPEED_SHAPE = 2.69
WINDSPEED_SCALE = 2.14


def windspeed(key, shape=(), dtype=jnp.float32):
    """Gamma(2.69, scale=2.14) windspeed [m/s] (cloud_cover_binary.py:5-23)."""
    return WINDSPEED_SCALE * jax.random.gamma(key, WINDSPEED_SHAPE, shape, dtype)


def gamma(key, a, scale, shape=None, dtype=jnp.float32):
    """Gamma with shape a and scale (clearskyindexmodel.py:80-82 draws)."""
    if shape is None:
        shape = jnp.broadcast_shapes(jnp.shape(a), jnp.shape(scale))
    return scale * jax.random.gamma(key, a, shape, dtype)


def normal(key, loc, scale, shape=None, dtype=jnp.float32):
    if shape is None:
        shape = jnp.broadcast_shapes(jnp.shape(loc), jnp.shape(scale))
    return loc + scale * jax.random.normal(key, shape, dtype)
