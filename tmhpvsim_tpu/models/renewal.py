"""Per-second binary cloud cover: alternating cloud/clear renewal process.

The reference (cloud_cover_binary.py:42-117) emits, each second, 1 ("sky
covered by a cloud") or 0 ("clear"), alternating cloud intervals (power-law
transit times, Wood & Field 2011) and clear intervals sized so the running
cloud fraction tracks the hourly cloud cover.  Its bookkeeping keeps growing
cumulative-length arrays (``sigma_cloud``/``sigma_clear``) and rejection-
samples up to 20 candidate cloud lengths against them (``next_cloud``,
cloud_cover_binary.py:80-107) — variable-length state and data-dependent trip
counts, the single hardest reference component to express in fixed-shape XLA
(SURVEY.md §7 hard part (a)).

TPU-first reformulation (``init``/``step`` below): the *constraints* the
reference machinery enforces are

  (1) cloud transit times follow the truncated power law;
  (2) each cloud+clear cycle has cloud fraction == the (capped) hourly cloud
      cover, i.e. clear = cloud * (1/cc - 1) — this is exactly how
      ``sigma_clear`` is defined (cloud_cover_binary.py:78,84);
  (3) a full cycle never exceeds 90 minutes (the ``tot_length < 90*60``
      rejection test at cloud_cover_binary.py:87).

Constraints (2)+(3) bound the cloud length at ``5400 * cc`` seconds, so
instead of rejection-sampling we draw directly from the power law *truncated
at that bound* — closed-form inverse CDF, zero rejection iterations, and the
whole renewal state collapses to three scalars ``(cloud_end, total_end,
sec)``.  One step is ~20 flops and fully branchless, which is what makes the
100k-chain per-second configs (BASELINE.json) feasible on the VPU.  The
distributional difference vs. the reference's candidate-selection heuristic
(which also biases cycles toward 1 h total via its argmin at
cloud_cover_binary.py:100) is covered by distribution tests against the
faithful implementation below.

``ReferenceRenewal`` is a stateful float64 implementation of the reference's
exact algorithm (arrays, rejection loop, argmin selection) used by the
asyncio/CPU backend and as the statistical ground truth in tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tmhpvsim_tpu.models import distributions as dist

MAX_CYCLE_S = 90 * 60
TARGET_CYCLE_S = 60 * 60
MAX_CLOUDCOVER = 0.95


# ---------------------------------------------------------------------------
# TPU kernel: O(1) carry, branchless
# ---------------------------------------------------------------------------


def cycle_from_u(u, cloudcover, windspeed):
    """One (cloud_length, total_length) cycle from a pre-drawn uniform.

    Cloud transit time from the power law truncated so that the full cycle
    cloud/cc stays under MAX_CYCLE_S; clear interval from the exact cloud-
    fraction constraint.  Taking ``u`` (not a key) lets the per-second scan
    consume batch-generated uniforms — no RNG hashing in the sequential
    body (models/clearsky_index.py csi_scan_block).  Depends only on the
    step's inputs, never the carry, so callers batch it over a whole block
    and keep the power-law transcendentals out of the sequential scan
    (see ``step_from_cycle``).
    """
    cc = jnp.clip(cloudcover, 1e-3, MAX_CLOUDCOVER)
    cap_m = MAX_CYCLE_S * cc * windspeed  # length cap in metres
    cloud = dist.cloud_length_seconds_from_u(u, windspeed, xmax_m=cap_m)
    total = cloud / cc
    return cloud, total


def _draw_cycle(key, cloudcover, windspeed, dtype):
    """Keyed wrapper over :func:`cycle_from_u`."""
    u = jax.random.uniform(key, jnp.shape(cloudcover), dtype=dtype)
    return cycle_from_u(u, cloudcover, windspeed)


def init(key, cloudcover, windspeed, dtype=jnp.float32):
    """Initial carry, phase randomised inside the first cycle
    (cloud_cover_binary.py:67-68)."""
    k_cycle, k_phase = jax.random.split(key)
    cloud, total = _draw_cycle(k_cycle, cloudcover, windspeed, dtype)
    sec = total * jax.random.uniform(k_phase, jnp.shape(cloud), dtype=dtype)
    return {"cloud_end": cloud, "total_end": total, "sec": sec}


def step_from_cycle(carry, cloud_new, total_new, dtype=jnp.float32):
    """Advance one second given this step's pre-computed candidate cycle
    (consumed only on redraw); returns (carry, covered), covered in {0., 1.}.

    The candidate (``cycle_from_u``) is carry-independent, so the hot scan
    batches it over the whole block and this body is pure compare/select —
    no transcendentals on the sequential path, which on TPU roughly doubles
    per-second throughput (the pow/exp per step used to dominate)."""
    sec = carry["sec"] + 1.0
    redraw = sec >= carry["total_end"]

    cloud_end = jnp.where(redraw, cloud_new, carry["cloud_end"])
    total_end = jnp.where(redraw, total_new, carry["total_end"])
    sec = jnp.where(redraw, jnp.ones_like(sec), sec)

    covered = (sec < cloud_end).astype(dtype)
    return {"cloud_end": cloud_end, "total_end": total_end, "sec": sec}, covered


def step_from_u(carry, u, cloudcover, windspeed, dtype=jnp.float32):
    """Advance one second; returns (carry, covered) with covered in {0., 1.}.

    ``u`` is this step's pre-drawn uniform (consumed only on cycle redraw);
    `cloudcover`/`windspeed` are the *current-second* interpolated values, so
    a redraw sees up-to-date parameters — the same effect as the reference
    calling update_parameters before every step (clearskyindexmodel.py:133-136).
    """
    cloud_new, total_new = cycle_from_u(u, cloudcover, windspeed)
    return step_from_cycle(carry, cloud_new, total_new, dtype)


def step(carry, key, cloudcover, windspeed, dtype=jnp.float32):
    """Keyed wrapper over :func:`step_from_u` (tests / ad-hoc use)."""
    u = jax.random.uniform(key, jnp.shape(cloudcover), dtype=dtype)
    return step_from_u(carry, u, cloudcover, windspeed, dtype)


# ---------------------------------------------------------------------------
# Faithful reference algorithm (numpy, stateful) — CPU backend & ground truth
# ---------------------------------------------------------------------------


class ReferenceRenewal:
    """The reference's exact renewal algorithm (cloud_cover_binary.py:42-117).

    Written from the algorithm description, float64 numpy, for the asyncio
    backend and for statistical ground-truthing of the TPU kernel:

    * cumulative candidate arrays: growing each cycle by prepending the new
      cloud/clear interval, keeping entries up to the selected candidate;
    * candidate selection: among <=20 power-law draws, the first that admits
      a positive clear interval and a cycle under 90 min, choosing the
      candidate index whose implied total is closest to 1 h;
    * on 20 rejections: reset the arrays from the hourly-mean template and
      retry once; if still infeasible (which for the reference is fatal —
      its assert at cloud_cover_binary.py:91 — and is *guaranteed* for
      cc ≲ 0.06), fall back to the unconstrained cloud-fraction renewal.
    """

    def __init__(self, cloudcover, windspeed, rng=None):
        self.rng = rng if rng is not None else np.random.default_rng()
        self.update_parameters(cloudcover, windspeed)
        self._reset_sigma()
        self._next_cloud()
        self.sec = int((self.cloud_length + self.clear_length) * self.rng.random())

    def update_parameters(self, cloudcover, windspeed=None):
        # The lower guard is a deliberate deviation: the reference crashes for
        # cc < 1/12 (reset_sigma builds *empty* arrays, every candidate is
        # rejected, and the recursion guard fires) and divides by zero at
        # cc == 0.  Unreachable with its accidental i.i.d. near-overcast
        # hourly sampler, but reachable with the documented persistent chain.
        self.cloudcover = min(max(float(cloudcover), 1e-3), MAX_CLOUDCOVER)
        if windspeed is not None:
            self.windspeed = float(windspeed)

    def _reset_sigma(self):
        n = max(int(self.cloudcover * 12), 1)
        self.sigma_cloud = 5 * 60 * np.arange(1, n + 1, dtype=np.float64)
        self.sigma_clear = (1 / self.cloudcover - 1) * self.sigma_cloud

    def _draw_cloud_seconds(self):
        beta = dist.CLOUD_LENGTH_BETA
        a = dist.CLOUD_LENGTH_XMAX_M ** (1 - beta)
        d = dist.CLOUD_LENGTH_XMIN_M ** (1 - beta) - a
        return (a + d * self.rng.random()) ** (1 / (1 - beta)) / self.windspeed

    def _next_cloud(self, retried=False):
        for _ in range(20):
            cloud = self._draw_cloud_seconds()
            cand_cloud = cloud + self.sigma_cloud
            cand_clear = (1 / self.cloudcover - 1) * cand_cloud
            total = cand_cloud + cand_clear
            ok = (cand_clear - self.sigma_clear > 0) & (total < MAX_CYCLE_S)
            if ok.any():
                break
        else:
            if retried:
                # Infeasible constraint set: for cc ≲ 0.06 every candidate
                # cycle exceeds 90 min (total >= 300s/cc), so the reference
                # algorithm can never succeed (it would hit its assert).
                # Fall back to the unconstrained renewal: keep the exact
                # cloud-fraction constraint, drop the cycle cap.
                cloud = self._draw_cloud_seconds()
                self.cloud_length = cloud
                self.clear_length = cloud * (1 / self.cloudcover - 1)
                self._reset_sigma()
                self.sec = 0
                return self.cloud_length, self.clear_length
            self._reset_sigma()
            return self._next_cloud(retried=True)

        idx = np.nonzero(ok)[0]
        pick = idx[np.abs(total[idx] - TARGET_CYCLE_S).argmin()]
        self.cloud_length = cloud
        self.clear_length = cand_clear[pick] - self.sigma_clear[pick]
        self.sigma_cloud = np.concatenate(([cloud], cand_cloud[: pick + 1]))
        self.sigma_clear = np.concatenate(([self.clear_length], cand_clear[: pick + 1]))
        self.sec = 0

    def __next__(self):
        self.sec += 1
        if self.sec < self.cloud_length:
            return 1
        if self.sec < self.cloud_length + self.clear_length:
            return 0
        self._next_cloud()
        return next(self)
