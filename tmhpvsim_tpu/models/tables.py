"""Tabulated / minimax transcendental kernels for the solar→pv chain.

BENCH_r05's roofline section attributes the raw-speed gap to the
transcendental-heavy irradiance chain (sin/cos/arccos/exp/log per
chain-second at ~390 flops/site-s, 1.4 GFLOP/s achieved).  This module
provides two interchangeable kernel sets behind the ``kernel_impl``
plan axis:

* :func:`exact_kernels` — every attribute is *literally* the ``xp``
  libm-equivalent op (``xp.sin`` is ``jnp.sin`` itself, not a wrapper),
  so model code written against a :class:`KernelSet` traces to the
  byte-identical jaxpr/HLO it produced before the axis existed.  This
  is the default and the correctness reference.
* :func:`table_kernels` — low-degree minimax polynomials (Cody–Waite
  argument reduction, cephes-derived coefficients) plus a genuine
  366-entry day-of-year lookup table for the Spencer extraterrestrial-
  radiation series.  All internal arithmetic is float32 regardless of
  the input dtype (bf16 inputs are up-cast on entry), which both bounds
  the error and keeps the bit-twiddling (``2**k`` by exponent-field
  construction) well-defined.

Published error bounds
----------------------

``MAX_ULP`` maps kernel name → the maximum error of the table kernel
measured against a NumPy float64 reference, in float32 ULPs under the
metric::

    err_ulp = |table - ref64| / max(spacing32(|ref64|), spacing32(1.0))

i.e. ULPs at the reference value with a floor of one ULP-at-1.0 so the
bound stays meaningful at the zeros of sin/log/…  The bounds hold over
the argument ranges the simulation actually exercises, published in
``ARG_RANGES`` and enforced by ``tests/test_precision.py``.
``spencer_factor`` additionally quantises its argument to the nearest
integral day-of-year (that is the point of the table); the bound is
stated at integral ``doy``, which is what the engine passes.

The end-to-end contract (BASELINE): a full ``kernel_impl='table'`` run
must match the exact-kernel reduce stats to 1e-5 relative, and the
PR-3 drift sentinel vs the f64 golden mirror must stay green — the
autotuner only selects ``table`` when the sentinel passes on the probe.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional

import numpy as np

try:  # pragma: no cover - exercised indirectly everywhere
    import jax
    import jax.numpy as jnp
except Exception:  # pragma: no cover - CPU-only envs without jax
    jax = None
    jnp = None

__all__ = [
    "KernelSet",
    "exact_kernels",
    "table_kernels",
    "MAX_ULP",
    "ARG_RANGES",
    "SPENCER_LUT",
]

#: published max error (float32 ULPs at the f64 reference, floored at
#: one ULP of 1.0 — see module docstring) of each table kernel.
MAX_ULP = {
    "sin": 4,
    "cos": 4,
    "tan": 64,
    "arcsin": 24,
    "arccos": 24,
    "arctan2": 8,
    "exp": 4,
    "log": 4,
    "powc": 64,
    "spencer_factor": 4,
}

#: argument ranges over which the ``MAX_ULP`` bounds are published —
#: the ranges the solar/pv chain actually produces.
ARG_RANGES = {
    "sin": (-400.0, 400.0),      # mean anomaly/longitude ~0.017*day2000
    "cos": (-400.0, 400.0),
    "tan": (-1.5, 1.5),          # apparent-elevation refraction arg
    "arcsin": (-1.0, 1.0),
    "arccos": (-1.0, 1.0),
    "arctan2": None,             # all quadrants, |x|,|y| <= 1e3
    "exp": (-87.0, 40.0),        # disc_dni clamps at 40; underflow below
    "log": (1e-6, 1e4),          # sapm_dc effective irradiance ratios
    "powc": (0.5, 100.0),        # airmass bases, exponents in [-1.7, 0)
    "spencer_factor": (1.0, 366.0),
}

_F32 = np.float32


def _spencer_factor64(doy: np.ndarray) -> np.ndarray:
    """Float64 Spencer (1971) Fourier series for Rav^2 — LUT source."""
    b = 2.0 * np.pi * (np.asarray(doy, np.float64) - 1.0) / 365.0
    return (1.00011 + 0.034221 * np.cos(b) + 0.00128 * np.sin(b)
            + 0.000719 * np.cos(2.0 * b) + 0.000077 * np.sin(2.0 * b))


#: 366-entry day-of-year lookup table for the Spencer factor, built in
#: float64 and rounded once to float32.  ~1.5 KiB: HBM-resident, served
#: by a single gather instead of four transcendentals per element.
SPENCER_LUT = _spencer_factor64(np.arange(1, 367)).astype(_F32)


@dataclasses.dataclass(frozen=True)
class KernelSet:
    """Bundle of the transcendental ops the solar/pv models consume.

    ``exact_kernels(xp)`` binds every field to the raw ``xp`` op, so
    models calling ``k.sin`` trace identically to calling ``xp.sin``.
    ``powc(x, p)`` is pow-with-constant-exponent (the airmass laws);
    ``spencer_factor`` is ``None`` for exact sets (the model computes
    the Fourier series inline) and the LUT gather for table sets.
    """

    name: str
    sin: Callable[..., Any]
    cos: Callable[..., Any]
    tan: Callable[..., Any]
    arcsin: Callable[..., Any]
    arccos: Callable[..., Any]
    arctan2: Callable[..., Any]
    exp: Callable[..., Any]
    log: Callable[..., Any]
    powc: Callable[..., Any]
    spencer_factor: Optional[Callable[..., Any]] = None


def _pow_const(x, p):
    return x ** p


_EXACT_CACHE: dict = {}


def exact_kernels(xp) -> KernelSet:
    """The libm-equivalent kernel set: every field IS the ``xp`` op."""
    key = id(xp)
    ks = _EXACT_CACHE.get(key)
    if ks is None:
        ks = KernelSet(
            name="exact",
            sin=xp.sin, cos=xp.cos, tan=xp.tan,
            arcsin=xp.arcsin, arccos=xp.arccos, arctan2=xp.arctan2,
            exp=xp.exp, log=xp.log, powc=_pow_const,
            spencer_factor=None,
        )
        _EXACT_CACHE[key] = ks
    return ks


# ---------------------------------------------------------------------------
# table/minimax implementations (always compute in float32)
# ---------------------------------------------------------------------------

_LOG2E = _F32(1.44269504088896341)
# Cody–Waite split of ln(2): hi exact in a handful of bits, lo the rest.
_LN2_HI = _F32(0.693359375)
_LN2_LO = _F32(-2.12194440e-4)
# Cody–Waite split of pi/2 for sin/cos quadrant reduction (cephes DP1..3
# scaled from pi/4 to pi/2): valid to |x| ~ 1e4 at ~1e-7 abs error.
_PI2_HI = _F32(1.5703125)
_PI2_MID = _F32(4.837512969970703125e-4)
_PI2_LO = _F32(7.549789948768648e-8)

_HALF_PI = _F32(math.pi / 2.0)
_PI = _F32(math.pi)
_QUARTER_PI = _F32(math.pi / 4.0)
# tan(pi/8): atan range-reduction breakpoint.
_TAN_PI8 = _F32(0.4142135623730951)


def _f32(xp, x):
    return xp.asarray(x).astype(_F32)


def _exp2i(xp, k):
    """2**k for integer-valued f32 ``k`` in [-126, 127] by constructing
    the float32 exponent field — no transcendental involved."""
    ki = k.astype(np.int32)
    bits = (ki + np.int32(127)) << np.int32(23)
    if jnp is not None and xp is jnp:
        return jax.lax.bitcast_convert_type(bits, jnp.float32)
    return np.asarray(bits, np.int32).view(np.float32)


def _fast_exp(xp, x):
    """Minimax expf: |rel err| ~ 2e-7 on the clamped domain."""
    x = xp.clip(_f32(xp, x), _F32(-87.0), _F32(88.0))
    kf = xp.round(x * _LOG2E)
    r = (x - kf * _LN2_HI) - kf * _LN2_LO
    # cephes expf polynomial for e^r on |r| <= 0.5*ln2
    p = _F32(1.9875691500e-4)
    p = p * r + _F32(1.3981999507e-3)
    p = p * r + _F32(8.3334519073e-3)
    p = p * r + _F32(4.1665795894e-2)
    p = p * r + _F32(1.6666665459e-1)
    p = p * r + _F32(5.0000001201e-1)
    p = p * r * r + r + _F32(1.0)
    return p * _exp2i(xp, kf)


def _fast_log(xp, x):
    """Minimax logf via frexp + atanh-style series; |err| ~ 1 ulp@1."""
    x = _f32(xp, x)
    m, e = xp.frexp(x)  # x = m * 2**e, m in [0.5, 1)
    # renormalise m to [sqrt(1/2), sqrt(2)) so log(m) is small
    lo = m < _F32(0.7071067811865476)
    m = xp.where(lo, m + m, m)
    e = xp.where(lo, e - 1, e).astype(_F32)
    f = m - _F32(1.0)
    s = f / (_F32(2.0) + f)
    z = s * s
    # atanh series: log(m) = 2s * (1 + z/3 + z^2/5 + z^3/7 + z^4/9)
    w = _F32(0.14798198280)
    w = w * z + _F32(0.15313838550)
    w = w * z + _F32(0.20000714765)
    w = w * z + _F32(0.33333331174)
    t = s * (_F32(2.0) + _F32(2.0) * z * w)
    return t + e * _LN2_HI + e * _LN2_LO


def _sin_poly(r):
    """cephes sinf core on |r| <= pi/4."""
    z = r * r
    w = _F32(-1.9515295891e-4)
    w = w * z + _F32(8.3321608736e-3)
    w = w * z + _F32(-1.6666654611e-1)
    return w * z * r + r


def _cos_poly(r):
    """cephes cosf core on |r| <= pi/4."""
    z = r * r
    w = _F32(2.443315711809948e-5)
    w = w * z + _F32(-1.388731625493765e-3)
    w = w * z + _F32(4.166664568298827e-2)
    return w * z * z - _F32(0.5) * z + _F32(1.0)


def _reduce_quadrant(xp, x):
    x = _f32(xp, x)
    nf = xp.round(x * _F32(2.0 / math.pi))
    r = ((x - nf * _PI2_HI) - nf * _PI2_MID) - nf * _PI2_LO
    q = nf.astype(np.int32) & np.int32(3)
    return r, q


def _fast_sin(xp, x):
    r, q = _reduce_quadrant(xp, x)
    sp, cp = _sin_poly(r), _cos_poly(r)
    v = xp.where((q & 1) == 0, sp, cp)
    return xp.where(q >= 2, -v, v)


def _fast_cos(xp, x):
    r, q = _reduce_quadrant(xp, x)
    sp, cp = _sin_poly(r), _cos_poly(r)
    v = xp.where((q & 1) == 0, cp, sp)
    neg = ((q + 1) & np.int32(3)) >= 2
    return xp.where(neg, -v, v)


def _fast_tan(xp, x):
    r, q = _reduce_quadrant(xp, x)
    sp, cp = _sin_poly(r), _cos_poly(r)
    even = (q & 1) == 0
    num = xp.where(even, sp, cp)
    den = xp.where(even, cp, -sp)
    return num / den


def _fast_arccos(xp, x):
    """Hastings-style arccos: sqrt(1-|x|) * P(|x|), mirrored for x<0.

    |abs err| <= ~2e-8 from the polynomial; f32 rounding dominates.
    """
    x = xp.clip(_f32(xp, x), _F32(-1.0), _F32(1.0))
    a = xp.abs(x)
    p = _F32(-0.0012624911)
    p = p * a + _F32(0.0066700901)
    p = p * a + _F32(-0.0170881256)
    p = p * a + _F32(0.0308918810)
    p = p * a + _F32(-0.0501743046)
    p = p * a + _F32(0.0889789874)
    p = p * a + _F32(-0.2145988016)
    p = p * a + _F32(1.5707963050)
    v = xp.sqrt(_F32(1.0) - a) * p
    return xp.where(x < _F32(0.0), _PI - v, v)


def _fast_arcsin(xp, x):
    return _HALF_PI - _fast_arccos(xp, x)


def _atan_poly(u):
    """cephes atanf core on |u| <= tan(pi/8)."""
    z = u * u
    w = _F32(8.05374449538e-2)
    w = w * z + _F32(-1.38776856032e-1)
    w = w * z + _F32(1.99777106478e-1)
    w = w * z + _F32(-3.33329491539e-1)
    return w * z * u + u


def _fast_arctan2(xp, y, x):
    y = _f32(xp, y)
    x = _f32(xp, x)
    ax, ay = xp.abs(x), xp.abs(y)
    mx = xp.maximum(ax, ay)
    mn = xp.minimum(ax, ay)
    t = mn / xp.maximum(mx, _F32(1e-30))
    # second reduction: t in [0,1] -> u in [-tan(pi/8), tan(pi/8)]
    big = t > _TAN_PI8
    u = xp.where(big, (t - _F32(1.0)) / (t + _F32(1.0)), t)
    a = _atan_poly(u)
    a = xp.where(big, a + _QUARTER_PI, a)
    a = xp.where(ay > ax, _HALF_PI - a, a)
    a = xp.where(x < _F32(0.0), _PI - a, a)
    a = xp.where(y < _F32(0.0), -a, a)
    # atan2(0, 0) -> 0 like libm
    return xp.where(mx == _F32(0.0), _F32(0.0) * a, a)


def _fast_powc(xp, x, p):
    """x**p for positive x and constant real p: exp(p * log(x))."""
    return _fast_exp(xp, _F32(p) * _fast_log(xp, x))


def _make_spencer_factor(xp):
    lut = xp.asarray(SPENCER_LUT)

    def spencer_factor(doy):
        idx = xp.clip(_f32(xp, doy).astype(np.int32) - 1, 0, 365)
        if jnp is not None and xp is jnp:
            return jnp.take(lut, idx)
        return lut[idx]

    return spencer_factor


_TABLE_CACHE: dict = {}


def table_kernels(xp) -> KernelSet:
    """The minimax/LUT kernel set.  Computes internally in float32 and
    returns float32 whatever the input dtype (bf16 inputs up-cast)."""
    key = id(xp)
    ks = _TABLE_CACHE.get(key)
    if ks is None:
        import functools
        bind = lambda f: functools.partial(f, xp)  # noqa: E731
        ks = KernelSet(
            name="table",
            sin=bind(_fast_sin), cos=bind(_fast_cos), tan=bind(_fast_tan),
            arcsin=bind(_fast_arcsin), arccos=bind(_fast_arccos),
            arctan2=bind(_fast_arctan2),
            exp=bind(_fast_exp), log=bind(_fast_log), powc=bind(_fast_powc),
            spencer_factor=_make_spencer_factor(xp),
        )
        _TABLE_CACHE[key] = ks
    return ks


def get_kernels(impl: str, xp) -> KernelSet:
    """Resolve a ``kernel_impl`` plan value to a :class:`KernelSet`."""
    if impl == "table":
        return table_kernels(xp)
    if impl == "exact":
        return exact_kernels(xp)
    raise ValueError(f"unknown kernel_impl: {impl!r}")
