"""Stochastic weather models and PV physics, as pure JAX + host-side grids."""
