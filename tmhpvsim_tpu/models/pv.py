"""PV electrical chain: POA irradiance -> cell temperature -> DC -> AC.

Re-derivation of the reference's pvlib call sequence (pvmodel.py:69-80) from
the primary models, as flat array math:

* SAPM cell temperature (King et al. 2004 eq. 11-12), the
  ``sapm_celltemp`` default mount, evaluated at the reference's fixed
  ambient conditions wind = 0 m/s, T_amb = 20 C (pvmodel.py:69-70);
* SAPM effective irradiance (King et al. 2004 eq. 7, in "suns");
* SAPM I-V points Imp/Vmp -> DC power (King et al. 2004 eq. 2-5);
* Sandia grid-inverter model (King et al. 2007) for AC power;
* final ``clip(lower=0).fillna(0)`` exactly as the reference's cache fill
  (pvmodel.py:80) — night tare and NaN become 0 W.

Functions take ``xp`` (numpy | jax.numpy) like models/solar.py, and read
coefficients from plain dicts (data/parameters.py vendored tables), so they
jit cleanly with coefficients baked in as constants.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from tmhpvsim_tpu.models import tables as _tables

DEG = np.pi / 180.0
BOLTZMANN = 1.380649e-23  # J/K
ELEM_CHARGE = 1.602176634e-19  # C
T0_C = 25.0  # SAPM reference cell temperature


def sapm_cell_temp(poa_global, module, wind_speed=0.0, temp_air_c=20.0,
                   xp=jnp, kernels=None):
    """SAPM back-of-module + cell temperature [C].

        T_mod  = POA * exp(a + b*wind) + T_amb
        T_cell = T_mod + POA/1000 * deltaT
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    t_mod = poa_global * k.exp(module["T_a"] + module["T_b"] * wind_speed) \
        + temp_air_c
    return t_mod + poa_global / 1000.0 * module["T_deltaT"]


def sapm_effective_irradiance(poa_direct, poa_diffuse, airmass_abs, cos_aoi,
                              module, xp=jnp, kernels=None):
    """SAPM effective irradiance in suns (reference irradiance 1000 W/m^2).

        F1(AMa) = A0 + A1*AMa + ... + A4*AMa^4     (spectral modifier)
        F2(AOI) = B0 + B1*AOI + ... + B5*AOI^5     (AOI in degrees)
        Ee = F1 * (Eb * F2 + FD * Ed) / 1000
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    ama = airmass_abs
    f1 = (
        module["A0"]
        + module["A1"] * ama
        + module["A2"] * ama**2
        + module["A3"] * ama**3
        + module["A4"] * ama**4
    )
    aoi_deg = k.arccos(xp.clip(cos_aoi, -1.0, 1.0)) / DEG
    f2 = (
        module["B0"]
        + module["B1"] * aoi_deg
        + module["B2"] * aoi_deg**2
        + module["B3"] * aoi_deg**3
        + module["B4"] * aoi_deg**4
        + module["B5"] * aoi_deg**5
    )
    f2 = xp.maximum(f2, 0.0)
    ee = f1 * (poa_direct * f2 + module["FD"] * poa_diffuse) / 1000.0
    return xp.maximum(ee, 0.0)


def sapm_dc(effective_irradiance, temp_cell_c, module, xp=jnp, kernels=None):
    """SAPM max-power point: returns dict(i_mp, v_mp, p_mp).

    King et al. 2004 eq. 3-5 with the thermal-voltage log terms; Ee in suns.
    Zero-irradiance steps produce v_mp = i_mp = 0 (the log is masked, not
    NaN'd — reference reaches the same end state via fillna(0) at
    pvmodel.py:80).
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    ee = effective_irradiance
    dt = temp_cell_c - T0_C
    ns = module["Cells_in_Series"]

    # Thermal voltage per cell times diode factor.
    delta = module["N"] * BOLTZMANN * (temp_cell_c + 273.15) / ELEM_CHARGE

    pos = ee > 0.0
    log_ee = k.log(xp.where(pos, ee, 1.0))

    i_mp = (
        module["Impo"]
        * (module["C0"] * ee + module["C1"] * ee**2)
        * (1.0 + module["Aimp"] * dt)
    )
    bvmp = module["Bvmpo"] + module["Mbvmp"] * (1.0 - ee)
    v_mp = (
        module["Vmpo"]
        + module["C2"] * ns * delta * log_ee
        + module["C3"] * ns * (delta * log_ee) ** 2
        + bvmp * dt
    )
    i_mp = xp.where(pos, xp.maximum(i_mp, 0.0), 0.0)
    v_mp = xp.where(pos, xp.maximum(v_mp, 0.0), 0.0)
    return {"i_mp": i_mp, "v_mp": v_mp, "p_mp": i_mp * v_mp}


def sandia_inverter_ac(v_dc, p_dc, inverter, xp=jnp):
    """Sandia grid-connected inverter model: AC power [W].

    King et al. 2007 performance-model quadratic with voltage-dependent
    coefficients; output saturates at Paco, and below the start-up power the
    inverter draws the night tare (-Pnt), matching the reference's
    ``snlinverter`` call at pvmodel.py:78.
    """
    paco = inverter["Paco"]
    dv = v_dc - inverter["Vdco"]
    a = inverter["Pdco"] * (1.0 + inverter["C1"] * dv)
    b = inverter["Pso"] * (1.0 + inverter["C2"] * dv)
    c = inverter["C0"] * (1.0 + inverter["C3"] * dv)

    a_b = xp.where(xp.abs(a - b) > 1e-12, a - b, 1e-12)
    pd = p_dc - b
    ac = (paco / a_b - c * a_b) * pd + c * pd * pd
    ac = xp.minimum(ac, paco)
    return xp.where(p_dc < inverter["Pso"], -xp.abs(inverter["Pnt"]), ac)


def power_from_csi(csi, geom, module, inverter, xp=jnp, kernels=None,
                   scope=None):
    """Clear-sky index -> AC watts, given precomputed block geometry.

    The chain-dependent half of the reference's ``populate_cache``
    (pvmodel.py:52-80): every input except ``csi`` comes from
    ``solar.block_geometry`` and is shared across chains; ``csi`` may carry
    leading batch dimensions, all geometry arrays broadcast against it.

    Steps: zenith-cap clip of csi -> GHI = csi*GHI_clear -> DISC DNI ->
    DHI closure -> Hay-Davies POA -> SAPM temp/Ee/DC -> Sandia AC ->
    clip(>=0) & NaN->0.

    ``kernels`` selects the transcendental implementation for the whole
    chain (models/tables.py); ``None`` traces the raw ``xp`` ops.
    ``scope``: optional phase-scope factory (the engine's gated
    ``_phase``, obs/attribution.py) — traces the whole irradiance→power
    chain inside the ``physics`` phase; None changes nothing.
    """
    from tmhpvsim_tpu.models import solar

    ctx = scope("physics") if scope is not None else \
        contextlib.nullcontext()
    with ctx:
        csi = xp.minimum(csi, geom["csi_cap"])
        ghi = csi * geom["ghi_clear"]
        dni = solar.disc_dni(ghi, geom["zenith"], geom["doy"], xp=xp,
                             kernels=kernels)
        dhi = xp.maximum(ghi - dni * geom["cos_zenith"], 0.0)

        poa = solar.haydavies_poa(
            geom["surface_tilt"], geom["cos_aoi"], geom["apparent_zenith"],
            ghi, dni, dhi, geom["dni_extra"], albedo=geom["albedo"], xp=xp,
            kernels=kernels,
        )
        t_cell = sapm_cell_temp(poa["poa_global"], module, xp=xp,
                                kernels=kernels)
        ee = sapm_effective_irradiance(
            poa["poa_direct"], poa["poa_diffuse"], geom["airmass_abs"],
            geom["cos_aoi"], module, xp=xp, kernels=kernels,
        )
        dc = sapm_dc(ee, t_cell, module, xp=xp, kernels=kernels)
        ac = sandia_inverter_ac(dc["v_mp"], dc["p_mp"], inverter, xp=xp)
        return xp.maximum(ac, 0.0)
