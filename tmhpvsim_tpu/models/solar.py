"""Solar geometry and irradiance models — array-generic, TPU-first.

The reference delegates this entire layer to pvlib 0.6.3 (pvmodel.py:50-68):
NREL-SPA solar position, Ineichen clear-sky GHI, DISC GHI->DNI decomposition,
and Hay-Davies plane-of-array transposition.  pvlib is pandas-heavy,
dict/DataFrame-shaped, and unusable inside ``jit``; this module re-derives the
same physics from the primary literature as flat array math:

* **Sun position** — the PSA algorithm (Blanco-Muriel et al. 2001, with the
  updated 2020 coefficient set, valid 2020-2050, mean error ~0.004 deg), a
  closed-form ~30-flop ephemeris, instead of NREL SPA (~1000 branchy lines;
  pointless precision for a stochastic simulation whose irradiance is
  dominated by sampled cloud noise).  Refraction-corrected apparent
  elevation uses the standard Bennett-style correction (as in NREL SPA
  sec. 3.12) with pressure from site altitude.
* **Airmass** — Kasten & Young 1989 relative airmass, pressure-corrected to
  absolute (the reference's default, via Location.get_airmass).
* **Extraterrestrial irradiance** — Spencer 1971 Fourier series.
* **Clear sky** — Ineichen & Perez 2002 with monthly Linke turbidity
  linearly interpolated over day-of-year (the reference interpolates
  pvlib's gridded monthly climatology the same way).
* **GHI->DNI** — Maxwell 1987 DISC with the Kasten 1966 airmass it was
  fitted against.
* **Transposition** — Hay & Davies 1980 sky diffuse + isotropic ground
  reflection (the reference's PVSystem.get_irradiance default, albedo 0.25).

Every function takes ``xp`` (numpy or jax.numpy): one set of formulas serves
both the jitted bfloat16/float32 TPU path and the float64 numpy golden path
the parity tests compare against (SURVEY.md §7 hard part (b)).

All angles in radians unless suffixed ``_deg``; irradiances in W/m^2.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from tmhpvsim_tpu.models import tables as _tables

TWO_PI = 2.0 * np.pi
DEG = np.pi / 180.0

#: Epoch seconds of the PSA reference instant 2000-01-01 12:00 UT.
_PSA_EPOCH0 = 946728000.0

#: Mean Earth radius / astronomical unit (PSA parallax correction).
_PARALLAX = 6371.01 / 149597.89 * 1e-3  # dimensionless, ~4.26e-5

SOLAR_CONSTANT = 1366.1     # W/m^2 (clear-sky & transposition extra radiation)
DISC_SOLAR_CONSTANT = 1370.0  # W/m^2 (Maxwell 1987 fit constant)

STD_PRESSURE = 101325.0     # Pa


def alt2pres(altitude_m):
    """ISA pressure at altitude [Pa] (standard lapse-rate barometric formula)."""
    return STD_PRESSURE * (1.0 - 2.25577e-5 * altitude_m) ** 5.25588


def sun_position(epoch_s, latitude_deg, longitude_deg, xp=jnp, kernels=None):
    """PSA+ sun position at UTC epoch seconds.

    ``epoch_s`` MUST be float64 (or int64): absolute epoch seconds (~1.7e9)
    quantize to ±64-128 s in float32 — about a degree of hour angle — so a
    float32 input is a silent correctness bug, rejected here.  The intended
    pattern is the engine's: evaluate geometry on the host in float64
    (it is chain-independent and O(block)) and ship float32 *results* to
    the device (engine/simulation.py host_inputs).

    Parameters are broadcastable arrays.  Returns a dict:
      ``zenith``      true topocentric zenith angle [rad] (no refraction;
                      apply :func:`apparent_elevation` separately)
      ``azimuth``     [rad], 0 = North, increasing eastward (pvlib
                      convention)
      ``cos_zenith``  cos of the true zenith

    Coefficients: Blanco et al. 2020 update of the PSA ephemeris.

    ``kernels`` selects the transcendental implementation (models/tables.py);
    ``None`` binds the raw ``xp`` ops — byte-identical traces to the
    pre-axis code.
    """
    dt_ = np.dtype(getattr(epoch_s, "dtype", np.float64))
    if dt_.kind == "f" and dt_.itemsize < 8:
        raise TypeError(
            "sun_position requires float64/int64 epoch seconds; float32 "
            "quantizes absolute epochs to >±64 s (see docstring)"
        )
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    lat = latitude_deg * DEG
    lon = longitude_deg * DEG

    # Elapsed days since 2000-01-01 12:00 UT (te), and UT decimal hour.
    te = (epoch_s - _PSA_EPOCH0) / 86400.0
    hour_ut = (epoch_s / 3600.0) % 24.0

    # Ecliptic coordinates.
    omega = 2.267127827e0 - 9.300339267e-4 * te
    mean_lon = 4.895036035e0 + 1.720279602e-2 * te
    mean_anom = 6.239468336e0 + 1.720200135e-2 * te
    ecl_lon = (
        mean_lon
        + 3.338320972e-2 * k.sin(mean_anom)
        + 3.497596876e-4 * k.sin(2.0 * mean_anom)
        - 1.544353226e-4
        - 8.689729360e-6 * k.sin(omega)
    )
    obliquity = (
        4.090904909e-1 - 6.213605399e-9 * te + 4.418094944e-5 * k.cos(omega)
    )

    # Celestial coordinates.
    sin_l = k.sin(ecl_lon)
    ra = k.arctan2(k.cos(obliquity) * sin_l, k.cos(ecl_lon)) % TWO_PI
    dec = k.arcsin(k.sin(obliquity) * sin_l)

    # Local hour angle from Greenwich mean sidereal time.
    gmst_h = 6.697096103e0 + 6.570984737e-2 * te + hour_ut
    lmst = gmst_h * 15.0 * DEG + lon
    ha = lmst - ra

    cos_lat, sin_lat = k.cos(lat), k.sin(lat)
    cos_dec, sin_dec = k.cos(dec), k.sin(dec)
    cos_ha = k.cos(ha)

    cos_zen = cos_lat * cos_ha * cos_dec + sin_dec * sin_lat
    cos_zen = xp.clip(cos_zen, -1.0, 1.0)
    zenith = k.arccos(cos_zen)
    azimuth = k.arctan2(
        -k.sin(ha), k.tan(dec) * cos_lat - sin_lat * cos_ha
    ) % TWO_PI

    # Parallax correction (sun observed from the surface, not the geocenter).
    zenith = zenith + _PARALLAX * k.sin(zenith)

    return {
        "zenith": zenith,
        "azimuth": azimuth,
        "cos_zenith": k.cos(zenith),
    }


def sun_position_split(day2000, sec_of_day, latitude_deg, longitude_deg,
                       xp=jnp, kernels=None):
    """PSA+ sun position from a float32-safe *split* time representation.

    ``day2000`` = whole days since 2000-01-01 00:00 UT (int or float,
    < 2^24 so exact in float32), ``sec_of_day`` = seconds within that UT
    day.  Each ephemeris term multiplies the coefficient by the day and
    fraction parts separately, so nothing ever forms the raw ~1.7e9 epoch:
    worst-case float32 error is ~0.01 deg of zenith — the device-side
    geometry path used for per-chain site grids, where host float64
    precompute per site would not scale (engine/simulation.py uses the
    float64 host path when all chains share one site).

    Same return dict as :func:`sun_position`.
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    lat = latitude_deg * DEG
    lon = longitude_deg * DEG

    frac = sec_of_day / 86400.0 - 0.5  # days relative to 12:00 UT
    hour_ut = sec_of_day / 3600.0

    def lin(const, coeff):
        # const + coeff*te with te = day2000 + frac, parts kept separate
        return (const + coeff * day2000) + coeff * frac

    omega = lin(2.267127827e0, -9.300339267e-4)
    mean_lon = lin(4.895036035e0, 1.720279602e-2)
    mean_anom = lin(6.239468336e0, 1.720200135e-2)
    ecl_lon = (
        mean_lon
        + 3.338320972e-2 * k.sin(mean_anom)
        + 3.497596876e-4 * k.sin(2.0 * mean_anom)
        - 1.544353226e-4
        - 8.689729360e-6 * k.sin(omega)
    )
    obliquity = lin(4.090904909e-1, -6.213605399e-9) \
        + 4.418094944e-5 * k.cos(omega)

    sin_l = k.sin(ecl_lon)
    ra = k.arctan2(k.cos(obliquity) * sin_l, k.cos(ecl_lon)) % TWO_PI
    dec = k.arcsin(k.sin(obliquity) * sin_l)

    # gmst hours: keep the large day product in its own mod-24 reduction
    gmst_h = (6.697096103e0 + 6.570984737e-2 * day2000) % 24.0 \
        + 6.570984737e-2 * frac + hour_ut
    lmst = gmst_h * 15.0 * DEG + lon
    ha = lmst - ra

    cos_lat, sin_lat = k.cos(lat), k.sin(lat)
    cos_dec, sin_dec = k.cos(dec), k.sin(dec)
    cos_ha = k.cos(ha)

    cos_zen = cos_lat * cos_ha * cos_dec + sin_dec * sin_lat
    cos_zen = xp.clip(cos_zen, -1.0, 1.0)
    zenith = k.arccos(cos_zen)
    azimuth = k.arctan2(
        -k.sin(ha), k.tan(dec) * cos_lat - sin_lat * cos_ha
    ) % TWO_PI
    zenith = zenith + _PARALLAX * k.sin(zenith)
    return {
        "zenith": zenith,
        "azimuth": azimuth,
        "cos_zenith": k.cos(zenith),
    }


def apparent_elevation(zenith, pressure=STD_PRESSURE, temperature_c=12.0,
                       xp=jnp, kernels=None):
    """Refraction-corrected elevation [rad] from true zenith.

    The NREL SPA atmospheric-refraction correction (Reda & Andreas 2004
    eq. 42), as pvlib applies with its default temperature 12 C and
    altitude-derived pressure: for elevation e [deg],

        de = (P/1010 mbar) * (283/(273+T)) * 1.02 / (60 * tan(e + 10.3/(e+5.11)))

    applied only while the top limb of the sun is above the horizon
    (e >= -0.26667 - 0.5667 deg); expressed branchlessly with ``where``.
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    e_deg = (np.pi / 2.0 - zenith) / DEG
    p_mbar = pressure / 100.0
    de = (
        (p_mbar / 1010.0)
        * (283.0 / (273.0 + temperature_c))
        * 1.02
        / (60.0 * k.tan((e_deg + 10.3 / (e_deg + 5.11)) * DEG))
    )
    de = xp.where(e_deg >= -(0.26667 + 0.5667), de, 0.0)
    return (e_deg + de) * DEG


def relative_airmass_kasten_young(apparent_zenith, xp=jnp, kernels=None):
    """Kasten & Young 1989 relative airmass from apparent zenith [rad].

    pvlib returns NaN past 90 deg; here the zenith is clamped just below the
    pole of the formula instead — downstream use is always multiplied by a
    night mask, and NaNs are poison on TPU.
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    z_deg = xp.clip(apparent_zenith / DEG, 0.0, 90.0)
    return 1.0 / (
        k.cos(z_deg * DEG) + 0.50572 * k.powc(96.07995 - z_deg, -1.6364)
    )


def relative_airmass_kasten1966(zenith, xp=jnp, kernels=None):
    """Kasten 1966 relative airmass (the DISC model's fit airmass)."""
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    z_deg = xp.clip(zenith / DEG, 0.0, 93.0)
    return 1.0 / (k.cos(z_deg * DEG) + 0.15 * k.powc(93.885 - z_deg, -1.253))


def extra_radiation_spencer(doy, solar_constant=SOLAR_CONSTANT, xp=jnp,
                            kernels=None):
    """Spencer 1971 extraterrestrial normal irradiance for day-of-year.

    With table kernels the four transcendentals collapse to one gather
    from the 366-entry day-of-year LUT (models/tables.py SPENCER_LUT).
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    if k.spencer_factor is not None:
        return solar_constant * k.spencer_factor(doy)
    b = TWO_PI * (doy - 1.0) / 365.0
    factor = (
        1.00011
        + 0.034221 * k.cos(b)
        + 0.00128 * k.sin(b)
        + 0.000719 * k.cos(2.0 * b)
        + 7.7e-5 * k.sin(2.0 * b)
    )
    return solar_constant * factor


def linke_turbidity(doy, monthly, xp=jnp):
    """Day-of-year Linke turbidity from a 12-value monthly climatology.

    Monthly values are taken as mid-month anchors and linearly interpolated
    (the same scheme pvlib's ``lookup_linke_turbidity(interp_turbidity=True)``
    applies to its gridded climatology).  Wrap-around at the year boundary.
    """
    monthly = xp.asarray(monthly)
    # Mid-month day-of-year anchors for a 365-day year.
    mids = xp.asarray(
        [15.5, 45.0, 74.5, 105.0, 135.5, 166.0, 196.5, 227.5, 258.0, 288.5,
         319.0, 349.5]
    )
    ext_mids = xp.concatenate([mids[-1:] - 365.0, mids, mids[:1] + 365.0])
    ext_vals = xp.concatenate([monthly[-1:], monthly, monthly[:1]])
    d = xp.asarray(doy, dtype=ext_mids.dtype)
    i = xp.clip(xp.searchsorted(ext_mids, d, side="right") - 1, 0, 12)
    f = (d - ext_mids[i]) / (ext_mids[i + 1] - ext_mids[i])
    return ext_vals[i] * (1.0 - f) + ext_vals[i + 1] * f


def ineichen_ghi(apparent_zenith, airmass_absolute, tl, altitude_m,
                 dni_extra, xp=jnp, kernels=None):
    """Ineichen & Perez 2002 clear-sky GHI [W/m^2].

    Same formulation the reference evaluates via Location.get_clearsky
    (pvmodel.py:60): altitude-corrected coefficients and Linke-turbidity
    attenuation (no Perez enhancement factor — see NOTE below).
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    fh1 = k.exp(-altitude_m / 8000.0)
    fh2 = k.exp(-altitude_m / 1250.0)
    cg1 = 5.09e-5 * altitude_m + 0.868
    cg2 = 3.92e-5 * altitude_m + 0.0387
    cos_zen = xp.maximum(k.cos(apparent_zenith), 0.0)
    # NOTE: the classical Perez enhancement factor exp(0.01*am^1.8) is
    # deliberately absent — pvlib disables it by default since 0.6.0, so the
    # reference's Location.get_clearsky path never applies it.
    ghi = (
        cg1
        * dni_extra
        * cos_zen
        * k.exp(-cg2 * airmass_absolute * (fh1 + fh2 * (tl - 1.0)))
    )
    return xp.maximum(ghi, 0.0)


def csi_zenith_cap(zenith, xp=jnp, kernels=None):
    """Physical upper bound on the clear-sky index as a function of zenith.

    The reference clips csi to ``27.21*exp(-114*cos z) + 1.665*exp(-4.494*
    cos z) + 1.08`` (pvmodel.py:52-58, an enhancement-limit fit from the
    Bright et al. model): near-overhead sun admits csi only slightly above 1,
    while low sun admits large cloud-enhancement spikes.
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    cos_z = k.cos(zenith)
    cap = (27.21 * k.exp(-114.0 * cos_z)
           + 1.665 * k.exp(-4.494 * cos_z) + 1.08)
    # Below the horizon the fit explodes (exp(90) ~ 1e39 at night), which
    # overflows the float32 cast on device.  The cap's only consumer is
    # ``minimum(csi, cap)`` and csi stays O(1), so any ceiling >> the
    # physical enhancement limit is equivalent — clamp to keep it finite.
    return xp.minimum(cap, 1e6)


def disc_dni(ghi, zenith, doy, xp=jnp, kernels=None):
    """Maxwell 1987 DISC: direct normal irradiance from GHI [W/m^2].

    Matches the reference's ``pvlib.irradiance.disc(ghi, zenith, times)``
    (pvmodel.py:63): Kasten 1966 airmass at standard pressure, kt clipped to
    [0, 2], zenith validity limit 87 deg.
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    i0 = extra_radiation_spencer(doy, DISC_SOLAR_CONSTANT, xp=xp, kernels=k)
    cos_zen = k.cos(zenith)
    # 0.065 = pvlib's min_cos_zenith for kt (disc default since 0.6.0):
    # keeps kt bounded through the 86.3-87 deg twilight band
    i0h = i0 * xp.maximum(cos_zen, 0.065)

    kt = xp.clip(ghi / i0h, 0.0, 2.0)
    am = relative_airmass_kasten1966(zenith, xp=xp, kernels=k)

    kt2 = kt * kt
    kt3 = kt2 * kt
    is_hi = kt > 0.6
    a = xp.where(
        is_hi,
        -5.743 + 21.77 * kt - 27.49 * kt2 + 11.56 * kt3,
        0.512 - 1.56 * kt + 2.286 * kt2 - 2.222 * kt3,
    )
    b = xp.where(is_hi, 41.4 - 118.5 * kt + 66.05 * kt2 + 31.9 * kt3,
                 0.37 + 0.962 * kt)
    c = xp.where(is_hi, -47.01 + 184.2 * kt - 222.0 * kt2 + 73.81 * kt3,
                 -0.28 + 0.932 * kt - 2.048 * kt2)

    knc = (
        0.866
        - 0.122 * am
        + 0.0121 * am * am
        - 0.000653 * am**3
        + 1.4e-5 * am**4
    )
    # exponent clamped: past the 87-deg validity limit c*am can overflow
    # float32 before the validity mask zeroes the result
    delta_kn = a + b * k.exp(xp.minimum(c * am, 40.0))
    dni = (knc - delta_kn) * i0

    valid = (zenith < 87.0 * DEG) & (ghi > 0.0)
    return xp.where(valid, xp.maximum(dni, 0.0), 0.0)


def angle_of_incidence_cos(surface_tilt_deg, surface_azimuth_deg, zenith,
                           azimuth, xp=jnp, kernels=None):
    """cos(AOI) between the sun vector and the panel normal (unclipped)."""
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    tilt = surface_tilt_deg * DEG
    saz = surface_azimuth_deg * DEG
    return (
        k.cos(tilt) * k.cos(zenith)
        + k.sin(tilt) * k.sin(zenith) * k.cos(azimuth - saz)
    )


def haydavies_poa(surface_tilt_deg, cos_aoi, zenith, ghi, dni, dhi,
                  dni_extra, albedo=0.25, xp=jnp, kernels=None):
    """Hay & Davies 1980 plane-of-array irradiance + isotropic ground.

    Matches PVSystem.get_irradiance's default transposition in the reference
    (pvmodel.py:66-68).  Returns dict with poa_direct / poa_diffuse /
    poa_global.
    """
    k = kernels if kernels is not None else _tables.exact_kernels(xp)
    tilt = surface_tilt_deg * DEG
    cos_tilt = k.cos(tilt)

    rb_num = xp.maximum(cos_aoi, 0.0)
    rb_den = xp.maximum(k.cos(zenith), 0.01745)  # pvlib's 89-deg floor
    rb = rb_num / rb_den

    ai = dni / dni_extra  # anisotropy index
    sky_diffuse = dhi * (ai * rb + (1.0 - ai) * 0.5 * (1.0 + cos_tilt))
    ground = ghi * albedo * 0.5 * (1.0 - cos_tilt)

    poa_direct = xp.maximum(dni * cos_aoi, 0.0)
    poa_diffuse = xp.maximum(sky_diffuse, 0.0) + ground
    return {
        "poa_direct": poa_direct,
        "poa_diffuse": poa_diffuse,
        "poa_global": poa_direct + poa_diffuse,
    }


def device_geometry(day2000, sec_of_day, doy, latitude_deg, longitude_deg,
                    altitude_m, surface_tilt_deg, surface_azimuth_deg,
                    albedo, turbidity_monthly, xp=jnp, kernels=None,
                    scope=None):
    """All geometry features from split time + scalar site parameters —
    float32-safe, jit/vmap-friendly (the per-chain site-grid path).

    Site parameters are scalars (vmap them over a grid); time arrays are
    shared.  Returns the same dict as :func:`block_geometry`.

    ``scope``: optional phase-scope factory (the engine's gated
    ``_phase`` helper, obs/attribution.py) — when given, the whole
    transcendental chain traces inside the ``geometry`` phase so device
    traces can price it; None (the default, and every host/numpy
    caller) changes nothing.
    """
    ctx = scope("geometry") if scope is not None else \
        contextlib.nullcontext()
    with ctx:
        pos = sun_position_split(day2000, sec_of_day, latitude_deg,
                                 longitude_deg, xp=xp, kernels=kernels)
        pressure = alt2pres(altitude_m)
        app_elev = apparent_elevation(pos["zenith"], pressure, xp=xp,
                                      kernels=kernels)
        app_zen = np.pi / 2.0 - app_elev

        am_rel = relative_airmass_kasten_young(app_zen, xp=xp,
                                               kernels=kernels)
        am_abs = am_rel * pressure / STD_PRESSURE

        dni_extra = extra_radiation_spencer(doy, xp=xp, kernels=kernels)
        tl = linke_turbidity(doy, turbidity_monthly, xp=xp)
        ghi_clear = ineichen_ghi(app_zen, am_abs, tl, altitude_m,
                                 dni_extra, xp=xp, kernels=kernels)
        cos_aoi = angle_of_incidence_cos(
            surface_tilt_deg, surface_azimuth_deg, app_zen, pos["azimuth"],
            xp=xp, kernels=kernels
        )
        return {
            "zenith": pos["zenith"],
            "cos_zenith": pos["cos_zenith"],
            "apparent_zenith": app_zen,
            "azimuth": pos["azimuth"],
            "csi_cap": csi_zenith_cap(pos["zenith"], xp=xp,
                                      kernels=kernels),
            "ghi_clear": ghi_clear,
            "dni_extra": dni_extra,
            "airmass_abs": am_abs,
            "cos_aoi": cos_aoi,
            "doy": xp.asarray(doy),
            "surface_tilt": surface_tilt_deg,
            "albedo": albedo,
        }


def block_geometry(epoch_s, doy, site, xp=jnp, kernels=None):
    """All chain-independent solar/irradiance features for a time block.

    One evaluation per block serves every chain (the csi stream is the only
    chain-dependent input to the power chain) — the key layout decision that
    keeps the per-chain work on the VPU elementwise (SURVEY.md §7 step 6-7).

    Returns dict of arrays shaped like ``epoch_s`` (plus the scalar site
    constants the power chain needs):
      zenith, cos_zenith, apparent_zenith, azimuth, csi_cap,
      ghi_clear, dni_extra, airmass_abs, cos_aoi, doy,
      surface_tilt, albedo
    """
    pos = sun_position(epoch_s, site.latitude, site.longitude, xp=xp,
                       kernels=kernels)
    pressure = alt2pres(site.altitude)
    app_elev = apparent_elevation(pos["zenith"], pressure, xp=xp,
                                  kernels=kernels)
    app_zen = np.pi / 2.0 - app_elev

    am_rel = relative_airmass_kasten_young(app_zen, xp=xp, kernels=kernels)
    am_abs = am_rel * pressure / STD_PRESSURE

    dni_extra = extra_radiation_spencer(doy, xp=xp, kernels=kernels)
    tl = linke_turbidity(doy, site.linke_turbidity_monthly, xp=xp)
    ghi_clear = ineichen_ghi(app_zen, am_abs, tl, site.altitude, dni_extra,
                             xp=xp, kernels=kernels)

    cos_aoi = angle_of_incidence_cos(
        site.surface_tilt, site.surface_azimuth, app_zen, pos["azimuth"],
        xp=xp, kernels=kernels
    )
    return {
        "zenith": pos["zenith"],
        "cos_zenith": pos["cos_zenith"],
        "apparent_zenith": app_zen,
        "azimuth": pos["azimuth"],
        "csi_cap": csi_zenith_cap(pos["zenith"], xp=xp, kernels=kernels),
        "ghi_clear": ghi_clear,
        "dni_extra": dni_extra,
        "airmass_abs": am_abs,
        "cos_aoi": cos_aoi,
        "doy": xp.asarray(doy),
        "surface_tilt": site.surface_tilt,
        "albedo": site.albedo,
    }


# ---------------------------------------------------------------------------
# Strided geometry (Plan.geom_stride): evaluate every s seconds, lerp to 1 Hz
# ---------------------------------------------------------------------------

#: geometry fields linearly interpolated between stride samples — the
#: TRIG-FREE outputs of the chain (angles in monotone sub-π ranges and
#: already-composed irradiance terms), each smooth at the ~7.3e-5 rad/s
#: apparent solar rate so the second-order lerp error over a 60 s
#: stride is far below the fields' physical scale.  ``azimuth`` is NOT
#: here: it wraps at 2π (a lerp through the wrap is catastrophically
#: wrong) and nothing downstream of ``cos_aoi`` — which IS interpolated
#: — consumes it (models/pv.py power_from_csi), so it is held at the
#: left sample instead.  ``doy`` keeps its exact per-second value: its
#: integer-day semantics feed the Spencer term and the turbidity LUT.
STRIDE_LERP_FIELDS = (
    "zenith", "cos_zenith", "apparent_zenith", "csi_cap",
    "ghi_clear", "dni_extra", "airmass_abs", "cos_aoi",
)

#: Published float64-oracle error bounds for ``geom_stride=60`` (the
#: coarsest supported stride; 30 is strictly tighter), in each field's
#: native units, in the models/tables.py ``MAX_ULP`` style.  Metric:
#: max |strided − per-second float64 oracle| over every DAYTIME second
#: (``cos_zenith >= 0.01`` — night values multiply a zero irradiance in
#: the power chain, and two night-only terms are intentionally
#: discontinuous there: the apparent-elevation refraction cutoff at
#: −0.83° and the csi-cap clamp) across solstice/equinox days at
#: equatorial, mid-latitude and polar sites.  Enforced by
#: tests/test_geom_stride.py; the end-to-end field-scale 1e-5
#: reduce-stats contract over a full simulated year is asserted there
#: too.
STRIDE_MAX_ABS_ERR = {
    "zenith": 5e-4,          # rad; worst measured 3.7e-4 (equatorial)
    "cos_zenith": 1e-5,      # worst measured 2.4e-6
    "apparent_zenith": 5e-4,  # rad; refraction steepens near the horizon
    "csi_cap": 0.3,          # kinked at low sun → lerp across the knee;
                             # large only where ghi_clear is ~0, so the
                             # end-to-end 1e-5 field-scale contract holds
    "ghi_clear": 0.5,        # W/m²; worst measured 2.1e-2 at the ramps
    "dni_extra": 0.05,       # W/m²; ~0.06 %/day orbital drift
    "airmass_abs": 0.2,      # Kasten–Young blows up toward the horizon;
                             # worst measured 2.5e-2 under the daytime mask
    "cos_aoi": 1e-4,         # worst measured 5.5e-6
}

#: the strides SimConfig.geom_stride admits (both divide 60, so stride
#: windows never straddle a minute-RNG group or a block boundary)
STRIDES = (1, 30, 60)


def interp_sampled(sampled, i, f, xp=jnp, scope=None):
    """Lerp the :data:`STRIDE_LERP_FIELDS` of a stride-sampled geometry
    dict at sample index ``i`` + fraction ``f`` in [0, 1).

    ``sampled`` holds arrays with a leading sample axis of length
    ``n_samples = T//stride + 1``; ``i``/``f`` may be scalars (the
    in-scan per-second case) or arrays (the batched host / wide case).
    Returns only the interpolated fields — callers add back the exact
    per-second ``doy`` and the site scalars.  ``scope``: optional phase
    scope factory; the lerp is geometry work (see
    :func:`device_geometry`)."""
    ctx = scope("geometry") if scope is not None else \
        contextlib.nullcontext()
    with ctx:
        out = {}
        for k in STRIDE_LERP_FIELDS:
            v = sampled[k]
            lo = v[i]
            fa = xp.asarray(f)
            if lo.ndim > fa.ndim:
                fa = fa.reshape(fa.shape + (1,) * (lo.ndim - fa.ndim))
            out[k] = lo * (1.0 - fa) + v[i + 1] * fa
        return out


def strided_block_geometry(epoch_s, doy, site, stride, xp=np, kernels=None):
    """:func:`block_geometry` evaluated on a stride-``s`` grid and
    linearly interpolated back to 1 Hz — the shared-site
    ``geom_stride`` fast path (engine/simulation.py ``host_inputs``
    runs it on the host in float64, so the device graph is untouched).

    The sample grid is ``0, s, 2s, …, T`` (``T//s + 1`` points); the
    endpoint epoch is the exact next second after the block while its
    ``doy`` is clamped to the block's last second (the two differ only
    across a UTC-midnight block seam, where the day-keyed terms move by
    ~0.06 % and the error is confined to the seam's final stride
    window — inside the published :data:`STRIDE_MAX_ABS_ERR` bounds).
    ``stride=1`` returns :func:`block_geometry` unchanged.
    Accuracy contract: :data:`STRIDE_MAX_ABS_ERR`.
    """
    epoch_s = xp.asarray(epoch_s)
    doy = xp.asarray(doy)
    T = epoch_s.shape[0]
    if stride <= 1:
        return block_geometry(epoch_s, doy, site, xp=xp, kernels=kernels)
    if stride not in STRIDES:
        raise ValueError(f"geom_stride must be one of {STRIDES}, "
                         f"got {stride}")
    if T % stride:
        raise ValueError(f"block length {T} not a multiple of "
                         f"geom_stride {stride}")
    ep_s = xp.concatenate([epoch_s[::stride], epoch_s[-1:] + 1.0])
    doy_s = xp.concatenate([doy[::stride], doy[-1:]])
    geom_s = block_geometry(ep_s, doy_s, site, xp=xp, kernels=kernels)
    pos = np.arange(T)
    i = pos // stride
    f = (pos % stride) / float(stride)
    out = dict(geom_s)
    out.update(interp_sampled(geom_s, i, f, xp=xp))
    out["doy"] = doy                       # exact per-second day index
    out["azimuth"] = geom_s["azimuth"][i]  # held: wraps at 2π, unconsumed
    return out
