"""Host-side time grid precomputation.

The reference advances a wall-clock ``datetime`` one second at a time and
derives, per step, (a) minute/hour/day fractions and (b) rollover events that
advance its interpolated samplers (clearskyindexmodel.py:113-126).  Data-
dependent calendar logic like that cannot live inside ``jit``; the TPU-native
design therefore precomputes every time-derived feature on the host as flat
numpy arrays over the (regular, 1 Hz) simulation grid and feeds them to the
device as scan inputs.  Everything here is deterministic, cheap (O(duration)
integer numpy), and computed *blockwise* so 10-year grids never materialise
at once.

Semantics matched to the reference:

* fractions — ``min_fraction = second/60``, ``hour_fraction = (minute +
  min_fraction)/60``, ``day_fraction = (hour + hour_fraction)/24`` of the
  *local* wall clock (clearskyindexmodel.py:113-118); computed here as
  modular arithmetic on local epoch seconds (identical, incl. across DST).
* rollovers — fire when the local minute/hour/day *field* differs from the
  previous second (clearskyindexmodel.py:120-126).  Note the asymmetry this
  implies around DST: on the backward transition the hour field repeats, so
  no hour rollover fires for two consecutive wall hours; on the forward
  transition a single rollover fires.  We reproduce both exactly by carrying
  the timezone's transition instants.
* the t=0 step never fires a rollover (the model is constructed at the grid
  start; ``prev_time is None`` branch at clearskyindexmodel.py:117-120).

Timezone handling uses stdlib ``zoneinfo`` (the reference uses pytz,
pvmodel.py:19); offsets are resolved once into a piecewise-constant table.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from zoneinfo import ZoneInfo

import numpy as np

_UTC = _dt.timezone.utc


def _probe_offset(tz: ZoneInfo, epoch: int) -> int:
    """UTC offset in seconds at the given epoch."""
    dt = _dt.datetime.fromtimestamp(epoch, tz)
    return int(dt.utcoffset().total_seconds())


def _offset_table(tz: ZoneInfo, lo: int, hi: int):
    """Piecewise-constant UTC offsets over [lo, hi).

    Returns (breaks, offsets): ``offsets[i]`` applies for epochs in
    ``[breaks[i], breaks[i+1])``.  Transition instants are located by hourly
    probing + bisection to 1 s (DST rules are hour-aligned in practice, but we
    do not rely on it).
    """
    lo, hi = int(lo) - 2 * 86400, int(hi) + 2 * 86400
    probes = np.arange(lo, hi + 3600, 3600, dtype=np.int64)
    offs = np.asarray([_probe_offset(tz, int(p)) for p in probes], dtype=np.int64)
    breaks = [lo]
    offsets = [int(offs[0])]
    for i in np.nonzero(np.diff(offs))[0]:
        a, b = int(probes[i]), int(probes[i + 1])
        while b - a > 1:  # bisect the exact transition second
            m = (a + b) // 2
            if _probe_offset(tz, m) == offs[i]:
                a = m
            else:
                b = m
        breaks.append(b)
        offsets.append(int(offs[i + 1]))
    return np.asarray(breaks, dtype=np.int64), np.asarray(offsets, dtype=np.int64)


@dataclasses.dataclass(frozen=True)
class TimeBlock:
    """Per-second time features for one contiguous block of the grid.

    All arrays have length ``len(epoch)``; ``*_idx`` are *global* sampler
    pair indices (0 at simulation start), so sampler value arrays generated
    once per run can be gathered per block.
    """

    offset: int                 # block start, seconds since simulation start
    epoch: np.ndarray           # int64, UTC epoch seconds
    local_sec: np.ndarray       # int64, epoch + utcoffset
    min_fraction: np.ndarray    # float64 in [0, 1)
    hour_fraction: np.ndarray   # float64 in [0, 1)
    day_fraction: np.ndarray    # float64 in [0, 1)
    new_min: np.ndarray         # bool: minute field changed vs previous second
    new_hour: np.ndarray        # bool
    new_day: np.ndarray         # bool
    min_idx: np.ndarray         # int64 global minute-interval index
    hour_idx: np.ndarray        # int64
    day_idx: np.ndarray         # int64
    month0: np.ndarray          # int64, local month, 0-based (turbidity gather)
    doy: np.ndarray             # int64, local day of year (1-based)


@dataclasses.dataclass(frozen=True)
class TimeGridSpec:
    """A 1 Hz local-calendar time grid of ``duration_s`` seconds.

    Construct with :meth:`from_local_start`; materialise features blockwise
    with :meth:`block`.
    """

    start_epoch: int
    duration_s: int
    tz_name: str
    tz_breaks: np.ndarray       # piecewise offset table
    tz_offsets: np.ndarray
    backward_transitions: np.ndarray  # epochs where the offset decreases
    midnight_epochs: np.ndarray  # epoch of each local midnight covering grid
    day_month0: np.ndarray       # per local day (aligned to midnight_epochs)
    day_doy: np.ndarray
    min_phase: int               # local_sec(start) % 60
    hour_phase: int              # local_sec(start) % 3600

    @classmethod
    def from_local_start(cls, start, duration_s: int, tz_name: str = "Europe/Berlin"):
        if isinstance(start, str):
            start = _dt.datetime.fromisoformat(start)
        tz = ZoneInfo(tz_name)
        if start.tzinfo is None:
            start = start.replace(tzinfo=tz)
        start_epoch = int(start.timestamp())
        end_epoch = start_epoch + int(duration_s)

        breaks, offsets = _offset_table(tz, start_epoch, end_epoch)
        backward = breaks[1:][np.diff(offsets) < 0]

        # Local midnights covering [start, end]: walk local dates.
        first_local = _dt.datetime.fromtimestamp(start_epoch, tz).date()
        last_local = _dt.datetime.fromtimestamp(end_epoch, tz).date()
        n_days = (last_local - first_local).days + 2
        midnights, months, doys = [], [], []
        for d in range(n_days):
            date = first_local + _dt.timedelta(days=d)
            mid = _dt.datetime(date.year, date.month, date.day, tzinfo=tz)
            midnights.append(int(mid.timestamp()))
            months.append(date.month - 1)
            doys.append(date.timetuple().tm_yday)

        local0 = start_epoch + offsets[np.searchsorted(breaks, start_epoch, "right") - 1]
        return cls(
            start_epoch=start_epoch,
            duration_s=int(duration_s),
            tz_name=tz_name,
            tz_breaks=breaks,
            tz_offsets=offsets,
            backward_transitions=backward,
            midnight_epochs=np.asarray(midnights, dtype=np.int64),
            day_month0=np.asarray(months, dtype=np.int64),
            day_doy=np.asarray(doys, dtype=np.int64),
            min_phase=int(local0 % 60),
            hour_phase=int(local0 % 3600),
        )

    # ---- sampler array sizes -------------------------------------------
    def _count(self, phase: int, period: int) -> int:
        """Number of epoch-phase boundaries in (start, start+duration]."""
        return int((self.duration_s - 1 + phase) // period)

    @property
    def n_minute_intervals(self) -> int:
        """Distinct minute pair-indices touched by the grid (max min_idx + 1)."""
        return self._count(self.min_phase, 60) + 1

    @property
    def n_hour_intervals(self) -> int:
        return self._count(self.hour_phase, 3600) + 1

    @property
    def n_day_intervals(self) -> int:
        last = self.start_epoch + self.duration_s - 1
        base = np.searchsorted(self.midnight_epochs, self.start_epoch, "right")
        return int(np.searchsorted(self.midnight_epochs, last, "right") - base) + 1

    # ---- hour features at arbitrary epochs -----------------------------
    def _hour_features(self, epoch: np.ndarray):
        """(hour_idx, hour_fraction) at given epochs — shared by block() and
        minute_value_features()."""
        off = self.tz_offsets[np.searchsorted(self.tz_breaks, epoch, "right") - 1]
        local = epoch + off
        rel = epoch - self.start_epoch
        n_back = np.searchsorted(self.backward_transitions, epoch, "right") \
            - np.searchsorted(self.backward_transitions, self.start_epoch, "right")
        hour_idx = (rel + self.hour_phase) // 3600 - n_back
        return local, hour_idx, (local % 3600) / 3600.0

    def minute_value_features(self, lo: int, hi: int):
        """Hour-interpolation features at the *draw instants* of minute-sampler
        values with indices in [lo, hi).

        Value i of a minute-rate InterpolatedSampler is drawn at the (i-1)-th
        minute rollover for i >= 2; values 0 and 1 are primed at the grid
        start (clearskyindexmodel.py:29-32,90-95).  The minute-noise draw
        reads the hourly cloud cover interpolated at its draw instant
        (clearskyindexmodel.py:86-88), so each value needs (hour pair index,
        hour fraction) at that instant.

        Returns (hour_idx[int64], hour_fraction[float64]) of length hi-lo.
        """
        i = np.arange(lo, hi, dtype=np.int64)
        j = np.maximum(i - 1, 1)
        rel = np.where(i >= 2, 60 * j - self.min_phase, 0)
        epoch = self.start_epoch + rel
        _, hour_idx, hour_frac = self._hour_features(epoch)
        return hour_idx, hour_frac

    # ---- blockwise feature materialisation -----------------------------
    def block(self, offset: int, length: int) -> TimeBlock:
        length = min(length, self.duration_s - offset)
        epoch = self.start_epoch + offset + np.arange(length, dtype=np.int64)
        local, hour_idx, hour_fraction = self._hour_features(epoch)

        min_fraction = (local % 60) / 60.0
        day_fraction = (local % 86400) / 86400.0

        rel = epoch - self.start_epoch
        t_pos = rel > 0  # no rollover fires at simulation start

        min_idx = (rel + self.min_phase) // 60
        new_min = ((rel + self.min_phase) % 60 == 0) & t_pos

        hour_boundary = (rel + self.hour_phase) % 3600 == 0
        is_backward = np.isin(epoch, self.backward_transitions)
        new_hour = hour_boundary & ~is_backward & t_pos

        base = np.searchsorted(self.midnight_epochs, self.start_epoch, "right")
        day_pos = np.searchsorted(self.midnight_epochs, epoch, "right")
        day_idx = day_pos - base
        new_day = np.isin(epoch, self.midnight_epochs) & t_pos

        day_number = day_pos - 1  # index into per-day calendar arrays
        return TimeBlock(
            offset=offset,
            epoch=epoch,
            local_sec=local,
            min_fraction=min_fraction,
            hour_fraction=hour_fraction,
            day_fraction=day_fraction,
            new_min=new_min,
            new_hour=new_hour,
            new_day=new_day,
            min_idx=min_idx,
            hour_idx=hour_idx,
            day_idx=day_idx,
            month0=self.day_month0[day_number],
            doy=self.day_doy[day_number],
        )
