"""Blockwise simulation engine (single-host orchestration layer)."""

from tmhpvsim_tpu.engine import compilecache  # noqa: F401
from tmhpvsim_tpu.engine.simulation import Simulation, BlockResult  # noqa: F401
from tmhpvsim_tpu.engine.slab import SlabScheduler  # noqa: F401
