"""Preemption-safe checkpoint/resume for the blockwise simulation.

The reference has no checkpointing at all — every restart loses the whole
stochastic state (SURVEY.md §5).  Here the design makes it nearly free: all
simulation state is one pytree of arrays plus a block offset
(engine/simulation.py), and every random draw is keyed by global index, so
``save -> restart -> load -> resume`` reproduces the uninterrupted run
bit-for-bit (verified by test_checkpoint.py).

Format: each snapshot is a single ``.npz`` with '/'-joined pytree paths;
PRNG key arrays are stored via ``jax.random.key_data`` under a ``key:``
prefix and re-wrapped on load.  No orbax dependency — the state is a few
MB and plain npz keeps the file greppable and future-proof.

On-disk layout (rotation + integrity, this module's preemption story):

* ``PATH`` — the anchor the caller names.  Always a complete npz of the
  newest generation (a hard link to it, so it costs no space), which
  keeps every ``os.path.exists(PATH)`` / ``load(PATH)`` consumer and
  every pre-rotation checkpoint working unchanged.
* ``PATH.g<N>`` — generation N's snapshot; the newest ``keep`` of them
  are retained.
* ``PATH.manifest.json`` — the sidecar integrity manifest: per-generation
  size + CRC32 + sha256 + resume block, and which generation is
  last-known-good.  ``load`` verifies the newest generation against it
  and falls back generation by generation when a torn write is detected
  — a WARN and one lost block, never a dead run.  A checkpoint without
  a manifest is a legacy single file and loads as generation 0.

Durability: the snapshot bytes are fsync'd before the atomic rename and
the parent directory is fsync'd after it (and again after the manifest
rewrite), so a power loss after ``save`` returns cannot lose the
generation — the satellite fix for the rename-only window the original
writer had.

Topology elasticity: ``save`` records the logical chain-axis *layout*
(which global chains this file holds, under what mesh/process topology)
as placement metadata, strictly separate from the identity echo
(``_config_echo``).  Identity mismatches — seed, rng_stream, models,
chain count — are still refused with the exact config-diff error;
placement deltas never refuse: ``load_elastic`` reassembles per-host
``PATH.host<i>`` shards into the full chain axis and reslices to the
resuming topology, so a run saved on 8 devices (or K host shards)
resumes on 1 device or a different mesh.  The layout's ``mesh_shape``
is descriptive only — 1-D ``[N]`` and 2-D ``[N, M]`` (chains x
scenario, parallel/mesh.py) meshes both reduce to the same contiguous
``chain_start``/``chain_stop`` records, so resumes are elastic across
mesh RANK too: a 1-host 1-D checkpoint resumes on a 2-host 2-D mesh
and vice versa (tests/test_distributed.py).
"""

from __future__ import annotations

import contextlib
import dataclasses
import glob
import hashlib
import json
import logging
import os
import re
import shutil
import threading
import time
import zlib
from typing import List, Optional, Tuple

import jax
import numpy as np

logger = logging.getLogger(__name__)

_KEY_PREFIX = "key:"
_META = "__meta__"

#: generations retained by ``save`` when the caller does not say
DEFAULT_KEEP = 3

#: sidecar manifest format (bumped only on incompatible manifest changes)
MANIFEST_FORMAT = 1

#: Version of the *random-stream layout* (how draws are derived from keys
#: and global indices).  Bump whenever the derivation changes — v2
#: switched the per-second streams from per-second fold_in+split to
#: minute-grouped counter draws; v3 switched the hourly/daily samplers to
#: global-index-keyed (fold_in) draws so any window regenerates without
#: history (windowed arrays, engine/simulation.py) — so a checkpoint from
#: an older build is REFUSED (clear config-mismatch error) instead of
#: silently resuming with different randomness and producing a hybrid
#: trace no version can reproduce.
RNG_STREAM_VERSION = 3


class CheckpointError(RuntimeError):
    """A checkpoint that cannot be used: missing, truncated, not an npz,
    or metadata-less.  Carries the path/size/verify detail and an
    actionable hint instead of a raw ``zipfile.BadZipFile``/``KeyError``.
    """

    _HINT = ("delete the checkpoint (and its .manifest.json / .g* "
             "siblings) to start fresh, or point --checkpoint at the "
             "file that belongs to this run")

    def __init__(self, path: str, detail: str, *,
                 size: Optional[int] = None, hint: Optional[str] = None):
        self.path = path
        self.detail = detail
        self.size = size
        msg = f"checkpoint {path}: {detail}"
        if size is not None:
            msg += f" (size {size} bytes)"
        super().__init__(f"{msg} — {hint or self._HINT}")


class CheckpointCorruptError(CheckpointError):
    """Every recorded generation failed integrity verification — raised
    only after the generation-by-generation fallback is exhausted."""


def _config_echo(config) -> dict:
    """The *identity* half of the config split: the full run
    configuration as JSON-able data — including site and model options,
    whose silent divergence across a resume would change physics/branch
    selection mid-trace.  A mismatch on any of these keys REFUSES the
    resume.  Performance knobs (block_impl, scan_unroll, slab_chains,
    blocks_per_dispatch, ...) are deliberately NOT echoed: every plan
    produces bit-identical trajectories, so a resume may run under a
    different plan than the run that saved.  *Placement* (mesh shape,
    device/process count, which chain slice a file holds) is never part
    of the echo either — it rides ``meta['layout']`` and a mismatch
    there reshards on load instead of refusing (``load_elastic``)."""
    return {
        "start": config.start,
        "duration_s": config.duration_s,
        "n_chains": config.n_chains,
        "seed": config.seed,
        "block_s": config.block_s,
        "dtype": config.dtype,
        "prng_impl": getattr(config, "prng_impl", "threefry2x32"),
        "rng_stream": RNG_STREAM_VERSION,
        "site": dataclasses.asdict(config.site),
        "site_grid": (dataclasses.asdict(config.site_grid)
                      if config.site_grid is not None else None),
        "output": config.output,
        "options": dataclasses.asdict(config.options),
        "meter_max_w": config.meter_max_w,
        # fleet identity rides as size + content digest rather than the
        # full column dump: a national fleet is millions of rows, and the
        # digest refuses on ANY per-site parameter drift just the same
        "fleet": ({"n": len(config.fleet),
                   "digest": config.fleet.digest()}
                  if getattr(config, "fleet", None) is not None else None),
    }


def _flatten(tree, prefix=""):
    out = {}
    for name, value in tree.items():
        path = f"{prefix}{name}"
        if isinstance(value, dict):
            out.update(_flatten(value, path + "/"))
        elif jax.dtypes.issubdtype(value.dtype, jax.dtypes.prng_key):
            out[_KEY_PREFIX + path] = np.asarray(jax.random.key_data(value))
        else:
            out[path] = np.asarray(value)
    return out


def _unflatten(flat, prng_impl: str = "threefry2x32"):
    tree = {}
    for path, value in flat.items():
        if path.startswith(_KEY_PREFIX):
            path = path[len(_KEY_PREFIX):]
            # key_data layout depends on the PRNG impl (threefry: 2 words,
            # rbg: 4), so the impl rides the checkpoint metadata
            value = jax.random.wrap_key_data(value, impl=prng_impl)
        node = tree
        *parents, leaf = path.split("/")
        for p in parents:
            node = node.setdefault(p, {})
        node[leaf] = value
    return tree


def _build_meta(flat, next_block: int, config, layout) -> dict:
    meta = {"next_block": int(next_block)}
    if config is not None:
        meta["prng_impl"] = getattr(config, "prng_impl", "threefry2x32")
        meta["config"] = _config_echo(config)
    else:
        # no config: infer the impl from the stored key_data layout
        # (threefry: 2 words, rbg: 4) so bare save()/load()
        # round-trips still reconstruct the right key type
        widths = {v.shape[-1] for k, v in flat.items()
                  if k.startswith(_KEY_PREFIX)}
        meta["prng_impl"] = "rbg" if widths == {4} else "threefry2x32"
    if layout is not None:
        meta["layout"] = dict(layout)
    return meta


def manifest_path(path: str) -> str:
    return path + ".manifest.json"


def _dir_of(path: str) -> str:
    return os.path.dirname(path) or "."


def _fsync_dir(dirpath: str) -> None:
    """Durability for renames/creates: fsync the directory entry itself
    (no-op on filesystems/platforms that refuse directory fds)."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - platform-dependent
        pass
    finally:
        os.close(fd)


def _digest(path: str) -> Tuple[int, int, str]:
    """(size, crc32, sha256-hex) of a file, streamed."""
    crc = 0
    sha = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            size += len(chunk)
            crc = zlib.crc32(chunk, crc)
            sha.update(chunk)
    return size, crc & 0xFFFFFFFF, sha.hexdigest()


def read_manifest(path: str) -> Optional[dict]:
    """The sidecar manifest of checkpoint ``path``, or None when absent
    or unreadable (an unreadable manifest degrades to legacy single-file
    behaviour with a WARN — the data file may still be fine)."""
    mp = manifest_path(path)
    try:
        with open(mp) as f:
            man = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        logger.warning("checkpoint manifest %s unreadable (%s); "
                       "treating checkpoint as a legacy single file",
                       mp, e)
        return None
    if not isinstance(man, dict) or \
            not isinstance(man.get("generations"), list):
        logger.warning("checkpoint manifest %s malformed; treating "
                       "checkpoint as a legacy single file", mp)
        return None
    return man


def _write_manifest(path: str, man: dict) -> None:
    mp = manifest_path(path)
    tmp = mp + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, mp)
    _fsync_dir(_dir_of(path))


def _point_anchor(path: str, gpath: str) -> None:
    """Atomically make the anchor ``path`` a complete copy of the newest
    generation.  A hard link costs no space and shares the inode (so a
    torn write through either name damages exactly one generation);
    filesystems without hard links get a plain copy."""
    lnk = path + ".lnk.tmp"
    with contextlib.suppress(FileNotFoundError):
        os.remove(lnk)
    try:
        os.link(gpath, lnk)
    except OSError:  # pragma: no cover - no-hardlink filesystems
        shutil.copyfile(gpath, lnk)
        with open(lnk, "rb") as f:
            with contextlib.suppress(OSError):
                os.fsync(f.fileno())
    os.replace(lnk, path)


def _write_generation(path: str, flat: dict, meta: dict, keep: int) -> None:
    """One durable rotation step: serialize to tmp, fsync, checksum,
    promote to ``path.g<N>``, re-point the anchor, rewrite the manifest,
    prune beyond ``keep``.  The anchor and manifest always describe a
    fully-written generation — there is no window where a crash leaves
    the checkpoint unusable (test_checkpoint.py torn-write matrix)."""
    from tmhpvsim_tpu.obs import metrics as obs_metrics

    d = _dir_of(path)
    man = read_manifest(path)
    gen = int((man or {}).get("latest", 0)) + 1
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **flat, **{_META: json.dumps(meta)})
        f.flush()
        os.fsync(f.fileno())
    size, crc, sha = _digest(tmp)
    gpath = f"{path}.g{gen}"
    os.replace(tmp, gpath)
    _fsync_dir(d)
    _point_anchor(path, gpath)
    _fsync_dir(d)
    entries = [e for e in (man or {}).get("generations", [])
               if isinstance(e, dict)
               and os.path.exists(os.path.join(d, e.get("file", "")))]
    entries.append({
        "gen": gen,
        "file": os.path.basename(gpath),
        "size": size,
        "crc32": crc,
        "sha256": sha,
        "next_block": int(meta.get("next_block", 0)),
        "saved_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    })
    keep = max(1, int(keep))
    kept, pruned = entries[-keep:], entries[:-keep]
    _write_manifest(path, {
        "format": MANIFEST_FORMAT,
        "keep": keep,
        "latest": gen,
        "generations": kept,
    })
    for e in pruned:
        with contextlib.suppress(OSError):
            os.remove(os.path.join(d, e["file"]))
    reg = obs_metrics.get_registry()
    reg.gauge("checkpoint.generations").set(len(kept))
    reg.gauge("checkpoint.latest_generation").set(gen)


def _commit(path: str, flat: dict, meta: dict, keep: int) -> None:
    """Chokepoint-instrumented write: ``checkpoint.write`` fires before
    anything touches disk (a failed save must leave the previous good
    checkpoint intact); ``checkpoint.corrupt`` fires after the commit so
    a ``truncate:K`` rule tears the just-written generation — the
    deterministic torn write the fallback tests recover from;
    ``checkpoint.committed`` fires last (a kill scheduled there is the
    crash-with-valid-checkpoint the recovery tests resume from)."""
    from tmhpvsim_tpu.runtime import faults

    if faults.ACTIVE is not None:
        faults.fire("checkpoint.write")
    _write_generation(path, flat, meta, keep)
    if faults.ACTIVE is not None:
        faults.fire("checkpoint.corrupt", path=path)
        faults.fire("checkpoint.committed")


def save(path: str, state, next_block: int, config=None, *,
         keep: Optional[int] = None, layout: Optional[dict] = None) -> None:
    """Write state + resume point (+ config echo for sanity checks).

    Durable and atomic: the snapshot is fsync'd, promoted to a new
    generation via ``os.replace``, the anchor re-pointed, the manifest
    rewritten and the parent directory fsync'd — a crash or power loss
    at ANY instant leaves the newest verifiable generation loadable.
    ``keep`` bounds the generations retained (default
    :data:`DEFAULT_KEEP`); ``layout`` attaches placement metadata
    (``Simulation.checkpoint_layout()``) for topology-elastic resume.
    """
    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.obs.profiler import annotate

    with obs_metrics.get_registry().timed("checkpoint.save_s"), \
            annotate("tmhpvsim/checkpoint.save"):
        flat = _flatten(state)
        meta = _build_meta(flat, next_block, config, layout)
        _commit(path, flat, meta,
                DEFAULT_KEEP if keep is None else keep)


def _size_of(path: str) -> Optional[int]:
    try:
        return os.path.getsize(path)
    except OSError:
        return None


def _read_npz(fpath: str) -> Tuple[dict, dict]:
    with np.load(fpath, allow_pickle=False) as data:
        meta = json.loads(str(data[_META]))
        flat = {k: data[k] for k in data.files if k != _META}
    return flat, meta


def _verify_entry(fpath: str, entry: dict) -> Optional[str]:
    """None when ``fpath`` matches its manifest entry, else the verify
    failure (missing / size / crc32 / sha256 mismatch)."""
    try:
        st_size = os.path.getsize(fpath)
    except OSError as e:
        return f"missing ({e.__class__.__name__})"
    want_size = entry.get("size")
    if want_size is not None and st_size != want_size:
        return f"size {st_size} != recorded {want_size}"
    size, crc, sha = _digest(fpath)
    if entry.get("crc32") is not None and crc != entry["crc32"]:
        return f"crc32 {crc:#010x} != recorded {entry['crc32']:#010x}"
    if entry.get("sha256") is not None and sha != entry["sha256"]:
        return "sha256 mismatch"
    return None


def _check_config(meta: dict, config) -> None:
    if config is None or "config" not in meta:
        return
    saved = meta["config"]
    # Echoes written before a key existed compare as that key's
    # then-implicit value, so old checkpoints stay resumable when the
    # echo schema grows (keys added in round 2 listed here).
    saved.setdefault("site_grid", None)
    saved.setdefault("output", "trace")
    saved.setdefault("prng_impl", "threefry2x32")
    # no rng_stream key = stream layout v1: deliberately NOT defaulted
    # to the current version, so pre-v2 checkpoints are refused rather
    # than resumed onto a different random stream
    saved.setdefault("rng_stream", 1)
    saved.setdefault("fleet", None)
    current = json.loads(json.dumps(_config_echo(config)))  # tuple->list
    if saved != current:
        keys = set(saved) | set(current)
        miss = object()
        diffs = {k: (saved.get(k, miss), current.get(k, miss))
                 for k in sorted(keys)
                 if saved.get(k, miss) != current.get(k, miss)}
        raise ValueError(
            f"checkpoint was written by a different configuration: "
            f"{diffs}"
        )


def _load_verified(path: str, config=None,
                   want_block: Optional[int] = None) -> Tuple[dict, dict]:
    """(flat, meta) of the newest generation that verifies against the
    manifest — falling back generation by generation on torn writes
    (WARN + ``checkpoint.verify_fail_total``/``checkpoint.fallback_total``
    counters), :class:`CheckpointCorruptError` only when nothing does.
    No manifest = legacy single file, loaded as generation 0 with typed
    errors instead of raw zipfile/KeyError surprises.  ``want_block``
    restricts the search to generations whose resume point matches (the
    shard-reassembly path aligning stragglers)."""
    from tmhpvsim_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.get_registry()
    d = _dir_of(path)
    man = read_manifest(path)
    if man is not None:
        entries = sorted(
            (e for e in man["generations"] if isinstance(e, dict)),
            key=lambda e: e.get("gen", 0), reverse=True)
        if want_block is not None:
            entries = [e for e in entries
                       if e.get("next_block") == want_block]
        newest_nb = entries[0].get("next_block") if entries else None
        tried: List[str] = []
        for e in entries:
            fpath = os.path.join(d, e.get("file", ""))
            if not os.path.exists(fpath) and \
                    e.get("gen") == man.get("latest") and \
                    os.path.exists(path):
                fpath = path  # anchor survives when the .g file was lost
            bad = _verify_entry(fpath, e)
            if bad is None:
                try:
                    flat, meta = _read_npz(fpath)
                except Exception as exc:
                    bad = (f"verified but unreadable "
                           f"({exc.__class__.__name__}: {exc})")
            if bad is not None:
                reg.counter("checkpoint.verify_fail_total").inc()
                logger.warning(
                    "checkpoint %s generation %s failed verification: %s",
                    path, e.get("gen"), bad)
                tried.append(f"g{e.get('gen')}: {bad}")
                continue
            if tried:
                reg.counter("checkpoint.fallback_total").inc()
                lost = ""
                if isinstance(newest_nb, int) and \
                        isinstance(e.get("next_block"), int):
                    lost = (f"; {newest_nb - e['next_block']} block(s) "
                            f"of progress lost")
                logger.warning(
                    "checkpoint %s: falling back to generation %s "
                    "(resumes at block %s%s)", path, e.get("gen"),
                    e.get("next_block"), lost)
            _check_config(meta, config)
            return flat, meta
        raise CheckpointCorruptError(
            path, "no generation passed integrity verification "
                  f"[{'; '.join(tried) or 'manifest lists none'}]",
            size=_size_of(path))
    # legacy single file: pre-rotation checkpoints load as generation 0
    try:
        flat, meta = _read_npz(path)
    except FileNotFoundError as exc:
        raise CheckpointError(path, "missing") from exc
    except Exception as exc:
        raise CheckpointError(
            path, f"unreadable as a checkpoint npz "
                  f"({exc.__class__.__name__}: {exc})",
            size=_size_of(path)) from exc
    _check_config(meta, config)
    return flat, meta


def _candidates(path: str):
    """File paths that may hold this checkpoint's metadata, best first:
    the anchor, then manifest generations newest-first, then per-host
    shard anchors (a multi-host run has no combined anchor at all)."""
    if os.path.exists(path):
        yield path
    man = read_manifest(path)
    if man is not None:
        d = _dir_of(path)
        for e in sorted((e for e in man["generations"]
                         if isinstance(e, dict)),
                        key=lambda e: e.get("gen", 0), reverse=True):
            fp = os.path.join(d, e.get("file", ""))
            if fp != path and os.path.exists(fp):
                yield fp
    for sp in _shard_paths(path):
        yield from _candidates(sp)


def peek_meta(path: str) -> dict:
    """Read only the metadata record (resume point + config echo) of the
    newest readable generation — falls back like :func:`load` but skips
    checksumming (callers peek for the seed, not for integrity)."""
    last: Optional[BaseException] = None
    for fpath in _candidates(path):
        try:
            with np.load(fpath, allow_pickle=False) as data:
                return json.loads(str(data[_META]))
        except Exception as exc:
            last = exc
    if last is None:
        raise CheckpointError(path, "missing")
    raise CheckpointError(
        path, f"no readable metadata in any generation "
              f"({last.__class__.__name__}: {last})",
        size=_size_of(path)) from last


def resumable(path: str) -> bool:
    """True when an existing run can resume from ``path``: the anchor
    exists, the manifest names a surviving generation, or per-host
    ``path.host<i>`` shards exist (``load_elastic`` reassembles them).
    The rotation-aware replacement for bare ``os.path.exists``."""
    if os.path.exists(path):
        return True
    man = read_manifest(path)
    if man is not None:
        d = _dir_of(path)
        if any(os.path.exists(os.path.join(d, e.get("file", "")))
               for e in man["generations"] if isinstance(e, dict)):
            return True
    return any(resumable(sp) for sp in _shard_paths(path))


def _shard_paths(path: str) -> List[str]:
    """Per-host shard anchors ``path.host<i>`` in host order (the
    multi-host pvsim naming, apps/pvsim.py)."""
    found = []
    pat = re.compile(re.escape(path) + r"\.host(\d+)$")
    for p in glob.glob(glob.escape(path) + ".host*"):
        m = pat.match(p)
        if m:
            found.append((int(m.group(1)), p))
    return [p for _, p in sorted(found)]


def load(path: str, config=None) -> Tuple[dict, int]:
    """Read (state, next_block); verifies integrity against the manifest
    (falling back to the newest generation that passes) and the config
    echo when given."""
    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.obs.profiler import annotate

    with obs_metrics.get_registry().timed("checkpoint.restore_s"), \
            annotate("tmhpvsim/checkpoint.restore"):
        flat, meta = _load_verified(path, config)
    return _finish_load(path, flat, meta)


def _finish_load(path: str, flat: dict, meta: dict) -> Tuple[dict, int]:
    nb = meta.get("next_block")
    if not isinstance(nb, int):
        raise CheckpointError(path, "metadata lacks a next_block resume "
                                    "point")
    return _unflatten(flat, meta.get("prng_impl", "threefry2x32")), nb


# legacy private alias (kept: the old single-file loader's name)
def _load(path: str, config=None) -> Tuple[dict, int]:
    return _finish_load(path, *_load_verified(path, config))


def _shard_chains(flat: dict, layout: Optional[dict]) -> int:
    """The chain count of one shard/file: from its layout when recorded,
    else inferred from a per-chain PRNG-key leaf (key_data is always
    (n_chains, words))."""
    if layout and isinstance(layout.get("chain_start"), int) and \
            isinstance(layout.get("chain_stop"), int):
        return layout["chain_stop"] - layout["chain_start"]
    for k, v in flat.items():
        if k.startswith(_KEY_PREFIX) and getattr(v, "ndim", 0) >= 1:
            return int(v.shape[0])
    raise CheckpointError(
        "<shard>", "cannot infer the shard's chain count (no layout "
                   "metadata and no per-chain key leaf)")


def _assemble_shards(path: str, shards: List[str],
                     config) -> Tuple[dict, dict]:
    """Reassemble per-host ``path.host<i>`` shard files into one full
    chain axis: every per-chain leaf (leading dim == the shard's chain
    count) is concatenated in chain order; replicated leaves ride from
    shard 0.  Shards whose newest generations disagree on the resume
    point align on the OLDEST common block (each shard's rotation keeps
    the generations to find it in)."""
    loaded = []
    for sp in shards:
        flat, meta = _load_verified(sp, config)
        loaded.append([sp, flat, meta])
    blocks = {m.get("next_block") for _, _, m in loaded}
    if len(blocks) > 1:
        nb = min(b for b in blocks if isinstance(b, int))
        logger.warning(
            "checkpoint shards of %s disagree on the resume point %s; "
            "aligning all shards on block %d", path, sorted(blocks), nb)
        for rec in loaded:
            if rec[2].get("next_block") != nb:
                try:
                    rec[1], rec[2] = _load_verified(rec[0], config,
                                                    want_block=nb)
                except CheckpointError as exc:
                    raise CheckpointCorruptError(
                        path, f"shard {rec[0]} has no generation at the "
                              f"common resume block {nb} ({exc.detail})"
                    ) from exc
    # chain order: by recorded layout when present, else host-index order
    def start_of(rec):
        lay = rec[2].get("layout") or {}
        return lay.get("chain_start", shards.index(rec[0]))

    loaded.sort(key=start_of)
    sizes = [_shard_chains(flat, meta.get("layout"))
             for _, flat, meta in loaded]
    lays = [m.get("layout") or {} for _, _, m in loaded]
    if all(isinstance(l.get("chain_start"), int) for l in lays):
        pos = 0
        for sp, lay, n in zip(shards, lays, sizes):
            if lay["chain_start"] != pos:
                raise CheckpointError(
                    path, f"shard chain slices are not contiguous: "
                          f"expected a shard starting at chain {pos}, "
                          f"found [{lay['chain_start']}, "
                          f"{lay.get('chain_stop')})")
            pos += n
    out = {}
    base = loaded[0][1]
    for k, v0 in base.items():
        per_chain = getattr(v0, "ndim", 0) >= 1 and \
            v0.shape[0] == sizes[0]
        if per_chain:
            out[k] = np.concatenate(
                [flat[k] for _, flat, _ in loaded], axis=0)
        else:
            out[k] = v0
    meta = dict(loaded[0][2])
    lay = dict(lays[0]) if lays[0] else {}
    total = sum(sizes)
    lay.update(n_chains=lay.get("n_chains", total),
               chain_start=0, chain_stop=total)
    meta["layout"] = lay
    return out, meta


def _slice_chains(path: str, flat: dict, meta: dict,
                  chain_slice: Tuple[int, int]) -> Tuple[dict, dict]:
    """Restrict a loaded flat tree to global chains [a, b) — the resume
    side of topology elasticity (a full checkpoint resuming on a pod
    slice, or a reslice after shard reassembly)."""
    a, b = int(chain_slice[0]), int(chain_slice[1])
    lay = meta.get("layout") or {}
    cur_a = lay.get("chain_start", 0)
    n_cur = _shard_chains(flat, lay if lay else None)
    cur_b = lay.get("chain_stop", cur_a + n_cur)
    if (cur_a, cur_b) == (a, b):
        return flat, meta
    if not (cur_a <= a and b <= cur_b):
        raise CheckpointError(
            path, f"holds chains [{cur_a}, {cur_b}) which does not cover "
                  f"the requested slice [{a}, {b})",
            hint="resume with the checkpoint that holds these chains, "
                 "or reassemble the full run from its .hostN shards")
    off = a - cur_a
    out = {k: (v[off:off + (b - a)]
               if getattr(v, "ndim", 0) >= 1 and v.shape[0] == n_cur
               else v)
           for k, v in flat.items()}
    meta = dict(meta)
    lay = dict(lay)
    lay.update(chain_start=a, chain_stop=b)
    meta["layout"] = lay
    return out, meta


def load_elastic(path: str, config=None, *,
                 chain_slice: Optional[Tuple[int, int]] = None
                 ) -> Tuple[dict, int]:
    """Topology-elastic :func:`load`: resume a checkpoint on a different
    chain-axis placement than it was saved under.

    * ``path`` exists (anchor or manifest): verified load, then — when
      ``chain_slice=(a, b)`` asks for a sub-range — the per-chain leaves
      are sliced to global chains [a, b) (a full single-host checkpoint
      resuming on one host of a pod slice).
    * ``path`` absent but ``path.host<i>`` shards exist: the shards are
      reassembled into the full chain axis (and then optionally sliced)
      — a K-host run resuming on 1 host, or on a different K.

    Identity is still enforced per underlying file (``_config_echo``
    diff ValueError); only placement is elastic.
    """
    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.obs.profiler import annotate

    with obs_metrics.get_registry().timed("checkpoint.restore_s"), \
            annotate("tmhpvsim/checkpoint.restore"):
        if os.path.exists(path) or read_manifest(path) is not None:
            flat, meta = _load_verified(path, config)
        else:
            shards = _shard_paths(path)
            if not shards:
                raise CheckpointError(
                    path, "missing (no anchor, no manifest generation, "
                          "no .host<i> shards)")
            flat, meta = _assemble_shards(path, shards, config)
        if chain_slice is not None:
            flat, meta = _slice_chains(path, flat, meta, chain_slice)
    return _finish_load(path, flat, meta)


class AsyncCheckpointWriter:
    """Checkpoint serialization off the critical path.

    ``submit`` runs the device→host gather synchronously (``_flatten``'s
    ``np.asarray`` per leaf IS the copy, so the snapshot is safe against
    the donation of the next block's carry — the same staging discipline
    as the double-buffered host output, PR 9) and hands the host bytes
    to a daemon thread that serializes, checksums, fsyncs, rotates and
    commits.  The scan loop never waits on the disk.

    Latest-wins queue of depth one: submitting while a snapshot is still
    pending replaces it (``checkpoint.async_dropped_total`` counts the
    superseded ones) — a newer state strictly dominates an older
    unwritten one, and a slow disk degrades checkpoint *cadence*, never
    block walls.  Write failures WARN and count
    (``checkpoint.async_write_failures_total``); :meth:`close` drains
    the queue and re-raises if the LAST write failed, so a run cannot
    silently finish without its final checkpoint durable on disk.
    """

    def __init__(self, path: str, *, config=None,
                 keep: Optional[int] = None):
        from tmhpvsim_tpu.obs import metrics as obs_metrics

        self.path = path
        self.config = config
        self.keep = DEFAULT_KEEP if keep is None else keep
        self._reg = obs_metrics.get_registry()
        self._depth = self._reg.gauge("checkpoint.async_queue_depth")
        self._cond = threading.Condition()
        self._pending: Optional[Tuple[dict, dict]] = None
        self._busy = False
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="ckpt-writer", daemon=True)
        self._thread.start()

    def submit(self, state, next_block: int,
               layout: Optional[dict] = None) -> None:
        """Snapshot ``state`` (synchronous host gather) and queue the
        durable write.  Returns as soon as the host copy exists."""
        flat = _flatten(state)
        meta = _build_meta(flat, next_block, self.config, layout)
        with self._cond:
            if self._pending is not None:
                self._reg.counter("checkpoint.async_dropped_total").inc()
            self._pending = (flat, meta)
            self._depth.set(1 + (1 if self._busy else 0))
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._pending is None:
                    return  # stopped and drained
                flat, meta = self._pending
                self._pending = None
                self._busy = True
                self._depth.set(1)
            err: Optional[BaseException] = None
            try:
                with self._reg.timed("checkpoint.save_s"):
                    _commit(self.path, flat, meta, self.keep)
                self._reg.counter("checkpoint.async_saves_total").inc()
            except BaseException as e:  # surfaces at close(); run goes on
                err = e
                self._reg.counter(
                    "checkpoint.async_write_failures_total").inc()
                logger.warning("async checkpoint write to %s failed: %s",
                               self.path, e)
            with self._cond:
                self._error = err  # a later success clears it
                self._busy = False
                self._depth.set(1 if self._pending is not None else 0)
                self._cond.notify_all()

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until the queue is drained (True) or ``timeout`` expires
        (False) — the preemption-grace path's bounded final sync."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending is not None or self._busy:
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    return False
                self._cond.wait(rem)
        return True

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain and stop the writer.  Raises :class:`CheckpointError`
        when the final write failed — a finishing run must not pretend
        its last checkpoint is on disk when it is not."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():  # pragma: no cover - stuck disk
            raise CheckpointError(
                self.path, "async checkpoint writer failed to drain",
                hint="the filesystem is stalled; the last snapshot may "
                     "not be durable")
        if self._error is not None:
            raise CheckpointError(
                self.path,
                f"final async checkpoint write failed "
                f"({self._error.__class__.__name__}: {self._error})"
            ) from self._error
