"""Checkpoint/resume for the blockwise simulation.

The reference has no checkpointing at all — every restart loses the whole
stochastic state (SURVEY.md §5).  Here the design makes it nearly free: all
simulation state is one pytree of arrays plus a block offset
(engine/simulation.py), and every random draw is keyed by global index, so
``save -> restart -> load -> resume`` reproduces the uninterrupted run
bit-for-bit (verified by test_checkpoint.py).

Format: a single ``.npz`` with '/'-joined pytree paths; PRNG key arrays are
stored via ``jax.random.key_data`` under a ``key:`` prefix and re-wrapped on
load.  No orbax dependency — the state is a few MB and plain npz keeps the
file greppable and future-proof.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Tuple

import jax
import numpy as np

_KEY_PREFIX = "key:"
_META = "__meta__"

#: Version of the *random-stream layout* (how draws are derived from keys
#: and global indices).  Bump whenever the derivation changes — v2
#: switched the per-second streams from per-second fold_in+split to
#: minute-grouped counter draws; v3 switched the hourly/daily samplers to
#: global-index-keyed (fold_in) draws so any window regenerates without
#: history (windowed arrays, engine/simulation.py) — so a checkpoint from
#: an older build is REFUSED (clear config-mismatch error) instead of
#: silently resuming with different randomness and producing a hybrid
#: trace no version can reproduce.
RNG_STREAM_VERSION = 3


def _config_echo(config) -> dict:
    """The full run configuration as JSON-able data — including site and
    model options, whose silent divergence across a resume would change
    physics/branch selection mid-trace.  Performance knobs (block_impl,
    scan_unroll, slab_chains, blocks_per_dispatch, ...) are deliberately
    NOT echoed: every plan produces bit-identical trajectories, so a
    resume may run under a different plan than the run that saved."""
    return {
        "start": config.start,
        "duration_s": config.duration_s,
        "n_chains": config.n_chains,
        "seed": config.seed,
        "block_s": config.block_s,
        "dtype": config.dtype,
        "prng_impl": getattr(config, "prng_impl", "threefry2x32"),
        "rng_stream": RNG_STREAM_VERSION,
        "site": dataclasses.asdict(config.site),
        "site_grid": (dataclasses.asdict(config.site_grid)
                      if config.site_grid is not None else None),
        "output": config.output,
        "options": dataclasses.asdict(config.options),
        "meter_max_w": config.meter_max_w,
    }


def _flatten(tree, prefix=""):
    out = {}
    for name, value in tree.items():
        path = f"{prefix}{name}"
        if isinstance(value, dict):
            out.update(_flatten(value, path + "/"))
        elif jax.dtypes.issubdtype(value.dtype, jax.dtypes.prng_key):
            out[_KEY_PREFIX + path] = np.asarray(jax.random.key_data(value))
        else:
            out[path] = np.asarray(value)
    return out


def _unflatten(flat, prng_impl: str = "threefry2x32"):
    tree = {}
    for path, value in flat.items():
        if path.startswith(_KEY_PREFIX):
            path = path[len(_KEY_PREFIX):]
            # key_data layout depends on the PRNG impl (threefry: 2 words,
            # rbg: 4), so the impl rides the checkpoint metadata
            value = jax.random.wrap_key_data(value, impl=prng_impl)
        node = tree
        *parents, leaf = path.split("/")
        for p in parents:
            node = node.setdefault(p, {})
        node[leaf] = value
    return tree


def save(path: str, state, next_block: int, config=None) -> None:
    """Write state + resume point (+ config echo for sanity checks).

    Atomic: writes ``path + '.tmp'`` then ``os.replace``s it, so a crash
    mid-save never corrupts the previous good checkpoint.  Writing through
    an open file object also keeps the exact filename (bare ``np.savez``
    silently appends '.npz', which would break resume-by-existence checks).
    """
    import os

    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.obs.profiler import annotate
    from tmhpvsim_tpu.runtime import faults

    with obs_metrics.get_registry().timed("checkpoint.save_s"), \
            annotate("tmhpvsim/checkpoint.save"):
        if faults.ACTIVE is not None:
            # "write" fires before anything touches disk (a failed save
            # must leave the previous good checkpoint intact)
            faults.fire("checkpoint.write")
        flat = _flatten(state)
        meta = {"next_block": int(next_block)}
        if config is not None:
            meta["prng_impl"] = getattr(config, "prng_impl",
                                        "threefry2x32")
            meta["config"] = _config_echo(config)
        else:
            # no config: infer the impl from the stored key_data layout
            # (threefry: 2 words, rbg: 4) so bare save()/load()
            # round-trips still reconstruct the right key type
            widths = {v.shape[-1] for k, v in flat.items()
                      if k.startswith(_KEY_PREFIX)}
            meta["prng_impl"] = "rbg" if widths == {4} else "threefry2x32"
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **flat, **{_META: json.dumps(meta)})
        os.replace(tmp, path)
        if faults.ACTIVE is not None:
            # "committed" fires after the atomic rename: a kill scheduled
            # here is the deterministic crash-with-valid-checkpoint the
            # recovery tests resume from
            faults.fire("checkpoint.committed")


def peek_meta(path: str) -> dict:
    """Read only the metadata record (resume point + config echo)."""
    with np.load(path, allow_pickle=False) as data:
        return json.loads(str(data[_META]))


def load(path: str, config=None) -> Tuple[dict, int]:
    """Read (state, next_block); verifies the config echo when given."""
    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.obs.profiler import annotate

    with obs_metrics.get_registry().timed("checkpoint.restore_s"), \
            annotate("tmhpvsim/checkpoint.restore"):
        return _load(path, config)


def _load(path: str, config=None) -> Tuple[dict, int]:
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(str(data[_META]))
        flat = {k: data[k] for k in data.files if k != _META}
    prng_impl = meta.get("prng_impl", "threefry2x32")
    if config is not None and "config" in meta:
        saved = meta["config"]
        # Echoes written before a key existed compare as that key's
        # then-implicit value, so old checkpoints stay resumable when the
        # echo schema grows (keys added in round 2 listed here).
        saved.setdefault("site_grid", None)
        saved.setdefault("output", "trace")
        saved.setdefault("prng_impl", "threefry2x32")
        # no rng_stream key = stream layout v1: deliberately NOT defaulted
        # to the current version, so pre-v2 checkpoints are refused rather
        # than resumed onto a different random stream
        saved.setdefault("rng_stream", 1)
        current = json.loads(json.dumps(_config_echo(config)))  # tuple->list
        if saved != current:
            keys = set(saved) | set(current)
            miss = object()
            diffs = {k: (saved.get(k, miss), current.get(k, miss))
                     for k in sorted(keys)
                     if saved.get(k, miss) != current.get(k, miss)}
            raise ValueError(
                f"checkpoint was written by a different configuration: "
                f"{diffs}"
            )
    return _unflatten(flat, prng_impl), meta["next_block"]
