"""Streaming scalar CPU model — the asyncio backend's simulator and the
float64 statistical ground truth for the JAX path.

A faithful re-derivation (not a port) of the reference's streaming model
stack: interpolated samplers advanced by a day/hour/minute rollover cascade
(clearskyindexmodel.py:101-126), the hourly cloud-cover sampler, the binary
renewal process, per-second composition (clearskyindexmodel.py:128-160),
and a blockwise-cached PV physics chain (pvmodel.py:38-87) built on
models/solar.py + models/pv.py with ``xp=numpy`` in float64.

Bug policy follows config.ModelOptions exactly as the JAX model does
(models/clearsky_index.py): the ``gamma.pdf`` NameError band is fixed to a
sample, branch assignment and the frozen cloudy sampler are reproduced by
default with opt-in fixes, and the hourly sampler draws i.i.d. single
Markov steps from state 1.0 unless ``persistent_cloud_chain`` (the
documented behaviour, default True) is on.

All randomness flows from one ``np.random.Generator`` — seedable, unlike
the reference's global scipy state (SURVEY.md §4 "no seeding").
"""

from __future__ import annotations

import datetime as _dt
from typing import Optional

import numpy as np

from tmhpvsim_tpu.config import ModelOptions, Site
from tmhpvsim_tpu.data import SANDIA_INVERTER, SAPM_MODULE
from tmhpvsim_tpu.models import pv as pvmod
from tmhpvsim_tpu.models.markov_hourly import transition_numpy
from tmhpvsim_tpu.models import solar
from tmhpvsim_tpu.models.clearsky_index import (
    CSI_CLEAR_DAY_LOC,
    CSI_CLEAR_DAY_SCALE,
    CSI_CLOUDY_GAMMA_HIGH,
    CSI_CLOUDY_GAMMA_MID,
    CSI_CLOUDY_NORM_LOC,
    CSI_CLOUDY_NORM_SCALE,
    NOISE_CLEAR,
    NOISE_CLOUDY,
    SIGMA_MIN_FACTOR,
    SIGMA_SEC_FACTOR,
)
from tmhpvsim_tpu.models.renewal import ReferenceRenewal


class _Sampler:
    """(before, after) pair with linear interpolation — the reference's
    InterpolatedSampler (clearskyindexmodel.py:12-40)."""

    def __init__(self, draw):
        self._draw = draw
        self.before = draw()
        self.after = draw()

    def advance(self):
        self.before = self.after
        self.after = self._draw()

    def interpolate(self, fraction: float) -> float:
        return (1.0 - fraction) * self.before + fraction * self.after


class GoldenClearskyIndex:
    """Streaming per-second clear-sky index, scalar float64.

    ``next(time)`` must be called with non-decreasing datetimes (the
    reference is driven at 1 Hz by fixedclock).
    """

    def __init__(self, time: _dt.datetime,
                 options: ModelOptions = ModelOptions(),
                 rng: Optional[np.random.Generator] = None):
        self.rng = rng if rng is not None else np.random.default_rng()
        self.options = options
        self._set_time(time, fire=False)

        # hourly cloud cover: persistent chain or the reference's accidental
        # i.i.d.-from-1.0 behaviour (clearskyindexmodel.py:61-63)
        self._cc_state = 1.0

        def draw_cc():
            nxt = transition_numpy(self.rng, self._cc_state)
            if self.options.persistent_cloud_chain:
                self._cc_state = nxt
            return nxt

        self.cloudcover_hour = _Sampler(draw_cc)
        self.clear_day = _Sampler(
            lambda: self.rng.normal(CSI_CLEAR_DAY_LOC, CSI_CLEAR_DAY_SCALE)
        )
        self.cloudy_hour = _Sampler(self._draw_cloudy)
        self.noise_min_cloudy = _Sampler(
            lambda: self._draw_minute_noise(*NOISE_CLOUDY)
        )
        self.noise_min_clear = _Sampler(
            lambda: self._draw_minute_noise(*NOISE_CLEAR)
        )
        self.windspeed_day = _Sampler(
            lambda: self.rng.gamma(2.69, 2.14)
        )
        self.renewal = ReferenceRenewal(
            self.cloudcover_hour.interpolate(0.0),
            self.windspeed_day.interpolate(0.0),
            self.rng,
        )

    # -- draw functions ------------------------------------------------

    def _draw_cloudy(self) -> float:
        """Cloudy-csi draw by cloud-cover band (clearskyindexmodel.py:68-84,
        NameError band fixed to a Gamma sample)."""
        cc = self.cloudcover_hour.interpolate(self._hour_fraction) \
            if hasattr(self, "cloudcover_hour") else 1.0
        if cc < 6 / 8:
            return self.rng.normal(CSI_CLOUDY_NORM_LOC, CSI_CLOUDY_NORM_SCALE)
        if cc < 7 / 8:
            a, s = CSI_CLOUDY_GAMMA_MID
        else:
            a, s = CSI_CLOUDY_GAMMA_HIGH
        return s * self.rng.gamma(a)

    def _draw_minute_noise(self, sigma0, sigma1) -> float:
        cc = self.cloudcover_hour.interpolate(self._hour_fraction) \
            if hasattr(self, "cloudcover_hour") else 1.0
        sigma = SIGMA_MIN_FACTOR * (sigma0 + sigma1 * 8.0 * cc)
        return self.rng.normal(1.0, sigma)

    # -- time cascade --------------------------------------------------

    def _set_time(self, time: _dt.datetime, fire: bool = True):
        min_fraction = time.second / 60.0
        self._hour_fraction = (time.minute + min_fraction) / 60.0
        self._day_fraction = (time.hour + self._hour_fraction) / 24.0
        self._min_fraction = min_fraction
        prev = getattr(self, "_time", None)
        self._time = time
        if not fire or prev is None:
            return
        if prev.day != time.day:
            self.clear_day.advance()
            self.windspeed_day.advance()
        if prev.hour != time.hour:
            self.cloudcover_hour.advance()
            self.clear_day.advance()
            if self.options.advance_cloudy_hour:
                self.cloudy_hour.advance()
        if prev.minute != time.minute:
            self.noise_min_cloudy.advance()
            self.noise_min_clear.advance()

    # -- per-second composition ----------------------------------------

    def next(self, time: _dt.datetime) -> float:
        """csi at ``time`` (clearskyindexmodel.py:128-160)."""
        self._set_time(time)
        cc = self.cloudcover_hour.interpolate(self._hour_fraction)

        self.renewal.update_parameters(
            cc, self.windspeed_day.interpolate(self._day_fraction)
        )
        covered = bool(next(self.renewal))
        #: exposed for the long-horizon parity harness (tests/test_parity.py)
        self.last_covered = covered

        # second-scale noise uses the clear sigmas in both branches
        # (clearskyindexmodel.py:152,158)
        s0, s1 = NOISE_CLEAR
        noise_sec = self.rng.normal(
            0.0, SIGMA_SEC_FACTOR * (s0 + s1 * 8.0 * cc)
        )

        use_clear = covered if not self.options.swap_covered_branches \
            else not covered
        if use_clear:
            base = self.clear_day.interpolate(self._day_fraction)
            nmin = self.noise_min_clear.interpolate(self._min_fraction)
        else:
            base = self.cloudy_hour.interpolate(self._hour_fraction)
            nmin = self.noise_min_cloudy.interpolate(self._min_fraction)
        return base * (nmin + noise_sec)


class GoldenPVModel:
    """Streaming AC power with blockwise physics precompute.

    The reference precomputes 5000-second blocks through its pvlib chain and
    serves ``next(time)`` from the cache (pvmodel.py:38-87).  Same scheme
    here, with the csi stream advanced sequentially and the physics applied
    vectorised in float64 over each block.
    """

    def __init__(self, time: _dt.datetime, site: Site = Site(),
                 options: ModelOptions = ModelOptions(),
                 rng: Optional[np.random.Generator] = None,
                 cache_s: int = 5000):
        self.site = site
        self.csi_model = GoldenClearskyIndex(time, options, rng)
        self.cache_s = cache_s
        self._tz = None  # lazily resolved ZoneInfo for local->epoch mapping
        self._cache_start = None
        self._cache = None
        self._fill(time)

    def _epoch(self, time: _dt.datetime) -> int:
        if time.tzinfo is None:
            from zoneinfo import ZoneInfo

            if self._tz is None:
                self._tz = ZoneInfo(self.site.timezone)
            time = time.replace(tzinfo=self._tz)
        return int(time.timestamp())

    def _fill(self, from_time: _dt.datetime):
        """Advance the csi stream ``cache_s`` seconds and run the physics."""
        csi = np.empty(self.cache_s)
        times = [from_time + _dt.timedelta(seconds=i)
                 for i in range(self.cache_s)]
        for i, t in enumerate(times):
            csi[i] = self.csi_model.next(t)

        epoch = np.asarray([self._epoch(t) for t in times], dtype=np.float64)
        doy = np.asarray([t.timetuple().tm_yday for t in times],
                         dtype=np.float64)
        geom = solar.block_geometry(epoch, doy, self.site, xp=np)
        ac = pvmod.power_from_csi(csi, geom, SAPM_MODULE, SANDIA_INVERTER,
                                  xp=np)
        self._cache_start = from_time
        self._cache = ac

    def next(self, time: _dt.datetime) -> float:
        """AC watts at ``time`` (whole-second, non-decreasing)."""
        i = int((time - self._cache_start).total_seconds())
        if i >= self.cache_s:
            self._fill(time)
            i = 0
        if i < 0:
            raise ValueError("GoldenPVModel.next requires monotonic time")
        return float(self._cache[i])
