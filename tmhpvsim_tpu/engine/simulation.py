"""Single-host blockwise simulation: the JAX-backend core loop.

The reference's pvsim joins two 1 Hz streams — a random meter-demand stream
(metersim.py:49-51) and the PV stream driven by the clear-sky-index model —
by timestamp, and appends ``time, meter, pv, residual load`` rows to a CSV
(pvsim.py:72-101).  Under the JAX backend both streams are generated on a
common time grid directly on device, so the reference's AMQP fan-out and
``SynchronizingFunnel`` collapse into array slots of one jitted block step
(SURVEY.md §2.4).

Execution layout (SURVEY.md §7 step 7):

* time is processed in fixed-size blocks of ``config.block_s`` seconds —
  the analogue of the reference's 5000-step ``populate_cache`` window
  (pvmodel.py:38-80), sized instead for device memory and dispatch overlap;
  ``block_s`` must be a multiple of 60 so every block spans the same number
  of minute-sampler values (constant shapes -> exactly one XLA compile);
* the time grid is *padded* to whole blocks, never shortened: padding rows
  are trimmed on the host after the gather;
* chain-independent per-block inputs — solar geometry (models/solar.py) and
  the calendar index arrays — are precomputed on the host in float64 (epoch
  seconds do not fit float32; ±64 s of quantisation would wreck the hour
  angle) and shipped as compact float32 arrays, O(block_s) bytes vs the
  O(n_chains × block_s) device-side work they parameterise;
* all chain state lives in one pytree carried block to block: sampler value
  arrays + renewal carry + per-chain keys.  Serialising it (plus the block
  offset) IS the checkpoint (SURVEY.md §5 "checkpoint/resume");
* every random draw is keyed by a *global* index (minute group for the
  per-second streams — one hash per minute, 60 counter-mode values — and
  sampler-value index for the slower samplers), so results are
  bit-identical under any block partition (block_s is always a multiple
  of 60) — verified by test_block_split_invariance and the engine
  block-size test.

The per-block device work is one fused computation: per-second csi scan
(VPU, O(1) carry) -> elementwise PV physics over (chains × block_s) ->
keyed meter draws -> residual; the only host traffic is the result gather
(trace mode) or per-chain running statistics (reduce mode).
"""

from __future__ import annotations

import contextlib
import dataclasses
import datetime as _dt
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.data import SANDIA_INVERTER, SAPM_MODULE
from tmhpvsim_tpu.obs import analytics as flt
from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs import telemetry as tel
from tmhpvsim_tpu.obs.profiler import BlockTimer, annotate, phase_scope
from tmhpvsim_tpu.models import clearsky_index as ci
from tmhpvsim_tpu.models import markov_hourly as mh
from tmhpvsim_tpu.models import pv as pvmod
from tmhpvsim_tpu.models import renewal
from tmhpvsim_tpu.models import solar
from tmhpvsim_tpu.models import tables as _tables
from tmhpvsim_tpu.models.timegrid import TimeGridSpec
from tmhpvsim_tpu.runtime import faults


@dataclasses.dataclass
class BlockResult:
    """One simulated block, gathered to host (trace mode).

    Arrays are (n_chains, length); ``epoch`` is (length,) int64 UTC epoch
    seconds; ``offset`` is the block start in simulation seconds.
    """

    offset: int
    epoch: np.ndarray
    meter: np.ndarray
    pv: np.ndarray
    residual: np.ndarray
    #: cross-chain ensemble statistics (set by ShardedSimulation)
    ensemble: dict = None


def _round_up(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


#: identity jit WITHOUT donation: XLA may not alias a non-donated input
#: to an output, so this returns fresh buffers.  The run loops pass
#: caller-provided resume pytrees through it before the first donating
#: dispatch, so donation never invalidates a reference the caller still
#: holds (tests/test_executor.py).
_copy_jit = jax.jit(lambda tree: tree)


class InputPrefetcher:
    """Overlap host-side block precompute with device compute.

    ``host_inputs`` is ~3 ms of float64 calendar + solar geometry per
    1080 s block on a 1-core host (benchmarks/PERF_ANALYSIS.md §4b) —
    negligible against a 50 ms wide block, co-limiting against a 4-6 ms
    scan-fused block, and fully serialised in trace mode where the
    per-block result gather blocks the main thread.  This one-slot
    prefetcher computes block bi+1's inputs in a worker thread while
    block bi's device work (and any host gather) is in flight.

    All computation runs in ONE worker thread, so ``host_inputs``'s
    internal state (the first-block ``_n_minute_vals`` latch) is accessed
    sequentially; the main thread only consumes finished results."""

    def __init__(self, sim: "Simulation", start_block: int, n_blocks: int):
        import concurrent.futures

        self._sim = sim
        self._n_blocks = n_blocks
        self._ex = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="host-inputs"
        )
        # a resumed run may have zero blocks left: nothing to prefetch
        self._slot = None if start_block >= n_blocks else (
            start_block, self._ex.submit(sim.host_inputs, start_block)
        )

    def get(self, block_i: int):
        """Inputs for ``block_i`` (prefetched if it was the expected next
        block), with block_i+1's prefetch kicked off before returning."""
        bi, fut = self._slot if self._slot is not None else (None, None)
        if bi != block_i:  # out-of-order consumer: compute directly
            fut = self._ex.submit(self._sim.host_inputs, block_i)
        if block_i + 1 < self._n_blocks:
            self._slot = (block_i + 1,
                          self._ex.submit(self._sim.host_inputs,
                                          block_i + 1))
        else:
            self._slot = None
        return fut.result()

    def close(self):
        self._ex.shutdown(wait=False, cancel_futures=True)


#: Reduce-mode statistics: one entry drives the accumulator init, the
#: per-block merge, both ensemble reductions and the summary-CSV columns —
#: add a statistic HERE and every consumer picks it up.
#: name -> (reduction kind, dtype kind); kinds: 'sum' | 'max' | 'min'.
REDUCE_STATS = {
    "pv_sum": ("sum", "f"),
    "pv_max": ("max", "f"),
    "meter_sum": ("sum", "f"),
    "residual_sum": ("sum", "f"),
    "residual_min": ("min", "f"),
    "residual_max": ("max", "f"),
    "n_seconds": ("sum", "i"),
}

#: float leaves of the scenario knob pytree (serve/: one (batch,) leaf
#: per knob in the compute dtype, plus an int32 ``horizon_s``).  Applied
#: per second INSIDE the scenario-batched fold as elementwise transforms
#: of the shared physics outputs — see ``_block_step_scan_scenario``;
#: ``serve.schema`` owns the request-side bounds and defaults.
SCENARIO_FLOAT_KNOBS = ("demand_scale", "demand_shift_w", "pv_scale",
                        "curtail_w", "weather_bias")


class Simulation:
    """Blockwise JAX simulation of ``config.n_chains`` independent sites.

    Usage::

        sim = Simulation(config)
        for block in sim.run_blocks():   # BlockResult per block, in order
            ...
        stats = sim.run_reduced()        # or: per-chain running statistics
    """

    def __init__(self, config: SimConfig, plan=None):
        if config.block_s % 60 != 0:
            raise ValueError("block_s must be a multiple of 60 (minute grid)")
        # Heterogeneous fleet (fleet/params.py): chain i simulates fleet
        # row i.  Non-uniform geometry derives the site grid; a
        # geometry-uniform fleet lowers onto the scalar-site path (its
        # shared Site and n_chains come from the fleet) so the traced
        # graph stays byte-identical to the no-fleet run.  A config that
        # already carries a site_grid of the same length (autotune probe
        # carves, explicit pairings) passes through untouched.
        if config.fleet is not None:
            fp = config.fleet
            if config.site_grid is None:
                if fp.uniform_geometry:
                    config = dataclasses.replace(
                        config, n_chains=len(fp), site=fp.uniform_site())
                else:
                    config = dataclasses.replace(
                        config, site_grid=fp.site_grid())
            elif len(config.site_grid) != len(fp):
                raise ValueError(
                    f"fleet has {len(fp)} sites but site_grid has "
                    f"{len(config.site_grid)} — they must pair 1:1 on "
                    "the chain axis")
        if config.site_grid is not None and \
                config.n_chains != len(config.site_grid):
            config = dataclasses.replace(
                config, n_chains=len(config.site_grid)
            )
        # slab bounds AFTER the site-grid override: the grid rewrites
        # n_chains, and a slab validated against the pre-override value
        # could silently slice short
        if config.n_chains_total is not None:
            if (config.chain_offset < 0 or
                    config.chain_offset + config.n_chains
                    > config.n_chains_total):
                raise ValueError(
                    f"chain slab [{config.chain_offset}, "
                    f"{config.chain_offset + config.n_chains}) outside "
                    f"n_chains_total={config.n_chains_total}"
                )
        elif config.chain_offset:
            raise ValueError("chain_offset requires n_chains_total")
        self.config = config
        # Resolve the execution plan (engine/autotune.py): static for
        # tune='off', measured/cached otherwise.  AFTER the site-grid
        # n_chains override, so probes and cache keys see the real batch.
        from tmhpvsim_tpu.engine import autotune

        self.plan = autotune.resolve_plan(config) if plan is None else plan
        #: the process-default metrics registry at construction time —
        #: apps that want an isolated per-run registry install it with
        #: obs.metrics.use_registry() BEFORE constructing the Simulation
        self.metrics = obs_metrics.get_registry()
        #: quiet internal block timer: apps keep their own (logging)
        #: BlockTimer as the single log voice; this one feeds the
        #: registry (engine.compile_s / engine.block_wall_s) and
        #: run_report()'s timing section
        self.timer = BlockTimer(config.n_chains, config.block_s,
                                log=False, registry=self.metrics,
                                prefix="engine")
        self._m_blocks = self.metrics.counter("engine.blocks_total")
        #: subclasses/callers with their own partitioning (the sharded
        #: mesh loop, checkpointed runs in apps/pvsim.py) clear this to
        #: keep run_reduced/run_ensemble from delegating to the
        #: SlabScheduler
        self.allow_slabs = True
        tz = (config.site_grid.timezone if config.site_grid is not None
              else config.site.timezone)
        self._padded_s = _round_up(config.duration_s, config.block_s)
        self.spec = TimeGridSpec.from_local_start(
            config.start, self._padded_s, tz
        )
        self.feats = ci.HostFeatures.from_spec(self.spec)
        self.dtype = jnp.dtype(config.dtype)
        #: mixed-precision compute path (Plan.compute_dtype): bf16
        #: applies to the pre-drawn per-second RNG streams, the
        #: shared-site geometry shipped by host_inputs and the csi handed
        #: to the physics chain; the scan carry, the time inputs and
        #: every accumulator stay f32/int32 (merge bit-exactness + the
        #: drift sentinel remain the correctness gate).  getattr: plans
        #: rebuilt from pre-precision cache entries predate the fields.
        self._mixed = getattr(self.plan, "compute_dtype", "f32") == "bf16"
        self._compute_dtype = (jnp.dtype(jnp.bfloat16) if self._mixed
                               else self.dtype)
        #: transcendental-kernel set for the solar/pv models
        #: (models/tables.py Plan.kernel_impl); None makes every model
        #: call trace the raw jnp ops — byte-identical historical HLO.
        self._kernels = (_tables.table_kernels(jnp)
                         if getattr(self.plan, "kernel_impl",
                                    "exact") == "table" else None)
        #: whole-block RNG pre-generation (Plan.rng_batch): 'block'
        #: hoists every second-noise draw out of the scan body into
        #: batched counter-mode tensors generated before the scan —
        #: same fold_in keying, bit-identical values
        #: (tests/test_rng_batch.py); 'scan' leaves every block impl's
        #: historical graph byte-identical.  getattr: plans rebuilt
        #: from pre-v11 autotune cache entries predate the field.
        self._rng_batch = getattr(self.plan, "rng_batch", "scan")
        #: strided solar geometry (Plan.geom_stride): evaluate the
        #: transcendental chain every s seconds and lerp the trig-free
        #: fields to 1 Hz (solar.STRIDE_LERP_FIELDS, published bound
        #: solar.STRIDE_MAX_ABS_ERR); 1 is byte-identical HLO.
        self._geom_stride = int(getattr(self.plan, "geom_stride", 1))
        if self._geom_stride > 1 and config.block_s % self._geom_stride:
            raise ValueError(
                f"geom_stride {self._geom_stride} must divide "
                f"block_s {config.block_s}")
        #: semantic phase scopes (SimConfig.phase_obs, obs/attribution):
        #: a PER-INSTANCE host-static flag — ``_phase`` consults it at
        #: trace time, so 'off' enters no ``jax.named_scope`` anywhere
        #: and the lowered HLO stays byte-identical
        #: (tests/test_attribution.py), while a module-global flag would
        #: leak scopes into other sims' lazily-retraced jits
        self._phase_obs = getattr(config, "phase_obs", "off") != "off"
        # rbg trap (benchmarks/PERF_ANALYSIS.md §7a): rbg/unsafe_rbg
        # keys serialize the vmapped per-chain draws on current TPU
        # backends — a measured ~76x block-step regression vs threefry.
        # Warn loudly at build time; refuse under the strict gate.
        if config.prng_impl in ("rbg", "unsafe_rbg"):
            _msg = (
                f"prng_impl={config.prng_impl!r}: rbg keys serialize the "
                "vmapped per-chain draws on current TPU backends (~76x "
                "slower block steps than threefry2x32, "
                "benchmarks/PERF_ANALYSIS.md §7a); use threefry2x32 "
                "unless you are measuring the trap itself"
            )
            if getattr(config, "telemetry_strict", False):
                raise ValueError(_msg)
            import warnings

            warnings.warn(_msg, RuntimeWarning, stacklevel=2)
        #: double-buffered trace output (_iter_blocks): overlap the host
        #: gather of block N with device dispatch of block N+1
        ov = getattr(config, "output_overlap", "auto")
        if ov not in ("auto", "off"):
            raise ValueError(
                f"output_overlap must be 'auto' or 'off', got {ov!r}")
        self._output_overlap = ov != "off"
        self.n_blocks = self._padded_s // config.block_s
        self._n_minute_vals = None  # fixed after first block (constant shape)
        # Static per-block sampler-window sizes (windowed arrays: the state
        # carries only RNG keys + a Markov carry, and each block
        # regenerates the hourly/daily sampler values its seconds touch —
        # every draw is keyed by GLOBAL value index, so windows reproduce
        # the same values as a full-run precompute.  Memory is O(block),
        # not O(duration): the property that makes 10-year x 1M-chain runs
        # feasible).  Bounds: a block of block_s seconds spans at most
        # block_s//3600 + 1 hour intervals; +1 early start (cloudy draws
        # read cc[k-1]), +2 interpolation upper values, +1 slack, checked
        # per block in host_inputs.
        bs = config.block_s
        self._w_hours = bs // 3600 + 5
        self._w_days = bs // 86400 + 3
        self._w_cd = self._w_hours + self._w_days

        root = jax.random.key(config.seed, impl=config.prng_impl)
        self._k_chains, _ = jax.random.split(root)
        self._block_jit = jax.jit(self._block_step, donate_argnums=0)
        self._stats_jit = jax.jit(self._block_stats)
        # donate meter/pv too: the block arrays are dead after the fold
        # (the tel path computes its fold BEFORE this jit), so their
        # O(n_chains x block_s) buffers are reusable immediately
        self._stats_acc_jit = jax.jit(self._block_stats_acc,
                                      donate_argnums=(0, 1, 3))
        #: reduce-mode fused path: producer + stats + merge in ONE jit so
        #: the (n_chains, block_s) meter/pv arrays never reach HBM (see
        #: SimConfig.stats_fusion); state and accumulator are donated so
        #: XLA reuses their buffers block to block
        self._fused_acc_jit = jax.jit(self._step_acc_fused,
                                      donate_argnums=(0, 2))
        #: reduce-mode scan-fused path (SimConfig.block_impl='scan'): the
        #: whole per-second pipeline inside one lax.scan, statistics in
        #: the carry — the TPU formulation (the wide one is HBM-bound)
        self._scan_acc_jit = jax.jit(self._block_step_scan_acc,
                                     donate_argnums=(0, 2))
        self._scan2_acc_jit = jax.jit(self._block_step_scan2_acc,
                                      donate_argnums=(0, 2))
        self._scan_series_jit = jax.jit(self._block_step_scan_series,
                                        donate_argnums=0)
        self._scan2_series_jit = jax.jit(self._block_step_scan2_series,
                                         donate_argnums=0)
        # the RESOLVED knobs come from the plan (auto heuristics, a probe,
        # or a cache entry — engine/autotune.py), not the raw config
        self._use_fused = self.plan.stats_fusion == "fused"
        self._impl = self.plan.block_impl
        self._unroll = self.plan.scan_unroll
        #: scan-family impls share the ensemble series path and labels
        self._use_scan = self._impl in ("scan", "scan2")
        self._series_jit = jax.jit(self._ensemble_series)
        #: memoized jitted initializers keyed by (kind, sharding) — a fresh
        #: jax.jit(closure) per call would never hit the trace cache, which
        #: matters for per-block users of step_reduced/init_reduce_acc
        self._init_jits = {}
        #: in-graph telemetry (obs/telemetry.py): dedicated tel jits are
        #: built ONLY when enabled and the off-path jits above are never
        #: touched, so telemetry='off' lowers to byte-identical HLO
        #: (asserted by tests/test_telemetry.py)
        self._telemetry = getattr(self.plan, "telemetry", "off")
        self._tel_last = None
        #: the DriftSentinel once telemetry has observed a block
        #: (obs/sentinel.py); run_report() embeds its verdict
        self.sentinel = None
        if self._telemetry != "off":
            self._scan_acc_tel_jit = jax.jit(
                self._block_step_scan_acc_tel, donate_argnums=(0, 2)
            )
            self._scan2_acc_tel_jit = jax.jit(
                self._block_step_scan2_acc_tel, donate_argnums=(0, 2)
            )
            self._wide_tel_jit = jax.jit(self._wide_telemetry)
        #: on-device fleet analytics (obs/analytics.py): same build
        #: discipline as telemetry — analytics jits exist only when the
        #: level is on, the off-path jits are never touched, and each
        #: tel x analytics combination has its own fused block step so
        #: the carry stays a single scan
        self._analytics = getattr(self.plan, "analytics", "off")
        self._fleet_last = None
        self._fleet_total = None
        self._fleet_params = None
        if self._analytics != "off":
            self._fleet_params = flt.params_from_config(self.config)
            if self._telemetry != "off":
                self._scan_acc_tel_fleet_jit = jax.jit(
                    self._block_step_scan_acc_tel_fleet,
                    donate_argnums=(0, 2))
                self._scan2_acc_tel_fleet_jit = jax.jit(
                    self._block_step_scan2_acc_tel_fleet,
                    donate_argnums=(0, 2))
            else:
                self._scan_acc_fleet_jit = jax.jit(
                    self._block_step_scan_acc_fleet, donate_argnums=(0, 2))
                self._scan2_acc_fleet_jit = jax.jit(
                    self._block_step_scan2_acc_fleet, donate_argnums=(0, 2))
            self._wide_fleet_jit = jax.jit(self._wide_fleet)
        #: heterogeneous-fleet gating (fleet/params.py): host-static
        #: flags decide which per-chain parameter leaves enter the state
        #: pytree (init_state) and which transforms are traced into the
        #: block steps.  An absent fleet — or one whose column is
        #: uniform at the neutral value — sets no flag, adds no leaf and
        #: traces no transform, so the homogeneous path lowers to
        #: byte-identical HLO vs the scalar configuration
        #: (tests/test_fleet.py).
        fp = config.fleet
        self._fleet = fp
        self._het_demand = fp is not None and fp.het_demand
        self._het_power = fp is not None and fp.het_power
        self._het_regime = fp is not None and fp.het_regime
        #: stacked per-regime Markov step tables, built only when some
        #: chain leaves regime 0 (row 0 is the Munich fit byte-for-byte)
        self._regime_params = (mh.regime_step_params(self.dtype)
                               if self._het_regime else None)
        #: per-cohort analytics group-by (obs/analytics.py): active only
        #: when analytics is on AND the fleet has >= 2 cohorts
        self._n_cohorts = (fp.n_cohorts
                           if fp is not None and self._analytics != "off"
                           and fp.n_cohorts > 1 else 0)
        #: multi-block fused dispatch factor (Plan.blocks_per_dispatch):
        #: K consecutive blocks run as one outer lax.scan in a single
        #: jit, so the host pays one dispatch per K blocks.  getattr:
        #: plans rebuilt from pre-v4 autotune cache entries may predate
        #: the field.
        self._k_dispatch = max(1, int(getattr(self.plan,
                                              "blocks_per_dispatch", 1)))
        #: memoized mega jits keyed by (kind, k) — the final partial
        #: group of a run compiles a second (smaller-k) variant, so at
        #: most two compiled shapes exist per kind per run
        self._mega_jits = {}
        #: scenario-serving dispatch (serve/): the jit and its fleet
        #: params are built lazily on first use — batch runs pay nothing
        self._scenario_jit = None
        self._scn_fleet_params = None
        #: block index B such that ``self.state`` is the state AFTER
        #: block B-1 — i.e. blocks [0, B) are folded into it.  Under
        #: multi-block dispatch the state only advances at megablock
        #: boundaries while per-block results/callbacks still fire, so
        #: checkpoint writers MUST gate saves on
        #: ``sim.state_block == block_index + 1`` (apps/pvsim.py does).
        self.state_block = 0
        self._m_dispatch = self.metrics.counter("executor.dispatches_total")
        self.metrics.gauge("executor.blocks_per_dispatch").set(
            self._k_dispatch)
        #: pod observability (obs/pod.py): the monitor is constructed
        #: lazily at the FIRST block boundary (the sharded subclass's
        #: mesh exists by then) and only when the axis is on — 'off'
        #: builds nothing, gathers nothing, stamps nothing, so the
        #: lowered HLO is byte-identical (tests/test_pod_obs.py)
        self._pod = None
        self._pod_on = getattr(config, "pod_obs", "off") != "off"
        #: per-phase device-time split (obs/attribution.py): host-set by
        #: whoever captured + attributed a scoped trace of this sim
        #: (bench.py's attribution mode); run_report() embeds it as the
        #: v15 ``attribution`` section and publishes ``device.phase.*``
        self.attribution = None
        if not getattr(self, "_defer_warm_start", False):
            self._warm_start()

    def _warm_start(self) -> None:
        """AOT plan warm-up (engine/compilecache.py): pre-lower and
        compile the resolved plan's block functions so the persistent
        compile cache is populated before the first real dispatch.
        No-op unless ``compilecache.configure()`` ran in this process.
        The sharded subclass sets ``_defer_warm_start`` and calls this
        after rebinding its jits to the shard_map builds."""
        from tmhpvsim_tpu.engine import compilecache

        compilecache.maybe_warm_up(self)

    # ------------------------------------------------------------------
    # chain state
    # ------------------------------------------------------------------

    def init_state(self, sharding=None):
        """Initial carried pytree for all chains.  With the block offset
        this is a complete checkpoint of the simulation — and it is
        O(1) PER CHAIN regardless of run duration: sampler values are
        regenerated per block from global-index-keyed draws (windowed
        arrays, see __init__), so the state holds only the per-chain RNG
        keys, the Markov-chain carry, the renewal carry, and three
        construction-time scalars (cc0 + the frozen cloudy pair the
        reference-compat mode interpolates forever).

        ``sharding`` (a NamedSharding over the chain axis) is applied as
        the jit's ``out_shardings`` so every leaf — including the site
        scalars — is born with the right layout.  That is the only
        construction that also works on a multi-host mesh, where
        ``jax.device_put`` cannot target the other hosts' devices."""
        opts = self.config.options
        feats = self.feats
        dtype = self.dtype
        grid = self.config.site_grid

        def one(key, regime=None):
            k_arr, k_min, k_renew, k_scan, k_meter = jax.random.split(key, 5)
            k_cc, k_cloudy, _k_day, k_ws = jax.random.split(k_arr, 4)
            # construction-time primer values (global indices 0, 1): the
            # renewal process starts from the samplers' *before* values
            # (clearskyindexmodel.py:98-99), cc0 is the construction-time
            # cloud-cover interpolation every k<2 cloudy draw sees, and
            # the cloudy pair is what compat mode interpolates forever.
            # Heterogeneous weather regimes prime from the chain's own
            # step table (regime 0 == the default table byte-for-byte).
            params = (None if regime is None
                      else mh.select_regime(self._regime_params, regime))
            cc01, _ = ci.cc_window(k_cc, 0, 2, jnp.asarray(1.0, dtype),
                                   opts, dtype, params=params)
            cc0 = cc01[0] * (1 - feats.f0_hour) + cc01[1] * feats.f0_hour
            ws0 = ci.ws_window(k_ws, 0, 1, dtype)[0]
            carry = renewal.init(k_renew, cc01[0], ws0, dtype)
            return {
                "cc_carry": jnp.asarray(1.0, dtype),  # state before hour 0
                "cc0": cc0,
                "cloudy_pair": ci.cloudy_window(k_cloudy, 0, 2, cc01, 0,
                                                cc0, dtype),
                "carry": carry,
                "k_arr": k_arr,
                "k_min": k_min,
                "k_scan": k_scan,
                "k_meter": k_meter,
            }

        def build():
            cfg = self.config
            # Chain slabs: keys come from the NOTIONAL total-run split,
            # sliced at the slab offset — threefry split is counter-based,
            # so split(k, total)[off:off+n] gives the slab the exact keys
            # those chains would get in the unslabbed run, making slab
            # concatenation bit-identical to it (SimConfig.n_chains_total).
            total = cfg.n_chains_total or cfg.n_chains
            keys = jax.random.split(self._k_chains, total)
            if total != cfg.n_chains or cfg.chain_offset:
                keys = keys[cfg.chain_offset:cfg.chain_offset
                            + cfg.n_chains]
            fp = self._fleet
            regime = (jnp.asarray(fp.weather_regime, jnp.int32)
                      if self._het_regime else None)
            state = (jax.vmap(one)(keys, regime)
                     if regime is not None else jax.vmap(one)(keys))
            # Heterogeneous fleet leaves (only the columns that ARE
            # heterogeneous — the absent-key discipline keeps the
            # homogeneous traced graph byte-identical): like the site
            # scalars below, they live in the state pytree so they get
            # the chain sharding, ride through shard_map specs, and land
            # in checkpoints without special-casing.  Broadcast rule:
            # leaf i pairs with chain i; slabs/shards carry the slice
            # their chains own (slice_fleet).
            fleet = {}
            if self._het_demand:
                fleet["demand_scale"] = jnp.asarray(fp.demand_scale, dtype)
                fleet["demand_shift_w"] = jnp.asarray(fp.demand_shift_w,
                                                      dtype)
            if self._het_power:
                fleet["pv_scale"] = jnp.asarray(fp.dc_capacity_scale,
                                                dtype)
                fleet["ac_limit_w"] = jnp.asarray(fp.ac_limit_w, dtype)
            if regime is not None:
                fleet["regime"] = regime
            if self._n_cohorts:
                fleet["cohort"] = jnp.asarray(fp.cohort, jnp.int32)
            if fleet:
                state["fleet"] = fleet
            if grid is not None:
                # per-chain site parameters live in the state pytree: they
                # get the chain sharding, ride through shard_map specs, and
                # land in checkpoints without any special-casing
                state["site"] = {
                    "latitude": jnp.asarray(grid.latitude, dtype),
                    "longitude": jnp.asarray(grid.longitude, dtype),
                    "altitude": jnp.asarray(grid.altitude, dtype),
                    "surface_tilt": jnp.asarray(grid.surface_tilt, dtype),
                    "surface_azimuth": jnp.asarray(grid.surface_azimuth,
                                                   dtype),
                    "albedo": jnp.asarray(grid.albedo, dtype),
                }
            return state

        return self._memo_jit("state", sharding, build)()

    def _memo_jit(self, kind, sharding, build):
        """One jitted zero-arg initializer per (kind, sharding).

        On a fully-addressable (single-host) mesh the sharding is applied
        by ``device_put`` AFTER an unsharded compile rather than as
        ``out_shardings``: compiling the initializer through the SPMD
        partitioner trips a dtype verifier bug in jax 0.4.x gamma/t
        while-loops (s64 vs s32 compare), and the layout of a one-shot
        initializer is not perf-critical.  Multi-host meshes keep
        ``out_shardings`` — ``device_put`` cannot target other hosts'
        devices there (and the partitioner path is required anyway).
        """
        key = (kind, sharding)
        fn = self._init_jits.get(key)
        if fn is None:
            if sharding is not None and getattr(
                sharding, "is_fully_addressable", True
            ):
                inner = jax.jit(build)

                def fn(_inner=inner, _sh=sharding):
                    return jax.device_put(_inner(), _sh)
            else:
                fn = jax.jit(build, out_shardings=sharding)
            self._init_jits[key] = fn
        return fn

    # ------------------------------------------------------------------
    # host-side per-block inputs (chain-independent, float64 precompute)
    # ------------------------------------------------------------------

    def host_inputs(self, block_i: int):
        """All chain-independent device inputs for one block.

        Geometry is evaluated here in float64 numpy — it is O(block_s) and
        shared by every chain — then cast to the compute dtype.

        Sampler indices (hour/day/pair) are REBASED to the block's sampler
        windows (``inputs["win"]``): the device step regenerates exactly
        the window of hourly/daily values this block touches from
        global-index-keyed draws, so the rebased index into the window
        reads the same value a full-run precompute would hold at the
        global index (windowed arrays, __init__).
        """
        cfg = self.config
        off = block_i * cfg.block_s
        blk = self.spec.block(off, cfg.block_s)
        block_idx, (mlo, mhi) = ci.host_block_index(
            self.spec, off, cfg.block_s, self.dtype, blk=blk
        )
        if self._n_minute_vals is None:
            self._n_minute_vals = mhi - mlo
        if mhi - mlo != self._n_minute_vals:
            raise AssertionError(
                "minute-value count changed across blocks; block_s must keep "
                "the minute grid aligned"
            )
        h_idx, h_frac = self.spec.minute_value_features(mlo, mhi)

        # --- sampler-window bounds (host ints) + index rebasing
        hb = int(blk.hour_idx[0])
        he = int(blk.hour_idx[-1])
        db = int(blk.day_idx[0])
        de = int(blk.day_idx[-1])
        hour_lo = max(hb - 1, 0)  # cloudy value k reads cc[k-1]
        day_lo = db
        cd_lo = hour_lo + day_lo  # rebased pair index (h-hour_lo)+(d-day_lo)
        hour_hi_need = max(he + 1, int(h_idx.max()) + 1)  # interp upper
        # Real exceptions, not asserts: under ``python -O`` an assert
        # vanishes and an out-of-window index would be silently CLAMPED by
        # JAX's gather semantics on device — wrong sampler values instead
        # of a loud failure (e.g. an unusual DST/calendar layout).
        if hour_hi_need - hour_lo + 1 > self._w_hours:
            raise RuntimeError(
                f"hour sampler window overflow in block {block_i}: need "
                f"[{hour_lo}, {hour_hi_need}] > {self._w_hours} slots"
            )
        if de + 1 - day_lo + 1 > self._w_days:
            raise RuntimeError(
                f"day sampler window overflow in block {block_i}: need "
                f"[{day_lo}, {de + 1}] > {self._w_days} slots"
            )
        if he + de + 1 - cd_lo + 1 > self._w_cd:
            raise RuntimeError(
                f"clear-day sampler window overflow in block {block_i}: "
                f"need [{cd_lo}, {he + de + 1}] > {self._w_cd} slots"
            )
        if block_i + 1 < self.n_blocks:
            nxt = self.spec.block((block_i + 1) * cfg.block_s, 1)
            hour_next_lo = max(int(nxt.hour_idx[0]) - 1, 0)
        else:
            hour_next_lo = hour_lo  # last block: carry stays put

        # Every leaf is HOST numpy with its final dtype: the jit call
        # transfers them at dispatch, skipping ~26 eager per-leaf
        # jnp.asarray dispatches per block (~70% of measured host_inputs
        # cost).  Same avals (numpy is never weakly typed), so no
        # recompiles; same IEEE casts, so bit-identical values.
        block_idx["hour_idx"] = block_idx["hour_idx"] - np.int32(hour_lo)
        block_idx["day_idx"] = block_idx["day_idx"] - np.int32(day_lo)
        mfeats = (
            np.asarray(h_idx - hour_lo, np.int32),
            np.asarray(h_frac, self.dtype),
        )

        inputs = {
            "block_idx": block_idx,
            "mlo": np.int32(mlo),
            "mfeats": mfeats,
            "win": {
                "hour_lo": np.int32(hour_lo),
                "hour_next_lo": np.int32(hour_next_lo),
                "day_lo": np.int32(day_lo),
                "cd_lo": np.int32(cd_lo),
            },
        }
        if cfg.site_grid is None:
            # shared site: exact float64 geometry on the host, cast once.
            # Under the mixed path the cast target is bf16 (except doy,
            # whose integer-day semantics feed the Spencer term/LUT and
            # must survive exactly) so the physics chain's type promotion
            # stays in the compute dtype instead of silently widening.
            # geom_stride>1 swaps in the stride-sampled + lerped float64
            # evaluation (solar.strided_block_geometry) — a pure
            # host-time lever here: the shipped dict has the same shapes
            # and dtypes, so the device graph is untouched.
            ep64 = blk.epoch.astype(np.float64)
            doy64 = blk.doy.astype(np.float64)
            if self._geom_stride > 1:
                geom64 = solar.strided_block_geometry(
                    ep64, doy64, cfg.site, self._geom_stride, xp=np,
                )
            else:
                geom64 = solar.block_geometry(ep64, doy64, cfg.site, xp=np)
            inputs["geom"] = {
                k: (np.asarray(v, self.dtype if k == "doy"
                               else self._compute_dtype)
                    if isinstance(v, np.ndarray) else v)
                for k, v in geom64.items()
            }
        else:
            # per-chain sites: ship the float32-safe split time; geometry
            # is evaluated on device per chain (solar.device_geometry)
            inputs["time_split"] = {
                "day2000": np.asarray(blk.epoch // 86400 - 10957,
                                      self.dtype),
                "sec_of_day": np.asarray(blk.epoch % 86400, self.dtype),
                "doy": np.asarray(blk.doy, self.dtype),
            }
            if self._geom_stride > 1:
                # stride-sampled split time (T//s + 1 rows) for the
                # device-side sample-outside-the-scan evaluation, plus
                # the per-second (sample index, fraction) lerp features.
                # The endpoint row is the exact next second after the
                # block (epoch arithmetic is exact in int64); its doy is
                # clamped to the block's last second — see
                # solar.strided_block_geometry on why that seam is
                # inside the published bounds.
                s = self._geom_stride
                ep_s = np.concatenate([blk.epoch[::s], blk.epoch[-1:] + 1])
                doy_s = np.concatenate([blk.doy[::s], blk.doy[-1:]])
                inputs["time_split_s"] = {
                    "day2000": np.asarray(ep_s // 86400 - 10957,
                                          self.dtype),
                    "sec_of_day": np.asarray(ep_s % 86400, self.dtype),
                    "doy": np.asarray(doy_s, self.dtype),
                }
                pos = np.arange(cfg.block_s)
                inputs["gs"] = {
                    "i": np.asarray(pos // s, np.int32),
                    "f": np.asarray((pos % s) / s, self._compute_dtype),
                }
        return inputs, blk.epoch

    # ------------------------------------------------------------------
    # device block step (jitted once; shapes constant across blocks)
    # ------------------------------------------------------------------

    def _phase(self, name: str):
        """Semantic-phase scope for trace-time code (obs/attribution):
        a ``jax.named_scope('ph__<name>')`` when ``phase_obs`` is on,
        else a nullcontext — the off path enters nothing, so its
        lowered HLO is byte-identical to a build without the axis.
        Also passed into the models entry points (solar/pv/
        clearsky_index ``scope=`` kwarg) so the stages a model owns are
        scoped where they are computed."""
        if self._phase_obs:
            return phase_scope(name)
        return contextlib.nullcontext()

    def _windows_one_chain(self, chain, inputs):
        """Regenerate ONE chain's sampler windows for one block (traced).

        Returns (arrays, minute_vals, new_cc_carry): the window arrays have
        the same structure as a full-run ``build_chain_arrays`` result but
        length O(block); indices arriving in ``inputs`` are already rebased
        to them (host_inputs).  The Markov carry is advanced to the next
        block's window start by selecting the already-generated state —
        blocks re-run from a checkpoint resume bit-identically because
        every draw is keyed by global index."""
        cfg = self.config
        dtype = self.dtype
        win = inputs["win"]
        k_cc, k_cloudy, k_day, k_ws = jax.random.split(chain["k_arr"], 4)

        # heterogeneous weather regimes: gather this chain's Markov step
        # table from the stacked regime leaves (one (R, 6)->(6,) take per
        # leaf under the chain vmap); None traces the historical graph
        with self._phase("markov"):
            params = (mh.select_regime(self._regime_params,
                                       chain["fleet"]["regime"])
                      if self._het_regime else None)
            cc_w, _ = ci.cc_window(k_cc, win["hour_lo"], self._w_hours,
                                   chain["cc_carry"], cfg.options, dtype,
                                   params=params)
            nxt, lo = win["hour_next_lo"], win["hour_lo"]
            adv = jnp.clip(nxt - lo - 1, 0, self._w_hours - 1)
            cc_carry = jnp.where(nxt == lo, chain["cc_carry"], cc_w[adv])

            arrays = {
                "cc": cc_w,
                "cloudy": ci.cloudy_window(k_cloudy, lo, self._w_hours,
                                           cc_w, lo, chain["cc0"], dtype),
                "clear_day": ci.clear_day_window(k_day, win["cd_lo"],
                                                 self._w_cd, dtype),
                "ws": ci.ws_window(k_ws, win["day_lo"], self._w_days,
                                   dtype),
            }
        with self._phase("rng"):
            mvals = ci.minute_noise_values_device(
                chain["k_min"], cc_w, inputs["mlo"], inputs["mfeats"],
                dtype
            )
        return arrays, mvals, cc_carry

    def _narrow_geom(self, geom):
        """Device-geometry dict narrowed to the compute dtype (mixed
        path; identity otherwise).  Geometry is always EVALUATED in f32
        — split-time inputs would not survive bf16's 8-bit mantissa —
        and only the result narrows, so the per-chain physics promotes
        to bf16 instead of silently widening back.  ``doy`` keeps its
        exact integer-valued representation (Spencer term / LUT index).
        """
        if not self._mixed:
            return geom
        cd = self._compute_dtype
        return {k: (v if k == "doy" else v.astype(cd))
                for k, v in geom.items()}

    def _block_step(self, state, inputs):
        """(state, inputs) -> (state', meter, pv), all on device.

        Two geometry modes (see ``host_inputs``): shared-site runs receive
        precomputed float64-host geometry in ``inputs["geom"]``; site-grid
        runs receive the float32-safe split time in ``inputs["time_split"]``
        and evaluate :func:`solar.device_geometry` per chain from the
        per-chain site scalars carried in ``state["site"]`` (vmapped, so
        the grid's geometry is one batched VPU computation on device).

        Residual load is deliberately NOT computed here: adding
        ``meter - pv`` as one more consumer of both streams makes XLA:CPU
        duplicate the whole RNG/csi/physics producer chain into a second
        fusion (measured: 2.56 vs 1.13 GFLOP compiled, ~3.5x wall time).
        Consumers derive it outside this jit — on the host in trace mode
        (``run_blocks``), in the separate ``_block_stats`` jit in reduce
        mode, where the inputs are materialised arrays and nothing can be
        re-fused backwards.
        """
        cfg = self.config
        block_idx = inputs["block_idx"]
        mlo = inputs["mlo"]
        dtype = self.dtype
        shared_geom = inputs.get("geom")
        strided = shared_geom is None and self._geom_stride > 1
        if shared_geom is None:
            ts = inputs["time_split"]
            turbidity = jnp.asarray(
                cfg.site_grid.linke_turbidity_monthly, dtype
            )
            if strided:
                tss = inputs["time_split_s"]
                gi, gf = inputs["gs"]["i"], inputs["gs"]["f"]

        def one_chain(chain, pre):
            if shared_geom is not None:
                geom = shared_geom
            else:
                site = chain["site"]
                td = tss if strided else ts
                geom = solar.device_geometry(
                    td["day2000"], td["sec_of_day"], td["doy"],
                    site["latitude"], site["longitude"], site["altitude"],
                    site["surface_tilt"], site["surface_azimuth"],
                    site["albedo"], turbidity, xp=jnp,
                    kernels=self._kernels, scope=self._phase,
                )
                geom = self._narrow_geom(geom)
                if strided:
                    # sample-grid evaluation above, lerp back to 1 Hz;
                    # doy stays the exact per-second value and the site
                    # scalars ride through (already compute-dtype)
                    g = solar.interp_sampled(geom, gi, gf, xp=jnp,
                                             scope=self._phase)
                    g["doy"] = jnp.asarray(ts["doy"])
                    g["surface_tilt"] = geom["surface_tilt"]
                    g["albedo"] = geom["albedo"]
                    geom = g
            arrays, mvals, cc_carry = self._windows_one_chain(chain, inputs)
            with self._phase("csi"):
                carry, csi, _covered = ci.csi_scan_block(
                    chain["k_scan"], arrays, mvals, mlo,
                    chain["carry"], block_idx, cfg.options, dtype,
                    unroll=self._unroll,
                    cloudy_pair=chain["cloudy_pair"],
                    draws=None if pre is None else (pre["u"], pre["z"]),
                )
                if self._mixed:
                    csi = csi.astype(self._compute_dtype)
            ac = pvmod.power_from_csi(
                csi, geom, SAPM_MODULE, SANDIA_INVERTER, xp=jnp,
                kernels=self._kernels, scope=self._phase,
            )
            if self._mixed:
                # back to the carry/accumulator dtype: every downstream
                # contract (stats fold, traces, telemetry) stays f32
                ac = ac.astype(dtype)
            # one hash per global minute + counter-mode 60-draws: see
            # ci.csi_scan_block on why (threefry cost dominates the block)
            with self._phase("rng"):
                meter = (pre["meter"] if pre is not None
                         else ci.meter_block(chain["k_meter"],
                                             block_idx["t"],
                                             cfg.meter_max_w, dtype))
            # heterogeneous per-site transforms (fleet/params.py): DC
            # capacity scale + inverter AC clip on pv, demand scale/shift
            # on the meter — traced only when the column is heterogeneous
            with self._phase("fleet"):
                if self._het_power:
                    fl = chain["fleet"]
                    ac = jnp.minimum(ac * fl["pv_scale"], fl["ac_limit_w"])
                if self._het_demand:
                    fl = chain["fleet"]
                    meter = (meter * fl["demand_scale"]
                             + fl["demand_shift_w"])
            return dict(chain, carry=carry, cc_carry=cc_carry), meter, ac

        pre = None
        if self._rng_batch == "block":
            # whole-block hoist (Plan.rng_batch='block'): the identical
            # minute-grouped counter draws, batched across chains BEFORE
            # the per-chain vmap — bit-identical values
            # (tests/test_rng_batch.py).  pre=None (the default) has no
            # pytree leaves, so the 'scan' graph stays byte-identical.
            t = block_idx["t"]
            with self._phase("rng"):
                u_all, z_all = jax.vmap(
                    lambda k: ci.block_draws(k, t, dtype))(state["k_scan"])
                meter_all = jax.vmap(
                    lambda k: ci.meter_block(k, t, cfg.meter_max_w, dtype)
                )(state["k_meter"])
            pre = {"u": u_all, "z": z_all, "meter": meter_all}
        return jax.vmap(one_chain)(state, pre)

    def _block_stats(self, meter, pv, t):
        """Per-chain statistics of one block from the *materialised* meter
        and pv arrays (its own jit — see ``_block_step`` on why residual
        must not share the producer jit).  Grid-padding seconds (global
        index >= duration) are masked out."""
        residual = meter - pv
        valid = (t < self.config.duration_s)
        nv = valid.sum()
        big = jnp.asarray(jnp.finfo(self.dtype).max, self.dtype)
        vz = jnp.where(valid, 1.0, 0.0).astype(self.dtype)
        return {
            "pv_sum": (pv * vz).sum(axis=1),
            "pv_max": jnp.where(valid, pv, -big).max(axis=1),
            "meter_sum": (meter * vz).sum(axis=1),
            "residual_sum": (residual * vz).sum(axis=1),
            "residual_min": jnp.where(valid, residual, big).min(axis=1),
            "residual_max": jnp.where(valid, residual, -big).max(axis=1),
            "n_seconds": jnp.broadcast_to(nv, (pv.shape[0],)),
        }

    def step_reduced(self, state, inputs):
        """One reduce-mode block: fused block step, then the stats jit."""
        state, meter, pv = self._block_jit(state, inputs)
        return state, self._stats_jit(meter, pv, inputs["block_idx"]["t"])

    def _ensemble_series(self, meter, pv):
        """Per-second cross-chain sums of one block's materialised arrays
        (its own jit via ``_series_jit`` — the usual no-refusion split).
        Returns (meter_sum, pv_sum), each (block_s,)."""
        return meter.sum(axis=0), pv.sum(axis=0)

    def run_ensemble(self, state=None, start_block: int = 0
                     ) -> Iterator[BlockResult]:
        """Fleet-level 1 Hz time series: per-second MEANS of meter, pv and
        residual over all chains — the "grid operator" stream.  Yields
        BlockResults whose arrays have a leading axis of 1 (the fleet
        mean), so every trace consumer (write_csv, _paced, checkpointing)
        works unchanged; only (block_s,) vectors ever reach the host, so
        this scales to the 100k-1M chain configs like reduce mode while
        still producing the reference's row-per-second CSV shape.

        Three formulations, like reduce mode: the wide producer + psum
        consumer; (``block_impl='scan'``, the accelerator default) the
        scan-fused series step that sums across chains inside the scan
        body and never materialises (n_chains, block_s) arrays; or
        (``'scan2'``) its nested variant with per-minute RNG tiles.

        When the resolved plan slabs the chain batch (engine/slab.py) a
        fresh run delegates to the SlabScheduler, which combines the
        slabs' fleet means chain-count-weighted; resumes (state/
        start_block) always run unslabbed.
        """
        if state is None and start_block == 0:
            sched = self._slab_scheduler()
            if sched is not None:
                return sched.run_ensemble()
        inv_n = 1.0 / self.config.n_chains
        use_scan = self._use_scan
        if self._impl == "scan2":
            series_jit = self._scan2_series_jit
        elif use_scan:
            series_jit = self._scan_series_jit
        else:
            series_jit = None

        def make(off, epoch, a, b, n_valid):
            # wide path: (a, b) are the (n_chains, block_s) meter/pv
            # arrays, reduced by the series jit; scan path: they already
            # ARE the per-second fleet sums straight from the series step
            m_sum, p_sum = (a, b) if use_scan else self._series_jit(a, b)
            m = self._repl_view(m_sum)[None, :n_valid] * inv_n
            p = self._repl_view(p_sum)[None, :n_valid] * inv_n
            return BlockResult(offset=off, epoch=epoch, meter=m, pv=p,
                               residual=m - p)

        return self._iter_blocks(state, start_block, make,
                                 block_jit=series_jit,
                                 mega_kind="series" if use_scan
                                 else "trace")

    @staticmethod
    def _repl_view(arr) -> np.ndarray:
        """Host copy of a replicated result (overridden by the sharded
        class for non-addressable meshes)."""
        return np.asarray(arr)

    def init_reduce_acc(self, sharding=None):
        """Zero accumulator for the reduce-mode run: one (n_chains,) leaf per
        statistic, kept ON DEVICE across all blocks so reduce mode never
        ships more than these few KB to the host, once, at the end.
        ``sharding``: as in :meth:`init_state`.

        Memory math for the headline configs (BASELINE #4/#5): trace mode
        would gather n_chains x block_s float32 per array per block — at
        100k chains x 8640 s that is ~3.5 GB/array/block; the accumulator is
        7 x n_chains x 4 B ~= 2.8 MB at 1M chains, block-count independent.
        """
        n = self.config.n_chains
        dt = self.dtype

        def build():
            big = jnp.asarray(jnp.finfo(dt).max, dt)
            init = {"sum": 0.0, "max": -big, "min": big}
            return {
                name: (jnp.zeros((n,), jnp.int32) if dkind == "i"
                       else jnp.full((n,), init[kind], dt))
                for name, (kind, dkind) in REDUCE_STATS.items()
            }

        return self._memo_jit("acc", sharding, build)()

    @staticmethod
    def _merge_acc(acc, cur):
        op = {"sum": jnp.add, "max": jnp.maximum, "min": jnp.minimum}
        return {
            name: op[kind](acc[name],
                           cur[name].astype(acc[name].dtype))
            for name, (kind, _) in REDUCE_STATS.items()
        }

    def _block_stats_acc(self, meter, pv, t, acc):
        """Stats of one block folded into the running accumulator."""
        return self._merge_acc(acc, self._block_stats(meter, pv, t))

    def _step_acc_fused(self, state, inputs, acc):
        """Producer + stats + merge as one traced computation (the
        reduce-mode 'fused' topology, SimConfig.stats_fusion)."""
        state, meter, pv = self._block_step(state, inputs)
        acc = self._block_stats_acc(meter, pv, inputs["block_idx"]["t"], acc)
        return state, acc

    def _scan_block_setup(self, state, inputs, predraw=True,
                          with_extras=False):
        """Shared preamble of the scan-fused paths (traced): windows,
        value-major tables, pre-drawn time-major RNG streams, geometry
        routing.  Returns (xs, step, cc_carry) where ``step(rc, x) ->
        (rc', meter, ac)`` runs one second of the full pipeline on
        (n_chains,) vectors.  ``predraw=False`` omits the u/z/meter
        streams from xs — the nested 'scan2' formulation draws them
        per-minute inside its outer scan instead (unless
        ``rng_batch='block'``, which flips the scan2 callers back to
        predraw so the whole block's streams are pre-generated and the
        outer body is a pure gather — see ``_scan2_outer``).
        ``with_extras=True``
        (telemetry paths only) appends a fourth return to ``step``: the
        intermediates the TelemetryAcc folds ({'csi', 'covered'}); the
        default step is byte-for-byte the untouched off path."""
        cfg = self.config
        dtype = self.dtype
        opts = cfg.options
        bi = inputs["block_idx"]
        t = bi["t"]
        shared_geom = inputs.get("geom")

        arrays, mvals, cc_carry = jax.vmap(
            lambda ch: self._windows_one_chain(ch, inputs)
        )(state)
        tables = ci.value_major_tables(arrays, mvals)
        tables["cloudy_pair"] = state["cloudy_pair"].T

        if predraw:
            # blocks are minute-aligned by construction (block_s % 60 == 0
            # and offsets are whole blocks), so local second s is draw
            # slot s % 60 of group s // 60 — exactly block_s // 60 groups
            g0 = t[0] // 60
            n_groups = t.shape[0] // 60
            # the u/z streams are the scan path's only (n_chains, block_s)
            # HBM materialisation; the mixed path halves their footprint.
            # The meter stream stays f32: its ensemble mean is checked
            # against a tight analytic band (obs/sentinel.py) that a
            # quantised uniform could escape.
            with self._phase("rng"):
                u_T, z_T = ci.scan_draws_tmajor(state["k_scan"], g0,
                                                n_groups,
                                                self._compute_dtype)
                meter_T = ci.meter_block_tmajor(
                    state["k_meter"], g0, n_groups, cfg.meter_max_w, dtype
                )

        geom_samp = None
        if shared_geom is None:
            ts = inputs["time_split"]
            site = state["site"]
            turbidity = jnp.asarray(
                cfg.site_grid.linke_turbidity_monthly, dtype
            )
            if self._geom_stride > 1:
                # geom_stride device path: evaluate the transcendental
                # chain ONCE per stride window for every chain — a
                # (n_samples, n_chains) batch OUTSIDE the scan — and
                # reduce the per-second scan work to a two-gather lerp
                # (solar.interp_sampled).  xs then carries only the
                # exact per-second doy plus the (sample index, fraction)
                # lerp features shipped by host_inputs.
                tss = inputs["time_split_s"]
                geom_samp = solar.device_geometry(
                    tss["day2000"][:, None], tss["sec_of_day"][:, None],
                    tss["doy"][:, None],
                    site["latitude"], site["longitude"], site["altitude"],
                    site["surface_tilt"], site["surface_azimuth"],
                    site["albedo"], turbidity, xp=jnp,
                    kernels=self._kernels, scope=self._phase,
                )
                geom_samp = self._narrow_geom(geom_samp)
                geom_xs = {"doy": ts["doy"], "gi": inputs["gs"]["i"],
                           "gf": inputs["gs"]["f"]}
            else:
                geom_xs = {k: ts[k]
                           for k in ("day2000", "sec_of_day", "doy")}
            geom_const = None
        else:
            # (block_s,) features ride the scan as xs rows; python-float
            # site constants close over
            geom_xs = {k: v for k, v in shared_geom.items()
                       if isinstance(v, jax.Array) and v.ndim == 1}
            geom_const = {k: v for k, v in shared_geom.items()
                          if k not in geom_xs}

        xs = {
            "t": t,
            "h": bi["hour_idx"], "d": bi["day_idx"],
            "m": bi["min_idx"] - inputs["mlo"],
            "hf": bi["hour_frac"], "df": bi["day_frac"], "mf": bi["min_frac"],
            "geom": geom_xs,
        }
        if predraw:
            xs.update(u=u_T, z=z_T, meter=meter_T)

        fl = state.get("fleet")
        fl_power = fl if self._het_power else None
        fl_demand = fl if self._het_demand else None

        def step(rc, x):
            with self._phase("csi"):
                rc, csi, covered = ci.csi_compose_step(
                    tables, x, rc, opts, dtype
                )
            if shared_geom is None:
                if geom_samp is not None:
                    g = solar.interp_sampled(geom_samp, x["geom"]["gi"],
                                             x["geom"]["gf"], xp=jnp,
                                             scope=self._phase)
                    g["doy"] = x["geom"]["doy"]
                    g["surface_tilt"] = geom_samp["surface_tilt"]
                    g["albedo"] = geom_samp["albedo"]
                else:
                    g = solar.device_geometry(
                        x["geom"]["day2000"], x["geom"]["sec_of_day"],
                        x["geom"]["doy"],
                        site["latitude"], site["longitude"],
                        site["altitude"],
                        site["surface_tilt"], site["surface_azimuth"],
                        site["albedo"], turbidity, xp=jnp,
                        kernels=self._kernels, scope=self._phase,
                    )
                    g = self._narrow_geom(g)
            else:
                g = dict(geom_const, **x["geom"])
            # mixed path: the physics chain runs in the compute dtype;
            # telemetry still folds the f32 csi (``extras`` below)
            csi_c = csi.astype(self._compute_dtype) if self._mixed else csi
            # astype: under jax_enable_x64 (test/golden envs) numpy-f64
            # physics constants weakly promote ac, which would break the
            # scan-carry type contract; on TPU (x32) this is a no-op —
            # and the mixed path's widening back to the carry dtype
            ac = pvmod.power_from_csi(
                csi_c, g, SAPM_MODULE, SANDIA_INVERTER, xp=jnp,
                kernels=self._kernels, scope=self._phase,
            ).astype(dtype)
            meter = x["meter"].astype(dtype)
            # heterogeneous per-site transforms: (n_chains,) fleet leaves
            # bound at setup, elementwise against the per-second vectors;
            # neither branch traces anything when the fleet is absent or
            # the column homogeneous (byte-identical scan body)
            with self._phase("fleet"):
                if fl_power is not None:
                    ac = jnp.minimum(ac * fl_power["pv_scale"],
                                     fl_power["ac_limit_w"])
                if fl_demand is not None:
                    meter = (meter * fl_demand["demand_scale"]
                             + fl_demand["demand_shift_w"])
            if with_extras:
                return (rc, meter, ac,
                        {"csi": csi, "covered": covered})
            return rc, meter, ac

        return xs, step, cc_carry

    def _make_acc_body(self, step):
        """The reduce-mode scan body: one second through ``step`` plus the
        statistics fold into the carried accumulator (shared by the flat
        'scan' and nested 'scan2' formulations)."""
        cfg = self.config
        dtype = self.dtype
        big = jnp.asarray(jnp.finfo(dtype).max, dtype)

        def body(carry, x):
            rc, st = carry
            rc, meter, ac = step(rc, x)
            residual = meter - ac
            valid = x["t"] < cfg.duration_s      # scalar: padding mask
            vz = jnp.where(valid, 1.0, 0.0).astype(dtype)
            st = {
                "pv_sum": st["pv_sum"] + ac * vz,
                "pv_max": jnp.maximum(st["pv_max"],
                                      jnp.where(valid, ac, -big)),
                "meter_sum": st["meter_sum"] + meter * vz,
                "residual_sum": st["residual_sum"] + residual * vz,
                "residual_min": jnp.minimum(st["residual_min"],
                                            jnp.where(valid, residual, big)),
                "residual_max": jnp.maximum(st["residual_max"],
                                            jnp.where(valid, residual, -big)),
                "n_seconds": st["n_seconds"] + valid.astype(jnp.int32),
            }
            return (rc, st), None

        return body

    def _block_step_scan_acc(self, state, inputs, acc):
        """Scan-fused reduce-mode block (SimConfig.block_impl='scan').

        One ``lax.scan`` over the block's seconds; each step runs the FULL
        pipeline — sampler interpolation, renewal, PV physics, meter,
        statistics fold — on (n_chains,) vectors, with the running
        statistics carried alongside the renewal state.  Nothing of shape
        (n_chains, block_s) is ever materialised except the three
        pre-drawn RNG streams (whose values are bit-identical to the wide
        path's, models/clearsky_index.py scan_draws_tmajor), which is what
        removes the wide formulation's ~20 HBM-round-tripped
        intermediates (measured bandwidth-bound on TPU v5e;
        benchmarks/PERF_ANALYSIS.md).
        """
        cfg = self.config
        xs, step, cc_carry = self._scan_block_setup(state, inputs)
        (rcarry, acc), _ = jax.lax.scan(
            self._make_acc_body(step), (state["carry"], acc), xs,
            unroll=self._unroll,
        )
        return dict(state, carry=rcarry, cc_carry=cc_carry), acc

    def _make_acc_tel_body(self, step):
        """Telemetry variant of ``_make_acc_body``: the same statistics
        fold (duplicated verbatim rather than factored out, so the off
        path's traced graph cannot change) plus the TelemetryAcc fold on
        a second carry passenger.  ``step`` must come from
        ``_scan_block_setup(..., with_extras=True)``."""
        cfg = self.config
        dtype = self.dtype
        big = jnp.asarray(jnp.finfo(dtype).max, dtype)
        level = self._telemetry

        def body(carry, x):
            (rc, st), ta = carry
            rc, meter, ac, extras = step(rc, x)
            residual = meter - ac
            valid = x["t"] < cfg.duration_s      # scalar: padding mask
            vz = jnp.where(valid, 1.0, 0.0).astype(dtype)
            st = {
                "pv_sum": st["pv_sum"] + ac * vz,
                "pv_max": jnp.maximum(st["pv_max"],
                                      jnp.where(valid, ac, -big)),
                "meter_sum": st["meter_sum"] + meter * vz,
                "residual_sum": st["residual_sum"] + residual * vz,
                "residual_min": jnp.minimum(st["residual_min"],
                                            jnp.where(valid, residual, big)),
                "residual_max": jnp.maximum(st["residual_max"],
                                            jnp.where(valid, residual, -big)),
                "n_seconds": st["n_seconds"] + valid.astype(jnp.int32),
            }
            with self._phase("telemetry"):
                ta = tel.fold_second(
                    ta, level, meter=meter, pv=ac, csi=extras["csi"],
                    residual=residual, covered=extras["covered"],
                    valid=valid,
                )
            return ((rc, st), ta), None

        return body

    def _block_step_scan_acc_tel(self, state, inputs, acc):
        """``_block_step_scan_acc`` with the TelemetryAcc riding the scan
        carry (plan.telemetry != 'off').  The accumulator is
        zero-initialised here, inside the jit, so the returned telemetry
        is this block's pure delta: the sharded wrapper can psum shard
        contributions without double-counting and the sentinel gets
        per-block moments.  The in-scan acc is per-chain (elementwise
        fold; see obs/telemetry.py) and collapses to shard-level scalars
        once, here, after the scan."""
        xs, step, cc_carry = self._scan_block_setup(state, inputs,
                                                    with_extras=True)
        n = state["carry"]["sec"].shape[0]
        ta0 = tel.init_acc(self._telemetry, self.dtype, n_chains=n)
        ((rcarry, acc), ta), _ = jax.lax.scan(
            self._make_acc_tel_body(step), ((state["carry"], acc), ta0),
            xs, unroll=self._unroll,
        )
        return (dict(state, carry=rcarry, cc_carry=cc_carry), acc,
                tel.reduce_chainwise(ta))

    def _block_step_scan2_acc_tel(self, state, inputs, acc):
        """``_block_step_scan2_acc`` with the TelemetryAcc riding both
        scan levels (see ``_block_step_scan_acc_tel``)."""
        xs, step, cc_carry = self._scan_block_setup(state, inputs,
                                                    predraw=(self._rng_batch == "block"),
                                                    with_extras=True)
        inner_body = self._make_acc_tel_body(step)

        def inner(carry, xs_inner):
            return jax.lax.scan(inner_body, carry, xs_inner,
                                unroll=self._unroll)[0], None

        n = state["carry"]["sec"].shape[0]
        ta0 = tel.init_acc(self._telemetry, self.dtype, n_chains=n)
        ((rcarry, acc), ta), _ = self._scan2_outer(
            state, xs, inner, ((state["carry"], acc), ta0)
        )
        return (dict(state, carry=rcarry, cc_carry=cc_carry), acc,
                tel.reduce_chainwise(ta))

    def _wide_telemetry(self, meter, pv, t):
        """Telemetry fold over the wide impl's materialised block arrays
        (meter/pv/residual only: the wide producer never materialises
        csi, which ``tel.summarize`` reports as unobserved)."""
        ta = tel.init_acc(self._telemetry, self.dtype)
        with self._phase("telemetry"):
            return tel.fold_wide(ta, self._telemetry, meter=meter, pv=pv,
                                 t=t, duration_s=self.config.duration_s)

    def _cohort_ids(self, state):
        """The (n_chains,) int32 cohort-id vector for the analytics
        group-by, or None when cohorts are off.  Read from the STATE
        pytree, not ``self._fleet`` — under shard_map/slabs the state
        carries exactly the chains this shard owns, so the ids always
        pair 1:1 with the fold's vectors."""
        return state["fleet"]["cohort"] if self._n_cohorts else None

    def _make_acc_fleet_body(self, step, cohort=None):
        """Fleet-analytics variant of ``_make_acc_body``: the same
        statistics fold (duplicated verbatim, same reasoning as
        ``_make_acc_tel_body``) plus the FleetAcc fold on a second carry
        passenger.  ``step`` must come from
        ``_scan_block_setup(..., with_extras=True)`` (the 'covered'
        regime mask; at level 'risk' it is DCE'd).  ``cohort``: per-chain
        group ids for the per-cohort sketches (None folds none)."""
        cfg = self.config
        dtype = self.dtype
        big = jnp.asarray(jnp.finfo(dtype).max, dtype)
        level = self._analytics
        params = self._fleet_params

        def body(carry, x):
            (rc, st), fa = carry
            rc, meter, ac, extras = step(rc, x)
            residual = meter - ac
            valid = x["t"] < cfg.duration_s      # scalar: padding mask
            vz = jnp.where(valid, 1.0, 0.0).astype(dtype)
            st = {
                "pv_sum": st["pv_sum"] + ac * vz,
                "pv_max": jnp.maximum(st["pv_max"],
                                      jnp.where(valid, ac, -big)),
                "meter_sum": st["meter_sum"] + meter * vz,
                "residual_sum": st["residual_sum"] + residual * vz,
                "residual_min": jnp.minimum(st["residual_min"],
                                            jnp.where(valid, residual, big)),
                "residual_max": jnp.maximum(st["residual_max"],
                                            jnp.where(valid, residual, -big)),
                "n_seconds": st["n_seconds"] + valid.astype(jnp.int32),
            }
            with self._phase("analytics"):
                fa = flt.fold_second(
                    fa, level, params, meter=meter, pv=ac,
                    residual=residual, covered=extras["covered"],
                    t=x["t"], valid=valid, cohort=cohort,
                )
            return ((rc, st), fa), None

        return body

    def _make_acc_tel_fleet_body(self, step, cohort=None):
        """Both passengers at once (telemetry AND analytics on): the
        stats fold, the TelemetryAcc fold and the FleetAcc fold in one
        scan body, so the carry stays a single scan."""
        cfg = self.config
        dtype = self.dtype
        big = jnp.asarray(jnp.finfo(dtype).max, dtype)
        tel_level = self._telemetry
        level = self._analytics
        params = self._fleet_params

        def body(carry, x):
            (rc, st), ta, fa = carry
            rc, meter, ac, extras = step(rc, x)
            residual = meter - ac
            valid = x["t"] < cfg.duration_s      # scalar: padding mask
            vz = jnp.where(valid, 1.0, 0.0).astype(dtype)
            st = {
                "pv_sum": st["pv_sum"] + ac * vz,
                "pv_max": jnp.maximum(st["pv_max"],
                                      jnp.where(valid, ac, -big)),
                "meter_sum": st["meter_sum"] + meter * vz,
                "residual_sum": st["residual_sum"] + residual * vz,
                "residual_min": jnp.minimum(st["residual_min"],
                                            jnp.where(valid, residual, big)),
                "residual_max": jnp.maximum(st["residual_max"],
                                            jnp.where(valid, residual, -big)),
                "n_seconds": st["n_seconds"] + valid.astype(jnp.int32),
            }
            with self._phase("telemetry"):
                ta = tel.fold_second(
                    ta, tel_level, meter=meter, pv=ac, csi=extras["csi"],
                    residual=residual, covered=extras["covered"],
                    valid=valid,
                )
            with self._phase("analytics"):
                fa = flt.fold_second(
                    fa, level, params, meter=meter, pv=ac,
                    residual=residual, covered=extras["covered"],
                    t=x["t"], valid=valid, cohort=cohort,
                )
            return ((rc, st), ta, fa), None

        return body

    def _block_step_scan_acc_fleet(self, state, inputs, acc):
        """``_block_step_scan_acc`` with the FleetAcc riding the scan
        carry (plan.analytics != 'off', telemetry off).  Zero-initialised
        inside the jit — the returned sketches are this block's pure
        delta, psum-safe — and collapsed to shard-level form once, after
        the scan (obs/analytics.py)."""
        xs, step, cc_carry = self._scan_block_setup(state, inputs,
                                                    with_extras=True)
        n = state["carry"]["sec"].shape[0]
        fa0 = flt.init_acc(self._analytics, self.dtype, n_chains=n,
                           params=self._fleet_params,
                           cohorts=self._n_cohorts)
        ((rcarry, acc), fa), _ = jax.lax.scan(
            self._make_acc_fleet_body(step, self._cohort_ids(state)),
            ((state["carry"], acc), fa0),
            xs, unroll=self._unroll,
        )
        return (dict(state, carry=rcarry, cc_carry=cc_carry), acc,
                flt.reduce_chainwise(fa))

    def _block_step_scan2_acc_fleet(self, state, inputs, acc):
        """``_block_step_scan2_acc`` with the FleetAcc riding both scan
        levels (see ``_block_step_scan_acc_fleet``)."""
        xs, step, cc_carry = self._scan_block_setup(state, inputs,
                                                    predraw=(self._rng_batch == "block"),
                                                    with_extras=True)
        inner_body = self._make_acc_fleet_body(step,
                                               self._cohort_ids(state))

        def inner(carry, xs_inner):
            return jax.lax.scan(inner_body, carry, xs_inner,
                                unroll=self._unroll)[0], None

        n = state["carry"]["sec"].shape[0]
        fa0 = flt.init_acc(self._analytics, self.dtype, n_chains=n,
                           params=self._fleet_params,
                           cohorts=self._n_cohorts)
        ((rcarry, acc), fa), _ = self._scan2_outer(
            state, xs, inner, ((state["carry"], acc), fa0)
        )
        return (dict(state, carry=rcarry, cc_carry=cc_carry), acc,
                flt.reduce_chainwise(fa))

    def _block_step_scan_acc_tel_fleet(self, state, inputs, acc):
        """Both accumulators riding the flat scan (telemetry AND
        analytics on); returns (state', acc, tel_delta, fleet_delta)."""
        xs, step, cc_carry = self._scan_block_setup(state, inputs,
                                                    with_extras=True)
        n = state["carry"]["sec"].shape[0]
        ta0 = tel.init_acc(self._telemetry, self.dtype, n_chains=n)
        fa0 = flt.init_acc(self._analytics, self.dtype, n_chains=n,
                           params=self._fleet_params,
                           cohorts=self._n_cohorts)
        ((rcarry, acc), ta, fa), _ = jax.lax.scan(
            self._make_acc_tel_fleet_body(step, self._cohort_ids(state)),
            ((state["carry"], acc), ta0, fa0), xs, unroll=self._unroll,
        )
        return (dict(state, carry=rcarry, cc_carry=cc_carry), acc,
                tel.reduce_chainwise(ta), flt.reduce_chainwise(fa))

    def _block_step_scan2_acc_tel_fleet(self, state, inputs, acc):
        """Both accumulators riding the nested scan; returns
        (state', acc, tel_delta, fleet_delta)."""
        xs, step, cc_carry = self._scan_block_setup(state, inputs,
                                                    predraw=(self._rng_batch == "block"),
                                                    with_extras=True)
        inner_body = self._make_acc_tel_fleet_body(step,
                                                   self._cohort_ids(state))

        def inner(carry, xs_inner):
            return jax.lax.scan(inner_body, carry, xs_inner,
                                unroll=self._unroll)[0], None

        n = state["carry"]["sec"].shape[0]
        ta0 = tel.init_acc(self._telemetry, self.dtype, n_chains=n)
        fa0 = flt.init_acc(self._analytics, self.dtype, n_chains=n,
                           params=self._fleet_params,
                           cohorts=self._n_cohorts)
        ((rcarry, acc), ta, fa), _ = self._scan2_outer(
            state, xs, inner, ((state["carry"], acc), ta0, fa0)
        )
        return (dict(state, carry=rcarry, cc_carry=cc_carry), acc,
                tel.reduce_chainwise(ta), flt.reduce_chainwise(fa))

    def _wide_fleet(self, meter, pv, t, cohort=None):
        """Fleet fold over the wide impl's materialised block arrays
        (scalar-form acc; the wide producer never materialises the cloud
        state, so the 'full' regime leaves stay unobserved).  ``cohort``:
        per-chain group ids matching the meter/pv chain axis."""
        fa = flt.init_acc(self._analytics, self.dtype,
                          params=self._fleet_params,
                          cohorts=self._n_cohorts)
        with self._phase("analytics"):
            return flt.fold_wide(fa, self._analytics, self._fleet_params,
                                 meter=meter, pv=pv, t=t,
                                 duration_s=self.config.duration_s,
                                 cohort=cohort)

    def _scan2_outer(self, state, xs, inner, carry0):
        """The nested ('scan2') outer scan, shared by the reduce and
        ensemble formulations: per-second features are tiled per minute
        ((T, ...) -> (n_min, 60, ...)), and each outer step draws that
        minute's (60, n_chains) RNG tile — same keyed slots as
        scan_draws_tmajor/meter_block_tmajor, so values are bit-identical
        to the flat scan's pre-drawn streams — then hands the tile to the
        ``inner(carry, xs_inner) -> (carry, ys)`` 60-second scan.  Returns
        ``lax.scan(outer, carry0, xs_t)``'s (carry, ys) with ys stacked
        per minute.

        ``rng_batch='block'``: the caller builds xs WITH the pre-drawn
        whole-block u/z/meter streams (``_scan_block_setup`` predraw),
        which the reshape above tiles to the exact (n_min, 60, n_chains)
        shape the in-body draws would produce — same keyed slots, so
        bit-identical values (tests/test_rng_batch.py) — and the outer
        body becomes a pure gather, no hashing.  Under mega-dispatch the
        per-block pre-generation happens inside the outer mega scan
        body, one inner block at a time, which bounds the stream HBM at
        O(n_chains × block_s) regardless of blocks_per_dispatch."""
        cfg = self.config
        dtype = self.dtype
        # mixed path: u/z tiles in the compute dtype (same keyed slots as
        # scan_draws_tmajor at the same dtype, so scan/scan2 stay
        # bit-identical to each other); the meter tile stays f32 like the
        # flat scan's meter stream (_scan_block_setup)
        cdt = self._compute_dtype
        n_min = xs["t"].shape[0] // 60
        g0 = xs["t"][0] // 60
        xs_t = jax.tree.map(
            lambda a: a.reshape((n_min, 60) + a.shape[1:]), xs
        )
        k_scan, k_meter = state["k_scan"], state["k_meter"]
        max_w = cfg.meter_max_w

        def outer(carry, xm):
            mi = xm.pop("_mi")
            if "u" in xm:
                # pre-generated tiles already ride the xs (rng_batch=
                # 'block'); the outer body does no hashing at all
                return inner(carry, xm)
            g = g0 + mi

            def draws(k):
                kg = jax.random.fold_in(k, g)
                u = jax.random.uniform(jax.random.fold_in(kg, 0), (60,),
                                       cdt)
                z = jax.random.normal(jax.random.fold_in(kg, 1), (60,),
                                      cdt)
                return u, z

            with self._phase("rng"):
                u, z = jax.vmap(draws, out_axes=1)(k_scan)   # (60, chains)
                mu = jax.vmap(
                    lambda k: jax.random.uniform(jax.random.fold_in(k, g),
                                                 (60,), dtype),
                    out_axes=1,
                )(k_meter)
            xs_inner = dict(xm, u=u, z=z, meter=max_w * mu)
            return inner(carry, xs_inner)

        xs_t["_mi"] = jnp.arange(n_min)
        return jax.lax.scan(outer, carry0, xs_t)

    def _block_step_scan2_acc(self, state, inputs, acc):
        """Nested scan-fused reduce block (SimConfig.block_impl='scan2').

        Same pipeline and bit-identical draws as 'scan', but the RNG
        streams are generated per MINUTE inside an outer scan — a
        (60, n_chains) tile at a time, consumed immediately by an inner
        unrolled scan over its 60 seconds — so even the pre-drawn streams
        never materialise at (block_s, n_chains): the last
        O(n_chains x block_s) HBM term of the flat scan
        (benchmarks/PERF_ANALYSIS.md §4a)."""
        cfg = self.config
        xs, step, cc_carry = self._scan_block_setup(state, inputs,
                                                    predraw=(self._rng_batch == "block"))
        inner_body = self._make_acc_body(step)

        def inner(carry, xs_inner):
            return jax.lax.scan(inner_body, carry, xs_inner,
                                unroll=self._unroll)[0], None

        (rcarry, acc), _ = self._scan2_outer(
            state, xs, inner, (state["carry"], acc)
        )
        return dict(state, carry=rcarry, cc_carry=cc_carry), acc

    def _block_step_scan2_series(self, state, inputs):
        """Nested scan-fused ensemble block: the 'scan2' counterpart of
        ``_block_step_scan_series`` — per-minute RNG tiles, inner scan
        emitting the local cross-chain (meter_sum, pv_sum) per second.
        Returns (state', meter_sum, pv_sum), each (block_s,); bit-identical
        values to the flat scan series step (same keyed draw slots), so
        ensemble mode accepts ``block_impl='scan2'`` without coercion."""
        cfg = self.config
        xs, step, cc_carry = self._scan_block_setup(state, inputs,
                                                    predraw=(self._rng_batch == "block"))

        def body(rc, x):
            rc, meter, ac = step(rc, x)
            return rc, (meter.sum(), ac.sum())

        def inner(carry, xs_inner):
            return jax.lax.scan(body, carry, xs_inner,
                                unroll=self._unroll)

        rcarry, (m_sum, p_sum) = self._scan2_outer(
            state, xs, inner, state["carry"]
        )
        return (dict(state, carry=rcarry, cc_carry=cc_carry),
                m_sum.reshape(-1), p_sum.reshape(-1))

    def _block_step_scan_series(self, state, inputs):
        """Scan-fused ensemble-mode block: same pipeline as
        ``_block_step_scan_acc`` but the per-step output is the local
        cross-chain SUM of meter and pv — (block_s,) vectors, so the
        fleet-mean stream scales exactly like reduce mode.  Returns
        (state', meter_sum, pv_sum); the sharded wrapper psums the sums
        over the mesh once per block."""
        xs, step, cc_carry = self._scan_block_setup(state, inputs)

        def body(rc, x):
            rc, meter, ac = step(rc, x)
            return rc, (meter.sum(), ac.sum())

        rcarry, (m_sum, p_sum) = jax.lax.scan(
            body, state["carry"], xs, unroll=self._unroll
        )
        return dict(state, carry=rcarry, cc_carry=cc_carry), m_sum, p_sum

    def step_acc(self, state, inputs, acc):
        """One reduce-mode block folded into the on-device accumulator."""
        if self._analytics != "off":
            return self._step_acc_fleet(state, inputs, acc)
        if self._telemetry != "off":
            return self._step_acc_tel(state, inputs, acc)
        if self._impl == "scan2":
            return self._scan2_acc_jit(state, inputs, acc)
        if self._impl == "scan":
            return self._scan_acc_jit(state, inputs, acc)
        if self._use_fused:
            return self._fused_acc_jit(state, inputs, acc)
        state, meter, pv = self._block_jit(state, inputs)
        acc = self._stats_acc_jit(meter, pv, inputs["block_idx"]["t"], acc)
        return state, acc

    def _step_acc_tel(self, state, inputs, acc):
        """Reduce-mode block with in-graph telemetry: the scan impls run
        their dedicated tel jits; the wide impl runs the split producer
        plus a telemetry fold over the materialised arrays (the fused
        topology is bypassed under telemetry — the fold needs the wide
        arrays anyway, so fusing would buy nothing).  The block's
        TelemetryAcc lands in ``self._tel_last`` for the per-block host
        flush (``_observe_telemetry``); the (state, acc) contract of
        ``step_acc`` is unchanged."""
        if self._impl == "scan2":
            state, acc, ta = self._scan2_acc_tel_jit(state, inputs, acc)
        elif self._impl == "scan":
            state, acc, ta = self._scan_acc_tel_jit(state, inputs, acc)
        else:
            state, meter, pv = self._block_jit(state, inputs)
            ta = self._wide_tel_jit(meter, pv, inputs["block_idx"]["t"])
            acc = self._stats_acc_jit(meter, pv, inputs["block_idx"]["t"],
                                      acc)
        self._tel_last = ta
        return state, acc

    def _step_acc_fleet(self, state, inputs, acc):
        """Reduce-mode block with fleet analytics (and possibly
        telemetry): the scan impls run their dedicated combo jits; the
        wide impl runs the split producer plus the bulk folds over the
        materialised arrays BEFORE the (donating) stats jit consumes
        them.  The block's FleetAcc delta lands in ``self._fleet_last``
        for the per-block host merge (``_observe_fleet``); the
        (state, acc) contract of ``step_acc`` is unchanged."""
        tel_on = self._telemetry != "off"
        if self._impl == "scan2":
            if tel_on:
                state, acc, ta, fa = self._scan2_acc_tel_fleet_jit(
                    state, inputs, acc)
                self._tel_last = ta
            else:
                state, acc, fa = self._scan2_acc_fleet_jit(
                    state, inputs, acc)
        elif self._impl == "scan":
            if tel_on:
                state, acc, ta, fa = self._scan_acc_tel_fleet_jit(
                    state, inputs, acc)
                self._tel_last = ta
            else:
                state, acc, fa = self._scan_acc_fleet_jit(
                    state, inputs, acc)
        else:
            state, meter, pv = self._block_jit(state, inputs)
            t = inputs["block_idx"]["t"]
            if tel_on:
                self._tel_last = self._wide_tel_jit(meter, pv, t)
            fa = (self._wide_fleet_jit(meter, pv, t,
                                       self._cohort_ids(state))
                  if self._n_cohorts
                  else self._wide_fleet_jit(meter, pv, t))
            # last: _stats_acc_jit donates the meter/pv buffers
            acc = self._stats_acc_jit(meter, pv, t, acc)
        self._fleet_last = fa
        return state, acc

    # ------------------------------------------------------------------
    # scenario-batched serving dispatch (serve/: SimConfig.serve_batch_sizes)
    # ------------------------------------------------------------------

    def scenario_fleet_params(self):
        """FleetParams of the scenario fold's risk sketch — resolved from
        the config independently of ``plan.analytics`` (a server always
        folds the sketch so any request may ask for the fleet result
        mode, even when the batch run would have analytics off)."""
        if self._scn_fleet_params is None:
            self._scn_fleet_params = flt.params_from_config(self.config)
        return self._scn_fleet_params

    def init_scenario_acc(self, batch: int, sharding=None):
        """Zero reduce accumulator with a leading scenario axis: one
        (batch, n_chains) leaf per statistic, same init values as
        :meth:`init_reduce_acc` so row ``i`` of a batch-of-N run folds
        exactly what a batch-of-1 run of scenario ``i`` folds.  The
        sharded subclass passes ``sharding`` to lay the batch axis over
        the ``scenario`` mesh axis (parallel/mesh.py)."""
        n = self.config.n_chains
        dt = self.dtype
        b = int(batch)

        def build():
            big = jnp.asarray(jnp.finfo(dt).max, dt)
            init = {"sum": 0.0, "max": -big, "min": big}
            return {
                name: (jnp.zeros((b, n), jnp.int32) if dkind == "i"
                       else jnp.full((b, n), init[kind], dt))
                for name, (kind, dkind) in REDUCE_STATS.items()
            }

        return self._memo_jit(("scenario_acc", b), sharding, build)()

    def scenario_abstract(self, batch: int):
        """ShapeDtypeStructs of a (batch,)-leaf scenario knob pytree —
        the abstract twin of ``serve.schema.encode_batch`` output."""
        b = int(batch)
        f = jax.ShapeDtypeStruct((b,), self.dtype)
        scen = {k: f for k in SCENARIO_FLOAT_KNOBS}
        scen["horizon_s"] = jax.ShapeDtypeStruct((b,), jnp.int32)
        # bounded site selector (serve/schema.py): -1 = whole fleet,
        # else restrict the fold to one chain / one cohort
        scen["site_index"] = jax.ShapeDtypeStruct((b,), jnp.int32)
        scen["cohort"] = jax.ShapeDtypeStruct((b,), jnp.int32)
        return scen

    def _block_step_scan_scenario(self, state, inputs, acc, scen):
        """Scenario-batched reduce block (serve/): the scan-fused block
        step with a leading scenario ``vmap`` axis over the chain axis.

        The physics pipeline (``step`` from ``_scan_block_setup``) runs
        ONCE per second on (n_chains,) vectors — scenario knobs never
        touch the RNG streams or the model state — and each second's
        meter/pv outputs are then re-read through every scenario's knob
        transform (demand scale/shift, DC-capacity x weather-regime
        scale, curtailment cap) by a vmapped fold: the reduce statistics
        mirror ``_make_acc_body`` exactly and a per-chain FleetAcc rides
        alongside so any request can ask for the fleet-risk sketch.  Per
        scenario validity is ``t < horizon_s`` on top of the duration
        mask, so padding rows (horizon 0) fold nothing and shorter
        horizons stop early without a separate shape.  Because every row
        of the batch applies independent elementwise transforms of the
        SAME per-second vectors, row ``i`` of a batch-of-N dispatch is
        bit-identical to a batch-of-1 dispatch of scenario ``i``
        (asserted by tests/test_serve.py).  Returns
        ``(state', acc', fleet_delta)`` where ``fleet_delta`` is the
        block's scalar-form FleetAcc per scenario (zero-initialised
        inside the jit — a pure per-block delta for the host merge).
        """
        # bounded site selector: chain iota vs the request's site index /
        # cohort tag.  -1 selects everything (an all-true mask folds the
        # same values, so whole-fleet replies are unchanged).  Closure
        # constants are safe in THIS unsharded wrapper; the sharded
        # dispatch (parallel/mesh.py) feeds the core explicit
        # chain-sharded device arguments instead, so each shard's rows
        # carry their true global chain ids.
        iota = jnp.arange(self.config.n_chains, dtype=jnp.int32)
        cohort_arr = (jnp.asarray(self._fleet.cohort, jnp.int32)
                      if self._fleet is not None
                      and self._fleet.n_cohorts > 1 else None)
        return self._scenario_block_core(state, inputs, acc, scen,
                                         iota, cohort_arr)

    def _scenario_block_core(self, state, inputs, acc, scen, chain_ids,
                             cohort_arr):
        """Body of :meth:`_block_step_scan_scenario` with the chain ids
        and cohort tags as explicit arguments.  ``chain_ids`` is the
        GLOBAL index of each local chain row (the full iota unsharded; a
        shard's slice of it under shard_map — shapes size the local
        accumulators, values key the site selector).  ``cohort_arr`` is
        the per-chain cohort tag, or None / a 0-d placeholder when the
        fleet has no cohorts (shard_map cannot pass None)."""
        cfg = self.config
        dtype = self.dtype
        big = jnp.asarray(jnp.finfo(dtype).max, dtype)
        params = self.scenario_fleet_params()
        batch = scen["horizon_s"].shape[0]
        if cohort_arr is not None and cohort_arr.ndim == 0:
            cohort_arr = None
        xs, step, cc_carry = self._scan_block_setup(state, inputs)
        facc = jax.tree.map(
            lambda l: jnp.broadcast_to(l, (batch,) + l.shape),
            flt.init_acc("risk", dtype, chain_ids.shape[0], params=params))
        iota = chain_ids

        def body(carry, x):
            rc, st, fa = carry
            rc, meter, ac = step(rc, x)
            t = x["t"]
            base_valid = t < cfg.duration_s

            def one(sc, st_i, fa_i):
                meter_i = meter * sc["demand_scale"] + sc["demand_shift_w"]
                pv_i = jnp.minimum(
                    ac * (sc["pv_scale"] * sc["weather_bias"]),
                    sc["curtail_w"])
                residual = meter_i - pv_i
                sel = (sc["site_index"] < 0) | (iota == sc["site_index"])
                if cohort_arr is not None:
                    sel = sel & ((sc["cohort"] < 0)
                                 | (cohort_arr == sc["cohort"]))
                valid = sel & base_valid & (t < sc["horizon_s"])
                vz = jnp.where(valid, 1.0, 0.0).astype(dtype)
                st_i = {
                    "pv_sum": st_i["pv_sum"] + pv_i * vz,
                    "pv_max": jnp.maximum(st_i["pv_max"],
                                          jnp.where(valid, pv_i, -big)),
                    "meter_sum": st_i["meter_sum"] + meter_i * vz,
                    "residual_sum": st_i["residual_sum"] + residual * vz,
                    "residual_min": jnp.minimum(
                        st_i["residual_min"],
                        jnp.where(valid, residual, big)),
                    "residual_max": jnp.maximum(
                        st_i["residual_max"],
                        jnp.where(valid, residual, -big)),
                    "n_seconds": st_i["n_seconds"]
                    + valid.astype(jnp.int32),
                }
                fa_i = flt.fold_second(
                    fa_i, "risk", params, meter=meter_i, pv=pv_i,
                    residual=residual, covered=None, t=t, valid=valid)
                return st_i, fa_i

            st, fa = jax.vmap(one)(scen, st, fa)
            return (rc, st, fa), None

        (rcarry, acc, facc), _ = jax.lax.scan(
            body, (state["carry"], acc, facc), xs, unroll=self._unroll)
        fdelta = jax.vmap(flt.reduce_chainwise)(facc)
        return dict(state, carry=rcarry, cc_carry=cc_carry), acc, fdelta

    def _get_scenario_jit(self):
        """The scenario dispatch jit, built on first use: serving-only —
        batch runs never touch it, so the default build cost is zero.
        State and the running reduce acc are donated (the FleetAcc delta
        is an output, not a carry); ``scen`` is not, so the batcher may
        re-dispatch the same scenario tree across blocks."""
        if self._scenario_jit is None:
            self._scenario_jit = jax.jit(self._block_step_scan_scenario,
                                         donate_argnums=(0, 2))
        return self._scenario_jit

    def scenario_step(self, state, inputs, acc, scen):
        """One scenario-batched block: ``(state, acc, scen) ->
        (state', acc', fleet_delta)``.  Counts as a dispatch."""
        self._m_dispatch.inc()
        return self._get_scenario_jit()(state, inputs, acc, scen)

    # ------------------------------------------------------------------
    # multi-block fused dispatch (Plan.blocks_per_dispatch > 1)
    # ------------------------------------------------------------------

    @staticmethod
    def _is_block_arr(leaf) -> bool:
        """Host-input leaves that vary per block and ride the mega scan
        as stacked xs.  np.generic matters: numpy SCALARS (the minute
        offset ``mlo``, the sampler window origins in ``win``) are not
        ndarray instances but are strongly-typed per-block values —
        treating them as constants would bake block 0's windows into
        every block of the dispatch."""
        return isinstance(leaf, (np.ndarray, np.generic, jax.Array))

    def _split_inputs(self, ins):
        """(xs, const) of a K-group of per-block ``host_inputs`` trees.

        Array leaves stack with a leading K axis and become the outer
        scan's xs; a scan slice of a stacked numpy scalar is a ()
        strongly-typed value — exactly the aval the per-block jits see.
        The remaining python-scalar leaves (shared-site geometry
        constants like surface_tilt/albedo) ride as a separate
        call-time ARGUMENT tree of the mega jit, so they trace as the
        same weak-typed scalar tracers the per-block jits see.  Neither
        stacking them (a strong float64 array — changes promotion) nor
        baking them as closure constants (XLA constant-folds the
        downstream transposition algebra and reassociates — observed
        one-ulp pv differences vs the per-block path) preserves
        bit-exactness.  Non-array leaves must be block-invariant — they
        are site constants by construction, and this asserts it.
        """
        keep_const = \
            lambda l: None if self._is_block_arr(l) else l  # noqa: E731
        const = jax.tree.map(keep_const, ins[0])
        for other in ins[1:]:
            oc = jax.tree.map(keep_const, other)
            if oc != const:
                raise AssertionError(
                    "non-array host-input leaves vary across the dispatch "
                    f"group: {oc!r} != {const!r} — cannot bake them as "
                    "mega-jit constants")
        xs = jax.tree.map(
            lambda *ls: np.stack(ls) if self._is_block_arr(ls[0]) else None,
            *ins)
        return xs, const

    @staticmethod
    def _merge_inputs(x, const):
        """Re-assemble one block's input tree inside the mega scan body:
        ``x`` is the scanned slice (None holes at constant positions),
        ``const`` the baked constants (None holes at array positions)."""
        return jax.tree.map(lambda c, v: v if c is None else c,
                            const, x, is_leaf=lambda n: n is None)

    def _mega_block_fn(self, kind: str):
        """The RAW (untraced) per-block function the mega scan body runs
        — the very computation the per-block jits wrap, so K-block
        dispatch is bit-identical to per-block dispatch on the scan
        family and for every reduce statistic (tested in
        tests/test_executor.py).  One caveat on the WIDE producer's raw
        per-second arrays (trace mode, wide ensemble): multi-device
        XLA:CPU compiles a fusion embedded in a loop body with different
        vector-epilogue boundaries than the same fusion at a jit root,
        so pv can differ by one ulp at a handful of seconds per block
        (observed only under ``--xla_force_host_platform_device_count``;
        single-device CPU is exact, TPU tiling is context-independent).
        The reduce folds absorb those ulps, which is why the reduce
        contract stays exact even on the wide impl.
        Kinds: 'acc' (reduce), 'acc_tel' (reduce + telemetry: returns a
        third per-block TelemetryAcc delta), 'acc_fleet' (reduce + fleet
        analytics: third output is the per-block FleetAcc delta),
        'acc_tel_fleet' (both: outputs 3 and 4 are the telemetry and
        fleet deltas), 'trace' (the wide producer), 'series' (the
        scan-family ensemble step)."""
        if kind == "acc":
            if self._impl == "scan2":
                return self._block_step_scan2_acc
            if self._impl == "scan":
                return self._block_step_scan_acc
            if self._use_fused:
                return self._step_acc_fused

            def wide_split(state, inputs, acc):
                # producer + fold composed in one trace: same float
                # semantics as the split jits (XLA fusion does not
                # reassociate; asserted for the fused topology in the
                # slow lane)
                state, meter, pv = self._block_step(state, inputs)
                return state, self._block_stats_acc(
                    meter, pv, inputs["block_idx"]["t"], acc)

            return wide_split
        if kind == "acc_tel":
            if self._impl == "scan2":
                return self._block_step_scan2_acc_tel
            if self._impl == "scan":
                return self._block_step_scan_acc_tel

            def wide_tel(state, inputs, acc):
                state, meter, pv = self._block_step(state, inputs)
                t = inputs["block_idx"]["t"]
                ta = self._wide_telemetry(meter, pv, t)
                return state, self._block_stats_acc(meter, pv, t, acc), ta

            return wide_tel
        if kind == "acc_fleet":
            if self._impl == "scan2":
                return self._block_step_scan2_acc_fleet
            if self._impl == "scan":
                return self._block_step_scan_acc_fleet

            def wide_fleet(state, inputs, acc):
                state, meter, pv = self._block_step(state, inputs)
                t = inputs["block_idx"]["t"]
                fa = self._wide_fleet(meter, pv, t,
                                      self._cohort_ids(state))
                return state, self._block_stats_acc(meter, pv, t, acc), fa

            return wide_fleet
        if kind == "acc_tel_fleet":
            if self._impl == "scan2":
                return self._block_step_scan2_acc_tel_fleet
            if self._impl == "scan":
                return self._block_step_scan_acc_tel_fleet

            def wide_tel_fleet(state, inputs, acc):
                state, meter, pv = self._block_step(state, inputs)
                t = inputs["block_idx"]["t"]
                ta = self._wide_telemetry(meter, pv, t)
                fa = self._wide_fleet(meter, pv, t,
                                      self._cohort_ids(state))
                return (state, self._block_stats_acc(meter, pv, t, acc),
                        ta, fa)

            return wide_tel_fleet
        if kind == "trace":
            return self._block_step
        if kind == "series":
            return (self._block_step_scan2_series if self._impl == "scan2"
                    else self._block_step_scan_series)
        raise ValueError(f"unknown mega-dispatch kind {kind!r}")

    def _build_mega_acc(self, k: int, tel: bool, fleet: bool = False):
        """Jitted K-block reduce dispatch: outer lax.scan carrying
        (state, acc), per-block accumulator snapshots (and telemetry /
        fleet deltas) stacked out as ys so block boundaries stay
        observable.  State and accumulator are donated — the carries
        never need a second HBM copy.  ``const`` is the block-invariant
        scalar tree from ``_split_inputs``, an argument (not a closure)
        so its python floats trace exactly as on the per-block path.
        ys shapes per combination: acc | (acc, ta) | (acc, fa) |
        (acc, ta, fa).  Overridden sharded: parallel/mesh.py puts the
        shard_map OUTSIDE the scan."""
        kind = "acc" + ("_tel" if tel else "") + ("_fleet" if fleet else "")
        fn = self._mega_block_fn(kind)

        def mega(state, xs, acc, const):
            def body(carry, x):
                st, a = carry
                inputs = self._merge_inputs(x, const)
                out = fn(st, inputs, a)
                st, a = out[0], out[1]
                if len(out) == 2:
                    return (st, a), a
                return (st, a), (a,) + tuple(out[2:])

            (state, acc), ys = jax.lax.scan(body, (state, acc), xs)
            return state, acc, ys

        return jax.jit(mega, donate_argnums=(0, 2))

    def _build_mega_blocks(self, kind: str, k: int):
        """Jitted K-block trace/series dispatch: outer scan carrying the
        state, per-block (a, b) outputs stacked with a leading K axis
        (sliced per block on the host side of ``_iter_blocks``).
        ``const`` is an argument for the same bit-exactness reason as in
        ``_build_mega_acc``."""
        fn = self._mega_block_fn(kind)

        def mega(state, xs, const):
            def body(st, x):
                st, a, b = fn(st, self._merge_inputs(x, const))
                return st, (a, b)

            state, (a_k, b_k) = jax.lax.scan(body, state, xs)
            return state, a_k, b_k

        return jax.jit(mega, donate_argnums=0)

    def _mega_dispatch(self, kind: str, ins):
        """(jitted mega fn, stacked xs, const scalar tree) for one group
        of per-block input trees.  Jits are memoized per
        (kind, len(ins)); const rides every call (block-invariant, see
        ``_split_inputs``)."""
        k = len(ins)
        xs, const = self._split_inputs(ins)
        key = (kind, k)
        if key not in self._mega_jits:
            if kind in ("acc", "acc_tel", "acc_fleet", "acc_tel_fleet"):
                self._mega_jits[key] = self._build_mega_acc(
                    k, tel="_tel" in kind, fleet="_fleet" in kind)
            else:
                self._mega_jits[key] = self._build_mega_blocks(kind, k)
        return self._mega_jits[key], xs, const

    def step_acc_multi(self, state, inputs_seq, acc):
        """K reduce-mode blocks as ONE device dispatch (the multi-block
        fused counterpart of :meth:`step_acc`): eliminates K-1 host
        round-trips while the stacked per-block accumulator snapshots
        (and telemetry deltas) keep every block boundary observable —
        checkpoints, the drift sentinel and on_block callbacks see exact
        block-boundary values.  Returns (state, acc, accs), extended
        with a stacked tels tree under telemetry and a stacked fleets
        tree under analytics (in that order, each only when on); every
        stacked leaf carries a leading len(inputs_seq) axis."""
        tel_on = self._telemetry != "off"
        fleet_on = self._analytics != "off"
        kind = ("acc" + ("_tel" if tel_on else "")
                + ("_fleet" if fleet_on else ""))
        mega, xs, const = self._mega_dispatch(kind, list(inputs_seq))
        state, acc, ys = mega(state, xs, acc, const)
        if tel_on or fleet_on:
            return (state, acc) + tuple(ys)
        return state, acc, ys

    def aot_targets(self):
        """(name, jitted fn, abstract args) triples of the jits the
        resolved plan + output mode will actually dispatch — the AOT
        warm-up surface (engine/compilecache.py ``warm_up``).  Args are
        abstract (eval_shape + ShapeDtypeStructs of one real
        ``host_inputs`` call), so enumeration never allocates
        chain-sized buffers; python-scalar input leaves stay raw, which
        lowers them as the same weak-typed scalars the live call passes.
        """
        state_abs = jax.eval_shape(self.init_state)
        inputs, _ = self.host_inputs(0)
        inputs_abs = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(np.shape(l),
                                           np.asarray(l).dtype)
            if self._is_block_arr(l) else l, inputs)
        mode = self.config.output
        tel_on = self._telemetry != "off"
        fleet_on = self._analytics != "off"
        out = []
        if mode == "reduce":
            acc_abs = jax.eval_shape(self.init_reduce_acc)
            if self._impl in ("scan", "scan2"):
                # the one combo jit __init__ actually built for this
                # tel x analytics combination
                suffix = (("_tel" if tel_on else "")
                          + ("_fleet" if fleet_on else ""))
                jit = getattr(self, f"_{self._impl}_acc{suffix}_jit")
                out.append((f"{self._impl}_acc", jit,
                            (state_abs, inputs_abs, acc_abs)))
            elif self._use_fused and not tel_on and not fleet_on:
                out.append(("fused_acc", self._fused_acc_jit,
                            (state_abs, inputs_abs, acc_abs)))
            else:
                _, m_abs, p_abs = jax.eval_shape(self._block_step,
                                                 state_abs, inputs_abs)
                t_abs = inputs_abs["block_idx"]["t"]
                out.append(("block", self._block_jit,
                            (state_abs, inputs_abs)))
                if tel_on:
                    out.append(("wide_tel", self._wide_tel_jit,
                                (m_abs, p_abs, t_abs)))
                if fleet_on:
                    out.append(("wide_fleet", self._wide_fleet_jit,
                                (m_abs, p_abs, t_abs)))
                out.append(("stats_acc", self._stats_acc_jit,
                            (m_abs, p_abs, t_abs, acc_abs)))
        elif mode == "ensemble":
            if self._impl == "scan2":
                out.append(("scan2_series", self._scan2_series_jit,
                            (state_abs, inputs_abs)))
            elif self._impl == "scan":
                out.append(("scan_series", self._scan_series_jit,
                            (state_abs, inputs_abs)))
            else:
                _, m_abs, p_abs = jax.eval_shape(self._block_step,
                                                 state_abs, inputs_abs)
                out.append(("block", self._block_jit,
                            (state_abs, inputs_abs)))
                out.append(("series", self._series_jit, (m_abs, p_abs)))
        else:  # trace
            out.append(("block", self._block_jit, (state_abs, inputs_abs)))
        if self._k_dispatch > 1 and self.n_blocks >= self._k_dispatch:
            out.extend(self._mega_aot_targets(inputs, state_abs, mode,
                                              tel_on))
        # scenario-serving buckets (SimConfig.serve_batch_sizes): one
        # target per batch size so a server started under the persistent
        # compile cache pre-compiles every shape its micro-batcher can
        # dispatch — the warm-restart zero-fresh-compiles guarantee
        for b in self.config.serve_batch_sizes:
            b = int(b)
            # bind the batch size as a closure, not an eval_shape
            # argument — init_scenario_acc shapes arrays with int(batch)
            # and must see the concrete python int
            acc_abs = jax.eval_shape(
                lambda _b=b: self.init_scenario_acc(_b))
            out.append((f"scenario_acc[{b}]", self._get_scenario_jit(),
                        (state_abs, inputs_abs, acc_abs,
                         self.scenario_abstract(b))))
        # resumed carries (and the scenario engine's shared base state)
        # pass through the non-donating identity copy before the first
        # donating dispatch; it only ever compiles on those paths, so
        # without warming it here a resumed run's single cold compile
        # would be this trivial copy
        out.append(("resume_copy", _copy_jit, (state_abs,)))
        if mode == "reduce":
            out.append(("resume_copy_acc", _copy_jit,
                        (jax.eval_shape(self.init_reduce_acc),)))
        return out

    def _mega_aot_targets(self, inputs, state_abs, mode, tel_on):
        """AOT targets for the full-K mega jit of the configured output
        mode (the final partial group, if any, compiles lazily — a small
        one-off)."""
        k = self._k_dispatch
        fleet_on = self._analytics != "off"
        kind = {"reduce": ("acc" + ("_tel" if tel_on else "")
                           + ("_fleet" if fleet_on else "")),
                "ensemble": "series" if self._use_scan else "trace",
                "trace": "trace"}[mode]
        # K copies of block 0's inputs: right shapes/dtypes/constants
        # for building + lowering; the stacked values are discarded,
        # const's raw python scalars lower as the weak-typed scalars
        # the live call passes
        mega, _, const = self._mega_dispatch(kind, [inputs] * k)
        xs_abs = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct((k,) + np.shape(l),
                                           np.asarray(l).dtype)
            if self._is_block_arr(l) else None, inputs)
        if kind in ("acc", "acc_tel"):
            acc_abs = jax.eval_shape(self.init_reduce_acc)
            return [(f"mega_{kind}[{k}]", mega,
                     (state_abs, xs_abs, acc_abs, const))]
        return [(f"mega_{kind}[{k}]", mega, (state_abs, xs_abs, const))]

    # ------------------------------------------------------------------
    # run loops
    # ------------------------------------------------------------------

    def _iter_blocks(self, state, start_block: int, make_result,
                     block_jit=None, mega_kind: str = "trace"
                     ) -> Iterator[BlockResult]:
        """THE per-block loop, shared by every trace-shaped mode (single
        and sharded run_blocks, run_ensemble in both formulations):
        init/place state, run the producer jit — ``block_jit`` overrides
        the default wide producer, any (state, inputs) -> (state, a, b)
        jit fits — trim grid padding, delegate the gather to
        ``make_result(off, epoch, a, b, n_valid)``.

        With ``Plan.blocks_per_dispatch > 1``, K blocks run as one mega
        jit (``mega_kind`` selects the per-block body matching
        ``block_jit``) and the stacked per-block outputs are sliced into
        the same ``make_result`` calls.  ``self.state`` then only
        advances at megablock boundaries; consumers that checkpoint it
        after a yielded block MUST gate on ``self.state_block ==
        block_index + 1`` (apps/pvsim.py does).

        ``SimConfig.output_overlap='auto'`` (and per-block dispatch)
        double-buffers the host side: block N+1 is DISPATCHED before
        block N's outputs are gathered/yielded, so the device computes
        N+1 while the host runs ``make_result`` + the consumer's
        CSV/telemetry work on N.  Donation-safe by construction — only
        the carried state is donated (argnum 0), never the (a, b)
        outputs, so the deferred gather reads buffers dispatch N+1
        cannot alias.  The same checkpoint gate keeps pipelining out of
        checkpointed runs: while block N is being consumed
        ``state_block`` is already N+2 (apps/pvsim.py also pins
        ``output_overlap='off'`` when checkpointing)."""
        cfg = self.config
        jit = self._block_jit if block_jit is None else block_jit
        state = self.init_state() if state is None \
            else _copy_jit(self._place_resume(
                self._check_resume_layout(state)))
        self.state = state
        self.state_block = start_block
        pf = InputPrefetcher(self, start_block, self.n_blocks)
        # No dispatch-ahead BEYOND the one-block double buffer: consumers
        # checkpoint ``self.state`` after processing the yielded block
        # (apps/pvsim.py), so the state must always correspond to the
        # last yielded MEGABLOCK (or the overlap must be off).  Further
        # host/device overlap comes from the input prefetcher + async
        # jax dispatch.
        self.timer.reset_clock()
        k = self._k_dispatch
        try:
            if k == 1 and self._output_overlap:
                pend = None  # previous block's un-gathered device outputs
                for bi in range(start_block, self.n_blocks):
                    if faults.ACTIVE is not None:
                        faults.fire("block.stall", block=bi)
                    inputs, epoch = pf.get(bi)
                    with annotate("tmhpvsim/block_step"):
                        self.state, a, b = jit(self.state, inputs)
                    self.state_block = bi + 1
                    self._m_dispatch.inc()
                    if pend is not None:
                        yield self._gather_result(pend, make_result)
                    pend = (bi, epoch, a, b)
                if pend is not None:
                    yield self._gather_result(pend, make_result)
                return
            bi = start_block
            while bi < self.n_blocks:
                kk = min(k, self.n_blocks - bi)
                if faults.ACTIVE is not None:
                    faults.fire("block.stall", block=bi)
                if kk == 1:
                    inputs, epoch = pf.get(bi)
                    with annotate("tmhpvsim/block_step"):
                        self.state, a, b = jit(self.state, inputs)
                    off = bi * cfg.block_s
                    n_valid = min(cfg.block_s, cfg.duration_s - off)
                    result = make_result(off, np.asarray(epoch[:n_valid]),
                                         a, b, n_valid)
                    self.state_block = bi + 1
                    # the gather in make_result synchronised, so the tick
                    # bounds this block's dispatch+compute+gather wall
                    self.timer.tick()
                    self._m_blocks.inc()
                    self._m_dispatch.inc()
                    if self._pod_on:
                        self._observe_pod(bi)
                    yield result
                else:
                    got = [pf.get(b) for b in range(bi, bi + kk)]
                    mega, xs, const = self._mega_dispatch(
                        mega_kind, [g[0] for g in got])
                    with annotate("tmhpvsim/mega_step"):
                        self.state, a_k, b_k = mega(self.state, xs, const)
                    self.state_block = bi + kk
                    results = []
                    for j in range(kk):
                        off = (bi + j) * cfg.block_s
                        n_valid = min(cfg.block_s, cfg.duration_s - off)
                        results.append(make_result(
                            off, np.asarray(got[j][1][:n_valid]),
                            a_k[j], b_k[j], n_valid))
                    # every make_result gathered, so one tick bounds the
                    # whole dispatch+compute+gather wall of the K blocks
                    self.timer.tick(n_blocks=kk)
                    self._m_blocks.inc(kk)
                    self._m_dispatch.inc()
                    if self._pod_on:
                        for j in range(kk):
                            self._observe_pod(bi + j)
                    yield from results
                bi += kk
        finally:
            pf.close()

    def _gather_result(self, pend, make_result):
        """Finish one double-buffered block: gather the deferred device
        outputs (the make_result host sync), tick the timer — which under
        overlap measures gather-to-gather, the pipelined steady state —
        and hand the BlockResult back to ``_iter_blocks``."""
        bi, epoch, a, b = pend
        cfg = self.config
        off = bi * cfg.block_s
        n_valid = min(cfg.block_s, cfg.duration_s - off)
        result = make_result(off, np.asarray(epoch[:n_valid]), a, b,
                             n_valid)
        self.timer.tick()
        self._m_blocks.inc()
        if self._pod_on:
            self._observe_pod(bi)
        return result

    def _trace_result(self, off, epoch, meter, pv, n_valid) -> BlockResult:
        """Per-chain gather: the trace-mode ``make_result``."""
        m = self._host_view(meter)[:, :n_valid]
        p = self._host_view(pv)[:, :n_valid]
        return BlockResult(
            offset=off, epoch=epoch, meter=m, pv=p,
            residual=m - p,  # host numpy: see _block_step docstring
        )

    def run_blocks(self, state=None, start_block: int = 0
                   ) -> Iterator[BlockResult]:
        """Yield BlockResults in time order; padding trimmed from the last."""
        return self._iter_blocks(state, start_block, self._trace_result)

    def run_reduced(self, state=None, on_block=None, acc=None,
                    start_block: int = 0):
        """Run everything, keeping only per-chain running statistics.

        The trace never reaches the host: each block folds into an on-device
        accumulator (``step_acc`` -> ``_stats_acc_jit``) and only the final
        (n_chains,) arrays are gathered — one transfer for the whole run.
        Returns dict of (n_chains,) numpy arrays, one per ``REDUCE_STATS``
        entry.  ``on_block(block_index, state, acc)`` is called after each
        block's dispatch with that block's pytrees (timing/checkpoint
        hooks).  The pytrees are BORROWED — the accumulator carry is
        donated to the next fold, which invalidates retained device
        references and reuses the underlying buffer (a zero-copy
        ``np.asarray`` view taken in the callback silently changes
        value).  Consume them during the callback (``ckpt.save`` does)
        or copy with ``np.array``.  ``acc``/``start_block`` resume a
        checkpointed run: the
        accumulator is part of the saved state, so a resumed reduce run
        folds on where it left off (apps/pvsim.py).  Subclasses redirect
        the per-block work by overriding ``step_acc``, resume placement
        via ``_place_resume`` and the final gather via ``_host_view``
        (ShardedSimulation runs this exact loop under shard_map)."""
        if start_block > 0 and acc is None:
            # trace-mode resume is (state, start_block), but reduce-mode
            # statistics live in the accumulator: restarting it from the
            # identity would silently present the remaining blocks' stats
            # as the full run's
            raise ValueError(
                "resuming run_reduced needs the checkpointed accumulator: "
                "pass acc= alongside state=/start_block="
            )
        if state is None and acc is None and start_block == 0:
            # a fresh run under a slabbing plan executes as sequential
            # slab-sized runs (engine/slab.py) — bit-identical results,
            # each slab inside the fast chain-count regime.  Resumed runs
            # carry single-build state and always run unslabbed.
            sched = self._slab_scheduler()
            if sched is not None:
                reduced = sched.run_reduced(on_block=on_block)
                # host-side accumulator: ensemble_stats folds numpy fine
                self._last_acc = reduced
                # hoist the scheduler's merged fleet total (each slab sim
                # is discarded after its run; the scheduler merge-folds
                # their totals — associative, so slab order is free)
                if getattr(sched, "fleet_total", None) is not None:
                    self._fleet_total = sched.fleet_total
                return reduced
        state = self.init_state() if state is None \
            else _copy_jit(self._place_resume(
                self._check_resume_layout(state)))
        self.state = state
        self.state_block = start_block
        # _copy_jit: the dispatch loop donates state and acc into every
        # jit; a resumed caller's own reference must survive the run
        acc = self.init_reduce_acc() if acc is None \
            else _copy_jit(self._place_resume(self._check_resume_layout(
                acc, self.init_reduce_acc, "acc")))
        self._last_acc = acc  # device-side, for ensemble_stats()
        pf = InputPrefetcher(self, start_block, self.n_blocks)
        self.timer.reset_clock()
        k = self._k_dispatch
        tel_on = self._telemetry != "off"
        fleet_on = self._analytics != "off"
        try:
            bi = start_block
            while bi < self.n_blocks:
                kk = min(k, self.n_blocks - bi)
                # host-side chaos chokepoint: a scheduled delay here is
                # the deterministic straggler the pod monitor detects
                # (never in-graph — the compiled HLO is untouched)
                if faults.ACTIVE is not None:
                    faults.fire("block.stall", block=bi)
                if kk == 1:
                    inputs, _ = pf.get(bi)
                    with annotate("tmhpvsim/block_step"):
                        self.state, acc = self.step_acc(self.state,
                                                        inputs, acc)
                    accs = tels = fleets = None
                else:
                    ins = [pf.get(b)[0] for b in range(bi, bi + kk)]
                    with annotate("tmhpvsim/mega_step"):
                        out = self.step_acc_multi(self.state, ins, acc)
                    self.state, acc, accs = out[0], out[1], out[2]
                    idx = 3
                    tels = fleets = None
                    if tel_on:
                        tels = out[idx]
                        idx += 1
                    if fleet_on:
                        fleets = out[idx]
                self.state_block = bi + kk
                self._last_acc = acc
                # async dispatch: per-dispatch ticks measure dispatch-to-
                # dispatch, which backpressure makes honest over a run
                # (same semantics as the app-level timers)
                self.timer.tick(n_blocks=kk)
                self._m_blocks.inc(kk)
                self._m_dispatch.inc()
                for j in range(kk):
                    bj = bi + j
                    # block-boundary accumulator snapshot: acc itself
                    # per-block, a stacked-ys slice mid-megablock
                    acc_j = acc if accs is None else \
                        jax.tree.map(lambda a, _j=j: a[_j], accs)
                    # BEFORE on_block: a strict sentinel raise must keep
                    # a poisoned block out of checkpoints/sinks
                    if tel_on:
                        if tels is not None:
                            self._tel_last = jax.tree.map(
                                lambda a, _j=j: a[_j], tels)
                        self._observe_telemetry(bj)
                    if fleet_on:
                        if fleets is not None:
                            self._fleet_last = jax.tree.map(
                                lambda a, _j=j: a[_j], fleets)
                        self._observe_fleet(bj)
                    if self._pod_on:
                        self._observe_pod(bj)
                    if on_block is not None:
                        on_block(bj, self.state, acc_j)
                bi += kk
        finally:
            pf.close()
        return {k: self._host_view(v) for k, v in acc.items()}

    def _observe_pod(self, bi: int) -> None:
        """Per-block pod heartbeat (obs/pod.py): gather every host's
        block wall, compute skew/straggler verdicts, and keep the pod
        section current.  COLLECTIVE under multi-process jax — every
        run path calls it from the per-block tail that executes
        identically on all hosts (the sharded dispatch already
        synchronised the pod at this boundary).  The monitor is built
        lazily here so the sharded subclass's ``self.mesh`` exists."""
        if self._pod is None:
            from tmhpvsim_tpu.obs.pod import PodMonitor
            from tmhpvsim_tpu.parallel.distributed import local_chain_slice

            cfg = self.config
            start, stop = 0, cfg.n_chains
            mesh = getattr(self, "mesh", None)
            try:
                multi = jax.process_count() > 1
            except Exception:
                multi = False
            if mesh is not None and multi:
                sl = local_chain_slice(cfg.n_chains, mesh)
                start, stop = sl.start, sl.stop
            self._pod = PodMonitor(
                n_chains=cfg.n_chains, block_s=cfg.block_s,
                straggler_factor=getattr(cfg, "pod_straggler_factor",
                                         2.0),
                registry=self.metrics, chain_start=start,
                chain_stop=stop)
        wall = self.timer.last_block_s()
        self._pod.observe_block(bi, wall,
                                (1.0 / wall) if wall > 0 else 0.0)

    def _observe_telemetry(self, bi: int) -> None:
        """Per-block telemetry flush: fetch the block's ~30 accumulator
        scalars (piggybacking on the per-block sync reduce mode already
        pays), publish them into the registry under ``device.*`` and hand
        the summary to the drift sentinel.  Constructed lazily so an
        'off' run never imports the sentinel."""
        if self._tel_last is None:
            return
        ta = {k: self._repl_view(v) for k, v in self._tel_last.items()}
        summary = tel.summarize(ta)
        tel.publish(self.metrics, summary)
        if self.sentinel is None:
            from tmhpvsim_tpu.obs.sentinel import DriftSentinel

            self.sentinel = DriftSentinel(
                self.config, level=self._telemetry,
                strict=getattr(self.config, "telemetry_strict", False),
            )
        self.sentinel.observe_block(bi, summary)

    def _observe_fleet(self, bi: int) -> None:
        """Per-block fleet flush: fetch the block's sketch delta
        (piggybacking on the per-block sync), merge it into the
        host-side run total (int64/float64 — exact past the per-block
        int32 bound) and publish the running summary under
        ``device.fleet.*``."""
        del bi
        if self._fleet_last is None:
            return
        fa = {k: self._repl_view(v) for k, v in self._fleet_last.items()}
        self._fleet_total = flt.merge_host(self._fleet_total, fa)
        flt.publish(self.metrics,
                    flt.summarize(fa, self._fleet_params))

    def fleet_summary(self):
        """The run-total ``fleet`` report section (obs/analytics.py
        summarize of the host-merged totals), or None when analytics is
        off / no block has been observed yet."""
        if self._fleet_total is None or self._fleet_params is None:
            return None
        return flt.summarize(self._fleet_total, self._fleet_params)

    def _slab_scheduler(self):
        """The SlabScheduler this run should delegate to, or None when
        slabbing does not apply: the plan doesn't slab, the config is
        itself already an explicit slab, or the caller disabled
        delegation (``allow_slabs`` — sharded meshes partition chains
        themselves; checkpointed runs need single-build state)."""
        cfg = self.config
        if (not self.allow_slabs or cfg.n_chains_total is not None
                or not 0 < self.plan.slab_chains < cfg.n_chains):
            return None
        from tmhpvsim_tpu.engine.slab import SlabScheduler

        return SlabScheduler(cfg, self.plan)

    def _place_resume(self, tree):
        """Loaded checkpoint pytrees (host numpy from ``checkpoint.load``)
        onto device.  The base class lets jit place them; the sharded
        subclass applies the chain sharding so a resumed run (including one
        with zero remaining blocks) has real device arrays."""
        return tree

    def _check_resume_layout(self, tree, init_fn=None, what="state"):
        """A resumed state/acc pytree must have this build's leaf set,
        dtypes, and trailing dims.  The rng_stream/config gate in
        checkpoint.load is the real guard; if a foreign layout ever slips
        past it (e.g. a hand-edited npz or a pre-windowed
        'arrays'-bearing v2 state), fail here with the leaf names instead
        of an opaque tree-structure error deep in jit (round-4 ADVICE).
        eval_shape traces the initializer without allocating, so the
        comparison is O(ms) at any chain count.  Axis 0 (chains) is
        deliberately NOT compared: a pod-slice checkpoint stores each
        host's local slice (host_local_tree), whose chain count is the
        per-host share of the global value eval_shape reports."""
        ku = jax.tree_util

        def sig(t):
            return {ku.keystr(p): (str(v.dtype), tuple(jnp.shape(v)[1:]))
                    for p, v in ku.tree_flatten_with_path(t)[0]}

        want = sig(jax.eval_shape(init_fn or self.init_state))
        got = sig(tree)
        if want != got:
            changed = sorted(f"{k}: expected {want[k]}, got {got[k]}"
                             for k in set(want) & set(got)
                             if want[k] != got[k])
            raise ValueError(
                f"resume {what} does not match this build's layout: "
                f"missing leaves {sorted(set(want) - set(got)) or '{}'}, "
                f"unexpected leaves {sorted(set(got) - set(want)) or '{}'}, "
                f"dtype/shape mismatches {changed or '{}'} — the "
                "checkpoint was written by an incompatible build or "
                "edited by hand"
            )
        return tree

    def host_local_tree(self, tree):
        """The checkpointable (host-addressable) view of a state/acc
        pytree.  Single-device state is already fully addressable; the
        sharded subclass restricts every chain-sharded leaf to this host's
        slice so each pod-slice host saves exactly the chains it owns
        (per-host checkpoint files, apps/pvsim.py)."""
        return tree

    def checkpoint_layout(self) -> dict:
        """Placement metadata for ``checkpoint.save(layout=...)``: which
        global chains this process's checkpoint file holds and under what
        topology.  Never identity — a resume under a different topology
        reshards from this record (checkpoint.load_elastic) instead of
        refusing.  An explicit slab config (autotune's chain_offset
        carving) reports its slice of the notional full run."""
        from tmhpvsim_tpu.parallel.distributed import chain_layout

        cfg = self.config
        total = getattr(cfg, "n_chains_total", None) or cfg.n_chains
        lay = chain_layout(total, getattr(self, "mesh", None))
        off = getattr(cfg, "chain_offset", 0) or 0
        if total != cfg.n_chains or off:
            lay.update(n_chains=int(total),
                       chain_start=int(off),
                       chain_stop=int(off + cfg.n_chains))
        return lay

    def resume_chain_slice(self):
        """The (start, stop) global chain range this process should load
        when resuming from a FULL (unsharded) checkpoint, or None when it
        needs the whole chain axis.  The multi-host sharded subclass
        returns its local slice so ``checkpoint.load_elastic`` can hand
        each host exactly its chains (topology-elastic resume)."""
        return None

    def local_reduced_view(self, reduced: dict) -> tuple:
        """(global chain slice, host-local dict) of a ``run_reduced``
        result — trivially everything on a single host; the sharded class
        returns this host's contiguous slice (parallel/mesh.py)."""
        return slice(0, self.config.n_chains), reduced

    @staticmethod
    def _host_view(arr) -> np.ndarray:
        """Device->host copy of one result leaf (sharded subclasses return
        only the addressable slice here — see ShardedSimulation)."""
        return np.array(arr)

    def ensemble_stats(self) -> dict:
        """Fleet-wide scalar aggregates of the last ``run_reduced``: the
        "grid operator" view the reference approximates by eyeballing N
        consumer CSVs (SURVEY.md §2.4).  Returns python floats/ints."""
        a = self._last_acc
        np_op = {"sum": np.sum, "max": np.max, "min": np.min}
        out = {}
        for name, (kind, dkind) in REDUCE_STATS.items():
            # float64 (or int64) accumulation for the cross-chain fold
            v = np.asarray(a[name], np.int64 if dkind == "i" else np.float64)
            out[name] = (int if dkind == "i" else float)(np_op[kind](v))
        return out

    def precision_doc(self):
        """The report's ``precision`` section when a non-default lever is
        active (``compute_dtype``/``kernel_impl``/``rng_batch``/
        ``geom_stride``), else None — reports written by app code and by
        :meth:`run_report` must agree."""
        cdt = getattr(self.plan, "compute_dtype", "f32")
        kimpl = getattr(self.plan, "kernel_impl", "exact")
        rb = getattr(self.plan, "rng_batch", "scan")
        gs = int(getattr(self.plan, "geom_stride", 1))
        if cdt == "f32" and kimpl == "exact" and rb == "scan" and gs == 1:
            return None
        return {
            "compute_dtype": cdt,
            "kernel_impl": kimpl,
            "rng_batch": rb,
            "geom_stride": gs,
            "telemetry": self.plan.telemetry,
            "output_overlap": bool(self._output_overlap),
        }

    def _attribution_jits(self) -> list:
        """``[(jit, make_args)]`` for the active reduce-mode block
        dispatch.  Each ``make_args()`` builds FRESH concrete arguments
        (block-0 inputs, new state/accumulator buffers) — the jits
        donate state and accumulator, so every dispatch of an
        ahead-of-time compiled executable needs live inputs."""
        if self._impl in ("scan", "scan2"):
            s2 = self._impl == "scan2"
            if self._analytics != "off":
                if self._telemetry != "off":
                    j = (self._scan2_acc_tel_fleet_jit if s2
                         else self._scan_acc_tel_fleet_jit)
                else:
                    j = (self._scan2_acc_fleet_jit if s2
                         else self._scan_acc_fleet_jit)
            elif self._telemetry != "off":
                j = self._scan2_acc_tel_jit if s2 else self._scan_acc_tel_jit
            else:
                j = self._scan2_acc_jit if s2 else self._scan_acc_jit
        elif self._use_fused and self._telemetry == "off" \
                and self._analytics == "off":
            j = self._fused_acc_jit
        else:
            # wide split path: producer + stats consumer are separate
            # jits; the consumer runs on zero-filled block arrays (the
            # numbers are irrelevant to op-time attribution)
            def block_args():
                inputs, _ = self.host_inputs(0)
                return (self.init_state(), inputs)

            def stats_args():
                inputs, _ = self.host_inputs(0)
                meter = jnp.zeros(
                    (self.config.n_chains, self.config.block_s),
                    self.dtype)
                return (meter, meter, inputs["block_idx"]["t"],
                        self.init_reduce_acc())

            return [(self._block_jit, block_args),
                    (self._stats_acc_jit, stats_args)]

        def acc_args():
            inputs, _ = self.host_inputs(0)
            return (self.init_state(), inputs, self.init_reduce_acc())

        return [(j, acc_args)]

    def attribution_hlo_texts(self) -> list:
        """Compiled (optimized) HLO text(s) of the active reduce-mode
        block dispatch — what ``obs.attribution.write_phase_map`` parses
        into the op-name → phase join basis.  Meaningful phase scopes
        appear only when ``phase_obs`` is on.

        CAVEAT: XLA instruction numbering is NOT stable across separate
        compilations of the same graph, so these texts only join against
        a trace of the very executables compiled here — use
        :meth:`attribution_capture`, which traces the same compiled
        objects, rather than pairing this with an independently captured
        trace."""
        return [j.lower(*args()).compile().as_text()
                for j, args in self._attribution_jits()]

    def attribution_capture(self, log_dir: str, n_dispatches: int = 2):
        """The whole scoped-capture protocol, self-contained: AOT-compile
        the active reduce-mode dispatch, warm up OUTSIDE the trace, run
        ``n_dispatches`` traced dispatches of the SAME executables,
        write the phase map parsed from those executables' optimized
        HLO, and attribute the trace (obs/attribution.py).

        The phase map must come from the very executables the trace
        recorded: instruction numbering differs between separate
        compilations of one graph, and a fresh ``lower().compile()`` at
        analysis time joins ~0% of the traced device time.  Sets and
        returns ``self.attribution`` (None when the trace yielded no
        attributable events); returns a ``(doc, stats)`` pair where
        stats carries ``compile_s`` / ``traced_wall_s`` /
        ``n_dispatches`` for the caller's timing sections."""
        import time as _time

        from tmhpvsim_tpu.obs import attribution as _attr
        from tmhpvsim_tpu.obs.profiler import device_trace

        t0 = _time.perf_counter()
        compiled = [(j.lower(*args()).compile(), args)
                    for j, args in self._attribution_jits()]
        texts = [c.as_text() for c, _ in compiled]
        for c, args in compiled:  # warm-up dispatch outside the trace
            jax.block_until_ready(c(*args()))
        compile_s = _time.perf_counter() - t0
        # args are built OUTSIDE the trace too — state/acc init runs its
        # own device ops, which would land in the trace as unattributed
        # noise (the jits donate, so each dispatch needs fresh buffers)
        staged = [[(c, args()) for c, args in compiled]
                  for _ in range(n_dispatches)]
        # force the staged buffers NOW: dispatch is async, and letting
        # the init computations execute inside the trace window floods
        # the profiler's event cap with jit_build ops (measured: they
        # drowned the real dispatch to a ~0.6% join)
        jax.block_until_ready([a for batch in staged for _, a in batch])
        t1 = _time.perf_counter()
        with device_trace(log_dir, python_tracer=False):
            for batch in staged:
                for c, a in batch:
                    jax.block_until_ready(c(*a))
        traced_wall_s = _time.perf_counter() - t1
        _attr.write_phase_map(log_dir, texts)
        self.attribution = _attr.attribute(log_dir)
        return self.attribution, {
            "compile_s": compile_s, "traced_wall_s": traced_wall_s,
            "n_dispatches": n_dispatches,
        }

    def run_report(self, app: str = "engine", path=None, headline=None):
        """The run's :class:`~tmhpvsim_tpu.obs.report.RunReport`: config,
        the resolved plan, the internal timer's compile/steady split, and
        every metric this run's registry accumulated (slab progress,
        checkpoint timings, pacing slip).  Writes to ``path`` when given;
        returns the validated document either way."""
        from tmhpvsim_tpu.obs.report import RunReport

        rep = RunReport(app, config=self.config, plan=self.plan)
        summary = self.timer.summary()
        rep.set_timing(summary)
        if self.attribution is not None:
            # publish BEFORE the metrics dump so the gauges land in it
            from tmhpvsim_tpu.obs.attribution import publish_phase_gauges

            publish_phase_gauges(self.metrics, self.attribution)
            rep.attribution = self.attribution
        rep.attach_metrics(self.metrics)
        if self.sentinel is not None:
            rep.telemetry = self.sentinel.report()
        fleet_sec = self.fleet_summary()
        if fleet_sec is not None:
            rep.fleet = fleet_sec
        prec = self.precision_doc()
        if prec is not None:
            rep.precision = prec
        if self._pod is not None:
            rep.pod = self._pod.doc()
        rep.headline = headline if headline is not None else {
            "site_seconds_per_s": summary["site_seconds_per_s"],
        }
        return rep.write(path) if path else rep.doc()


def write_csv(path: str, blocks: Iterator[BlockResult], chain: int = 0,
              tz=None, append: bool = False):
    """Write the reference CSV format — header ``time,meter,pv,residual
    load``, one row per second (pvsim.py:78-83) — for one selected chain.

    ``tz`` converts the grid's UTC epochs to wall time for the ``time``
    column; rows are written as naive local datetimes like the reference's
    (which prints the fixedclock's naive local grid).  Default: the
    process's local timezone.  Pass the site's ZoneInfo to get site-local
    rows regardless of host timezone.  ``append`` skips the header and adds
    to an existing file (checkpoint resume).
    """
    import csv

    mode = "a" if append else "w"
    with open(path, mode=mode, newline="", buffering=1) as f:
        w = csv.writer(f)
        if not append:
            w.writerow(["time", "meter", "pv", "residual load"])
        for blk in blocks:
            for e, m, p, r in zip(
                blk.epoch, blk.meter[chain], blk.pv[chain], blk.residual[chain]
            ):
                t = _dt.datetime.fromtimestamp(int(e), tz)
                if tz is not None:
                    t = t.replace(tzinfo=None)
                w.writerow([t, m, p, r])
