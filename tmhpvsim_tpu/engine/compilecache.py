"""Warm-start executor: persistent compilation cache + AOT plan warm-up.

BENCH_r05 measured ~0.29 s steady blocks against 66.8-79.6 s compiles per
variant on TPU v5e — a single cold compile exceeds the whole <60 s
target budget.  This module removes that cost from every run after the
first:

* :func:`configure` enables JAX's on-disk compilation cache under a
  per-device-kind subdirectory (a v5e executable is useless to a CPU
  process and vice versa), with the entry-size/compile-time floors
  lowered so EVERY executable is persisted and the warm/cold counters
  below are exact, not sampled.
* A process-global ``jax.monitoring`` listener maps the cache's
  hit/miss events onto the metrics registry
  (``executor.compile_warm_total`` / ``executor.compile_cold_total``).
  The registry is resolved at event time, so per-run
  ``obs.metrics.use_registry()`` isolation sees its own counts.
* :func:`warm_up` AOT-compiles (``fn.lower(*abstract).compile()``) the
  resolved :class:`~tmhpvsim_tpu.config.Plan`'s block functions from
  abstract shapes at ``Simulation`` build time, populating the disk
  cache before the first real dispatch.  ``Simulation.__init__`` calls
  this automatically — but only when the cache has been configured, so
  plain library use pays nothing.
* :func:`executor_doc` snapshots the counters into the run report's
  ``executor`` section (schema v4, obs/report.py).
* :func:`warm_up` also harvests the first hot per-block executable's
  ``cost_analysis()`` flops/bytes as the *measured* cost basis
  (:func:`measured_cost`) — ``obs/cost.py`` consumes it as
  ``basis: "measured"`` with no manual plumbing, and the executor doc
  carries the raw numbers.

Cache-dir precedence: explicit argument > ``TMHPVSIM_COMPILE_CACHE`` >
``$XDG_CACHE_HOME/tmhpvsim_tpu/xla`` (``~/.cache`` fallback).  The
values ``off``/``none``/``0``/empty disable the cache entirely.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

logger = logging.getLogger(__name__)

#: environment override for the cache base directory (also honours the
#: ``off`` spellings below) — lets the battery script and ``bench.py``
#: child processes steer or disable the cache without new plumbing
ENV_VAR = "TMHPVSIM_COMPILE_CACHE"

#: spellings of "no cache" accepted by configure()/the env var/--compile-cache
OFF_VALUES = frozenset({"off", "none", "0", ""})

# process-global state: the persistent cache is a jax.config property,
# so there is exactly one active cache dir per process.  ``cost`` is
# the auto-harvested ``compiled.cost_analysis()`` of the hot per-block
# jit (set by warm_up, read by obs/cost.py as the measured basis).
_state = {"dir": None, "configured": False, "listener": None,
          "cost": None}

#: aot_targets whose cost_analysis is NOT the hot per-block dispatch
#: (mega jits fold K blocks, resume copies are identity, scenario
#: batches are the serving path) — the harvest skips them
_COST_SKIP_PREFIXES = ("mega_", "resume_copy", "scenario_acc")


def default_dir() -> str:
    """``$XDG_CACHE_HOME/tmhpvsim_tpu/xla`` (mirrors autotune.cache_path)."""
    root = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    return os.path.join(root, "tmhpvsim_tpu", "xla")


def _device_kind_slug() -> str:
    """Filesystem-safe slug of the primary device kind ('tpu-v5e',
    'cpu', ...); 'unknown' when no backend is reachable."""
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:  # no backend / not yet initialisable
        kind = None
    slug = "".join(
        c if (c.isalnum() or c in "-_.") else "-" for c in (kind or "").lower()
    ).strip("-")
    return slug or "unknown"


def cache_dir() -> Optional[str]:
    """The active per-device-kind cache directory (None when disabled)."""
    return _state["dir"]


def is_configured() -> bool:
    return _state["configured"]


def _on_event(event: str, **kwargs) -> None:
    # jax.monitoring fires these on the persistent-cache paths:
    #   cache_hits   -> executable deserialised from disk (warm compile)
    #   cache_misses -> freshly compiled and stored (cold compile)
    # Resolve the registry at EVENT time so use_registry() scopes work.
    if event == "/jax/compilation_cache/cache_hits":
        from tmhpvsim_tpu.obs import metrics as obs_metrics

        obs_metrics.get_registry().counter("executor.compile_warm_total").inc()
    elif event == "/jax/compilation_cache/cache_misses":
        from tmhpvsim_tpu.obs import metrics as obs_metrics

        obs_metrics.get_registry().counter("executor.compile_cold_total").inc()


def _install_listener() -> None:
    if _state["listener"] is not None:
        return
    import jax

    jax.monitoring.register_event_listener(_on_event)
    _state["listener"] = _on_event


def configure(base_dir: Optional[str] = None) -> Optional[str]:
    """Enable the persistent compilation cache; returns the resolved
    per-device-kind directory, or None when disabled.

    ``base_dir`` precedence: explicit argument > :data:`ENV_VAR` >
    :func:`default_dir`.  Any :data:`OFF_VALUES` spelling disables the
    cache (and un-configures a previously configured one, so tests can
    restore a clean state).
    """
    import jax

    if base_dir is None:
        base_dir = os.environ.get(ENV_VAR)
        if base_dir is None:
            base_dir = default_dir()
    if str(base_dir).strip().lower() in OFF_VALUES:
        if _state["configured"]:
            jax.config.update("jax_compilation_cache_dir", None)
            _reset_cache_singleton()
        _state["dir"] = None
        _state["configured"] = False
        return None

    d = os.path.join(
        os.path.abspath(os.path.expanduser(str(base_dir))), _device_kind_slug()
    )
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    # Floor removal: by default JAX only persists executables above a
    # compile-time/entry-size threshold, which would make fast CPU test
    # kernels invisible to the cache and the warm/cold counters wrong.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _reset_cache_singleton()
    _install_listener()
    _state["dir"] = d
    _state["configured"] = True
    logger.info("persistent compilation cache at %s", d)
    return d


def _reset_cache_singleton() -> None:
    """Drop jax's in-process cache object so a dir change takes effect.

    The on-disk cache is lazily materialised ONCE per process from
    ``jax_compilation_cache_dir``; without this reset, a process that
    already compiled something (and thereby initialised the cache
    against the old dir — or against no dir at all) would silently keep
    writing to the old location after :func:`configure`."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as e:  # pragma: no cover - private-API drift guard
        logger.warning("compilation-cache reset unavailable: %s", e)


def maybe_warm_up(sim) -> Optional[dict]:
    """AOT warm-up hook for ``Simulation.__init__``: no-op unless
    :func:`configure` has enabled the cache in this process."""
    if not _state["configured"]:
        return None
    return warm_up(sim)


def warm_up(sim) -> dict:
    """AOT-compile the simulation's resolved block functions.

    Iterates ``sim.aot_targets()`` — the (name, jitted fn, abstract
    args) triples of the jits the resolved output mode will actually
    dispatch — and runs ``fn.lower(*args).compile()`` on each.  The
    compiled executables land in the persistent disk cache (AOT
    compilation does not feed the jit call path's in-memory cache; its
    value is that the first real dispatch deserialises instead of
    compiling).  Per-target failures are non-fatal: warm-up is an
    optimisation, never a correctness gate.
    """
    from tmhpvsim_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.get_registry()
    t0 = time.perf_counter()
    compiled = 0
    errors = 0
    targets = []
    try:
        targets = list(sim.aot_targets())
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("AOT target enumeration failed: %s", e)
        errors += 1
    for name, fn, args in targets:
        try:
            exe = fn.lower(*args).compile()
            compiled += 1
        except Exception as e:
            errors += 1
            logger.warning("AOT warm-up of %s failed: %s", name, e)
            continue
        _harvest_cost(sim, name, exe)
    wall = time.perf_counter() - t0
    if compiled:
        reg.counter("executor.aot_warmup_total").inc(compiled)
    if errors:
        reg.counter("executor.aot_warmup_errors_total").inc(errors)
    reg.gauge("executor.aot_warmup_s").add(wall)
    return {
        "targets": len(targets),
        "compiled": compiled,
        "errors": errors,
        "wall_s": wall,
    }


def _harvest_cost(sim, name: str, compiled) -> None:
    """Attach the FIRST hot per-block target's XLA ``cost_analysis()``
    flops/bytes to the process state — the measured basis the cost
    audit consumes (obs/cost.py), with NO manual plumbing: every AOT
    warm-up harvests it for free.

    The per-dispatch figures are normalised by the dispatch's simulated
    site-seconds (``n_chains × block_s`` — the skip list keeps multi-
    block mega jits and non-dispatch targets out), so the stored
    ``flops_per_site_s`` / ``bytes_per_site_s`` compare directly with
    the static-v1 model's anchors.  ``cost_analysis`` returns a dict on
    current jax and a one-element list of dicts on older releases; the
    HBM-traffic key is spelled ``"bytes accessed"``.  Harvest failures
    are silent by design — measurement is an upgrade, never a gate.
    """
    if _state.get("cost") is not None:
        return
    if name.startswith(_COST_SKIP_PREFIXES):
        return
    try:
        ca = compiled.cost_analysis()
    except Exception as e:
        logger.debug("cost_analysis unavailable for %s: %s", name, e)
        return
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return
    flops = ca.get("flops")
    nbytes = ca.get("bytes accessed")
    if not isinstance(flops, (int, float)) or flops <= 0:
        return
    try:
        site_s = float(sim.config.n_chains) * float(sim.config.block_s)
    except Exception:
        return
    if site_s <= 0:
        return
    cost = {
        "target": name,
        "site_s_per_dispatch": site_s,
        "flops": float(flops),
        "flops_per_site_s": float(flops) / site_s,
    }
    if isinstance(nbytes, (int, float)) and nbytes > 0:
        cost["bytes_accessed"] = float(nbytes)
        cost["bytes_per_site_s"] = float(nbytes) / site_s
    tr = ca.get("transcendentals")
    if isinstance(tr, (int, float)) and tr > 0:
        cost["transcendentals"] = float(tr)
    _state["cost"] = cost
    logger.info(
        "measured cost basis from %s: %.1f flops / %.1f bytes per "
        "site-second", name, cost["flops_per_site_s"],
        cost.get("bytes_per_site_s", 0.0),
    )


def measured_cost() -> Optional[dict]:
    """The auto-harvested XLA ``cost_analysis`` of the hot per-block
    jit, normalised per site-second (None until an AOT warm-up compiled
    one in this process).  ``obs.cost.cost_doc`` reads this as the
    ``basis: "measured"`` input."""
    return _state.get("cost")


def executor_doc(registry=None) -> Optional[dict]:
    """Executor section for a run report (schema v4): warm/cold compile
    counts, dispatch counts and AOT warm-up stats from ``registry``
    (default: the process registry).  None when nothing executor-related
    was recorded and no cache is configured — callers can attach it
    unconditionally."""
    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.obs import report as obs_report

    reg = registry if registry is not None else obs_metrics.get_registry()
    doc = obs_report.executor_section(reg.snapshot())
    if doc is None and not _state["configured"]:
        return None
    doc = doc or {}
    doc.setdefault("compile_warm", 0)
    doc.setdefault("compile_cold", 0)
    doc["cache_dir"] = _state["dir"]
    if _state.get("cost") is not None:
        doc["cost_analysis"] = dict(_state["cost"])
    return doc
