"""Runtime autotuner: probe-based execution-plan selection with a
persistent per-device cache.

Round-5 hardware runs (benchmarks/PERF_ANALYSIS.md §7a) proved throughput
is a cliff function of the static knobs: the scan-fused block at
65536x1080 runs 3.5 ms at ``scan_unroll=8`` but 60-193 ms once the
unrolled live set spills VMEM, and the winning combination differs by
backend (CPU prefers ``wide``, TPU ``scan``, long-horizon shapes
``scan2``).  This module makes that tuning a subsystem instead of
folklore:

* :func:`static_plan` — the historical ``'auto'`` heuristics, resolved
  into a concrete :class:`~tmhpvsim_tpu.config.Plan` (``tune='off'``,
  zero overhead);
* :func:`probe_grid` — time a small candidate grid (``block_impl`` x
  ``scan_unroll`` x slab size) with short REAL-block probes: compile
  once, time a couple of steady blocks, and free each candidate
  Simulation before the next so HBM-residency poisoning (§7a fact 2:
  a resident sim degraded later timed runs up to 30x) cannot skew the
  comparison.  Every candidate of one config simulates the same run
  (keyed construction), so plan choice is purely a performance decision;
* a JSON cache keyed by (device kind, backend, n_chains, block_s, dtype,
  prng_impl, engine version) under ``~/.cache/tmhpvsim_tpu/autotune.json``
  (override: ``TMHPVSIM_AUTOTUNE_CACHE``) so later runs at the same key
  pay zero probe cost;
* :func:`resolve_plan_for_mesh` — multi-host meshes probe on process 0
  at the per-device shape and broadcast the winner, so every host runs
  the same plan without N hosts re-probing.

``bench.py`` shares :func:`time_reduce_blocks` (its variant sweep and
these probes are the same measurement protocol).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import tempfile
import time

from tmhpvsim_tpu.config import Plan, SimConfig, slice_grid
from tmhpvsim_tpu import fleet as fleet_mod

logger = logging.getLogger(__name__)

#: bump when the engine's block formulations change meaning: stale cache
#: entries (different key) are simply ignored, never misapplied
AUTOTUNE_ENGINE_VERSION = 1

#: candidate grid (module-level so tests/callers can narrow it)
CANDIDATE_IMPLS = ("wide", "scan", "scan2")
CANDIDATE_UNROLLS = (1, 4, 8, 12)
#: slab sizes; None means n_chains (no slabbing).  65536 is the measured
#: single-chip sweet spot, 16384 a guard for smaller-VMEM parts.
CANDIDATE_SLAB_CHAINS = (None, 65536, 16384)
#: blocks fused per device dispatch (engine/simulation.py
#: ``blocks_per_dispatch``), probed as a fourth grid axis when
#: ``SimConfig.blocks_per_dispatch`` is left 0 (auto)
CANDIDATE_BLOCKS_PER_DISPATCH = (1, 4)
#: precision axes (config.Plan ``compute_dtype`` / ``kernel_impl``).
#: NOT part of the base candidate product: the staged search in
#: :func:`probe_grid` first picks the structural winner at the resolved
#: precision, then probes precision variants of that winner only — and a
#: non-default variant may win only when the drift sentinel passes on a
#: strict-telemetry gate run (exact/f32 is never silently replaced).
CANDIDATE_COMPUTE_DTYPES = ("f32", "bf16")
CANDIDATE_KERNEL_IMPLS = ("exact", "table")
#: scan-restructuring axes (config.Plan ``rng_batch`` / ``geom_stride``),
#: probed in the same sentinel-gated stage 2 as the precision axes:
#: whole-block RNG pre-generation is bit-identical by construction but
#: still rides the gate (a candidate that cannot complete the gate run
#: must not win); strided geometry is an approximation and the gate is
#: its runtime drift check on top of the published static bound.
CANDIDATE_RNG_BATCHES = ("scan", "block")
CANDIDATE_GEOM_STRIDES = (1, 60)

#: chains/blocks of the sentinel gate run (small: it pays a compile)
SENTINEL_GATE_CHAINS = 4096
SENTINEL_GATE_BLOCKS = 4

#: steady blocks timed per probe (after the one compile/warm-up block)
PROBE_TIMED_BLOCKS = 2

#: probes performed by this process (tests assert cache hits via this)
PROBE_COUNT = 0

#: compile seconds of the most recent real probe — cache-WARM when the
#: persistent compile cache (engine/compilecache.py) is configured, so
#: the plan-cache entry records what a warm start actually costs.
#: probe_grid copies it into each candidate record; None after a
#: monkeypatched/fake probe.
LAST_PROBE_COMPILE_S = None


# ---------------------------------------------------------------------------
# static resolution (tune='off' and the probe fallback)
# ---------------------------------------------------------------------------


def _resolve_fusion(config: SimConfig) -> str:
    import jax

    if config.stats_fusion == "auto":
        return "fused" if jax.default_backend() != "cpu" else "split"
    if config.stats_fusion in ("fused", "split"):
        return config.stats_fusion
    raise ValueError(
        f"stats_fusion must be 'auto', 'fused' or 'split', "
        f"got {config.stats_fusion!r}"
    )


def _resolve_telemetry(config: SimConfig) -> str:
    level = getattr(config, "telemetry", "off")
    if level not in ("off", "light", "full"):
        raise ValueError(
            f"telemetry must be 'off', 'light' or 'full', got {level!r}"
        )
    return level


def _resolve_analytics(config: SimConfig) -> str:
    level = getattr(config, "analytics", "off")
    if level not in ("off", "risk", "full"):
        raise ValueError(
            f"analytics must be 'off', 'risk' or 'full', got {level!r}"
        )
    return level


def _resolve_impl(config: SimConfig) -> str:
    import jax

    if config.block_impl == "auto":
        return "scan" if jax.default_backend() != "cpu" else "wide"
    if config.block_impl in ("wide", "scan", "scan2"):
        return config.block_impl
    raise ValueError(
        f"block_impl must be 'auto', 'wide', 'scan' or 'scan2', "
        f"got {config.block_impl!r}"
    )


def _resolve_compute_dtype(config: SimConfig) -> str:
    cdt = getattr(config, "compute_dtype", "auto")
    if cdt == "auto":
        return "f32"  # the tuner's staged probe may still pick bf16
    if cdt in ("f32", "bf16"):
        return cdt
    raise ValueError(
        f"compute_dtype must be 'auto', 'f32' or 'bf16', got {cdt!r}"
    )


def _resolve_kernel_impl(config: SimConfig) -> str:
    ki = getattr(config, "kernel_impl", "auto")
    if ki == "auto":
        return "exact"  # the tuner's staged probe may still pick 'table'
    if ki in ("exact", "table"):
        return ki
    raise ValueError(
        f"kernel_impl must be 'auto', 'exact' or 'table', got {ki!r}"
    )


def _resolve_rng_batch(config: SimConfig) -> str:
    rb = getattr(config, "rng_batch", "auto")
    if rb == "auto":
        return "scan"  # the tuner's staged probe may still pick 'block'
    if rb in ("scan", "block"):
        return rb
    raise ValueError(
        f"rng_batch must be 'auto', 'scan' or 'block', got {rb!r}"
    )


def _resolve_geom_stride(config: SimConfig) -> int:
    gs = int(getattr(config, "geom_stride", 0))
    if gs == 0:
        return 1  # auto: the tuner's staged probe may still pick coarser
    if gs in (1, 30, 60):
        return gs
    raise ValueError(
        f"geom_stride must be 0 (auto), 1, 30 or 60, got {gs!r}"
    )


def _escalate_telemetry(level: str, compute_dtype: str) -> str:
    """bf16 must never run unwatched: an 'off' telemetry request
    escalates to 'light' whenever the mixed-precision path is active, so
    the drift sentinel vs the f64 golden mirror stays the correctness
    gate (SimConfig.compute_dtype docstring)."""
    if compute_dtype == "bf16" and level == "off":
        return "light"
    return level


def static_plan(config: SimConfig) -> Plan:
    """The un-measured plan: 'auto' knobs resolved by backend heuristic
    (scan+fused on accelerators, wide+split on CPU — the historical
    behaviour), no slabbing."""
    cdt = _resolve_compute_dtype(config)
    return Plan(
        block_impl=_resolve_impl(config),
        scan_unroll=config.scan_unroll,
        stats_fusion=_resolve_fusion(config),
        slab_chains=config.n_chains,
        source="static",
        telemetry=_escalate_telemetry(_resolve_telemetry(config), cdt),
        analytics=_resolve_analytics(config),
        # 0 (auto) resolves to per-block dispatch without measurement;
        # the fused dispatch only enters statically when pinned
        blocks_per_dispatch=max(1, config.blocks_per_dispatch),
        compute_dtype=cdt,
        kernel_impl=_resolve_kernel_impl(config),
        rng_batch=_resolve_rng_batch(config),
        geom_stride=_resolve_geom_stride(config),
    )


# ---------------------------------------------------------------------------
# measurement (shared with bench.py)
# ---------------------------------------------------------------------------


def time_reduce_blocks(sim, n_blocks: int, n_rounds: int = 1,
                       profile_dir=None, expect_platform=None):
    """(compile_s, best_steady_s, rate): one warm-up dispatch, then
    n_rounds x n_blocks timed reduce-mode dispatches through the public
    step_acc path, best round kept (the tunnel TPU's throughput varies
    ~2x between otherwise identical runs).  A sim resolved to
    ``blocks_per_dispatch=k > 1`` is timed the way it actually runs —
    each dispatch is one ``step_acc_multi`` megablock covering k blocks,
    and the rate credits all of them — so ``sim.n_blocks`` must cover
    ``k * (1 + n_blocks*n_rounds)`` blocks; rate is simulated
    site-seconds per wall second.  ``expect_platform`` arms the
    device-trace platform guard when ``profile_dir`` is set
    (obs/profiler.py)."""
    import contextlib

    import jax

    from tmhpvsim_tpu.engine.simulation import InputPrefetcher

    k = max(1, getattr(sim, "_k_dispatch", 1))
    sim.state = sim.init_state()
    acc = sim.init_reduce_acc()
    pf = InputPrefetcher(sim, 0, sim.n_blocks)

    def dispatch(bi, acc):
        if k == 1:
            inputs, _ = pf.get(bi)
            sim.state, acc = sim.step_acc(sim.state, inputs, acc)
        else:
            ins = [pf.get(b)[0] for b in range(bi, bi + k)]
            out = sim.step_acc_multi(sim.state, ins, acc)
            sim.state, acc = out[0], out[1]
        return acc

    t_c = time.perf_counter()
    acc = dispatch(0, acc)
    jax.block_until_ready(acc)
    compile_s = time.perf_counter() - t_c

    trace = contextlib.nullcontext()
    if profile_dir:
        from tmhpvsim_tpu.obs.profiler import device_trace

        trace = device_trace(profile_dir, expect_platform=expect_platform)

    best = float("inf")
    bi = k
    try:
        with trace:
            for _ in range(n_rounds):
                t0 = time.perf_counter()
                for _ in range(n_blocks):
                    acc = dispatch(bi, acc)
                    bi += k
                jax.block_until_ready(acc)
                best = min(best, time.perf_counter() - t0)
    finally:
        pf.close()
    n = sim.config.n_chains
    bs = sim.config.block_s
    return compile_s, best, n * bs * n_blocks * k / best


def probe_plan(config: SimConfig, plan: Plan,
               n_timed: int = PROBE_TIMED_BLOCKS) -> float:
    """Measure one candidate plan with a short real-block run; returns its
    rate (site-seconds/wall-second).

    The probe simulates ``min(n_chains, slab_chains)`` chains for
    ``n_timed + 1`` blocks of the target ``block_s`` — the slab-sized
    shape each slab of the full run would execute — through the same
    timed path as bench.py's variants.  The candidate Simulation goes out
    of scope before the next candidate compiles, freeing its device
    buffers (HBM-residency poisoning, module docstring)."""
    global LAST_PROBE_COMPILE_S
    from tmhpvsim_tpu.engine.simulation import Simulation

    n = min(config.n_chains, plan.slab_chains)
    k = max(1, plan.blocks_per_dispatch)
    pcfg = dataclasses.replace(
        config,
        tune="off",
        n_chains=n,
        n_chains_total=None,
        chain_offset=0,
        site_grid=slice_grid(config.site_grid, 0, n),
        fleet=(fleet_mod.slice_fleet(config.fleet, 0, n)
               if config.fleet is not None else None),
        # k blocks per dispatch: the probe must cover one warm-up
        # dispatch plus n_timed timed ones (time_reduce_blocks)
        duration_s=config.block_s * k * (n_timed + 1),
        output="reduce",
    )
    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.obs.profiler import annotate

    obs_metrics.get_registry().counter("autotune.probes_total").inc()
    sim = Simulation(pcfg, plan=dataclasses.replace(plan, slab_chains=n))
    with annotate("tmhpvsim/autotune.probe"):
        compile_s, _, rate = time_reduce_blocks(sim, n_timed, 1)
    LAST_PROBE_COMPILE_S = compile_s
    del sim  # free device buffers before the next candidate compiles
    return rate


def candidate_plans(config: SimConfig, slabs: bool = True) -> list:
    """The candidate grid for one config: block_impl x scan_unroll x slab
    size, with an explicitly pinned (non-'auto') ``block_impl`` respected
    and slab sizes >= n_chains deduplicated to the unslabbed candidate.
    ``slabs=False`` drops the slab dimension (per-mesh tuning probes at
    the fixed per-device shape)."""
    fusion = _resolve_fusion(config)
    impls = (CANDIDATE_IMPLS if config.block_impl == "auto"
             else (_resolve_impl(config),))
    slab_sizes = []
    for s in (CANDIDATE_SLAB_CHAINS if slabs else (None,)):
        n = config.n_chains if s is None else min(s, config.n_chains)
        if n > 0 and n not in slab_sizes:
            slab_sizes.append(n)
    # fourth axis: blocks fused per dispatch — probed only when the
    # config leaves it 0 (auto); an explicit pin is respected like a
    # pinned block_impl
    kds = (CANDIDATE_BLOCKS_PER_DISPATCH if config.blocks_per_dispatch == 0
           else (max(1, config.blocks_per_dispatch),))
    analytics = _resolve_analytics(config)
    # the base grid runs at the RESOLVED precision ('auto' -> f32/exact):
    # precision variants are probed as a second stage on the structural
    # winner only (probe_grid), not as a 4x product blow-up here
    cdt = _resolve_compute_dtype(config)
    ki = _resolve_kernel_impl(config)
    rb = _resolve_rng_batch(config)
    gs = _resolve_geom_stride(config)
    telemetry = _escalate_telemetry(_resolve_telemetry(config), cdt)
    return [
        Plan(block_impl=impl, scan_unroll=u, stats_fusion=fusion,
             slab_chains=slab, source="probe", telemetry=telemetry,
             analytics=analytics, blocks_per_dispatch=kd,
             compute_dtype=cdt, kernel_impl=ki,
             rng_batch=rb, geom_stride=gs)
        for impl in impls
        for u in CANDIDATE_UNROLLS
        for slab in slab_sizes
        for kd in kds
    ]


def _candidate_record(plan: Plan) -> dict:
    return {
        "block_impl": plan.block_impl,
        "scan_unroll": plan.scan_unroll,
        "stats_fusion": plan.stats_fusion,
        "slab_chains": plan.slab_chains,
        "blocks_per_dispatch": plan.blocks_per_dispatch,
        "compute_dtype": plan.compute_dtype,
        "kernel_impl": plan.kernel_impl,
        "rng_batch": plan.rng_batch,
        "geom_stride": plan.geom_stride,
    }


def _sentinel_gate(config: SimConfig, plan: Plan) -> bool:
    """True when a short strict-telemetry run of ``plan`` passes the
    drift sentinel (obs/sentinel.py) against the f64 golden reference.

    The probe path (``time_reduce_blocks``) drives ``step_acc`` directly
    and never reaches ``_observe_telemetry``, so a performance probe
    alone would never trip the sentinel — this explicit gate runs a
    small ``run_reduced`` with ``telemetry_strict`` so a numerically
    unsound bf16/table candidate raises :class:`DriftError` instead of
    silently winning on speed.  Any non-DriftError failure also fails
    the gate (a candidate that cannot complete the gate run must not be
    selected)."""
    from tmhpvsim_tpu.engine.simulation import Simulation
    from tmhpvsim_tpu.obs.sentinel import DriftError

    n = min(config.n_chains, plan.slab_chains, SENTINEL_GATE_CHAINS)
    gcfg = dataclasses.replace(
        config,
        tune="off",
        n_chains=n,
        n_chains_total=None,
        chain_offset=0,
        site_grid=slice_grid(config.site_grid, 0, n),
        fleet=(fleet_mod.slice_fleet(config.fleet, 0, n)
               if config.fleet is not None else None),
        duration_s=config.block_s * SENTINEL_GATE_BLOCKS,
        output="reduce",
        telemetry="light",
        telemetry_strict=True,
        blocks_per_dispatch=1,
    )
    gplan = dataclasses.replace(plan, slab_chains=n, telemetry="light",
                                analytics="off", blocks_per_dispatch=1)
    try:
        sim = Simulation(gcfg, plan=gplan)
        sim.run_reduced()
    except DriftError as e:
        logger.warning("autotune sentinel gate REJECTED %s/%s: %s",
                       plan.compute_dtype, plan.kernel_impl, e)
        return False
    except Exception as e:
        logger.warning("autotune sentinel gate failed to run for %s/%s "
                       "(%s); candidate rejected", plan.compute_dtype,
                       plan.kernel_impl, e)
        return False
    finally:
        sim = None  # free device buffers before the next candidate
    return True


def _precision_variants(config: SimConfig, winner: Plan) -> list:
    """Stage-2 candidates: the structural winner with each non-default
    combination of the sentinel-gated axes — precision
    (``compute_dtype``/``kernel_impl``) and scan restructuring
    (``rng_batch``/``geom_stride``) — that the config leaves to the
    tuner ('auto' axes only — an explicit pin is respected like a
    pinned block_impl)."""
    cdts = (CANDIDATE_COMPUTE_DTYPES
            if getattr(config, "compute_dtype", "auto") == "auto"
            else (winner.compute_dtype,))
    kis = (CANDIDATE_KERNEL_IMPLS
           if getattr(config, "kernel_impl", "auto") == "auto"
           else (winner.kernel_impl,))
    rbs = (CANDIDATE_RNG_BATCHES
           if getattr(config, "rng_batch", "auto") == "auto"
           else (winner.rng_batch,))
    gss = (CANDIDATE_GEOM_STRIDES
           if int(getattr(config, "geom_stride", 0)) == 0
           else (winner.geom_stride,))
    base = (winner.compute_dtype, winner.kernel_impl,
            winner.rng_batch, winner.geom_stride)
    out = []
    for cdt in cdts:
        for ki in kis:
            for rb in rbs:
                for gs in gss:
                    if (cdt, ki, rb, gs) == base:
                        continue
                    out.append(dataclasses.replace(
                        winner, compute_dtype=cdt, kernel_impl=ki,
                        rng_batch=rb, geom_stride=gs,
                        telemetry=_escalate_telemetry(winner.telemetry,
                                                      cdt)))
    return out


def probe_grid(config: SimConfig, slabs: bool = True) -> tuple:
    """Time every candidate plan; returns (best plan, candidate records).

    Two stages: the structural grid (block_impl x scan_unroll x slab x
    blocks_per_dispatch) probed at the config's resolved precision, then
    precision variants (``compute_dtype`` / ``kernel_impl``) of the
    stage-1 winner only.  A variant must first pass
    :func:`_sentinel_gate` — the default exact/f32 path is never
    silently replaced by a candidate the drift sentinel has not cleared,
    no matter how fast it probes.

    A candidate that fails to compile/run is recorded with its error and
    skipped; if every candidate fails the static plan is returned so a
    broken probe environment degrades to the historical behaviour instead
    of killing the run."""
    global PROBE_COUNT, LAST_PROBE_COMPILE_S
    best = None
    records = []

    def probe_one(plan, rec):
        global PROBE_COUNT, LAST_PROBE_COMPILE_S
        PROBE_COUNT += 1
        LAST_PROBE_COMPILE_S = None
        try:
            rate = probe_plan(config, plan)
        except Exception as e:
            logger.warning("autotune candidate %s failed: %s", rec, e)
            rec["error"] = str(e)[:200]
            records.append(rec)
            return None
        rec["rate"] = round(rate, 1)
        if LAST_PROBE_COMPILE_S is not None:
            # cache-warm when the persistent compile cache is on
            rec["compile_s"] = round(LAST_PROBE_COMPILE_S, 3)
        records.append(rec)
        logger.info("autotune probe impl=%s unroll=%d slab=%d kd=%d "
                    "dtype=%s kernels=%s: %.3g site-s/s", plan.block_impl,
                    plan.scan_unroll, plan.slab_chains,
                    plan.blocks_per_dispatch, plan.compute_dtype,
                    plan.kernel_impl, rate)
        return rate

    for plan in candidate_plans(config, slabs=slabs):
        rate = probe_one(plan, _candidate_record(plan))
        if rate is not None and (best is None or rate > best[1]):
            best = (plan, rate)
    if best is None:
        logger.warning("every autotune candidate failed; falling back to "
                       "the static plan")
        return static_plan(config), records
    # stage 2: sentinel-gated precision variants of the winner
    for plan in _precision_variants(config, best[0]):
        rec = _candidate_record(plan)
        if not _sentinel_gate(config, plan):
            rec["sentinel"] = "fail"
            records.append(rec)
            continue
        rec["sentinel"] = "pass"
        rate = probe_one(plan, rec)
        if rate is not None and rate > best[1]:
            best = (plan, rate)
    return best[0], records


# ---------------------------------------------------------------------------
# persistent per-device cache
# ---------------------------------------------------------------------------


def cache_path() -> str:
    env = os.environ.get("TMHPVSIM_AUTOTUNE_CACHE")
    if env:
        return env
    root = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    return os.path.join(root, "tmhpvsim_tpu", "autotune.json")


def plan_key(config: SimConfig, mesh_shape=None) -> str:
    """Cache key: everything the winning plan is conditional on — the
    device model + backend and the shape/dtype/PRNG knobs that move the
    optimum — plus the engine version (stale formulations never match)."""
    import jax

    dev = jax.devices()[0]
    parts = [
        dev.device_kind, jax.default_backend(), config.n_chains,
        config.block_s, config.dtype, config.prng_impl,
        AUTOTUNE_ENGINE_VERSION,
    ]
    # chains stopped being exchangeable once fleets landed: a plan tuned
    # for one parameter mix must not be replayed onto another, so the
    # fleet shape + content digest joins the key (fleet-less configs keep
    # their historical keys — cache entries stay warm across the upgrade)
    if getattr(config, "fleet", None) is not None:
        parts.append(
            f"fleet{len(config.fleet)}-{config.fleet.digest()[:12]}")
    # a scenario mesh axis changes the serving dispatch each chip
    # compiles, so (N, M>1) meshes key separately.  1-D and (N, 1)
    # meshes share the historical key on purpose: they lower to
    # byte-identical HLO (parallel/mesh.py), so their optima are the
    # same plan and existing cache entries stay warm.
    if mesh_shape is not None and len(mesh_shape) > 1 and \
            int(mesh_shape[1]) > 1:
        parts.append("mesh" + "x".join(str(int(s)) for s in mesh_shape))
    return "|".join(str(x) for x in parts)


def _load_cache(path: str) -> dict:
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}  # missing or corrupt: behave like a cold cache


def _plan_from_entry(entry: dict) -> Plan:
    p = entry["plan"]
    plan = Plan(
        block_impl=str(p["block_impl"]),
        scan_unroll=int(p["scan_unroll"]),
        stats_fusion=str(p["stats_fusion"]),
        slab_chains=int(p["slab_chains"]),
        source="cache",
        # entries persisted before the fused dispatch existed have no
        # blocks_per_dispatch key; they keep meaning per-block dispatch
        blocks_per_dispatch=int(p.get("blocks_per_dispatch", 1)),
        # entries persisted before the precision axes existed keep
        # meaning the historical exact/f32 path
        compute_dtype=str(p.get("compute_dtype", "f32")),
        kernel_impl=str(p.get("kernel_impl", "exact")),
        # entries persisted before the scan-restructuring axes existed
        # keep meaning the historical per-minute-hash / per-second path
        rng_batch=str(p.get("rng_batch", "scan")),
        geom_stride=int(p.get("geom_stride", 1)),
    )
    if plan.block_impl not in ("wide", "scan", "scan2") or \
            plan.stats_fusion not in ("fused", "split") or \
            plan.scan_unroll < 1 or plan.slab_chains < 1 or \
            plan.blocks_per_dispatch < 1 or \
            plan.compute_dtype not in ("f32", "bf16") or \
            plan.kernel_impl not in ("exact", "table") or \
            plan.rng_batch not in ("scan", "block") or \
            plan.geom_stride not in (1, 30, 60):
        raise ValueError(f"malformed cached plan {p!r}")
    return plan


def _store_plan(path: str, key: str, plan: Plan, candidates: list) -> None:
    """Merge one entry into the cache, atomically (tmp + rename) so a
    concurrent reader never sees a torn file.  Cache write failures are
    logged, not raised — the plan is already resolved."""
    try:
        cache = _load_cache(path)
        entry = {
            "plan": {
                "block_impl": plan.block_impl,
                "scan_unroll": plan.scan_unroll,
                "stats_fusion": plan.stats_fusion,
                "slab_chains": plan.slab_chains,
                "blocks_per_dispatch": plan.blocks_per_dispatch,
                "compute_dtype": plan.compute_dtype,
                "kernel_impl": plan.kernel_impl,
                "rng_batch": plan.rng_batch,
                "geom_stride": plan.geom_stride,
            },
            "candidates": candidates,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        # surface the winner's (cache-warm) compile time at entry level
        for c in candidates:
            if (c.get("block_impl") == plan.block_impl
                    and c.get("scan_unroll") == plan.scan_unroll
                    and c.get("slab_chains") == plan.slab_chains
                    and c.get("blocks_per_dispatch",
                              1) == plan.blocks_per_dispatch
                    and c.get("compute_dtype", "f32") == plan.compute_dtype
                    and c.get("kernel_impl", "exact") == plan.kernel_impl
                    and c.get("rng_batch", "scan") == plan.rng_batch
                    and c.get("geom_stride", 1) == plan.geom_stride
                    and c.get("compile_s") is not None):
                entry["compile_s"] = c["compile_s"]
                break
        cache[key] = entry
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(cache, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except BaseException:
            os.unlink(tmp)
            raise
    except OSError as e:
        logger.warning("autotune cache write failed (%s): %s", path, e)


def cached_candidates(config: SimConfig) -> list:
    """The probe records persisted with this config's cached plan
    ([] when the key is absent) — lets callers/tests compare the winner
    against the other candidates without re-probing."""
    entry = _load_cache(cache_path()).get(plan_key(config))
    return list(entry.get("candidates", ())) if entry else []


# ---------------------------------------------------------------------------
# resolution entry points
# ---------------------------------------------------------------------------


def resolve_plan(config: SimConfig, slabs: bool = True,
                 mesh_shape=None) -> Plan:
    """The plan a :class:`Simulation` of ``config`` should run.

    ``tune='off'``: the static plan (no measurement, no cache IO).
    ``tune='auto'``: the cached plan for this key if present, else probe
    the candidate grid and persist the winner.  ``tune='force'``: probe
    and persist even on a cache hit."""
    if config.tune == "off":
        return static_plan(config)
    if config.tune not in ("auto", "force"):
        raise ValueError(
            f"tune must be 'auto', 'off' or 'force', got {config.tune!r}"
        )
    path = cache_path()
    key = plan_key(config, mesh_shape=mesh_shape)
    if config.tune == "auto":
        entry = _load_cache(path).get(key)
        if entry is not None:
            try:
                # cache entries never persist telemetry/analytics (not
                # tuned knobs); re-apply this config's request.  An
                # explicit blocks_per_dispatch pin (>= 1) also overrides
                # whatever an earlier auto probe persisted under this
                # key, as do explicit (non-'auto') precision pins.
                plan = dataclasses.replace(
                    _plan_from_entry(entry),
                    analytics=_resolve_analytics(config),
                )
                if config.blocks_per_dispatch >= 1:
                    plan = dataclasses.replace(
                        plan,
                        blocks_per_dispatch=config.blocks_per_dispatch,
                    )
                if getattr(config, "compute_dtype", "auto") != "auto":
                    plan = dataclasses.replace(
                        plan, compute_dtype=_resolve_compute_dtype(config))
                if getattr(config, "kernel_impl", "auto") != "auto":
                    plan = dataclasses.replace(
                        plan, kernel_impl=_resolve_kernel_impl(config))
                if getattr(config, "rng_batch", "auto") != "auto":
                    plan = dataclasses.replace(
                        plan, rng_batch=_resolve_rng_batch(config))
                if int(getattr(config, "geom_stride", 0)) != 0:
                    plan = dataclasses.replace(
                        plan, geom_stride=_resolve_geom_stride(config))
                # telemetry escalation must see the FINAL compute_dtype
                # (a cached bf16 winner escalates an 'off' request too)
                return dataclasses.replace(
                    plan,
                    telemetry=_escalate_telemetry(
                        _resolve_telemetry(config), plan.compute_dtype),
                )
            except (KeyError, TypeError, ValueError) as e:
                logger.warning("ignoring malformed autotune cache entry "
                               "for %s: %s", key, e)
    plan, candidates = probe_grid(config, slabs=slabs)
    if plan.source == "probe":  # don't cache the all-failed fallback
        _store_plan(path, key, plan, candidates)
    return dataclasses.replace(
        plan,
        telemetry=_escalate_telemetry(_resolve_telemetry(config),
                                      plan.compute_dtype),
        analytics=_resolve_analytics(config))


def broadcast_plan(plan: Plan) -> Plan:
    """Process 0's plan on every process of a multi-host run (no-op
    single-process).  Encoded as a small int array over the existing
    jax.distributed channel — no new transport."""
    import jax

    if jax.process_count() == 1:
        return plan
    import numpy as np
    from jax.experimental import multihost_utils

    impls = ("wide", "scan", "scan2")
    fusions = ("split", "fused")
    dtypes = ("f32", "bf16")
    kimpls = ("exact", "table")
    rbs = ("scan", "block")
    enc = np.asarray([
        impls.index(plan.block_impl), plan.scan_unroll,
        plan.slab_chains, fusions.index(plan.stats_fusion),
        plan.blocks_per_dispatch,
        dtypes.index(plan.compute_dtype), kimpls.index(plan.kernel_impl),
        rbs.index(getattr(plan, "rng_batch", "scan")),
        int(getattr(plan, "geom_stride", 1)),
    ], dtype=np.int32)
    out = np.asarray(multihost_utils.broadcast_one_to_all(enc))
    source = plan.source if jax.process_index() == 0 else "broadcast"
    return Plan(
        block_impl=impls[int(out[0])],
        scan_unroll=int(out[1]),
        stats_fusion=fusions[int(out[3])],
        slab_chains=int(out[2]),
        source=source,
        # telemetry IS broadcast-sensitive through the winner's dtype:
        # process 0's bf16 pick must escalate 'off' on every host
        telemetry=_escalate_telemetry(plan.telemetry,
                                      dtypes[int(out[5])]),
        analytics=plan.analytics,
        blocks_per_dispatch=int(out[4]),
        compute_dtype=dtypes[int(out[5])],
        kernel_impl=kimpls[int(out[6])],
        rng_batch=rbs[int(out[7])],
        geom_stride=int(out[8]),
    )


def resolve_plan_for_mesh(config: SimConfig, n_dev: int,
                          mesh_shape=None) -> Plan:
    """Plan resolution for a sharded run over ``n_dev`` devices: probe at
    the PER-DEVICE chain shape (that is what each chip executes under
    shard_map), on process 0 only, and broadcast the winner so every host
    runs the same plan.  ``mesh_shape`` (the mesh's device-grid shape)
    joins the cache key — see :func:`plan_key`.  Slabbing is disabled —
    the sharded loop drives all devices in lockstep, so the slab
    dimension does not apply."""
    import jax

    if config.tune == "off":
        plan = static_plan(config)
    else:
        n_eff = (len(config.site_grid) if config.site_grid is not None
                 else config.n_chains)
        per_dev = max(1, n_eff // n_dev)
        pcfg = dataclasses.replace(
            config,
            n_chains=per_dev,
            n_chains_total=None,
            chain_offset=0,
            site_grid=slice_grid(config.site_grid, 0, per_dev),
            fleet=(fleet_mod.slice_fleet(config.fleet, 0, per_dev)
                   if config.fleet is not None else None),
        )
        if jax.process_count() > 1 and jax.process_index() != 0:
            plan = static_plan(pcfg)  # replaced by the broadcast below
        else:
            plan = resolve_plan(pcfg, slabs=False, mesh_shape=mesh_shape)
        plan = broadcast_plan(plan)
    # slabbing never applies to the sharded loop; pin it off
    n_eff = (len(config.site_grid) if config.site_grid is not None
             else config.n_chains)
    return dataclasses.replace(plan, slab_chains=n_eff)
