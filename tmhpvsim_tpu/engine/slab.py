"""Chain-slab scheduler: execute a big run as sequential slab-sized runs.

Promotes the bench-only slab workaround (benchmarks/PERF_ANALYSIS.md §7c)
into the engine: chain counts past the single-chip sweet spot (measured
round 5: ~14x/block cliff at 262144 chains when the scan body's unrolled
live set spills VMEM) execute as sequential slabs of ``plan.slab_chains``
chains, each a plain :class:`~tmhpvsim_tpu.engine.simulation.Simulation`
over chains [off, off+n) of the notional full run
(``SimConfig.n_chains_total``/``chain_offset``).

Keyed construction makes this EXACT, not approximate: per-chain keys are
``split(seed-key, n_chains_total)`` sliced at the offset (threefry split
is counter-based) and every draw is keyed by global value index, so the
concatenation of the slabs' outputs is BIT-identical to the unslabbed run
(tests/test_engine.py TestChainSlabs; re-asserted through this scheduler
in tests/test_autotune.py).  Each slab Simulation is freed before the
next compiles — equal-shape slabs share one jit executable via the
persistent compile cache, and no slab's buffers stay HBM-resident to
degrade the next (PERF_ANALYSIS §7a fact 2).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from tmhpvsim_tpu.config import Plan, SimConfig
from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs.profiler import annotate


class SlabScheduler:
    """Sequential slab execution of ``config`` under ``plan``.

    Built by ``Simulation`` when ``plan.slab_chains < n_chains`` (and the
    config is not itself already a slab); drives one slab-sized
    Simulation at a time through the parent's own run loops.
    """

    def __init__(self, config: SimConfig, plan: Plan):
        if config.n_chains_total is not None:
            raise ValueError(
                "SlabScheduler cannot re-slab an explicit chain slab "
                "(n_chains_total is already set)"
            )
        if not 0 < plan.slab_chains < config.n_chains:
            raise ValueError(
                f"slab_chains={plan.slab_chains} must be in "
                f"(0, n_chains={config.n_chains}) to slab"
            )
        self.config = config
        self.plan = plan
        total = config.n_chains
        slab = plan.slab_chains
        # the same keyed chain-range carving the multi-host path uses
        # per process (parallel/distributed.carve_config) — one shared
        # definition of "chains [off, off+n) of a notional total run"
        from tmhpvsim_tpu.parallel.distributed import carve_config

        self.slab_cfgs = []
        for off in range(0, total, slab):
            n = min(slab, total - off)
            self.slab_cfgs.append(carve_config(config, off, n,
                                               total=total))
        # merged fleet-analytics total across slabs (None when analytics
        # is off); every risk leaf merges by exact int sum / extremum so
        # the slabbed fleet section is bit-identical to the unslabbed one
        self.fleet_total = None

    def __len__(self):
        return len(self.slab_cfgs)

    def checkpoint_layout(self) -> dict:
        """Placement metadata for the scheduler's notional full run —
        parity with ``Simulation.checkpoint_layout`` so a slabbed run's
        checkpoints carry the same (full-axis) layout the unslabbed run
        would write."""
        from tmhpvsim_tpu.parallel.distributed import chain_layout

        return chain_layout(self.config.n_chains, None)

    def _make_sim(self, cfg: SimConfig):
        from tmhpvsim_tpu.engine.simulation import Simulation

        # per-slab plan: same resolved knobs, slabbing consumed.  The
        # replace also carries blocks_per_dispatch, so each slab runs
        # the same fused dispatch as the resolved plan; on_block still
        # fires once per block, keeping the global counter exact.
        plan = dataclasses.replace(self.plan, slab_chains=cfg.n_chains)
        return Simulation(cfg, plan=plan)

    def run_reduced(self, on_block=None) -> dict:
        """Per-chain running statistics of the full run: each slab's
        ``run_reduced`` concatenated in chain order — bit-identical to the
        unslabbed result (module docstring).  ``on_block(bi, state, acc)``
        receives a GLOBAL block counter (slab-major: slab 0's blocks, then
        slab 1's, ...) so timing hooks see monotonic progress."""
        reg = obs_metrics.get_registry()
        g_total = reg.gauge("slab.total")
        g_done = reg.gauge("slab.completed")
        g_total.set(len(self.slab_cfgs))
        g_done.set(0)
        reg.counter("slab.runs_total").inc()
        outs = []
        gblock = 0
        for si, cfg in enumerate(self.slab_cfgs):
            sim = self._make_sim(cfg)
            cb = None
            if on_block is not None:
                def cb(bi, state, acc, _g=gblock):
                    return on_block(_g + bi, state, acc)
            with annotate(f"tmhpvsim/slab{si}"):
                outs.append(sim.run_reduced(on_block=cb))
            gblock += sim.n_blocks
            if getattr(sim, "_fleet_total", None) is not None:
                from tmhpvsim_tpu.obs import analytics

                self.fleet_total = analytics.merge_host(
                    self.fleet_total, sim._fleet_total)
            g_done.set(si + 1)
            del sim  # free the slab's buffers before the next compiles
        return {k: np.concatenate([o[k] for o in outs]) for k in outs[0]}

    def run_ensemble(self) -> Iterator:
        """Fleet-level 1 Hz series of the full run: chain-count-weighted
        combination of the slabs' per-second fleet means.  Slabs run to
        completion one at a time (the per-block vectors are only
        O(block_s) on the host), then the combined BlockResults are
        yielded in time order."""
        reg = obs_metrics.get_registry()
        g_total = reg.gauge("slab.total")
        g_done = reg.gauge("slab.completed")
        g_total.set(len(self.slab_cfgs))
        g_done.set(0)
        reg.counter("slab.runs_total").inc()
        total = self.config.n_chains
        meta = None       # [(offset, epoch)]
        m_sums = p_sums = None
        for si, cfg in enumerate(self.slab_cfgs):
            sim = self._make_sim(cfg)
            w = cfg.n_chains / total
            with annotate(f"tmhpvsim/slab{si}"):
                blocks = list(sim.run_ensemble())
            if meta is None:
                meta = [(b.offset, b.epoch) for b in blocks]
                m_sums = [w * b.meter for b in blocks]
                p_sums = [w * b.pv for b in blocks]
            else:
                for i, b in enumerate(blocks):
                    m_sums[i] = m_sums[i] + w * b.meter
                    p_sums[i] = p_sums[i] + w * b.pv
            g_done.set(si + 1)
            del sim
        from tmhpvsim_tpu.engine.simulation import BlockResult

        for (off, epoch), m, p in zip(meta, m_sums, p_sums):
            yield BlockResult(offset=off, epoch=epoch, meter=m, pv=p,
                              residual=m - p)
