"""Observability: block timing + device profiler traces.

The reference's only observability is stdlib logging and a behind-realtime
warning (SURVEY.md §5).  The TPU build adds the two things that matter for
a device workload: per-block throughput accounting (simulated site-seconds
per wall second — the benchmark metric) and ``jax.profiler`` traces for
XLA-level inspection in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import logging
import time

logger = logging.getLogger(__name__)


class BlockTimer:
    """Accumulates per-block wall times and derives throughput.

    Usage::

        timer = BlockTimer(n_chains=cfg.n_chains, block_s=cfg.block_s)
        for blk in sim.run_blocks():
            timer.tick()        # call once per completed block
        timer.summary()         # dict; also logged at INFO
    """

    def __init__(self, n_chains: int, block_s: int):
        self.n_chains = n_chains
        self.block_s = block_s
        self._last = time.perf_counter()
        self._first_dt = None
        self.block_times = []

    def tick(self) -> float:
        now = time.perf_counter()
        dt = now - self._last
        self._last = now
        if self._first_dt is None:
            self._first_dt = dt  # includes compile; kept separately
        else:
            self.block_times.append(dt)
        rate = self.n_chains * self.block_s / dt
        logger.info(
            "block done in %.3f s (%.3g site-s/s)%s", dt, rate,
            " [first: includes compile]" if not self.block_times else "",
        )
        return dt

    def summary(self) -> dict:
        steady = self.block_times or [self._first_dt or 0.0]
        total = sum(steady)
        out = {
            "n_blocks_timed": len(steady),
            "first_block_s": self._first_dt,
            "steady_block_s": total / len(steady),
            "site_seconds_per_s": (
                self.n_chains * self.block_s * len(steady) / total
                if total else 0.0
            ),
        }
        logger.info("throughput: %(site_seconds_per_s).3g site-s/s "
                    "(steady block %(steady_block_s).3f s)", out)
        return out


@contextlib.contextmanager
def device_trace(log_dir: str):
    """``jax.profiler`` trace scope (view in TensorBoard / Perfetto)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
