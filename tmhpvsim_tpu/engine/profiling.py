"""Compatibility shim: block timing + profiler moved to ``obs``.

The observability subsystem (metrics registry, run reports, platform-
guarded device traces) lives in :mod:`tmhpvsim_tpu.obs`; this module
re-exports the profiler names so existing imports — and test
monkeypatching of ``engine.profiling.BlockTimer`` — keep working.
"""

from __future__ import annotations

from tmhpvsim_tpu.obs.profiler import (  # noqa: F401
    BlockTimer,
    PlatformMismatchError,
    annotate,
    device_trace,
    read_manifest,
)
