"""Deprecated compatibility shim: block timing + profiler moved to ``obs``.

The observability subsystem (metrics registry, run reports, platform-
guarded device traces) lives in :mod:`tmhpvsim_tpu.obs`; this module
re-exports the profiler names so existing imports — and test
monkeypatching of ``engine.profiling.BlockTimer`` — keep working.

Importing it emits a :class:`DeprecationWarning` attributed to the
importer (``stacklevel=2``), and the test suite escalates
DeprecationWarnings raised from inside ``tmhpvsim_tpu.*`` to errors
(pyproject filterwarnings), so no new internal import of the shim can
land.
"""

from __future__ import annotations

import warnings

warnings.warn(
    "tmhpvsim_tpu.engine.profiling is deprecated; import from "
    "tmhpvsim_tpu.obs.profiler instead",
    DeprecationWarning,
    stacklevel=2,
)

from tmhpvsim_tpu.obs.profiler import (  # noqa: E402,F401
    BlockTimer,
    PlatformMismatchError,
    annotate,
    device_trace,
    read_manifest,
)
