"""Multi-chip / multi-host execution layer."""

from tmhpvsim_tpu.parallel.mesh import (  # noqa: F401
    ShardedSimulation,
    chain_sharding,
    make_mesh,
)
