"""Multi-chip / multi-host execution layer."""

try:
    # jax >= 0.6 exports shard_map at the top level and spells the
    # replication-check kwarg ``check_vma``
    from jax import shard_map  # noqa: F401
except ImportError:  # jax 0.4.x: experimental home, kwarg is ``check_rep``
    import functools as _functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @_functools.wraps(_shard_map)
    def shard_map(f, **kw):
        kw.setdefault("check_rep", kw.pop("check_vma", True))
        return _shard_map(f, **kw)


from tmhpvsim_tpu.parallel.mesh import (  # noqa: E402,F401
    CHAIN_AXIS,
    SCENARIO_AXIS,
    ShardedSimulation,
    chain_sharding,
    make_mesh,
    scenario_sharding,
)
