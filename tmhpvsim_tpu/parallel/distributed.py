"""Multi-host initialisation and host-local data movement.

The reference has no multi-node story at all — its cross-process transport
is a RabbitMQ broker on localhost (SURVEY.md §2.4).  For pod slices the
TPU-native framework uses the standard JAX runtime instead: one Python
process per host, ``jax.distributed`` over DCN for control, ICI for the
collectives issued inside ``shard_map`` (parallel/mesh.py).

Nothing here opens sockets itself; it wires up the JAX runtime from the
standard environment (TPU pods export everything needed) and provides the
host-local views a CSV-writing process needs.
"""

from __future__ import annotations

import json
import logging
import os

import jax
import numpy as np

logger = logging.getLogger(__name__)


def _already_initialized() -> bool:
    """``jax.distributed.is_initialized()``, tolerating jax < 0.5 where the
    accessor does not exist and the client handle must be read directly."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    state = getattr(jax.distributed, "global_state", None)
    if state is None:  # jax.distributed re-exports from jax._src.distributed
        from jax._src import distributed as _dist_src

        state = _dist_src.global_state
    return getattr(state, "client", None) is not None


def initialize(coordinator=None, num_processes=None,
               process_id=None) -> bool:
    """Initialise ``jax.distributed`` for a multi-host run; no-op
    (returns False) when the resolved process count is < 2.

    Explicit arguments (the ``--coordinator/--num-processes/--process-id``
    CLI flags) take precedence; any left None falls back to its env-var
    equivalent (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
    ``JAX_PROCESS_ID``) so launchers that export the environment and
    launchers that template argv both work.
    """
    # NB: the env vars must be inspected BEFORE any jax query that can
    # initialise a backend — even jax.process_count() does, after which
    # jax.distributed.initialize() is forbidden.
    if _already_initialized():
        return True  # already initialised by the runtime/launcher
    addr = (coordinator if coordinator
            else os.environ.get("JAX_COORDINATOR_ADDRESS"))
    nproc = (num_processes if num_processes is not None
             else os.environ.get("JAX_NUM_PROCESSES"))
    pid = (process_id if process_id is not None
           else os.environ.get("JAX_PROCESS_ID", "0"))
    try:
        nproc_i = int(nproc) if nproc else 0
        pid_i = int(pid)
    except ValueError:
        logger.warning(
            "malformed num_processes/process_id (%r/%r); staying "
            "single-process", nproc, pid,
        )
        return False
    if not addr or nproc_i <= 1:
        return False
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=nproc_i,
        process_id=pid_i,
    )
    logger.info(
        "jax.distributed initialised: process %d/%d, %d local / %d global "
        "devices", jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True


def initialize_from_env() -> bool:
    """Initialise ``jax.distributed`` from the environment alone — the
    historical entry point; equivalent to :func:`initialize` with no
    explicit arguments."""
    return initialize()


def local_chain_slice(n_chains: int, mesh) -> slice:
    """The [start, stop) chain indices owned by this host process.

    The mesh lays chains out contiguously over the flat device list, so a
    host's chains are a contiguous slice aligned to its addressable
    devices — the slice a per-host CSV writer or checkpointer owns.
    """
    n_dev = mesh.devices.size
    per_dev = n_chains // n_dev
    flat = list(mesh.devices.flat)
    local = [i for i, d in enumerate(flat)
             if d.process_index == jax.process_index()]
    if not local:
        return slice(0, 0)
    lo, hi = min(local), max(local) + 1
    return slice(lo * per_dev, hi * per_dev)


def chain_layout(n_chains: int, mesh=None) -> dict:
    """The logical chain-axis layout of this process's checkpoint file —
    placement metadata riding ``meta['layout']`` (engine/checkpoint.py).

    Strictly descriptive: which global chains [chain_start, chain_stop)
    of the n_chains total this file holds, and under what topology
    (process/device counts, mesh shape) it was written.  NEVER part of
    the identity echo — a resume under a different topology reshards
    from this record instead of refusing.
    """
    lay = {
        "n_chains": int(n_chains),
        "chain_start": 0,
        "chain_stop": int(n_chains),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
    }
    if mesh is not None:
        lay["n_devices"] = int(mesh.devices.size)
        lay["mesh_shape"] = [int(s) for s in mesh.devices.shape]
        if jax.process_count() > 1:
            sl = local_chain_slice(n_chains, mesh)
            lay["chain_start"] = int(sl.start)
            lay["chain_stop"] = int(sl.stop)
    else:
        lay["n_devices"] = 1
    return lay


def carve_config(config, offset: int, n: int, total=None):
    """Chain-range sub-view [offset, offset+n) of ``config``: the keyed
    construction that makes slabbed, sharded and multi-host runs EXACT —
    per-chain keys come from ``split(seed-key, n_chains_total)`` sliced
    at the offset, and the site grid / fleet pytrees are sliced to the
    same rows (``slice_grid``/``slice_fleet``).  ``tune`` is pinned off:
    every carve happens after plan resolution (engine/slab.py per slab,
    this module per process)."""
    import dataclasses

    from tmhpvsim_tpu import fleet as fleet_mod
    from tmhpvsim_tpu.config import slice_grid

    total = config.n_chains if total is None else int(total)
    return dataclasses.replace(
        config,
        tune="off",
        n_chains=int(n),
        n_chains_total=total,
        chain_offset=int(offset),
        site_grid=slice_grid(config.site_grid, offset, n),
        fleet=(fleet_mod.slice_fleet(config.fleet, offset, n)
               if config.fleet is not None else None),
    )


def carve_process_config(config, mesh):
    """The chain-range sub-view THIS process owns under ``mesh`` — the
    per-host carving for host-side work (per-host CSV writers, fleet
    digests, host-local validation).  Device-side state needs no carving
    (``init_state`` compiles with out_shardings and each host fills only
    its addressable shards); this is for the host halves of the
    pipeline.  Single-process meshes return ``config`` unchanged."""
    if jax.process_count() == 1:
        return config
    sl = local_chain_slice(config.n_chains, mesh)
    return carve_config(config, sl.start, sl.stop - sl.start,
                        total=config.n_chains)


def mesh_doc(mesh, n_chains=None) -> dict:
    """The run report's ``mesh`` section (obs/report.py schema v13):
    device-grid shape and axis names, plus the process topology —
    everything a reader needs to interpret per-host artefacts and the
    sharded throughput numbers."""
    doc = {
        "shape": [int(s) for s in mesh.devices.shape],
        "axis_names": [str(a) for a in mesh.axis_names],
        "n_devices": int(mesh.devices.size),
        "process_count": int(jax.process_count()),
        "process_index": int(jax.process_index()),
    }
    if n_chains is not None:
        doc["n_chains"] = int(n_chains)
        doc["chains_per_device"] = int(n_chains) // int(mesh.devices.size)
        sl = local_chain_slice(int(n_chains), mesh)
        doc["chain_start"] = int(sl.start)
        doc["chain_stop"] = int(sl.stop)
    return doc


def host_gather_ensemble(arr) -> np.ndarray:
    """Fetch a replicated (ensemble) array to host numpy.

    Replicated outputs of the sharded block step are fully addressable on
    every host; this is a plain device->host copy, no DCN traffic.
    """
    return np.asarray(arr)


def psum_telemetry(ta: dict, axis_name: str) -> dict:
    """Mesh-wide reduction of a per-shard TelemetryAcc (traced, inside
    shard_map): counters/sums psum, running extrema pmin/pmax — the kind
    per leaf comes from ``obs.telemetry.leaf_kinds``.  The result is
    replicated, so the per-block host flush reads any one shard."""
    from tmhpvsim_tpu.obs.telemetry import leaf_kinds

    coll = {"sum": jax.lax.psum, "min": jax.lax.pmin, "max": jax.lax.pmax}
    kinds = leaf_kinds(ta)
    return {k: coll[kinds[k]](v, axis_name) for k, v in ta.items()}


def psum_fleet(fa: dict, axis_name: str) -> dict:
    """Mesh-wide reduction of a per-shard FleetAcc (traced, inside
    shard_map).  Same shape as :func:`psum_telemetry` with the kind
    dispatch from ``obs.analytics.leaf_kinds``; every ``risk``-level
    leaf is an int32 count (psum) or extremum (pmin/pmax), so the
    reduction is exactly associative — the sharded fleet section is
    bit-identical to the single-device one."""
    from tmhpvsim_tpu.obs.analytics import leaf_kinds

    coll = {"sum": jax.lax.psum, "min": jax.lax.pmin, "max": jax.lax.pmax}
    kinds = leaf_kinds(fa)
    return {k: coll[kinds[k]](v, axis_name) for k, v in fa.items()}


def gather_rows(row: np.ndarray) -> np.ndarray:
    """Every process's fixed-width float64 row, stacked in
    process-index order: ``(process_count, len(row))``.

    COLLECTIVE under multi-process jax — all processes must call it
    with the same row width (the pod heartbeat path calls it at block
    boundaries, where the sharded dispatch already synchronised
    everyone).  Single-process runs return ``row[None]`` without
    touching any collective, so callers never need their own guard.
    Unlike :func:`gather_metrics` there is no length negotiation: one
    ``process_allgather`` round per call, which is what makes it cheap
    enough for per-block heartbeats.
    """
    row = np.asarray(row, dtype=np.float64).ravel()
    if jax.process_count() == 1:
        return row[None]
    from jax.experimental import multihost_utils

    out = np.asarray(multihost_utils.process_allgather(row))
    return out.reshape(jax.process_count(), row.size)


def gather_metrics(snapshot: dict) -> list:
    """Every process's metrics snapshot, in process-index order.

    COLLECTIVE: all processes must call it (same pattern as
    engine/autotune.py broadcast_plan).  Process 0 embeds the result as
    the run report's ``processes`` section; the other processes get the
    same list and simply skip writing.  Single-process runs return
    ``[snapshot]`` without touching any collective.

    Snapshots are host-side python dicts, so they ride DCN as
    JSON-encoded uint8 payloads: an allgather of the byte lengths sizes
    a zero-padded buffer allgather, then each row decodes back to a
    dict.
    """
    if jax.process_count() == 1:
        return [snapshot]
    from jax.experimental import multihost_utils

    payload = np.frombuffer(
        json.dumps(snapshot).encode("utf-8"), dtype=np.uint8
    )
    lengths = multihost_utils.process_allgather(
        np.asarray([payload.size], dtype=np.int32)
    ).ravel()
    buf = np.zeros(int(lengths.max()), dtype=np.uint8)
    buf[:payload.size] = payload
    rows = multihost_utils.process_allgather(buf)
    return [
        json.loads(bytes(rows[i][:int(lengths[i])]).decode("utf-8"))
        for i in range(len(lengths))
    ]
