"""Chain-parallel execution over a TPU device mesh.

The reference's only parallelism is "run N independent pvsim consumer
processes against one RabbitMQ fanout exchange" (SURVEY.md §2.3,
metersim.py:25-28 / pvsim.py:62-63) — replication with a broker as the
fan-out.  The TPU-native equivalent shards the *chain* batch axis of one
simulation across the chips of a ``jax.sharding.Mesh`` and replaces the
broker with in-process XLA collectives over ICI:

* every per-chain quantity (sampler arrays, renewal carry, keys, traces)
  is sharded on the mesh — pure data parallelism, zero communication in
  the hot loop;
* cross-chain *ensemble* statistics (the "grid operator" view: aggregate
  residual load per second over the whole fleet) are one ``psum`` per
  block over ICI — the only collective the workload needs, exactly where
  the reference's AMQP fan-out + funnel join used to sit (SURVEY.md §2.4);
* multi-host slices extend the same mesh over DCN via
  ``jax.distributed`` (parallel/distributed.py); each host feeds and
  gathers only its addressable shard.

The mesh is either the historical 1-D ``(chains,)`` layout or a named
2-D ``(chains, scenario)`` grid (:func:`make_mesh`).  Batch runs treat
the two mesh axes as one flat data-parallel pool: chain-indexed leaves
shard over *both* axes (``P((CHAIN_AXIS, SCENARIO_AXIS))``) and every
collective reduces over the axis-name tuple, so a ``(N, M)`` mesh is
purely a layout decision — an ``(N, 1)`` mesh compiles to byte-identical
HLO vs the 1-D path, and ``(N, M)`` results are bit-identical to the
``(N*M,)`` 1-D mesh (tests/test_parallel.py).  Scenario *serving* is
where the second axis earns its name: the scenario-batched dispatch
(``Simulation._block_step_scan_scenario``) maps the request batch onto
``scenario`` and the chain axis onto ``chains``, so a ``pvsim serve``
what-if batch parallelises across chips instead of timesharing one.

Tested on 8 virtual CPU devices (tests/conftest.py sets
``--xla_force_host_platform_device_count=8``; SURVEY.md §4).
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# the version-compat shim (check_vma <-> check_rep) lives in the package
# __init__, which runs before this module on any import path
from tmhpvsim_tpu.parallel import shard_map

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine.simulation import BlockResult, Simulation

CHAIN_AXIS = "chains"
SCENARIO_AXIS = "scenario"


def make_mesh(chain_devices: Optional[Sequence] = None,
              scenario_devices: Union[int, Sequence, None] = None) -> Mesh:
    """A mesh over all (or the given) devices.

    ``scenario_devices=None`` (the historical signature) builds the flat
    1-D ``(chains,)`` mesh: the workload is embarrassingly parallel over
    chains, XLA maps the single axis onto the physical ICI torus itself,
    and the one collective we issue (psum of per-second ensemble sums)
    rides nearest-neighbour rings.

    ``scenario_devices=M`` (an int, or a sequence whose length is taken)
    builds the named 2-D ``(chains, scenario)`` mesh: the flat device
    list reshaped C-order to ``(n_devices // M, M)`` — the
    mesh-construction pattern of SNIPPETS.md [3] — so chains stay
    contiguous over the flat device list and the per-host slice
    arithmetic (:func:`~tmhpvsim_tpu.parallel.distributed.local_chain_slice`)
    is layout-independent.  ``M=1`` is a genuine 2-D mesh that lowers to
    byte-identical HLO vs the 1-D path (tests/test_parallel.py).
    """
    devices = (list(jax.devices()) if chain_devices is None
               else list(chain_devices))
    if scenario_devices is None:
        return Mesh(np.asarray(devices), (CHAIN_AXIS,))
    m = (int(scenario_devices) if isinstance(scenario_devices, int)
         else len(list(scenario_devices)))
    if m < 1:
        raise ValueError(f"scenario_devices={m} must be >= 1")
    if len(devices) % m != 0:
        raise ValueError(
            f"{len(devices)} devices do not divide into a scenario axis "
            f"of {m}"
        )
    grid = np.asarray(devices).reshape(len(devices) // m, m)
    return Mesh(grid, (CHAIN_AXIS, SCENARIO_AXIS))


def data_axes(mesh: Mesh):
    """The axis-name argument chain-indexed data shards over: the bare
    ``chains`` name on a 1-D mesh, the ``(chains, scenario)`` tuple on a
    2-D mesh (batch runs treat both axes as one flat data-parallel
    pool; ``jax.lax.psum``/``pmin``/``pmax`` accept the tuple form, so
    the leaf-kind dispatch in ``psum_telemetry``/``psum_fleet`` is
    reused unchanged)."""
    names = mesh.axis_names
    return names[0] if len(names) == 1 else tuple(names)


def chain_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that splits the leading (chain) axis across the mesh —
    over every mesh axis, so a ``(N, M)`` mesh gives ``N*M`` chain
    shards."""
    return NamedSharding(mesh, P(data_axes(mesh)))


def scenario_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for ``(batch, chains)`` scenario accumulators on a 2-D
    mesh: batch over ``scenario``, chains over ``chains``.  Requires a
    mesh built with ``make_mesh(scenario_devices=...)``."""
    if SCENARIO_AXIS not in mesh.axis_names:
        raise ValueError(
            f"mesh {mesh.axis_names} has no {SCENARIO_AXIS!r} axis; build "
            "it with make_mesh(scenario_devices=...)"
        )
    return NamedSharding(mesh, P(SCENARIO_AXIS, CHAIN_AXIS))


class ShardedSimulation(Simulation):
    """`engine.Simulation` with the chain axis sharded across a mesh.

    Differences from the single-chip parent:

    * ``init_state()`` lays out every chain-indexed leaf with a
      ``NamedSharding`` over the mesh's data axes (n_chains must divide
      by the mesh size);
    * the block step runs under ``shard_map``; a separate consumer jit
      reduces the per-second ensemble sums of pv and residual over *all*
      chains with ``psum`` over ICI, replicated on every chip;
    * BlockResults carry the global ensemble means in ``.ensemble``;
    * on a 2-D ``(chains, scenario)`` mesh the scenario-batched serving
      dispatch (``scenario_step``) maps the request batch onto the
      ``scenario`` axis and the chains onto ``chains`` — the serve
      batcher's vmapped scenario axis parallelised across chips.

    Numerical contract vs the single-device run: all keys and global
    indices are identical, so the integer RNG streams (meter draws,
    renewal decisions) are bit-identical under any mesh layout.  The
    float32 physics chain is identical only to a few ULPs: XLA compiles
    the block step for the per-shard batch shape, and its instruction
    selection (fusion order, FMA contraction) is shape-dependent, so
    e.g. a 1-chain shard and an 8-chain batch round differently in the
    transcendental-heavy solar/PV math.  Deterministic for a fixed
    per-shard shape — which depends only on the mesh SIZE, not its
    shape, so ``(N, M)`` results are bit-identical to ``(N*M,)``
    (tests/test_parallel.py); there is no cross-chain reduction in the
    per-chain outputs.

    The scan-restructuring plan axes shard transparently: the
    ``rng_batch='block'`` pre-generated streams are per-chain values
    born INSIDE the shard_mapped block step (each shard hoists only its
    own chains' draws — same fold_in keys, so sharded 'block' stays
    bit-identical to sharded 'scan'; tests/test_rng_batch.py), and the
    ``geom_stride`` sample/lerp features ship as extra replicated
    ``host_inputs`` leaves riding the existing ``P()`` input spec.
    """

    #: the base __init__ must not AOT-warm the unsharded jits this
    #: subclass is about to replace — _warm_start runs after the rebinds
    _defer_warm_start = True

    def __init__(self, config: SimConfig, mesh: Optional[Mesh] = None,
                 plan=None):
        mesh = mesh if mesh is not None else make_mesh(
            scenario_devices=(config.mesh_scenario
                              if getattr(config, "mesh_scenario", 0) >= 1
                              else None))
        if tuple(mesh.axis_names) not in (
                (CHAIN_AXIS,), (CHAIN_AXIS, SCENARIO_AXIS)):
            raise ValueError(
                f"mesh axes {mesh.axis_names} are not "
                f"({CHAIN_AXIS!r},) or ({CHAIN_AXIS!r}, {SCENARIO_AXIS!r})"
            )
        #: axis-name argument of every data spec and collective: the
        #: bare chain axis on a 1-D mesh, the (chains, scenario) tuple
        #: on a 2-D one (see data_axes)
        self._axis = data_axes(mesh)
        if plan is None:
            # per-mesh tuning (engine/autotune.py): probe at the
            # per-device chain shape — that is what each chip executes
            # under shard_map — on process 0 only, broadcast the winner.
            # tune='off' resolves statically; chain slabbing never
            # applies here (the mesh partitions the chain axis itself).
            from tmhpvsim_tpu.engine import autotune

            plan = autotune.resolve_plan_for_mesh(
                config, mesh.devices.size,
                mesh_shape=tuple(int(s) for s in mesh.devices.shape))
        super().__init__(config, plan=plan)
        self.allow_slabs = False
        self.mesh = mesh
        n_dev = self.mesh.devices.size
        if self.config.n_chains % n_dev != 0:
            raise ValueError(
                f"n_chains={self.config.n_chains} must be divisible by the "
                f"mesh size {n_dev}"
            )
        self._sharded_block = self._build_sharded_block()
        self._sharded_stats_acc = self._build_sharded_stats_acc()
        self._trace_ensemble = self._build_trace_ensemble()
        self._sharded_ensemble = self._build_sharded_ensemble()
        # Rebind the reduce/ensemble-path jits to their shard_map versions
        # (same signatures) so the parent's step_acc/run_reduced and
        # run_ensemble drive the sharded path unchanged — one copy of each
        # per-block sequence.
        self._block_jit = self._sharded_block
        self._stats_acc_jit = self._sharded_stats_acc
        self._fused_acc_jit = self._build_sharded_fused_acc()
        self._scan_acc_jit = self._build_sharded_scan_acc()
        self._scan2_acc_jit = self._build_sharded_scan_acc(
            self._block_step_scan2_acc
        )
        self._scan_series_jit = self._build_sharded_scan_series()
        self._scan2_series_jit = self._build_sharded_scan_series(
            self._block_step_scan2_series
        )
        self._series_jit = self._trace_ensemble
        if self._telemetry != "off":
            self._scan_acc_tel_jit = self._build_sharded_scan_acc_tel()
            self._scan2_acc_tel_jit = self._build_sharded_scan_acc_tel(
                self._block_step_scan2_acc_tel
            )
            self._wide_tel_jit = self._build_sharded_wide_tel()
        if self._analytics != "off":
            if self._telemetry != "off":
                self._scan_acc_tel_fleet_jit = \
                    self._build_sharded_scan_acc_tel_fleet()
                self._scan2_acc_tel_fleet_jit = \
                    self._build_sharded_scan_acc_tel_fleet(
                        self._block_step_scan2_acc_tel_fleet)
            else:
                self._scan_acc_fleet_jit = \
                    self._build_sharded_scan_acc_fleet()
                self._scan2_acc_fleet_jit = \
                    self._build_sharded_scan_acc_fleet(
                        self._block_step_scan2_acc_fleet)
            self._wide_fleet_jit = self._build_sharded_wide_fleet()
        self._warm_start()

    def init_state(self):
        return super().init_state(sharding=chain_sharding(self.mesh))

    def _build_sharded_block(self):
        """The producer jit under shard_map: this chip's chain shard through
        the parent's vmapped ``_block_step``, inputs replicated.  Pure data
        parallelism — zero collectives; everything downstream of the meter
        and pv arrays (residual, ensemble sums, statistics) lives in
        separate consumer jits so XLA cannot re-fuse it backwards into a
        duplicated producer chain (see ``Simulation._block_step``)."""
        mapped = shard_map(
            self._block_step,
            mesh=self.mesh,
            in_specs=(P(self._axis), P()),
            out_specs=(P(self._axis), P(self._axis), P(self._axis)),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=0)

    def _build_sharded_stats_acc(self):
        """Reduce-mode consumer under shard_map: fold this shard's
        materialised meter/pv arrays into the chain-sharded accumulator.
        Zero collectives in the loop (the psum happens once at the end, in
        ``_build_sharded_ensemble``)."""
        spec_c, spec_r = P(self._axis), P()
        mapped = shard_map(
            self._block_stats_acc,
            mesh=self.mesh,
            in_specs=(spec_c, spec_c, spec_r, spec_c),
            out_specs=spec_c,
            check_vma=False,
        )
        # meter/pv donated alongside the accumulator, mirroring the
        # parent's split-path jit (the tel fold runs before this jit)
        return jax.jit(mapped, donate_argnums=(0, 1, 3))

    def _build_sharded_fused_acc(self):
        """Reduce-mode fused topology under shard_map (see
        SimConfig.stats_fusion): producer + stats + merge per shard in one
        jit, zero collectives, state and accumulator donated."""
        spec_c, spec_r = P(self._axis), P()
        mapped = shard_map(
            self._step_acc_fused,
            mesh=self.mesh,
            in_specs=(spec_c, spec_r, spec_c),
            out_specs=(spec_c, spec_c),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=(0, 2))

    def _build_sharded_scan_acc(self, fn=None):
        """Scan-fused reduce topology under shard_map (see
        SimConfig.block_impl; ``fn`` picks the flat or nested variant):
        the whole per-second pipeline per shard, zero collectives, state
        and accumulator donated."""
        spec_c, spec_r = P(self._axis), P()
        mapped = shard_map(
            self._block_step_scan_acc if fn is None else fn,
            mesh=self.mesh,
            in_specs=(spec_c, spec_r, spec_c),
            out_specs=(spec_c, spec_c),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=(0, 2))

    def _build_sharded_scan_acc_tel(self, fn=None):
        """Telemetry variant of ``_build_sharded_scan_acc``: each shard
        folds its own TelemetryAcc inside the scan, then the per-block
        deltas are psum/pmin/pmax-reduced over the mesh — one tiny
        collective tree of ~30 scalars per block, replicated output so
        the host flush reads any one shard
        (parallel/distributed.psum_telemetry)."""
        from tmhpvsim_tpu.parallel import distributed

        inner = self._block_step_scan_acc_tel if fn is None else fn

        def step(state, inputs, acc):
            state, acc, ta = inner(state, inputs, acc)
            with self._phase("collectives"):
                return (state, acc,
                        distributed.psum_telemetry(ta, self._axis))

        spec_c, spec_r = P(self._axis), P()
        mapped = shard_map(
            step, mesh=self.mesh,
            in_specs=(spec_c, spec_r, spec_c),
            out_specs=(spec_c, spec_c, spec_r),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=(0, 2))

    def _build_sharded_wide_tel(self):
        """Wide-impl telemetry fold under shard_map: per-shard fold over
        the materialised meter/pv arrays, mesh-reduced like the scan
        variant."""
        from tmhpvsim_tpu.parallel import distributed

        def fold(meter, pv, t):
            ta = self._wide_telemetry(meter, pv, t)
            with self._phase("collectives"):
                return distributed.psum_telemetry(ta, self._axis)

        mapped = shard_map(
            fold, mesh=self.mesh,
            in_specs=(P(self._axis), P(self._axis), P()),
            out_specs=P(),
            check_vma=False,
        )
        return jax.jit(mapped)

    def _build_sharded_scan_acc_fleet(self, fn=None):
        """Fleet-analytics variant of ``_build_sharded_scan_acc``: each
        shard folds its own FleetAcc inside the scan, then the per-block
        sketch deltas psum/pmin/pmax over the mesh
        (parallel/distributed.psum_fleet) — every risk leaf is an int32
        count or extremum, so the reduction is exactly associative and
        the replicated result is bit-identical to a single-device run."""
        from tmhpvsim_tpu.parallel import distributed

        inner = self._block_step_scan_acc_fleet if fn is None else fn

        def step(state, inputs, acc):
            state, acc, fa = inner(state, inputs, acc)
            with self._phase("collectives"):
                return state, acc, distributed.psum_fleet(fa, self._axis)

        spec_c, spec_r = P(self._axis), P()
        mapped = shard_map(
            step, mesh=self.mesh,
            in_specs=(spec_c, spec_r, spec_c),
            out_specs=(spec_c, spec_c, spec_r),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=(0, 2))

    def _build_sharded_scan_acc_tel_fleet(self, fn=None):
        """Both accumulators riding the sharded scan (telemetry AND
        analytics on): one psum tree each per block, both replicated."""
        from tmhpvsim_tpu.parallel import distributed

        inner = (self._block_step_scan_acc_tel_fleet if fn is None
                 else fn)

        def step(state, inputs, acc):
            state, acc, ta, fa = inner(state, inputs, acc)
            with self._phase("collectives"):
                return (state, acc,
                        distributed.psum_telemetry(ta, self._axis),
                        distributed.psum_fleet(fa, self._axis))

        spec_c, spec_r = P(self._axis), P()
        mapped = shard_map(
            step, mesh=self.mesh,
            in_specs=(spec_c, spec_r, spec_c),
            out_specs=(spec_c, spec_c, spec_r, spec_r),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=(0, 2))

    def _build_sharded_wide_fleet(self):
        """Wide-impl fleet fold under shard_map: per-shard scalar-form
        fold over the materialised meter/pv arrays, mesh-reduced like
        the scan variant."""
        from tmhpvsim_tpu.parallel import distributed

        if self._n_cohorts:
            # cohort ids shard with the chains; the (C,) cohort leaves in
            # the accumulator are shared scatter targets and psum-merge
            def fold(meter, pv, t, cohort):
                fa = self._wide_fleet(meter, pv, t, cohort)
                with self._phase("collectives"):
                    return distributed.psum_fleet(fa, self._axis)

            in_specs = (P(self._axis), P(self._axis), P(), P(self._axis))
        else:
            def fold(meter, pv, t):
                fa = self._wide_fleet(meter, pv, t)
                with self._phase("collectives"):
                    return distributed.psum_fleet(fa, self._axis)

            in_specs = (P(self._axis), P(self._axis), P())

        mapped = shard_map(
            fold, mesh=self.mesh,
            in_specs=in_specs,
            out_specs=P(),
            check_vma=False,
        )
        return jax.jit(mapped)

    def _build_sharded_scan_series(self, series_fn=None):
        """Ensemble mode's scan-fused step under shard_map (``series_fn``
        picks the flat or nested variant): each shard scans its chains and
        emits LOCAL per-second sums; one psum pair per block replicates
        the fleet totals — the same single collective per block as the
        wide ensemble path."""
        series = (self._block_step_scan_series if series_fn is None
                  else series_fn)

        def fn(state, inputs):
            state, m_sum, p_sum = series(state, inputs)
            with self._phase("collectives"):
                return (state, jax.lax.psum(m_sum, self._axis),
                        jax.lax.psum(p_sum, self._axis))

        mapped = shard_map(
            fn, mesh=self.mesh,
            in_specs=(P(self._axis), P()),
            out_specs=(P(self._axis), P(), P()),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=0)

    def _build_trace_ensemble(self):
        """Trace/ensemble-mode consumer: per-second sums of meter and pv
        over *all* chains — one ``psum`` over ICI, replicated on every chip.
        This collective is exactly where the reference's AMQP fan-out +
        funnel join used to sit (SURVEY.md §2.4).  Same signature as the
        parent's ``_ensemble_series``, so it rebinds as ``_series_jit``
        and ``run_ensemble`` runs sharded unchanged."""

        def ens(meter, pv):
            with self._phase("collectives"):
                m_sum = jax.lax.psum(meter.sum(axis=0), self._axis)
                p_sum = jax.lax.psum(pv.sum(axis=0), self._axis)
            return m_sum, p_sum

        mapped = shard_map(
            ens, mesh=self.mesh,
            in_specs=(P(self._axis), P(self._axis)), out_specs=(P(), P()),
            check_vma=False,
        )
        return jax.jit(mapped)

    def _build_mega_acc(self, k, tel, fleet=False):
        """Sharded multi-block fused dispatch, reduce path: the shard_map
        sits OUTSIDE the outer ``lax.scan`` so the whole K-block
        megablock is one SPMD program per shard — still zero in-loop
        collectives on the acc path, and under telemetry/analytics the
        per-block deltas take the same one-psum-per-block tree as the
        per-block wrappers (``_build_sharded_scan_acc_tel`` /
        ``_build_sharded_scan_acc_fleet``), just issued from inside the
        scan body.  Stacked per-block acc snapshots come back
        chain-sharded on axis 1; stacked tel/fleet deltas are
        replicated."""
        from tmhpvsim_tpu.parallel import distributed

        kind = "acc" + ("_tel" if tel else "") + ("_fleet" if fleet else "")
        fn = self._mega_block_fn(kind)

        def mega(state, xs, acc, const):
            def body(carry, x):
                st, a = carry
                inputs = self._merge_inputs(x, const)
                out = fn(st, inputs, a)
                st, a = out[0], out[1]
                extras = []
                idx = 2
                with self._phase("collectives"):
                    if tel:
                        extras.append(distributed.psum_telemetry(
                            out[idx], self._axis))
                        idx += 1
                    if fleet:
                        extras.append(distributed.psum_fleet(
                            out[idx], self._axis))
                if extras:
                    return (st, a), (a,) + tuple(extras)
                return (st, a), a

            (state, acc), ys = jax.lax.scan(body, (state, acc), xs)
            return state, acc, ys

        spec_c, spec_r = P(self._axis), P()
        spec_k = P(None, self._axis)  # (k, chains, ...) stacked snapshots
        n_extras = int(tel) + int(fleet)
        ys_spec = ((spec_k,) + (spec_r,) * n_extras) if n_extras else spec_k
        mapped = shard_map(
            mega, mesh=self.mesh,
            in_specs=(spec_c, spec_r, spec_c, spec_r),
            out_specs=(spec_c, spec_c, ys_spec),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=(0, 2))

    def _build_mega_blocks(self, kind, k):
        """Sharded multi-block fused dispatch, ensemble/trace path.
        ``series`` psums each block's local per-second sums inside the
        scan body (fleet totals replicated, as in
        ``_build_sharded_scan_series``); ``trace`` keeps the raw
        chain-sharded meter/pv stacks and leaves the psum to the
        per-block ``_trace_ensemble`` call on each slice."""
        fn = self._mega_block_fn(kind)
        series = kind == "series"

        def mega(state, xs, const):
            def body(st, x):
                st, a, b = fn(st, self._merge_inputs(x, const))
                if series:
                    with self._phase("collectives"):
                        a = jax.lax.psum(a, self._axis)
                        b = jax.lax.psum(b, self._axis)
                return st, (a, b)

            state, (a_k, b_k) = jax.lax.scan(body, state, xs)
            return state, a_k, b_k

        spec_c = P(self._axis)
        out_ab = P() if series else P(None, self._axis)
        mapped = shard_map(
            mega, mesh=self.mesh,
            in_specs=(spec_c, P(), P()),
            out_specs=(spec_c, out_ab, out_ab),
            check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=0)

    # ------------------------------------------------------------------
    # scenario-batched serving dispatch on the mesh (serve/)
    # ------------------------------------------------------------------

    def _has_scenario_axis(self) -> bool:
        return SCENARIO_AXIS in self.mesh.axis_names

    def init_scenario_acc(self, batch: int, sharding=None):
        """Scenario accumulator born with the serving layout: batch over
        ``scenario`` (2-D mesh), chains over ``chains``.  On a 1-D mesh
        the batch axis is replicated — every chip folds every scenario
        of its own chain shard, the pre-2-D behaviour."""
        if sharding is None:
            sharding = (scenario_sharding(self.mesh)
                        if self._has_scenario_axis()
                        else NamedSharding(self.mesh, P(None, CHAIN_AXIS)))
        return super().init_scenario_acc(batch, sharding=sharding)

    def _get_scenario_jit(self):
        """The scenario dispatch under shard_map: chains over the
        ``chains`` axis; the request batch over ``scenario`` when the
        mesh has one (each chip computes its chain shard's physics once
        per second and re-reads it through only its scenario column's
        knobs), replicated otherwise (pure chain parallelism — each chip
        folds the whole batch for its own chains).  The per-scenario
        FleetAcc delta psums over the chain axes in-graph
        (parallel/distributed.psum_fleet — the same leaf-kind dispatch
        as the batch path), so the host merge reads a complete,
        bit-identical sketch from any one chain shard."""
        if self._scenario_jit is None:
            from tmhpvsim_tpu.parallel import distributed

            two_d = self._has_scenario_axis()
            spec_c = P(CHAIN_AXIS)
            spec_b = P(SCENARIO_AXIS) if two_d else P()
            spec_acc = (P(SCENARIO_AXIS, CHAIN_AXIS) if two_d
                        else P(None, CHAIN_AXIS))

            def step(state, inputs, acc, scen, chain_ids, cohort):
                state, acc, fd = self._scenario_block_core(
                    state, inputs, acc, scen, chain_ids, cohort)
                # each chain shard folded only its own chains; collapse
                # the chain axis in-graph so the delta is complete on
                # every shard (sharded only over the scenario axis)
                fd = distributed.psum_fleet(fd, CHAIN_AXIS)
                return state, acc, fd

            mapped = shard_map(
                step, mesh=self.mesh,
                in_specs=(spec_c, P(), spec_acc, spec_b, spec_c,
                          spec_c if self._n_cohorts else P()),
                out_specs=(spec_c, spec_acc, spec_b),
                check_vma=False,
            )
            inner = jax.jit(mapped, donate_argnums=(0, 2))
            ids, cohort = self._scenario_consts()

            def call(state, inputs, acc, scen, _jit=inner, _ids=ids,
                     _cohort=cohort):
                return _jit(state, inputs, acc, scen, _ids, _cohort)

            call.lower = lambda st, inp, acc, scen, _jit=inner, _ids=ids, \
                _cohort=cohort: _jit.lower(st, inp, acc, scen, _ids, _cohort)
            self._scenario_jit = call
        return self._scenario_jit

    def _scenario_consts(self):
        """Global chain ids and cohort tags as DEVICE inputs for the
        sharded scenario dispatch: shard_map slices them with the chain
        specs, so each shard's rows carry their true global indices —
        the closure-constant construction of the unsharded path would
        rebuild the FULL arrays inside every shard."""
        ids = jnp.arange(self.config.n_chains, dtype=jnp.int32)
        cohort = (jnp.asarray(self._fleet.cohort, jnp.int32)
                  if self._n_cohorts
                  else jnp.zeros((), jnp.int32))
        sh = NamedSharding(self.mesh, P(CHAIN_AXIS))
        ids = jax.device_put(ids, sh)
        if self._n_cohorts:
            cohort = jax.device_put(cohort, sh)
        return ids, cohort

    def scenario_batch_align(self) -> int:
        """The multiple serve batch buckets must round up to so the
        request batch divides evenly over the ``scenario`` mesh axis
        (1 on a 1-D mesh — no constraint)."""
        if not self._has_scenario_axis():
            return 1
        return int(self.mesh.devices.shape[
            self.mesh.axis_names.index(SCENARIO_AXIS)])

    def step_reduced(self, state, inputs):
        """One sharded reduce-mode block: ``step_acc`` into a fresh sharded
        accumulator (a one-block fold of sum/max/min over the zero/identity
        init IS that block's statistics — tested against the base class in
        tests/test_parallel.py)."""
        return self.step_acc(state, inputs, self.init_reduce_acc())

    def _build_sharded_ensemble(self):
        """Cross-chain aggregates of the accumulator: one ``psum``/``pmax``
        tree over ICI, result replicated on every chip — the collective
        that replaces the reference's fan-out + eyeball aggregation.
        Statistic kinds come from ``REDUCE_STATS`` (engine/simulation.py)."""
        from tmhpvsim_tpu.engine.simulation import REDUCE_STATS

        def ens(a):
            local = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}
            coll = {"sum": jax.lax.psum, "max": jax.lax.pmax,
                    "min": jax.lax.pmin}
            return {
                name: coll[kind](local[kind](a[name]), self._axis)
                for name, (kind, _) in REDUCE_STATS.items()
            }

        mapped = shard_map(
            ens, mesh=self.mesh, in_specs=P(self._axis), out_specs=P(),
            check_vma=False,
        )
        return jax.jit(mapped)

    def init_reduce_acc(self):
        return super().init_reduce_acc(sharding=chain_sharding(self.mesh))

    def _is_multihost(self) -> bool:
        return any(d.process_index != jax.process_index()
                   for d in self.mesh.devices.flat)

    def _place_resume(self, tree):
        """Checkpointed pytrees re-enter with the chain sharding they were
        saved from (host numpy otherwise reaches ``_host_view`` unplaced
        when a resume has no blocks left to run).

        Single host: a plain ``device_put`` of the full tree — including
        a tree loaded from a checkpoint written under a DIFFERENT device
        count or mesh shape (``checkpoint.load_elastic`` already
        reassembled/resliced the chain axis; placement is elastic, only
        identity refuses).  Pod slice: each host loaded only ITS chain
        slice (its per-host checkpoint file, or its ``resume_chain_slice``
        of a full checkpoint), so the global sharded arrays are assembled
        with ``jax.make_array_from_process_local_data`` — every process
        contributes the contiguous chains its devices own, no DCN
        traffic.  PRNG-key leaves ride as their key_data words and are
        re-wrapped on the assembled array."""
        sh = chain_sharding(self.mesh)
        if not self._is_multihost():
            return jax.device_put(tree, sh)

        def place(v):
            if hasattr(v, "dtype") and jax.dtypes.issubdtype(
                    v.dtype, jax.dtypes.prng_key):
                kd = np.asarray(jax.random.key_data(v))
                arr = jax.make_array_from_process_local_data(sh, kd)
                return jax.random.wrap_key_data(
                    arr, impl=self.config.prng_impl
                )
            return jax.make_array_from_process_local_data(sh, np.asarray(v))

        return jax.tree.map(place, tree)

    def host_local_tree(self, tree):
        """Restrict every chain-sharded leaf to this host's contiguous
        chain slice (``_host_view``) so a pod-slice host checkpoints
        exactly the chains it owns — the save-side counterpart of
        ``_place_resume``'s per-process reassembly.  PRNG-key leaves are
        sliced via their key_data words and re-wrapped."""

        def conv(v):
            if hasattr(v, "dtype") and jax.dtypes.issubdtype(
                    v.dtype, jax.dtypes.prng_key):
                kd = self._host_view(jax.random.key_data(v))
                return jax.random.wrap_key_data(
                    jnp.asarray(kd), impl=self.config.prng_impl
                )
            return self._host_view(v)

        return jax.tree.map(conv, tree)

    def resume_chain_slice(self):
        """This host's (start, stop) chain range for an elastic resume
        from a FULL checkpoint (one written without per-host sharding):
        None on a single host (load everything); on a pod slice the
        contiguous range this host's devices own, so
        ``checkpoint.load_elastic`` slices the full chain axis down to
        exactly what ``_place_resume`` will contribute."""
        if not self._is_multihost():
            return None
        from tmhpvsim_tpu.parallel.distributed import local_chain_slice

        sl = local_chain_slice(self.config.n_chains, self.mesh)
        return (int(sl.start), int(sl.stop))

    @staticmethod
    def _host_view(arr) -> np.ndarray:
        """Device->host copy of a chain-sharded array: the whole array when
        fully addressable, else this host's shards in chain order.

        This is the multi-host (pod slice) output contract for both run
        modes: a global gather is impossible there (the array spans
        non-addressable devices) and unwanted (it would ride DCN); each
        host gets the contiguous chain slice its own devices hold — the
        same slice ``local_reduced_view``/``local_chain_slice`` report."""
        if arr.is_fully_addressable:
            return np.array(arr)
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards])

    @staticmethod
    def _repl_view(arr) -> np.ndarray:
        """Host copy of a replicated (out_specs=P()) result: any one
        addressable shard carries the full value, so this never gathers
        over DCN on a pod slice."""
        if arr.is_fully_addressable:
            return np.asarray(arr)
        return np.asarray(arr.addressable_shards[0].data)

    def ensemble_stats(self) -> dict:
        """Fleet-wide aggregates via the on-device psum tree (replicated
        output — a host copy, never a DCN gather on multi-host)."""
        from tmhpvsim_tpu.engine.simulation import REDUCE_STATS

        out = self._sharded_ensemble(self._last_acc)
        return {k: (int(v) if REDUCE_STATS[k][1] == "i" else float(v))
                for k, v in out.items()}

    def local_reduced_view(self, reduced: dict) -> tuple:
        """(slice, dict) restriction of ``run_reduced`` output to the chains
        this host's devices own — what a per-host CSV writer/checkpointer
        consumes on a pod slice (parallel/distributed.py).  On multi-host,
        ``run_reduced`` already returns exactly this slice, so the arrays
        pass through unchanged."""
        from tmhpvsim_tpu.parallel.distributed import local_chain_slice

        sl = local_chain_slice(self.config.n_chains, self.mesh)
        first = next(iter(reduced.values()))
        if len(first) != self.config.n_chains:  # already host-local
            return sl, reduced
        return sl, {k: v[sl] for k, v in reduced.items()}

    def run_blocks(self, state=None, start_block: int = 0
                   ) -> Iterator[BlockResult]:
        """Sharded trace mode.  Single-host: BlockResults carry all chains.
        Multi-host: the chain axis of ``meter``/``pv``/``residual`` is this
        host's contiguous slice only (``_host_view``), while ``.ensemble``
        is always the global fleet view (replicated psum output) — so a
        per-host CSV writer and a global grid-operator stream both work on
        a pod slice without any DCN gather.  Runs the parent's shared
        block loop; only the gather differs (per-chain result + the psum
        ensemble attachment)."""
        inv_n = 1.0 / self.config.n_chains

        def make(off, epoch, meter, pv, n_valid):
            m_sum, p_sum = self._trace_ensemble(meter, pv)
            blk = self._trace_result(off, epoch, meter, pv, n_valid)
            ms = self._repl_view(m_sum)[:n_valid]
            ps = self._repl_view(p_sum)[:n_valid]
            blk.ensemble = {
                "pv_mean": ps * inv_n,
                "residual_mean": (ms - ps) * inv_n,
            }
            return blk

        return self._iter_blocks(state, start_block, make)
