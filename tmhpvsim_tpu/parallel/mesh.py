"""Chain-parallel execution over a TPU device mesh.

The reference's only parallelism is "run N independent pvsim consumer
processes against one RabbitMQ fanout exchange" (SURVEY.md §2.3,
metersim.py:25-28 / pvsim.py:62-63) — replication with a broker as the
fan-out.  The TPU-native equivalent shards the *chain* batch axis of one
simulation across the chips of a ``jax.sharding.Mesh`` and replaces the
broker with in-process XLA collectives over ICI:

* every per-chain quantity (sampler arrays, renewal carry, keys, traces)
  is sharded on the ``chains`` mesh axis — pure data parallelism, zero
  communication in the hot loop;
* cross-chain *ensemble* statistics (the "grid operator" view: aggregate
  residual load per second over the whole fleet) are one ``psum`` per
  block over ICI — the only collective the workload needs, exactly where
  the reference's AMQP fan-out + funnel join used to sit (SURVEY.md §2.4);
* multi-host slices extend the same mesh over DCN via
  ``jax.distributed`` (parallel/distributed.py); each host feeds and
  gathers only its addressable shard.

Tested on 8 virtual CPU devices (tests/conftest.py sets
``--xla_force_host_platform_device_count=8``; SURVEY.md §4).
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from tmhpvsim_tpu.config import SimConfig
from tmhpvsim_tpu.engine.simulation import BlockResult, Simulation

CHAIN_AXIS = "chains"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, axis name ``chains``.

    The workload is embarrassingly parallel over chains, so a flat 1-D mesh
    is the right topology on any slice shape: XLA maps the single axis onto
    the physical ICI torus itself, and the one collective we issue (psum of
    per-second ensemble sums) rides nearest-neighbour rings.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    return Mesh(np.asarray(devices), (CHAIN_AXIS,))


def chain_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that splits the leading (chain) axis across the mesh."""
    return NamedSharding(mesh, P(CHAIN_AXIS))


class ShardedSimulation(Simulation):
    """`engine.Simulation` with the chain axis sharded across a mesh.

    Differences from the single-chip parent:

    * ``init_state()`` lays out every chain-indexed leaf with a
      ``NamedSharding`` over the ``chains`` axis (n_chains must divide by
      the mesh size);
    * the block step runs under ``shard_map`` and additionally returns the
      per-second ensemble sums of pv and residual over *all* chains,
      reduced with ``psum`` over ICI and replicated on every chip;
    * BlockResults carry the global ensemble means in ``.ensemble``.
    """

    def __init__(self, config: SimConfig, mesh: Optional[Mesh] = None):
        super().__init__(config)
        self.mesh = mesh if mesh is not None else make_mesh()
        n_dev = self.mesh.devices.size
        if config.n_chains % n_dev != 0:
            raise ValueError(
                f"n_chains={config.n_chains} must be divisible by the mesh "
                f"size {n_dev}"
            )
        self._sharded_block = self._build_sharded_block()

    def init_state(self):
        state = super().init_state()
        sharding = chain_sharding(self.mesh)
        return jax.device_put(state, sharding)

    def _build_sharded_block(self):
        spec_state = P(CHAIN_AXIS)
        spec_repl = P()

        def block(state, inputs):
            # Inside shard_map: `state` is this chip's chain shard, inputs
            # are replicated.  The parent's vmapped step runs unchanged on
            # the shard; the ensemble reduction is the one collective.
            new_state, meter, pv, residual = self._block_step(state, inputs)
            pv_sum = jax.lax.psum(pv.sum(axis=0), CHAIN_AXIS)
            res_sum = jax.lax.psum(residual.sum(axis=0), CHAIN_AXIS)
            return new_state, meter, pv, residual, pv_sum, res_sum

        mapped = shard_map(
            block,
            mesh=self.mesh,
            in_specs=(spec_state, spec_repl),
            out_specs=(spec_state, spec_state, spec_state, spec_state,
                       spec_repl, spec_repl),
            check_vma=False,
        )
        return jax.jit(mapped)

    def run_blocks(self, state=None, start_block: int = 0
                   ) -> Iterator[BlockResult]:
        cfg = self.config
        if state is None:
            state = self.init_state()
        self.state = state
        inv_n = 1.0 / cfg.n_chains
        for bi in range(start_block, self.n_blocks):
            inputs, epoch = self.host_inputs(bi)
            (self.state, meter, pv, residual, pv_sum, res_sum
             ) = self._sharded_block(self.state, inputs)
            off = bi * cfg.block_s
            n_valid = min(cfg.block_s, cfg.duration_s - off)
            blk = BlockResult(
                offset=off,
                epoch=np.asarray(epoch[:n_valid]),
                meter=np.asarray(meter)[:, :n_valid],
                pv=np.asarray(pv)[:, :n_valid],
                residual=np.asarray(residual)[:, :n_valid],
            )
            blk.ensemble = {
                "pv_mean": np.asarray(pv_sum)[:n_valid] * inv_n,
                "residual_mean": np.asarray(res_sum)[:n_valid] * inv_n,
            }
            yield blk
