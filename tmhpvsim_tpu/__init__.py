"""tmhpvsim-tpu: TPU-native photovoltaic simulation & streaming framework.

A ground-up re-design of the capabilities of ``coroa/tmhpvsim`` (reference at
/root/reference) for JAX/XLA on TPU.  The reference simulates, per second,

  * a random electricity demand ("meter") stream, and
  * a stochastic PV generation stream (Markov-chain cloud cover -> clear-sky
    index -> irradiance -> AC power, following Bright et al. 2015 + a pvlib
    physics chain),

joins the two 1 Hz streams by timestamp and writes ``time, meter, pv,
residual load`` CSV rows (reference: tmhpvsim/pvsim.py:86-101).

This framework keeps that capability surface (same CLI entrypoints and flags,
an asyncio/AMQP streaming backend) and adds a TPU-first execution backend
(``--backend=jax``) in which the whole per-second Monte Carlo loop is a
``jit(shard_map(vmap(lax.scan(step))))`` over a device mesh: thousands to
millions of independent site-chains, each advancing hourly/daily/minute/second
stochastic state, evaluated blockwise over the time grid with the PV physics
chain fully vectorized.

Layout (mirrors SURVEY.md section 7's build order):

  models/    stochastic weather + clear-sky-index + PV physics (pure JAX)
  engine/    single-chip blockwise simulation engine and numpy golden path
  parallel/  mesh/sharding layer: shard_map across chips, multi-host helpers
  runtime/   asyncio streaming runtime (clock, funnel, retry, AMQP broker)
  offline/   working shape-parameter fitting tool (replaces the reference's
             broken pymc3 pipeline, cloud_cover_hourly.py:118-267)
  data/      vendored distribution shape parameters + PV coefficients
"""

def __getattr__(name):
    # lazy: resolving the version may shell out to git (tmhpvsim_tpu/
    # _version.py); importing the package must not pay that
    if name == "__version__":
        from tmhpvsim_tpu._version import __version__ as v

        return v
    raise AttributeError(name)
