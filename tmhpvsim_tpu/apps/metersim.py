"""metersim: 1 Hz random electricity-demand producer.

Reference behaviour (metersim.py): sample uniform [0, 9000) W once per
second on the fixedclock grid, queue, and publish each value as a JSON
float to a fanout exchange with the measurement time in the message
timestamp.  The publisher coroutine retries forever with 5 s delay on
broker failures; on shutdown, queued-but-unsent values are counted and
warned about (metersim.py:76-77).
"""

from __future__ import annotations

import asyncio
import datetime as _dt
import logging
from typing import Optional

import numpy as np

from tmhpvsim_tpu.runtime import asyncretry, fixedclock, forever
from tmhpvsim_tpu.runtime.broker import make_transport

logger = logging.getLogger(__name__)


def get_meter_value(rng: Optional[np.random.Generator] = None,
                    max_w: float = 9000.0) -> float:
    """One uniform [0, max_w) demand sample (metersim.py:49-51)."""
    rng = rng if rng is not None else np.random.default_rng()
    return float(max_w * rng.random())


async def read_meter_values(queue: asyncio.Queue, realtime: bool,
                            rng=None, duration_s=None,
                            start: Optional[_dt.datetime] = None) -> None:
    """Producer loop: one (time, value) per clock tick (metersim.py:53-62)."""
    rng = rng if rng is not None else np.random.default_rng()
    async for time in fixedclock(rate=1, realtime=realtime, start=start,
                                 duration_s=duration_s):
        await queue.put((time, get_meter_value(rng)))


async def send_queue_to_transport(queue: asyncio.Queue, url, exchange) -> None:
    """Publisher loop with forever-retry (metersim.py:13-47).

    A value dequeued when publish fails is held across the reconnect and
    re-sent first (the reference gets the same no-loss property from
    ``asyncio.shield``, metersim.py:43-45) — and ``task_done`` always
    matches its ``get``, so a bounded run's ``queue.join()`` cannot hang on
    a failed publish.
    """
    pending = None

    @asyncretry(delay=5, attempts=forever)
    async def run():
        nonlocal pending
        async with make_transport(url, exchange) as transport:
            while True:
                if pending is None:
                    pending = await queue.get()
                time, value = pending
                await transport.publish(value, time)
                pending = None
                queue.task_done()

    await run()


async def metersim_main(amqp_url, exchange, realtime, seed=None,
                        duration_s=None, start=None) -> None:
    """App orchestrator (metersim.py:64-77): producer + publisher tasks."""
    queue: asyncio.Queue = asyncio.Queue()
    rng = np.random.default_rng(seed)
    read = asyncio.create_task(
        read_meter_values(queue, realtime, rng, duration_s, start)
    )
    send = asyncio.create_task(send_queue_to_transport(queue, amqp_url,
                                                       exchange))
    try:
        done, _ = await asyncio.wait(
            {read, send}, return_when=asyncio.FIRST_COMPLETED
        )
        for t in done:
            t.result()
        # bounded run: wait for the queue to drain before stopping the sender
        await queue.join()
    finally:
        for t in (read, send):
            t.cancel()
        if not queue.empty():
            logger.warning(
                "%d sampled meter_values have not been sent", queue.qsize()
            )
