"""metersim: 1 Hz random electricity-demand producer.

Reference behaviour (metersim.py): sample uniform [0, 9000) W once per
second on the fixedclock grid, queue, and publish each value as a JSON
float to a fanout exchange with the measurement time in the message
timestamp.  The publisher coroutine retries forever with 5 s delay on
broker failures; on shutdown, queued-but-unsent values are counted and
warned about (metersim.py:76-77).
"""

from __future__ import annotations

import asyncio
import contextlib
import datetime as _dt
import logging
import time as _time
from typing import Optional

import numpy as np

from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs.trace import Tracer
from tmhpvsim_tpu.runtime import fixedclock, reconnect_policy
from tmhpvsim_tpu.runtime.broker import make_transport

logger = logging.getLogger(__name__)

#: demand ceiling [W] — the reference's uniform [0, 9000) (metersim.py:49-51);
#: SimConfig.meter_max_w is the engine-side owner of the same value
METER_MAX_W = 9000.0


def get_meter_value(rng: Optional[np.random.Generator] = None,
                    max_w: float = METER_MAX_W) -> float:
    """One uniform [0, max_w) demand sample (metersim.py:49-51)."""
    rng = rng if rng is not None else np.random.default_rng()
    return float(max_w * rng.random())


async def read_meter_values(queue: asyncio.Queue, realtime: bool,
                            rng=None, duration_s=None,
                            start: Optional[_dt.datetime] = None) -> None:
    """Producer loop: one (time, value) per clock tick (metersim.py:53-62)."""
    rng = rng if rng is not None else np.random.default_rng()
    async for time in fixedclock(rate=1, realtime=realtime, start=start,
                                 duration_s=duration_s):
        await queue.put((time, get_meter_value(rng)))


async def read_meter_values_jax(queue: asyncio.Queue, realtime: bool,
                                seed=None, duration_s=None,
                                start: Optional[_dt.datetime] = None,
                                block_s: int = 600,
                                prng_impl: str = "threefry2x32") -> None:
    """Device-batched producer: the ``--backend=jax`` meter stream.

    Same external behaviour as :func:`read_meter_values` (one uniform
    [0, METER_MAX_W) value per fixedclock tick into the queue), but the
    values are generated on device in ``block_s``-second blocks with the
    engine's keyed scheme (``ci.minute_grouped_keys``: one threefry key
    per minute index, 60 counter-mode draws — the same helper the
    simulation's meter stream uses), so a run is deterministic per seed
    and the publisher empties a device buffer instead of calling the RNG
    per second.  The device call runs in a worker thread: the first block
    triggers XLA compilation (seconds — and this environment's remote-TPU
    backend can stall outright), which must not freeze the event loop the
    publisher and broker heartbeats live on."""
    import jax
    import jax.numpy as jnp

    from tmhpvsim_tpu.models import clearsky_index as ci

    if start is None:
        start = _dt.datetime.now()
    start = start.replace(microsecond=0)
    if seed is None:
        import secrets

        seed = secrets.randbits(31)
    root = jax.random.key(seed, impl=prng_impl)
    assert block_s % 60 == 0

    @jax.jit
    def block_vals(sec0):
        t = sec0 + jnp.arange(block_s)
        return ci.meter_block(root, t, METER_MAX_W)

    m_blocks = obs_metrics.get_registry().counter("metersim.blocks_total")
    vals, i, sec = None, 0, 0
    async for time in fixedclock(rate=1, realtime=realtime, start=start,
                                 duration_s=duration_s):
        if vals is None or i == block_s:
            vals = await asyncio.to_thread(
                lambda s: np.asarray(block_vals(s)), sec
            )
            m_blocks.inc()
            i = 0
        await queue.put((time, float(vals[i])))
        i += 1
        sec += 1


async def send_queue_to_transport(queue: asyncio.Queue, url, exchange,
                                  tracer: Optional[Tracer] = None) -> None:
    """Publisher loop with forever-retry (metersim.py:13-47).

    A value dequeued when publish fails is held across the reconnect and
    re-sent first (the reference gets the same no-loss property from
    ``asyncio.shield``, metersim.py:43-45) — and ``task_done`` always
    matches its ``get``, so a bounded run's ``queue.join()`` cannot hang on
    a failed publish.

    Every payload is additively stamped with a ``seq`` and the
    publisher's monotonic publish time (``pub_us``, µs) so an
    instrumented consumer can measure publish→join latency and spot
    gaps; the stamp rides out-of-band of the JSON float body
    (runtime/broker.py), so reference consumers are unaffected.  The
    held-across-reconnect value keeps its seq but is re-stamped with the
    actual (re)publish time.
    """
    pending = None
    seq = 0
    m_pub = obs_metrics.get_registry().counter(
        "metersim.values_published_total"
    )

    async def run():
        nonlocal pending, seq
        async with make_transport(url, exchange) as transport:
            while True:
                if pending is None:
                    time, value = await queue.get()
                    pending = (seq, time, value)
                    seq += 1
                n, time, value = pending
                meta = {"seq": n, "pub_us": _time.monotonic_ns() // 1000}
                if tracer:
                    with tracer.span("publish", "broker", seq=n):
                        await transport.publish(value, time, meta=meta)
                else:
                    await transport.publish(value, time, meta=meta)
                m_pub.inc()
                pending = None
                queue.task_done()

    await reconnect_policy(name="metersim.send_queue").call(run)


async def metersim_main(amqp_url, exchange, realtime, seed=None,
                        duration_s=None, start=None,
                        backend: str = "asyncio",
                        trace: Optional[str] = None,
                        compile_cache: Optional[str] = None,
                        obs_port: Optional[int] = None,
                        obs_bind: str = "127.0.0.1") -> None:
    """App orchestrator (metersim.py:64-77): producer + publisher tasks.
    ``backend='jax'`` swaps the per-second numpy producer for the
    device-batched one; the transport/publisher side is identical.

    ``trace`` names a Chrome-trace JSON (obs/trace.py): publish spans
    land in the ring, the full ring is exported there on exit, and an
    unhandled exception dumps the last-30-s flight slice to
    ``trace + '.crash.json'`` before re-raising.

    ``obs_port`` (``--obs-port``) binds the live ops plane (obs/live.py:
    ``/metrics``, ``/healthz``, ``/readyz``, ``/flight``) and turns on
    cross-process trace propagation — every published value's meta gains
    ``trace_id``/``span_id`` for downstream correlation."""
    from tmhpvsim_tpu.obs import trace as obs_trace
    from tmhpvsim_tpu.obs.live import maybe_obs_server

    tracer = Tracer() if trace else None
    if obs_port is not None:
        obs_trace.enable_propagation(True)
    async with maybe_obs_server(obs_port, host=obs_bind, tracer=tracer):
        await _metersim_run(amqp_url, exchange, realtime, seed,
                            duration_s, start, backend, trace,
                            compile_cache, tracer)


async def _metersim_run(amqp_url, exchange, realtime, seed, duration_s,
                        start, backend, trace, compile_cache,
                        tracer) -> None:
    queue: asyncio.Queue = asyncio.Queue()
    if backend == "jax":
        # persistent XLA cache: the block producer's jit deserialises
        # from disk on the second run instead of recompiling
        from tmhpvsim_tpu.engine import compilecache

        compilecache.configure(compile_cache)
        read = asyncio.create_task(
            read_meter_values_jax(queue, realtime, seed, duration_s, start)
        )
    else:
        rng = np.random.default_rng(seed)
        read = asyncio.create_task(
            read_meter_values(queue, realtime, rng, duration_s, start)
        )
    send = asyncio.create_task(send_queue_to_transport(queue, amqp_url,
                                                       exchange, tracer))
    try:
        done, _ = await asyncio.wait(
            {read, send}, return_when=asyncio.FIRST_COMPLETED
        )
        for t in done:
            t.result()
        # bounded run: wait for the queue to drain before stopping the sender
        await queue.join()
    except asyncio.CancelledError:
        raise
    except BaseException:
        if tracer:
            with contextlib.suppress(Exception):
                tracer.dump_flight(trace + ".crash.json")
        raise
    finally:
        for t in (read, send):
            t.cancel()
        if not queue.empty():
            logger.warning(
                "%d sampled meter_values have not been sent", queue.qsize()
            )
        if tracer:
            with contextlib.suppress(Exception):
                tracer.export(trace, process_name="metersim")
