"""pvsim: consume the meter stream, simulate PV, join, write CSV.

Reference behaviour (pvsim.py): three concurrent tasks — a 1 Hz PV
simulation loop, an AMQP consumer with forever-retry, and a CSV writer —
joined through a SynchronizingFunnel keyed by timestamp; rows are
``time, meter, pv, residual load`` (pvsim.py:72-84).  On shutdown the
number of stranded half-records is warned about (pvsim.py:100-101).

The JAX backend (``backend='jax'``) replaces all of it with the blockwise
device simulation (engine/simulation.py): both streams are generated on the
common grid in-process, so there is no broker, no funnel, and the same CSV
comes out orders of magnitude faster (SURVEY.md §2.4).
"""

from __future__ import annotations

import asyncio
import datetime as _dt
import logging
from collections import namedtuple
from typing import Optional

import numpy as np

from tmhpvsim_tpu.config import ModelOptions, Site
from tmhpvsim_tpu.runtime import SynchronizingFunnel, asyncretry, fixedclock, \
    forever
from tmhpvsim_tpu.runtime.broker import make_transport

logger = logging.getLogger(__name__)

#: Joined record (pvsim.py:19).
Data = namedtuple("Data", ["meter", "pv"])


async def read_pv_values(funnel: SynchronizingFunnel, realtime: bool,
                         seed=None, duration_s=None,
                         start: Optional[_dt.datetime] = None) -> None:
    """1 Hz PV loop feeding the funnel (pvsim.py:21-41)."""
    from tmhpvsim_tpu.engine.golden import GoldenPVModel

    if start is None:
        start = _dt.datetime.now()
    start = start.replace(microsecond=0)
    model = GoldenPVModel(start, Site(), ModelOptions(),
                          np.random.default_rng(seed))
    async for time in fixedclock(rate=1, realtime=realtime, start=start,
                                 duration_s=duration_s):
        time = time.replace(microsecond=0)
        await funnel.put(time, pv=model.next(time))


async def read_transport(funnel: SynchronizingFunnel, url, exchange) -> None:
    """Meter consumer with forever-retry (pvsim.py:43-70)."""

    @asyncretry(delay=5, attempts=forever)
    async def run():
        async with make_transport(url, exchange) as transport:
            async for time, value in transport.subscribe():
                await funnel.put(time, meter=value)

    await run()


async def write_file(filename: str, queue: asyncio.Queue) -> None:
    """CSV sink, line-buffered for tail-ability (pvsim.py:72-84)."""
    import csv

    with open(filename, mode="w", newline="", buffering=1) as file:
        writer = csv.writer(file)
        writer.writerow(["time"] + list(Data._fields) + ["residual load"])
        while True:
            time, data = await queue.get()
            writer.writerow([time] + list(data) + [data.meter - data.pv])
            queue.task_done()


async def pvsim_main(file, amqp_url, exchange, realtime, seed=None,
                     duration_s=None, start=None) -> None:
    """App orchestrator (pvsim.py:86-101)."""
    queue: asyncio.Queue = asyncio.Queue()
    funnel = SynchronizingFunnel(Data, queue)
    tasks = [
        asyncio.create_task(read_pv_values(funnel, realtime, seed,
                                           duration_s, start)),
        asyncio.create_task(read_transport(funnel, amqp_url, exchange)),
        asyncio.create_task(write_file(file, queue)),
    ]
    try:
        done, _ = await asyncio.wait(tasks,
                                     return_when=asyncio.FIRST_COMPLETED)
        for t in done:
            t.result()
        await queue.join()
    finally:
        for t in tasks:
            t.cancel()
        if len(funnel) > 0:
            logger.warning(
                "%d undelivered meter_values have been lost", len(funnel)
            )


def pvsim_jax(file, duration_s: int, n_chains: int, seed: int,
              start: Optional[str] = None, chain: int = 0,
              sharded: bool = False) -> None:
    """The JAX backend: blockwise device simulation straight to CSV."""
    from tmhpvsim_tpu.config import SimConfig
    from tmhpvsim_tpu.engine import Simulation
    from tmhpvsim_tpu.engine.simulation import write_csv

    if start is None:
        start = _dt.datetime.now().replace(microsecond=0).isoformat(" ")
    cfg = SimConfig(
        start=start,
        duration_s=duration_s,
        n_chains=n_chains,
        seed=seed,
        block_s=min(8640, max(60, (duration_s // 60) * 60)),
    )
    if sharded:
        from tmhpvsim_tpu.parallel import ShardedSimulation

        sim = ShardedSimulation(cfg)
    else:
        sim = Simulation(cfg)
    from zoneinfo import ZoneInfo

    write_csv(file, sim.run_blocks(), chain=chain,
              tz=ZoneInfo(cfg.site.timezone))
