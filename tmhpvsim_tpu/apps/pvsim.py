"""pvsim: consume the meter stream, simulate PV, join, write CSV.

Reference behaviour (pvsim.py): three concurrent tasks — a 1 Hz PV
simulation loop, an AMQP consumer with forever-retry, and a CSV writer —
joined through a SynchronizingFunnel keyed by timestamp; rows are
``time, meter, pv, residual load`` (pvsim.py:72-84).  On shutdown the
number of stranded half-records is warned about (pvsim.py:100-101).

The JAX backend (``backend='jax'``) replaces all of it with the blockwise
device simulation (engine/simulation.py): both streams are generated on the
common grid in-process, so there is no broker, no funnel, and the same CSV
comes out orders of magnitude faster (SURVEY.md §2.4).
"""

from __future__ import annotations

import asyncio
import contextlib
import datetime as _dt
import logging
import time as _time
from collections import namedtuple
from typing import Optional

import numpy as np

from tmhpvsim_tpu.config import ModelOptions, Site
from tmhpvsim_tpu.obs import metrics as obs_metrics
from tmhpvsim_tpu.obs.trace import Tracer
from tmhpvsim_tpu.runtime import SynchronizingFunnel, fixedclock, \
    reconnect_policy
from tmhpvsim_tpu.runtime.broker import make_transport

logger = logging.getLogger(__name__)

#: Joined record (pvsim.py:19).
Data = namedtuple("Data", ["meter", "pv"])


class _StreamStats:
    """Per-message latency accounting for the streaming backend.

    ``publish→join`` uses the publisher's monotonic stamp (``pub_us`` in
    the message meta, metersim.py): meaningful when producer and
    consumer share a process (the local:// deployment and every e2e
    test); across hosts the clocks are unrelated and the value is
    clamped at 0 — the join→csv leg and the funnel counters stay exact
    everywhere.  Both pending maps are bounded so evicted/never-joined
    timestamps cannot leak memory on an unbounded run.
    """

    _MAX_PENDING = 20_000

    def __init__(self, registry):
        self.h_pub_join = registry.histogram("streaming.publish_to_join_s")
        self.h_join_csv = registry.histogram("streaming.join_to_csv_s")
        self.c_rows = registry.counter("pvsim.rows_written_total")
        self._pub_us: dict = {}
        self._join_ns: dict = {}

    @staticmethod
    def _cap(d: dict, cap: int) -> None:
        while len(d) >= cap:
            d.pop(next(iter(d)))  # insertion order ~ oldest timestamp

    def on_consume(self, t, meta: Optional[dict]) -> None:
        if meta and isinstance(meta.get("pub_us"), (int, float)):
            self._cap(self._pub_us, self._MAX_PENDING)
            self._pub_us[t] = meta["pub_us"]

    def on_join(self, t) -> None:
        now_ns = _time.monotonic_ns()
        pub = self._pub_us.pop(t, None)
        if pub is not None:
            self.h_pub_join.observe(max(0.0, now_ns / 1e3 - pub) / 1e6)
        self._cap(self._join_ns, self._MAX_PENDING)
        self._join_ns[t] = now_ns

    def on_row(self, t) -> None:
        j = self._join_ns.pop(t, None)
        if j is not None:
            self.h_join_csv.observe(
                max(0.0, (_time.monotonic_ns() - j) / 1e9))
        self.c_rows.inc()


class _JoinFront:
    """Queue facade handed to the funnel in place of the raw output
    queue: the funnel awaits ``put`` on completed records only, so this
    is exactly the join-complete instant — stamp it (latency + trace
    event) and forward.  The writer keeps consuming the real queue."""

    __slots__ = ("_queue", "_stream", "_tracer")

    def __init__(self, queue: asyncio.Queue,
                 stream: Optional[_StreamStats] = None,
                 tracer: Optional[Tracer] = None):
        self._queue = queue
        self._stream = stream
        self._tracer = tracer

    async def put(self, item) -> None:
        t, _rec = item
        if self._stream is not None:
            self._stream.on_join(t)
        if self._tracer:
            self._tracer.instant("join", "funnel", t=str(t))
        await self._queue.put(item)


async def read_pv_values(funnel: SynchronizingFunnel, realtime: bool,
                         seed=None, duration_s=None,
                         start: Optional[_dt.datetime] = None,
                         tracer: Optional[Tracer] = None) -> None:
    """1 Hz PV loop feeding the funnel (pvsim.py:21-41)."""
    from tmhpvsim_tpu.engine.golden import GoldenPVModel

    if start is None:
        start = _dt.datetime.now()
    start = start.replace(microsecond=0)
    model = GoldenPVModel(start, Site(), ModelOptions(),
                          np.random.default_rng(seed))
    async for time in fixedclock(rate=1, realtime=realtime, start=start,
                                 duration_s=duration_s):
        time = time.replace(microsecond=0)
        value = model.next(time)
        if tracer:
            # the span includes any backpressure wait inside put — that
            # wait IS the interesting part of a stalled-join timeline
            with tracer.span("funnel.put", "pv"):
                await funnel.put(time, pv=value)
        else:
            await funnel.put(time, pv=value)


async def read_transport(funnel: SynchronizingFunnel, url, exchange,
                         counter: Optional[dict] = None,
                         stream: Optional[_StreamStats] = None,
                         tracer: Optional[Tracer] = None) -> None:
    """Meter consumer with forever-reconnect (pvsim.py:43-70); the
    jittered-backoff policy replaces the reference's fixed 5 s sleep."""

    from tmhpvsim_tpu.obs import trace as obs_trace

    async def run():
        async with make_transport(url, exchange) as transport:
            async for time, value, meta in transport.subscribe(
                    with_meta=True):
                if counter is not None:
                    counter["meter"] = counter.get("meter", 0) + 1
                if stream is not None:
                    stream.on_consume(time, meta)
                # bind the producer's propagated trace (no-op when the
                # ops plane is off) so consume/join events stitch onto
                # the publisher's timeline by trace_id
                with obs_trace.extracted(meta):
                    if tracer:
                        tracer.instant("consume", "stream",
                                       seq=(meta or {}).get("seq"))
                        with tracer.span("funnel.put", "stream"):
                            await funnel.put(time, meter=value)
                    else:
                        await funnel.put(time, meter=value)

    await reconnect_policy(name="pvsim.read_transport").call(run)


async def _no_meter_watchdog(counter: dict, url, timeout_s: float = 10.0):
    """Warn once when no meter message arrived within ``timeout_s`` — the
    symptom of pointing pvsim at a broker no metersim publishes to (and,
    with local:// URLs, of running the pair in separate processes: the
    in-process broker cannot span OS processes)."""
    await asyncio.sleep(timeout_s)
    if counter.get("meter", 0) == 0:
        extra = (
            " local:// transports are in-process only — metersim must run "
            "inside the same process to join." if (url or "local://")
            .startswith("local://") else ""
        )
        logger.warning(
            "no meter messages received after %.0f s; is metersim "
            "publishing to this exchange?%s", timeout_s, extra,
        )


async def write_file(filename: str, queue: asyncio.Queue,
                     stream: Optional[_StreamStats] = None,
                     tracer: Optional[Tracer] = None) -> None:
    """CSV sink, line-buffered for tail-ability (pvsim.py:72-84)."""
    import csv

    with open(filename, mode="w", newline="", buffering=1) as file:
        writer = csv.writer(file)
        writer.writerow(["time"] + list(Data._fields) + ["residual load"])
        while True:
            time, data = await queue.get()
            row = [time] + list(data) + [data.meter - data.pv]
            if tracer:
                with tracer.span("csv.write", "csv"):
                    writer.writerow(row)
            else:
                writer.writerow(row)
            if stream is not None:
                stream.on_row(time)
            queue.task_done()


async def pvsim_main(file, amqp_url, exchange, realtime, seed=None,
                     duration_s=None, start=None,
                     trace: Optional[str] = None,
                     metrics_path: Optional[str] = None,
                     run_report_path: Optional[str] = None,
                     obs_port: Optional[int] = None,
                     obs_bind: str = "127.0.0.1") -> None:
    """App orchestrator (pvsim.py:86-101).

    Streaming observability (obs/): ``trace`` records the consume →
    funnel-put → join → csv-write timeline into a ring and exports it as
    Chrome-trace JSON on exit (crash dumps land at
    ``trace + '.crash.json'``); ``metrics_path`` attaches a sink to the
    process-default registry; ``run_report_path`` writes a RunReport
    whose ``streaming`` section carries the publish→join / join→csv
    latency quantiles and funnel/retry/broker counters.  The tracer is
    a local instance (not the process default) so two app mains sharing
    one process — the e2e tests — cannot race on a global swap.

    ``obs_port`` (``--obs-port``) binds the live ops plane (obs/live.py)
    and turns on cross-process trace propagation (obs/trace.py)."""
    from tmhpvsim_tpu.obs import trace as obs_trace
    from tmhpvsim_tpu.obs.live import maybe_obs_server

    if obs_port is not None:
        obs_trace.enable_propagation(True)
    tracer0 = Tracer() if trace else None
    async with maybe_obs_server(obs_port, host=obs_bind, tracer=tracer0):
        await _pvsim_stream_run(file, amqp_url, exchange, realtime, seed,
                                duration_s, start, trace, metrics_path,
                                run_report_path, tracer0)


async def _pvsim_stream_run(file, amqp_url, exchange, realtime, seed,
                            duration_s, start, trace, metrics_path,
                            run_report_path, tracer) -> None:
    reg = obs_metrics.get_registry()
    sink = None
    if metrics_path:
        sink = obs_metrics.make_sink(metrics_path)
        reg.add_sink(sink)
    # per-record latency accounting only when some observability output
    # was asked for: with none of --trace/--metrics/--run-report the
    # funnel keeps the RAW queue and the hot path pays exactly one
    # `if tracer:` truth test per record (the ≤1% disabled-cost gate,
    # tests/test_trace.py)
    stream = (_StreamStats(reg)
              if (trace or metrics_path or run_report_path) else None)
    queue: asyncio.Queue = asyncio.Queue()
    front = (_JoinFront(queue, stream, tracer)
             if (stream is not None or tracer) else queue)
    # 60 s lookahead: under --no-realtime the local pv loop free-runs; the
    # funnel blocks it from racing ahead of the broker-paced meter stream,
    # which would otherwise evict every pv-only record before its meter
    # value arrives (join starvation; see runtime/funnel.py)
    funnel = SynchronizingFunnel(
        Data, front, max_lookahead=_dt.timedelta(seconds=60)
    )
    counter: dict = {}
    watchdog = asyncio.create_task(_no_meter_watchdog(counter, amqp_url))
    tasks = [
        asyncio.create_task(read_pv_values(funnel, realtime, seed,
                                           duration_s, start, tracer)),
        asyncio.create_task(read_transport(funnel, amqp_url, exchange,
                                           counter, stream, tracer)),
        asyncio.create_task(write_file(file, queue, stream, tracer)),
    ]
    try:
        done, _ = await asyncio.wait(tasks,
                                     return_when=asyncio.FIRST_COMPLETED)
        for t in done:
            t.result()
        await queue.join()
    except asyncio.CancelledError:
        raise  # orderly shutdown: no crash artifact
    except BaseException:
        if tracer:
            # the flight recorder's whole point: the last 30 s of
            # timeline survive an unhandled exception
            with contextlib.suppress(Exception):
                tracer.dump_flight(trace + ".crash.json")
        raise
    finally:
        for t in tasks:
            t.cancel()
        watchdog.cancel()
        if len(funnel) > 0:
            logger.warning(
                "%d undelivered meter_values have been lost", len(funnel)
            )
        if tracer:
            with contextlib.suppress(Exception):
                tracer.export(trace, process_name="pvsim")
        if run_report_path:
            try:
                from tmhpvsim_tpu.obs.report import RunReport

                rep = RunReport("pvsim.stream")
                rep.attach_metrics(reg)
                rep.write(run_report_path)
            except Exception as e:  # must not mask the run's own outcome
                logger.warning("run report write failed: %s", e)
        if sink is not None:
            reg.flush(event="end")
            reg.remove_sink(sink)
            with contextlib.suppress(Exception):
                sink.close()


class _PreemptStop(Exception):
    """Internal signal: stop the run loop at a block boundary with the
    latest snapshot durable — raised by the checkpoint hooks on a
    SIGTERM under ``--preempt-grace`` or a chaos ``signal.preempt``."""

    def __init__(self, block: int):
        super().__init__(f"preempted after block {block}")
        self.block = block


class _PreemptWatch:
    """Preemption-notice watcher for a checkpointed run.

    With ``grace_s > 0`` a SIGTERM handler is armed that only sets a
    flag — the run finishes the in-flight block, takes/drains one final
    snapshot and exits cleanly inside the grace window (the supervisor
    SIGKILLs past it, runtime/supervise.py).  The chaos chokepoint
    ``signal.preempt`` (runtime/faults.py) is consulted either way, so
    the preemption path is testable in-process without real signals.
    """

    def __init__(self, grace_s: float):
        import signal as _signal

        self.grace_s = grace_s
        self._flag = False
        self._old = None
        if grace_s and grace_s > 0:
            try:
                self._old = _signal.signal(_signal.SIGTERM, self._on_term)
            except ValueError:  # pragma: no cover - non-main thread
                self._old = None

    def _on_term(self, signum, frame):
        self._flag = True
        logger.warning("SIGTERM received; finishing the current block "
                       "and snapshotting (grace %.1f s)", self.grace_s)

    def should_stop(self) -> bool:
        if self._flag:
            return True
        from tmhpvsim_tpu.runtime import faults

        if faults.ACTIVE is not None:
            try:
                faults.fire("signal.preempt")
            except faults.FaultInjected:
                return True
        return False

    def restore(self) -> None:
        import signal as _signal

        if self._old is not None:
            _signal.signal(_signal.SIGTERM, self._old)
            self._old = None


def _ckpt_teardown(writer, watch, suppress: bool = False) -> None:
    """Restore the SIGTERM handler and drain/close the async writer.
    ``suppress`` is the error-unwind path: a close failure must not mask
    the exception already in flight."""
    if watch is not None:
        watch.restore()
    if writer is None:
        return
    if not suppress:
        writer.close()
        return
    try:
        writer.close(timeout=10.0)
    except Exception as e:
        logger.warning("async checkpoint writer close failed during "
                       "error unwind: %s", e)


def _resume_source(checkpoint, ckpt_global, sim):
    """(path, chain_slice) to resume from, or (None, None).

    Preference order: this process's own checkpoint (the per-host file
    on a pod slice, the plain path otherwise; shards of a previous
    multi-host run also count — ``checkpoint.resumable``), then the
    unsuffixed global checkpoint of a run saved under a DIFFERENT
    process layout, loaded elastically as this host's chain slice."""
    from tmhpvsim_tpu.engine import checkpoint as ckpt

    if ckpt.resumable(checkpoint):
        return checkpoint, None
    if ckpt_global != checkpoint and ckpt.resumable(ckpt_global):
        return ckpt_global, sim.resume_chain_slice()
    return None, None


def pvsim_jax(file, duration_s: int, n_chains: int, seed: int,
              start: Optional[str] = None, chain: int = 0,
              sharded: bool = False,
              mesh_scenario: int = 0,
              coordinator: Optional[str] = None,
              num_processes: Optional[int] = None,
              process_id: Optional[int] = None,
              checkpoint: Optional[str] = None,
              block_s: Optional[int] = None,
              realtime: bool = False,
              site_grid=None,
              fleet=None,
              profile_dir: Optional[str] = None,
              output: str = "trace",
              prng_impl: str = "threefry2x32",
              block_impl: str = "auto",
              tune: str = "off",
              telemetry: str = "off",
              telemetry_strict: bool = False,
              analytics: str = "off",
              metrics_path: Optional[str] = None,
              run_report_path: Optional[str] = None,
              trace: Optional[str] = None,
              compile_cache: Optional[str] = None,
              blocks_per_dispatch: int = 0,
              compute_dtype: str = "auto",
              kernel_impl: str = "auto",
              rng_batch: str = "auto",
              geom_stride: int = 0,
              output_overlap: str = "auto",
              checkpoint_keep: int = 3,
              checkpoint_async: str = "off",
              preempt_grace_s: float = 0.0,
              obs_port: Optional[int] = None,
              obs_bind: str = "127.0.0.1",
              pod_obs: str = "off",
              pod_straggler_factor: float = 2.0,
              phase_obs: str = "off") -> None:
    """The JAX backend: blockwise device simulation straight to CSV.

    With ``checkpoint``, state is saved after every block and an existing
    checkpoint resumes the run (appending to the CSV) — restart-safe long
    simulations, which the reference cannot do at all (SURVEY.md §5).
    Saves rotate through ``checkpoint_keep`` integrity-verified
    generations (engine/checkpoint.py manifest); ``checkpoint_async='on'``
    moves serialization to a background writer; ``preempt_grace_s > 0``
    arms a SIGTERM handler that finishes the current block, drains one
    final snapshot and exits cleanly — the preemption-notice shape.
    Resume is topology-elastic: a checkpoint saved under a different
    device count/mesh (or as per-host shards) is reassembled/resliced on
    load; only identity keys (seed, chains, models, rng_stream) refuse.

    With ``realtime``, rows are released on the 1 Hz wall-clock grid (the
    reference's default streaming mode) while the device simulates blocks
    ahead — tail the CSV and it ticks once a second.

    With ``output='reduce'``, no per-second trace is materialised at all:
    per-chain running statistics accumulate on device and FILE gets one
    summary row per chain plus an ``ensemble`` row — the output mode that
    scales to the 100k-1M chain configs (BASELINE #4/#5).

    With ``output='ensemble'``, FILE gets the reference's row-per-second
    CSV shape but each row is the fleet MEAN over all chains (the "grid
    operator" stream): only (block_s,) vectors reach the host, so this
    also scales to 100k+ chains — one psum per block on a sharded mesh.
    Checkpoint/resume and --realtime pacing work exactly as in trace mode.

    Observability (obs/): ``metrics_path`` streams per-block metric
    snapshots to a JSONL (or ``.prom``) sink; ``run_report_path`` writes
    the schema-versioned RunReport after the run.  Both ride a fresh
    per-run registry so the artifacts never mix runs.  On a pod slice
    every process gathers its metrics (a collective) and process 0
    embeds them in its report.

    ``telemetry`` ('off'|'light'|'full', reduce mode only) folds
    in-graph NaN/Inf counters + moments into the block step
    (obs/telemetry.py) and runs the drift sentinel per block;
    ``telemetry_strict`` escalates sentinel WARNs to DriftError.  The
    sentinel's verdict lands in the report's ``telemetry`` section.

    ``analytics`` ('off'|'risk'|'full', reduce mode only) folds the
    fleet-risk accumulator into the same block step (obs/analytics.py:
    residual quantile sketch, exceedance curve, loss-of-load
    probability, ramp extrema; 'full' adds per-regime conditional
    means).  The merged fleet summary lands in the report's ``fleet``
    section (schema v5).

    ``compute_dtype`` ('auto'|'f32'|'bf16') and ``kernel_impl``
    ('auto'|'exact'|'table') select the mixed-precision compute path and
    the tabulated transcendental kernels (models/tables.py); bf16
    auto-escalates ``telemetry='off'`` to 'light' so the drift sentinel
    watches the run.  ``rng_batch`` ('auto'|'scan'|'block') hoists the
    scan body's per-minute noise draws into whole-block counter-mode
    tensors generated before the scan (bit-identical by construction —
    same ``fold_in`` keying); ``geom_stride`` (0=auto|1|30|60)
    evaluates solar geometry every s seconds and lerps the trig-free
    quantities back to 1 Hz (error bound published in
    models/solar.py:STRIDE_MAX_ABS_ERR).  ``output_overlap``
    ('auto'|'off') double-buffers
    the trace/ensemble host gather against the next block's dispatch;
    checkpointed runs force it off (the checkpoint writer gates on
    ``state_block``, which pipelining breaks by design).

    ``trace`` records host-side per-block instants into the streaming
    tracer's ring (obs/trace.py) and exports Chrome-trace JSON there on
    exit; the pid is the real os.getpid(), so a jax.profiler device
    trace from ``profile_dir`` merges next to it in Perfetto as a
    separate process row.  A crashing run dumps the last-30-s flight
    slice to ``trace + '.crash.json'`` first.

    ``obs_port`` (``--obs-port``) binds the live ops plane (obs/live.py)
    on a daemon thread — ``/metrics`` serves this run's registry (cost
    gauges update at block granularity mid-run), ``/readyz`` flips to
    200 once the first block has completed (AOT warm-up + compile done),
    ``/flight`` snapshots the tracer ring.  Unset, no socket is bound
    and no per-message stamps are added anywhere.
    """
    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.obs.profiler import read_manifest
    from tmhpvsim_tpu.obs.report import RunReport

    registry = obs_metrics.MetricsRegistry()
    if metrics_path:
        registry.add_sink(obs_metrics.make_sink(metrics_path))
    tracer = Tracer() if trace else None
    obs_server = None
    ready_state = {"warm": False, "blocks": 0}
    if obs_port is not None:
        from tmhpvsim_tpu.obs import trace as obs_trace
        from tmhpvsim_tpu.obs.live import ObsServer

        obs_trace.enable_propagation(True)
        obs_server = ObsServer(
            obs_port, obs_bind, registry=registry, tracer=tracer,
            ready=lambda: (ready_state["warm"], dict(ready_state)))
        obs_server.start_threaded()  # bind errors surface here, pre-run
    # the Simulation binds the process-default registry at construction,
    # so the per-run registry must be installed around the whole run
    with obs_metrics.use_registry(registry):
        try:
            sim = _pvsim_jax_run(
                file, duration_s, n_chains, seed, start=start,
                chain=chain, sharded=sharded,
                mesh_scenario=mesh_scenario,
                coordinator=coordinator,
                num_processes=num_processes,
                process_id=process_id,
                checkpoint=checkpoint,
                block_s=block_s, realtime=realtime, site_grid=site_grid,
                fleet=fleet,
                profile_dir=profile_dir, output=output,
                prng_impl=prng_impl, block_impl=block_impl, tune=tune,
                telemetry=telemetry, telemetry_strict=telemetry_strict,
                analytics=analytics,
                trace=trace, tracer=tracer, compile_cache=compile_cache,
                blocks_per_dispatch=blocks_per_dispatch,
                compute_dtype=compute_dtype, kernel_impl=kernel_impl,
                rng_batch=rng_batch, geom_stride=geom_stride,
                output_overlap=output_overlap,
                checkpoint_keep=checkpoint_keep,
                checkpoint_async=checkpoint_async,
                preempt_grace_s=preempt_grace_s,
                pod_obs=pod_obs,
                pod_straggler_factor=pod_straggler_factor,
                phase_obs=phase_obs,
                ready_state=ready_state,
            )
        except (Exception, KeyboardInterrupt):
            if tracer:
                with contextlib.suppress(Exception):
                    tracer.dump_flight(trace + ".crash.json")
            raise
        finally:
            if obs_server is not None:
                obs_server.close_threaded()
            registry.flush(event="end")
            registry.close()
            if tracer:
                with contextlib.suppress(Exception):
                    tracer.export(trace, process_name="pvsim")
    if not run_report_path:
        return
    import jax

    summary = sim.timer.summary()
    rep = RunReport("pvsim", config=sim.config, plan=sim.plan)
    rep.set_timing(summary)
    rep.attach_metrics(registry)
    from tmhpvsim_tpu.engine import compilecache

    ex = compilecache.executor_doc(registry)
    if ex is not None:  # adds cache_dir to the counter section
        rep.executor = ex
    rep.headline = {"site_seconds_per_s": summary["site_seconds_per_s"]}
    if summary.get("site_seconds_per_s"):
        from tmhpvsim_tpu.obs import cost as obs_cost

        plan = sim.plan
        rep.cost = obs_cost.cost_doc(
            site_s_per_s=summary["site_seconds_per_s"],
            block_impl=plan.block_impl,
            compute_dtype=getattr(plan, "compute_dtype", None),
            kernel_impl=getattr(plan, "kernel_impl", None),
            rng_batch=getattr(plan, "rng_batch", None),
            geom_stride=getattr(plan, "geom_stride", None),
            device_kind=jax.devices()[0].device_kind,
        )
    if getattr(sim, "sentinel", None) is not None:
        rep.telemetry = sim.sentinel.report()
    if hasattr(sim, "fleet_summary"):
        fleet_sec = sim.fleet_summary()
        if fleet_sec is not None:
            rep.fleet = fleet_sec
    if hasattr(sim, "precision_doc"):
        prec = sim.precision_doc()
        if prec is not None:
            rep.precision = prec
    if profile_dir:
        rep.profile = read_manifest(profile_dir)
    if getattr(sim, "mesh", None) is not None:
        from tmhpvsim_tpu.parallel.distributed import mesh_doc

        rep.mesh = mesh_doc(sim.mesh, n_chains=sim.config.n_chains)
    if getattr(sim, "_pod", None) is not None:
        rep.pod = sim._pod.doc()
    if jax.process_count() > 1:
        from tmhpvsim_tpu.parallel.distributed import gather_metrics

        procs = gather_metrics(registry.snapshot())  # collective
        if jax.process_index() != 0:
            return  # process 0 writes the (combined) report
        rep.processes = procs
    rep.write(run_report_path)


def _pvsim_jax_run(file, duration_s: int, n_chains: int, seed: int,
                   start: Optional[str] = None, chain: int = 0,
                   sharded: bool = False,
                   mesh_scenario: int = 0,
                   coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   checkpoint: Optional[str] = None,
                   block_s: Optional[int] = None,
                   realtime: bool = False,
                   site_grid=None,
                   fleet=None,
                   profile_dir: Optional[str] = None,
                   output: str = "trace",
                   prng_impl: str = "threefry2x32",
                   block_impl: str = "auto",
                   tune: str = "off",
                   telemetry: str = "off",
                   telemetry_strict: bool = False,
                   analytics: str = "off",
                   trace: Optional[str] = None,
                   tracer: Optional[Tracer] = None,
                   compile_cache: Optional[str] = None,
                   blocks_per_dispatch: int = 0,
                   compute_dtype: str = "auto",
                   kernel_impl: str = "auto",
                   rng_batch: str = "auto",
                   geom_stride: int = 0,
                   output_overlap: str = "auto",
                   checkpoint_keep: int = 3,
                   checkpoint_async: str = "off",
                   preempt_grace_s: float = 0.0,
                   pod_obs: str = "off",
                   pod_straggler_factor: float = 2.0,
                   phase_obs: str = "off",
                   ready_state: Optional[dict] = None):
    """The run body behind :func:`pvsim_jax`; returns the Simulation so
    the wrapper can assemble the run report from its config/plan/timer.

    ``ready_state`` is the wrapper's live-ops readiness dict: the first
    completed block flips ``warm`` (AOT warm-up + compile done) and
    every block bumps ``blocks`` — what ``/readyz`` reports mid-run."""
    import contextlib
    import os
    from zoneinfo import ZoneInfo

    from tmhpvsim_tpu.config import SimConfig
    from tmhpvsim_tpu.engine import Simulation, checkpoint as ckpt
    from tmhpvsim_tpu.engine.simulation import write_csv
    from tmhpvsim_tpu.obs import cost as obs_cost
    from tmhpvsim_tpu.obs import metrics as obs_metrics
    from tmhpvsim_tpu.obs.profiler import BlockTimer, device_trace
    from tmhpvsim_tpu.parallel.distributed import initialize

    reg = obs_metrics.get_registry()

    # Supervised-restart provenance (runtime/supervise.py stamps the
    # attempt number into the child's env): the run report's resilience
    # section can then tell a warm restart from a cold start.
    restart = os.environ.get("TMHPVSIM_SUPERVISED_RESTART")
    if restart and restart.isdigit() and int(restart) > 0:
        reg.gauge("resilience.supervised_restarts").set(int(restart))

    # Join a pod slice when launched under a multi-host runtime; no-op
    # single-process.  Explicit --coordinator/--num-processes/--process-id
    # flags override the env-var equivalents.  Must run before any
    # jax.devices() query.  Guarded: stale coordinator env vars in a
    # shell must degrade to a single-host run, not kill the simulation
    # (the failure class that cost round 1 its benchmark) — but an
    # EXPLICIT coordinator that fails must fail loudly, not silently run
    # a duplicate single-host simulation.
    try:
        initialize(coordinator=coordinator, num_processes=num_processes,
                   process_id=process_id)
    except Exception as e:
        if coordinator:
            raise
        logger.warning("jax.distributed init failed (%s); continuing "
                       "single-host", e)

    import jax

    ckpt_global = checkpoint  # the unsuffixed path (elastic resume)
    if jax.process_count() > 1:
        # Pod slice: every host writes (and checkpoints) only the chains
        # its own devices hold — per-host files, no DCN gathers.  Resume
        # under a DIFFERENT layout is elastic: _resume_source falls back
        # to the global checkpoint resliced to this host's chains, and a
        # later single-process run reassembles the .hostN shards
        # (checkpoint.load_elastic).
        suffix = f".host{jax.process_index()}"
        file = f"{file}{suffix}"
        if checkpoint:
            checkpoint = f"{checkpoint}{suffix}"
        logger.info("multi-host run (%d processes): output %s",
                    jax.process_count(), file)

    # Persistent compilation cache + AOT warm-up: must be configured
    # BEFORE the Simulation is constructed (the warm-up hook runs in
    # __init__).  None resolves env var/default dir; 'off' disables.
    from tmhpvsim_tpu.engine import compilecache

    compilecache.configure(compile_cache)

    if start is None:
        start = _dt.datetime.now().replace(microsecond=0).isoformat(" ")
    if block_s is None:
        block_s = min(8640, max(60, (duration_s // 60) * 60))
    if checkpoint and output_overlap != "off":
        # the checkpoint writer gates saves on ``sim.state_block ==
        # block_index + 1``; the double buffer dispatches block N+1
        # before block N is consumed, so every gate would miss — force
        # the serial loop rather than silently skipping every save
        output_overlap = "off"
        logger.info("checkpointing disables output_overlap")
    cfg = SimConfig(
        start=start,
        duration_s=duration_s,
        n_chains=n_chains,
        seed=seed,
        block_s=block_s,
        site_grid=site_grid,
        fleet=fleet,
        output=output,
        prng_impl=prng_impl,
        block_impl=block_impl,
        tune=tune,
        telemetry=telemetry,
        telemetry_strict=telemetry_strict,
        analytics=analytics,
        trace=trace,
        blocks_per_dispatch=blocks_per_dispatch,
        compute_dtype=compute_dtype,
        kernel_impl=kernel_impl,
        rng_batch=rng_batch,
        geom_stride=geom_stride,
        output_overlap=output_overlap,
        checkpoint_keep=checkpoint_keep,
        checkpoint_async=checkpoint_async,
        preempt_grace_s=preempt_grace_s,
        mesh_scenario=mesh_scenario,
        pod_obs=pod_obs,
        pod_straggler_factor=pod_straggler_factor,
        phase_obs=phase_obs,
    )
    if sharded:
        from tmhpvsim_tpu.parallel import ShardedSimulation

        sim = ShardedSimulation(cfg)
    else:
        sim = Simulation(cfg)
    cfg = sim.config  # site_grid may have adjusted n_chains
    plan = sim.plan
    logger.info(
        "plan [%s]: block_impl=%s scan_unroll=%d stats_fusion=%s "
        "slab_chains=%d blocks_per_dispatch=%d compute_dtype=%s "
        "kernel_impl=%s rng_batch=%s geom_stride=%d", plan.source,
        plan.block_impl, plan.scan_unroll, plan.stats_fusion,
        plan.slab_chains, plan.blocks_per_dispatch,
        getattr(plan, "compute_dtype", "f32"),
        getattr(plan, "kernel_impl", "exact"),
        getattr(plan, "rng_batch", "scan"),
        getattr(plan, "geom_stride", 1),
    )

    # Live-ops cost attribution (obs/cost.py): per-block device.cost.*
    # gauges published BEFORE the block flush so /metrics and JSONL
    # sinks show achieved FLOPs / roofline fraction at block
    # granularity mid-run.  Also flips the wrapper's readiness state:
    # the first completed block means AOT warm-up + compile are done.
    device_kind = jax.devices()[0].device_kind

    def _block_obs(timer, bi):
        if ready_state is not None:
            ready_state["warm"] = True
            ready_state["blocks"] = bi + 1
        rate = timer.rate()
        if not rate:
            return
        obs_cost.publish_gauges(reg, obs_cost.cost_doc(
            site_s_per_s=rate,
            block_impl=plan.block_impl,
            compute_dtype=getattr(plan, "compute_dtype", None),
            kernel_impl=getattr(plan, "kernel_impl", None),
            rng_batch=getattr(plan, "rng_batch", None),
            geom_stride=getattr(plan, "geom_stride", None),
            device_kind=device_kind))

    if checkpoint and plan.slab_chains < cfg.n_chains:
        # a slabbed run has no single resumable state pytree; checkpointed
        # runs execute unslabbed (the plan's other knobs still apply)
        sim.allow_slabs = False
        logger.info("checkpointing disables chain slabbing "
                    "(slab_chains=%d ignored)", plan.slab_chains)

    writer, preempt = None, None
    if checkpoint:
        preempt = _PreemptWatch(cfg.preempt_grace_s)
        if cfg.checkpoint_async == "on":
            writer = ckpt.AsyncCheckpointWriter(
                checkpoint, config=cfg, keep=cfg.checkpoint_keep)

    def _save_ckpt(tree, next_block):
        lay = sim.checkpoint_layout()
        if writer is not None:
            writer.submit(tree, next_block, layout=lay)
        else:
            ckpt.save(checkpoint, tree, next_block, cfg,
                      keep=cfg.checkpoint_keep, layout=lay)

    def _preempt_report(stop: _PreemptStop) -> None:
        reg.counter("checkpoint.preempt_snapshots_total").inc()
        print(
            f"pvsim: preempted — state through block {stop.block + 1}"
            f"/{sim.n_blocks} checkpointed to {checkpoint}; rerun the "
            f"same command to finish"
        )

    if output == "reduce":
        if realtime:
            raise ValueError("reduce mode has no per-second rows to pace; "
                             "drop --realtime")
        # Reduce-mode checkpointing: the on-device accumulator rides the
        # saved pytree next to the chain state, so the long configs
        # (BASELINE #4/#5: 10-year, 1M-chain) are restart-safe.  The CSV
        # is written once at the end, so unlike trace mode there is no
        # partial-rows window to truncate on resume.
        state, acc, start_block = None, None, 0
        src, rsl = (_resume_source(checkpoint, ckpt_global, sim)
                    if checkpoint else (None, None))
        if src:
            tree, start_block = ckpt.load_elastic(src, cfg,
                                                  chain_slice=rsl)
            state, acc = tree["state"], tree["acc"]
            logger.info("resuming reduce run from %s at block %d",
                        src, start_block)
            reg.counter("resilience.resumed_total").inc()
            reg.gauge("resilience.resumed_block").set(start_block)
        dtrace = device_trace(profile_dir) if profile_dir else \
            contextlib.nullcontext()
        # under a slabbing plan each on_block tick covers one slab-sized
        # block (engine/slab.py), not the full chain batch
        n_tick = (plan.slab_chains if sim.allow_slabs
                  and plan.slab_chains < cfg.n_chains else cfg.n_chains)
        timer = BlockTimer(n_tick, cfg.block_s)

        def on_block(bi, state, acc):
            timer.tick()
            if tracer:
                tracer.instant("block", "engine", block=bi)
            _block_obs(timer, bi)
            reg.flush(event="block")
            # state_block gate: under a fused multi-block dispatch
            # (blocks_per_dispatch > 1) sim.state only advances at
            # megablock boundaries — saving mid-megablock would pair
            # block bi's accumulator with a later state.  state_block ==
            # bi + 1 holds exactly when `state` IS the state after block
            # bi (always true per-block).
            if checkpoint and sim.state_block == bi + 1:
                # host_local_tree: on a pod slice each host saves only its
                # chain slice (the per-host file this process owns)
                _save_ckpt(
                    sim.host_local_tree({"state": state, "acc": acc}),
                    bi + 1)
            if preempt is not None and preempt.should_stop():
                raise _PreemptStop(bi)

        try:
            with dtrace:
                reduced = sim.run_reduced(state=state, acc=acc,
                                          start_block=start_block,
                                          on_block=on_block)
        except _PreemptStop as stop:
            # the writer drain below IS the final snapshot (sync mode
            # already saved synchronously in on_block)
            _ckpt_teardown(writer, preempt)
            _preempt_report(stop)
            return sim
        except BaseException:
            _ckpt_teardown(writer, preempt, suppress=True)
            raise
        _ckpt_teardown(writer, preempt)
        ensemble = sim.ensemble_stats()
        sl, local = sim.local_reduced_view(reduced)
        _write_reduced_csv(file, local, ensemble, chain_start=sl.start or 0)
        stats = timer.summary()
        print(
            f"pvsim[reduce]: {cfg.n_chains} chains x {cfg.duration_s} s at "
            f"{stats['site_seconds_per_s']:.3g} site-s/s; fleet pv_max "
            f"{ensemble['pv_max']:.1f} W"
            + (f"; profile in {profile_dir}" if profile_dir else "")
        )
        return sim

    if output == "ensemble" and chain != 0:
        raise ValueError("ensemble mode writes the fleet mean; --chain "
                         "does not apply (drop it or use trace mode)")

    # Trace mode on a pod slice: --chain is a GLOBAL chain id, but each
    # host's BlockResults carry only its local slice (ShardedSimulation
    # run_blocks).  The owning host writes the trace; the others still
    # iterate every block (the per-block ensemble psum is a collective all
    # hosts must join) but skip the CSV.
    write_trace = True
    if output == "trace" and sharded and jax.process_count() > 1:
        from tmhpvsim_tpu.parallel.distributed import local_chain_slice

        if not (0 <= chain < cfg.n_chains):
            raise ValueError(
                f"--chain {chain} out of range for {cfg.n_chains} chains"
            )
        sl = local_chain_slice(cfg.n_chains, sim.mesh)
        write_trace = sl.start <= chain < sl.stop
        if write_trace:
            chain -= sl.start
        else:
            logger.info(
                "global chain %d lives on another host (this host owns "
                "%d-%d); participating without writing a trace",
                chain, sl.start, sl.stop - 1,
            )

    state, start_block = None, 0
    src, rsl = (_resume_source(checkpoint, ckpt_global, sim)
                if checkpoint else (None, None))
    if src:
        state, start_block = ckpt.load_elastic(src, cfg, chain_slice=rsl)
        logger.info("resuming from %s at block %d", src, start_block)
        reg.counter("resilience.resumed_total").inc()
        reg.gauge("resilience.resumed_block").set(start_block)
        # Exactly-once CSV rows: a crash can land between "rows of block b
        # written" and "checkpoint for b saved", leaving extra rows from
        # block start_block in the file.  Truncate back to the checkpoint —
        # and refuse to resume against a missing/short CSV (appending there
        # would silently fabricate a gap-ridden headerless file).  Gated on
        # write_trace: a pod-slice host that does not own --chain
        # checkpoints state but never writes a CSV, so there is nothing to
        # reconcile there.
        if write_trace:
            expect = 1 + min(cfg.duration_s, start_block * cfg.block_s)
            got = _truncate_csv(file, expect)
            if got < expect:
                raise RuntimeError(
                    f"checkpoint {checkpoint} expects {expect} existing "
                    f"lines in {file} but found {got}; restore the CSV "
                    f"that belongs to this checkpoint or delete the "
                    f"checkpoint to restart"
                )

    timer = BlockTimer(cfg.n_chains, cfg.block_s)
    runner = sim.run_ensemble if output == "ensemble" else sim.run_blocks

    def blocks():
        for bi, blk in enumerate(
            runner(state=state, start_block=start_block),
            start=start_block,
        ):
            timer.tick()
            if tracer:
                tracer.instant("block", "engine", block=bi)
            _block_obs(timer, bi)
            reg.flush(event="block")
            if realtime:
                yield from _paced(blk)
            else:
                yield blk
            # control returns here after write_csv wrote (and line-flushed)
            # this block's rows — only then is the checkpoint advanced, so
            # a crash can duplicate work but never lose rows.  The
            # state_block gate (see reduce mode above) keeps saves on
            # megablock boundaries under blocks_per_dispatch > 1, where
            # sim.state is ahead of mid-megablock bi values.
            if checkpoint and sim.state_block == bi + 1:
                _save_ckpt(sim.host_local_tree(sim.state), bi + 1)
            if preempt is not None and preempt.should_stop():
                raise _PreemptStop(bi)

    tzname = (cfg.site_grid.timezone if cfg.site_grid is not None
              else cfg.site.timezone)
    dtrace = device_trace(profile_dir) if profile_dir else \
        contextlib.nullcontext()
    try:
        with dtrace:
            if write_trace:
                write_csv(file, blocks(), chain=chain, tz=ZoneInfo(tzname),
                          append=start_block > 0)
            else:  # non-owning host: run every block (collectives), no CSV
                for _ in blocks():
                    pass
    except _PreemptStop as stop:
        # rows through stop.block are on disk (the save fires only after
        # write_csv consumed the block); draining the writer makes the
        # matching snapshot durable before the clean exit
        _ckpt_teardown(writer, preempt)
        _preempt_report(stop)
        return sim
    except BaseException:
        _ckpt_teardown(writer, preempt, suppress=True)
        raise
    _ckpt_teardown(writer, preempt)
    stats = timer.summary()
    # steady_block_s is None when only the compile-inclusive first block
    # was timed (single-block runs) — say so rather than fake a steady rate
    if stats["steady_block_s"] is not None:
        block_txt = f"steady block {stats['steady_block_s']:.3f} s"
    elif stats["compile_s"] is not None:
        block_txt = f"single block {stats['compile_s']:.3f} s incl. compile"
    else:
        block_txt = "no blocks timed"  # fully-resumed run: 0 blocks left
    print(
        f"pvsim: {cfg.n_chains} chains x {cfg.duration_s} s simulated at "
        f"{stats['site_seconds_per_s']:.3g} site-s/s "
        f"({block_txt}"
        + (f"; profile in {profile_dir}" if profile_dir else "") + ")"
    )
    return sim


def _write_reduced_csv(path: str, reduced: dict, ensemble: dict,
                       chain_start: int = 0) -> None:
    """Per-chain summary rows + one fleet 'ensemble' row.

    Columns come from ``REDUCE_STATS`` (engine/simulation.py); *_sum
    columns are watt-seconds over the simulated duration (divide by 3600
    for Wh).  ``chain_start`` offsets the chain ids so a pod-slice host
    writing its local slice labels rows with GLOBAL chain numbers; the
    ensemble row is the fleet-wide psum view and is identical across
    hosts' files.
    """
    import csv

    from tmhpvsim_tpu.engine.simulation import REDUCE_STATS

    keys = list(REDUCE_STATS)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["chain"] + keys)
        n = len(reduced[keys[0]])
        for i in range(n):
            w.writerow([chain_start + i] + [reduced[k][i] for k in keys])
        w.writerow(["ensemble"] + [ensemble[k] for k in keys])


def _paced(blk, rate: float = 1.0):
    """Re-emit a BlockResult as single-row blocks on the wall-clock grid —
    the jax backend's analogue of fixedclock realtime pacing."""
    import dataclasses
    import time

    t0 = time.monotonic()
    for i in range(len(blk.epoch)):
        behind = (time.monotonic() - t0) - i / rate
        if behind < 0:
            time.sleep(-behind)
        yield dataclasses.replace(
            blk,
            offset=blk.offset + i,
            epoch=blk.epoch[i : i + 1],
            meter=blk.meter[:, i : i + 1],
            pv=blk.pv[:, i : i + 1],
            residual=blk.residual[:, i : i + 1],
        )


def _truncate_csv(path: str, keep_lines: int) -> int:
    """Truncate ``path`` to its first ``keep_lines`` lines; returns the
    number of lines actually present afterwards (0 for a missing file)."""
    import os

    if not os.path.exists(path):
        return 0
    with open(path, "r+") as f:
        n = 0
        for _ in range(keep_lines):
            if not f.readline():
                return n  # fewer lines than the checkpoint expects
            n += 1
        f.truncate(f.tell())
        return n
