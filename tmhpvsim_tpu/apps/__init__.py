"""Application orchestrators behind the metersim / pvsim entrypoints."""
