"""Timestamp-join primitive for merging partial records from N streams.

Reference semantics (utils.py:47-67): a dict cache keyed by timestamp;
each ``put(time, field=value)`` merges into the cached record; when every
field is present the completed record is moved to the output queue.  It is
the entire stream-join machinery between the AMQP meter feed and the local
PV feed (pvsim.py:86-101).

Deviations from the reference (both documented in SURVEY.md §5):

* leak fix — the reference's cache grows without bound if one stream
  stalls.  ``max_pending`` (default 10 000) evicts the oldest incomplete
  records with a warning instead of exhausting memory; ``None`` restores
  the unbounded behaviour.
* backpressure — under ``--no-realtime`` the local PV stream can free-run
  thousands of simulated seconds ahead of the broker-paced meter stream,
  so every pv-only record ages past ``max_pending`` and is evicted before
  its meter value arrives: the leak fix alone would turn the leak into
  join *starvation*.  ``max_lookahead`` bounds how far any producer may
  run ahead of the slowest *other* stream: ``put`` first delivers its
  value (so the join can always progress — this ordering makes the wait
  deadlock-free), then blocks until the other streams are within the
  window.  A stream that has never delivered imposes no *time* constraint
  (there is no clock to be ahead of), but ``max_initial_pending`` caps how
  many records a producer may pile up before it — otherwise a slow-to-
  start peer (first-block XLA compile, broker reconnect) would watch its
  joinable records get evicted before its first value.  All stall
  decisions key on the BINDING
  stream — the one pinning min(newest): if it makes no progress for
  ``stall_timeout_s`` the funnel logs and suspends that producer's
  backpressure until it advances again — so a meter feed that dies
  degrades to the old free-run-and-evict behaviour instead of hanging the
  app, while a merely slow one keeps blocking the producer (the binding
  stream's progress, and only its progress, resets the stall clock —
  other live streams must not mask a dead one).
"""

from __future__ import annotations

import asyncio
import heapq
import logging
import math
import time as _time
from typing import NamedTuple, Optional, Type

logger = logging.getLogger(__name__)

#: eviction warnings are rate-limited to one per this many seconds
#: (mirrors clock.PacingMonitor): a --no-realtime free-run can evict
#: thousands of records per second, and per-event visibility lives in
#: the ``funnel.evicted_total`` counter, not the log
EVICT_WARN_EVERY_S = 10.0

#: sentinel: "use the default initial-pending cap, clamped under
#: max_pending" — distinct from an explicit value (validated) or None
#: (disabled)
_DEFAULT_INITIAL = object()


class SynchronizingFunnel:
    """Merge per-timestamp partial records; emit completed ones in put-order.

    ``record_type`` is a NamedTuple class whose fields are the joined
    streams (the reference's ``Data = namedtuple(..., ['meter', 'pv'])``,
    pvsim.py:19); missing fields are NaN until every stream delivered.
    """

    def __init__(self, record_type: Type[NamedTuple],
                 queue: "asyncio.Queue",
                 max_pending: Optional[int] = 10_000,
                 max_lookahead=None,
                 stall_timeout_s: float = 10.0,
                 max_initial_pending: Optional[int] = _DEFAULT_INITIAL):
        self._type = record_type
        self._blank = record_type(*([math.nan] * len(record_type._fields)))
        self._queue = queue
        self._cache: dict = {}
        #: min-heap of times ever inserted into the cache, for O(log n)
        #: oldest-first eviction; entries go stale when a record completes
        #: (lazy deletion: _evict_if_needed skips keys no longer cached)
        self._age_heap: list = []
        self.max_pending = max_pending
        #: max `time` distance a producer may run ahead of the slowest other
        #: stream (same type as `time - time`: timedelta for datetimes,
        #: number for numeric grids); None disables backpressure
        self.max_lookahead = max_lookahead
        self.stall_timeout_s = stall_timeout_s
        #: before the other streams deliver their FIRST value there is no
        #: clock to be ahead of, but an unbounded free-run would fill the
        #: cache past max_pending and evict the very records the late
        #: stream will want to join (e.g. pv racing ahead while a jax
        #: metersim compiles its first block).  Cap the pending records a
        #: producer may accumulate in that window; stall/suspend semantics
        #: apply as usual if the other stream never shows up.
        if max_initial_pending is _DEFAULT_INITIAL:
            # default: clamp under max_pending so eviction can never keep
            # the cache below the cap and silently disable it
            max_initial_pending = 3600 if max_pending is None \
                else min(3600, max(1, max_pending // 2))
        elif (max_pending is not None and max_initial_pending is not None
                and max_initial_pending >= max_pending):
            raise ValueError(
                f"max_initial_pending ({max_initial_pending}) must be < "
                f"max_pending ({max_pending}): eviction would keep the "
                "cache below the cap and silently disable it"
            )
        self.max_initial_pending = max_initial_pending
        self.n_evicted = 0
        self._last_evict_warn: Optional[float] = None
        self._evict_warns_suppressed = 0
        # instrumentation (obs/metrics.py): binds the process-default
        # registry at construction, like the engine layers — construct
        # funnels inside a use_registry scope to isolate a run
        from tmhpvsim_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.get_registry()
        self._g_pending = reg.gauge("funnel.pending_depth")
        self._g_high_water = reg.gauge("funnel.pending_high_water")
        self._c_evicted = reg.counter("funnel.evicted_total")
        self._c_stalls = reg.counter("funnel.stall_suspends_total")
        self._c_bp_waits = reg.counter("funnel.backpressure_waits_total")
        self._high_water = 0
        self._newest: dict = {}       # field -> newest time delivered
        self._advanced = asyncio.Event()
        #: per-producer suspension: {other-streams key -> the BINDING
        #: (minimum) floor at the moment that producer's backpressure gave
        #: up; cleared when it advances}
        self._suspended: dict = {}

    def __len__(self):
        return len(self._cache)

    async def put(self, time, **fields) -> None:
        from tmhpvsim_tpu.runtime import faults

        if faults.ACTIVE is not None:
            await faults.afire("funnel.stall")
        rec = self._cache.get(time, self._blank)._replace(**fields)
        if any(isinstance(v, float) and math.isnan(v) for v in rec):
            if time not in self._cache:
                heapq.heappush(self._age_heap, time)
            self._cache[time] = rec
            await self._evict_if_needed()
            depth = len(self._cache)
            self._g_pending.set(depth)
            if depth > self._high_water:
                self._high_water = depth
                self._g_high_water.set(depth)
        else:
            self._cache.pop(time, None)
            # drain stale heap entries now, not only at eviction time: in a
            # healthy join the cache stays small and eviction never runs,
            # but every record passed through the heap — without this the
            # heap gains one entry per joined timestamp forever.  Times
            # arrive near-monotonically, so completed records surface at
            # the heap top and this stays amortised O(log n)...
            while self._age_heap and self._age_heap[0] not in self._cache:
                heapq.heappop(self._age_heap)
            # ...and a compaction backstop bounds the pathological case
            # (completions in anti-chronological order keep stale entries
            # buried mid-heap)
            if len(self._age_heap) > 2 * len(self._cache) + 64:
                self._age_heap = list(self._cache)
                heapq.heapify(self._age_heap)
            self._g_pending.set(len(self._cache))
            await self._queue.put((time, rec))
        for f in fields:
            cur = self._newest.get(f)
            if cur is None or time > cur:
                self._newest[f] = time
        self._advanced.set()  # wake producers waiting on this stream
        await self._backpressure(time, fields)

    def _floors(self, others) -> Optional[tuple]:
        """Newest times of the ``others`` streams, or None while any of
        them has not delivered yet."""
        vals = tuple(self._newest.get(f) for f in others)
        return None if None in vals else vals

    async def _backpressure(self, time, fields) -> None:
        if self.max_lookahead is None:
            return
        others = tuple(f for f in self._type._fields if f not in fields)
        if not others:
            return  # complete record: nothing to wait for
        loop = asyncio.get_event_loop()
        deadline = loop.time() + self.stall_timeout_s
        first = self._floors(others)
        last_binding = None if first is None else min(first)
        waited = False
        while True:
            floors = self._floors(others)
            # All decisions key on the BINDING floor (the slowest other
            # stream): with 3+ streams, a live stream's progress must
            # neither reset the stall clock for a dead one pinning the
            # minimum, nor re-arm a suspension taken against it.  A None
            # binding means some stream has not delivered at all yet —
            # no clock to be ahead of, but the pending-cache cap applies.
            binding = None if floors is None else min(floors)
            if others in self._suspended:
                susp = self._suspended[others]
                advanced = (binding is not None
                            and (susp is None or binding > susp))
                if not advanced:
                    return  # still stalled: stay in free-run mode
                del self._suspended[others]  # it advanced: re-arm
            if binding is None:
                if self.max_initial_pending is None or \
                        len(self._cache) <= self.max_initial_pending:
                    return
            elif time <= binding + self.max_lookahead:
                return
            if binding is not None and \
                    (last_binding is None or binding > last_binding):
                # progress of the binding stream resets the stall clock:
                # only a genuinely *silent* constraint trips the timeout, a
                # slow-but-live one keeps this producer blocked (that is
                # the backpressure)
                last_binding = binding
                deadline = loop.time() + self.stall_timeout_s
            remaining = deadline - loop.time()
            if remaining <= 0:
                self._suspended[others] = binding
                self._c_stalls.inc()
                logger.warning(
                    "funnel backpressure: stream(s) %s made no progress "
                    "for %.0f s (newest: %s); resuming free-run until they "
                    "advance", others, self.stall_timeout_s, self._newest,
                )
                return
            if not waited:
                waited = True
                self._c_bp_waits.inc()  # one count per put that blocked
            self._advanced.clear()
            try:
                await asyncio.wait_for(self._advanced.wait(), remaining)
            except asyncio.TimeoutError:
                pass  # loop once more; the deadline branch handles it

    async def _evict_if_needed(self):
        if self.max_pending is None or len(self._cache) <= self.max_pending:
            return
        # pop stale heap entries (records that completed and left the cache)
        # until the top is a live pending time — amortised O(log n) vs the
        # O(n) min(self._cache) scan this replaces.  Guarded: every cached
        # time is heappushed in put(), so the heap always holds a superset
        # of the cached times and this loop cannot run dry.  If that
        # invariant is ever broken by future code (a direct _cache insert,
        # an exception between the two writes), the cheap length check
        # below catches it BEST-EFFORT (stale heap entries can mask
        # missing ones) and rebuilds the heap from the cache — restoring
        # oldest-first eviction in the detected cases and, above all,
        # guaranteeing heappop never raises IndexError mid-funnel.  An
        # exact set-comparison guard would detect every break but cost
        # O(n) per eviction, which is the scan this heap exists to avoid.
        while True:
            if len(self._age_heap) < len(self._cache):
                self._age_heap = list(self._cache)
                heapq.heapify(self._age_heap)
            oldest = heapq.heappop(self._age_heap)
            if oldest in self._cache:
                break
        self._cache.pop(oldest)
        self.n_evicted += 1
        self._c_evicted.inc()
        self._warn_eviction()

    def _warn_eviction(self, now: Optional[float] = None) -> bool:
        """Rate-limited eviction WARN (at most one per
        :data:`EVICT_WARN_EVERY_S`, with a suppressed-count suffix —
        the PacingMonitor pattern).  ``now`` is injectable for tests;
        returns True when it warned."""
        if now is None:
            now = _time.monotonic()
        if self._last_evict_warn is not None and \
                now - self._last_evict_warn < EVICT_WARN_EVERY_S:
            self._evict_warns_suppressed += 1
            return False
        suffix = ""
        if self._evict_warns_suppressed:
            suffix = (f" ({self._evict_warns_suppressed} similar warnings "
                      f"suppressed in the last {EVICT_WARN_EVERY_S:.0f} s)")
        self._last_evict_warn = now
        self._evict_warns_suppressed = 0
        logger.warning(
            "funnel cache exceeded %d pending records; evicted %d "
            "incomplete (one input stream is stalled?)%s",
            self.max_pending, self.n_evicted, suffix,
        )
        return True
