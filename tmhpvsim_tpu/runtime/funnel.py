"""Timestamp-join primitive for merging partial records from N streams.

Reference semantics (utils.py:47-67): a dict cache keyed by timestamp;
each ``put(time, field=value)`` merges into the cached record; when every
field is present the completed record is moved to the output queue.  It is
the entire stream-join machinery between the AMQP meter feed and the local
PV feed (pvsim.py:86-101).

Deviations from the reference (both documented in SURVEY.md §5):

* leak fix — the reference's cache grows without bound if one stream
  stalls.  ``max_pending`` (default 10 000) evicts the oldest incomplete
  records with a warning instead of exhausting memory; ``None`` restores
  the unbounded behaviour.
* backpressure — under ``--no-realtime`` the local PV stream can free-run
  thousands of simulated seconds ahead of the broker-paced meter stream,
  so every pv-only record ages past ``max_pending`` and is evicted before
  its meter value arrives: the leak fix alone would turn the leak into
  join *starvation*.  ``max_lookahead`` bounds how far any producer may
  run ahead of the slowest *other* stream: ``put`` first delivers its
  value (so the join can always progress — this ordering makes the wait
  deadlock-free), then blocks until the other streams are within the
  window.  A stream that has never delivered imposes no constraint (there
  is no clock to be ahead of).  All stall decisions key on the BINDING
  stream — the one pinning min(newest): if it makes no progress for
  ``stall_timeout_s`` the funnel logs and suspends that producer's
  backpressure until it advances again — so a meter feed that dies
  degrades to the old free-run-and-evict behaviour instead of hanging the
  app, while a merely slow one keeps blocking the producer (the binding
  stream's progress, and only its progress, resets the stall clock —
  other live streams must not mask a dead one).
"""

from __future__ import annotations

import asyncio
import logging
import math
from typing import NamedTuple, Optional, Type

logger = logging.getLogger(__name__)


class SynchronizingFunnel:
    """Merge per-timestamp partial records; emit completed ones in put-order.

    ``record_type`` is a NamedTuple class whose fields are the joined
    streams (the reference's ``Data = namedtuple(..., ['meter', 'pv'])``,
    pvsim.py:19); missing fields are NaN until every stream delivered.
    """

    def __init__(self, record_type: Type[NamedTuple],
                 queue: "asyncio.Queue",
                 max_pending: Optional[int] = 10_000,
                 max_lookahead=None,
                 stall_timeout_s: float = 10.0):
        self._type = record_type
        self._blank = record_type(*([math.nan] * len(record_type._fields)))
        self._queue = queue
        self._cache: dict = {}
        self.max_pending = max_pending
        #: max `time` distance a producer may run ahead of the slowest other
        #: stream (same type as `time - time`: timedelta for datetimes,
        #: number for numeric grids); None disables backpressure
        self.max_lookahead = max_lookahead
        self.stall_timeout_s = stall_timeout_s
        self.n_evicted = 0
        self._newest: dict = {}       # field -> newest time delivered
        self._advanced = asyncio.Event()
        #: per-producer suspension: {other-streams key -> the BINDING
        #: (minimum) floor at the moment that producer's backpressure gave
        #: up; cleared when it advances}
        self._suspended: dict = {}

    def __len__(self):
        return len(self._cache)

    async def put(self, time, **fields) -> None:
        rec = self._cache.get(time, self._blank)._replace(**fields)
        if any(isinstance(v, float) and math.isnan(v) for v in rec):
            self._cache[time] = rec
            await self._evict_if_needed()
        else:
            self._cache.pop(time, None)
            await self._queue.put((time, rec))
        for f in fields:
            cur = self._newest.get(f)
            if cur is None or time > cur:
                self._newest[f] = time
        self._advanced.set()  # wake producers waiting on this stream
        await self._backpressure(time, fields)

    def _floors(self, others) -> Optional[tuple]:
        """Newest times of the ``others`` streams, or None while any of
        them has not delivered yet."""
        vals = tuple(self._newest.get(f) for f in others)
        return None if None in vals else vals

    async def _backpressure(self, time, fields) -> None:
        if self.max_lookahead is None:
            return
        others = tuple(f for f in self._type._fields if f not in fields)
        if not others:
            return  # complete record: nothing to wait for
        loop = asyncio.get_event_loop()
        deadline = loop.time() + self.stall_timeout_s
        first = self._floors(others)
        last_binding = None if first is None else min(first)
        while True:
            floors = self._floors(others)
            if floors is None:
                # a stream that never delivered has no clock to be ahead
                # of; backpressure starts at its first value
                return
            # All decisions key on the BINDING floor (the slowest other
            # stream): with 3+ streams, a live stream's progress must
            # neither reset the stall clock for a dead one pinning the
            # minimum, nor re-arm a suspension taken against it.
            binding = min(floors)
            if others in self._suspended:
                if binding <= self._suspended[others]:
                    return  # still stalled: stay in free-run mode
                del self._suspended[others]  # it advanced: re-arm
            if time <= binding + self.max_lookahead:
                return
            if last_binding is None or binding > last_binding:
                # progress of the binding stream resets the stall clock:
                # only a genuinely *silent* constraint trips the timeout, a
                # slow-but-live one keeps this producer blocked (that is
                # the backpressure)
                last_binding = binding
                deadline = loop.time() + self.stall_timeout_s
            remaining = deadline - loop.time()
            if remaining <= 0:
                self._suspended[others] = binding
                logger.warning(
                    "funnel backpressure: stream(s) %s made no progress "
                    "for %.0f s (newest: %s); resuming free-run until they "
                    "advance", others, self.stall_timeout_s, self._newest,
                )
                return
            self._advanced.clear()
            try:
                await asyncio.wait_for(self._advanced.wait(), remaining)
            except asyncio.TimeoutError:
                pass  # loop once more; the deadline branch handles it

    async def _evict_if_needed(self):
        if self.max_pending is None or len(self._cache) <= self.max_pending:
            return
        oldest = min(self._cache)
        self._cache.pop(oldest)
        self.n_evicted += 1
        if self.n_evicted == 1 or self.n_evicted % 1000 == 0:
            logger.warning(
                "funnel cache exceeded %d pending records; evicted %d "
                "incomplete (one input stream is stalled?)",
                self.max_pending, self.n_evicted,
            )
