"""Timestamp-join primitive for merging partial records from N streams.

Reference semantics (utils.py:47-67): a dict cache keyed by timestamp;
each ``put(time, field=value)`` merges into the cached record; when every
field is present the completed record is moved to the output queue.  It is
the entire stream-join machinery between the AMQP meter feed and the local
PV feed (pvsim.py:86-101).

Deviation (leak fix): the reference's cache grows without bound if one
stream stalls (SURVEY.md §5).  ``max_pending`` (default 10 000) evicts the
oldest incomplete records with a warning instead of exhausting memory;
``None`` restores the unbounded behaviour.
"""

from __future__ import annotations

import asyncio
import logging
import math
from typing import NamedTuple, Optional, Type

logger = logging.getLogger(__name__)


class SynchronizingFunnel:
    """Merge per-timestamp partial records; emit completed ones in put-order.

    ``record_type`` is a NamedTuple class whose fields are the joined
    streams (the reference's ``Data = namedtuple(..., ['meter', 'pv'])``,
    pvsim.py:19); missing fields are NaN until every stream delivered.
    """

    def __init__(self, record_type: Type[NamedTuple],
                 queue: "asyncio.Queue",
                 max_pending: Optional[int] = 10_000):
        self._type = record_type
        self._blank = record_type(*([math.nan] * len(record_type._fields)))
        self._queue = queue
        self._cache: dict = {}
        self.max_pending = max_pending
        self.n_evicted = 0

    def __len__(self):
        return len(self._cache)

    async def put(self, time, **fields) -> None:
        rec = self._cache.get(time, self._blank)._replace(**fields)
        if any(isinstance(v, float) and math.isnan(v) for v in rec):
            self._cache[time] = rec
            await self._evict_if_needed()
        else:
            self._cache.pop(time, None)
            await self._queue.put((time, rec))

    async def _evict_if_needed(self):
        if self.max_pending is None or len(self._cache) <= self.max_pending:
            return
        oldest = min(self._cache)
        self._cache.pop(oldest)
        self.n_evicted += 1
        if self.n_evicted == 1 or self.n_evicted % 1000 == 0:
            logger.warning(
                "funnel cache exceeded %d pending records; evicted %d "
                "incomplete (one input stream is stalled?)",
                self.max_pending, self.n_evicted,
            )
