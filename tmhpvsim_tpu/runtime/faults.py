"""Deterministic fault injection for chaos testing.

A :class:`FaultPlan` is a seeded schedule of faults that instrumented
chokepoints consult at runtime.  The default path is a module-global
``None`` check (``if faults.ACTIVE is not None:``) so production code
pays one attribute load per chokepoint and nothing else — with chaos
disabled the broker hot paths and the compiled HLO are untouched by
construction (every chokepoint is host-side Python).

Spec grammar (``--chaos SPEC`` / ``TMHPVSIM_CHAOS``)::

    SPEC    := RULE (';' RULE)*
    RULE    := POINT '=' ACTION [':' ARG] '@' TRIGGER ['x' COUNT]
    POINT   := broker.connect | broker.publish | broker.deliver
             | tcp.partition | funnel.stall | serve.dispatch
             | checkpoint.write | checkpoint.corrupt
             | checkpoint.committed | signal.preempt | block.stall
    ACTION  := raise | delay:SECONDS | drop | dup | kill
             | truncate:BYTES
    TRIGGER := 'n'K        fire on the K-th call (1-based); 'x'C extends
                           the window to calls K .. K+C-1
             | 'every'K    fire on every K-th call; 'x'C caps total fires
             | 'p'P        fire with probability P per call (seeded,
                           per-rule RNG); 'x'C caps total fires

Examples::

    broker.publish=raise@n3          third publish raises
    broker.deliver=dup@p0.05x2       ~5% of deliveries duplicated, max 2
    funnel.stall=delay:0.5@every100  every 100th put stalls 0.5 s
    checkpoint.committed=kill@n2     SIGKILL right after the 2nd commit
    checkpoint.corrupt=truncate:120@n2   tear the 2nd checkpoint write
    signal.preempt=raise@n3          preemption notice on the 3rd block
    block.stall=delay:0.5@every2     every 2nd block dispatch stalls
                                     0.5 s (deterministic straggler)

Actions: ``raise`` raises :class:`FaultInjected` (a ``ConnectionError``,
so transport retry paths treat it as transient), ``delay:S`` sleeps,
``drop``/``dup`` are returned to the chokepoint which suppresses or
repeats the unit of work, ``kill`` delivers SIGKILL to this process
— the deterministic mid-run crash used by the recovery tests — and
``truncate:BYTES`` truncates the file the chokepoint passed as
``path=...`` context down to BYTES bytes (the deterministic torn write
the checkpoint fallback tests recover from; only ``checkpoint.corrupt``
supplies a path today).

Determinism: probability triggers draw from ``random.Random`` seeded
from ``(plan seed, rule index)``, so firing is independent of rule
ordering and of any other RNG in the process.
"""

from __future__ import annotations

import contextlib
import logging
import os
import random
import signal
import threading
import time

logger = logging.getLogger(__name__)

ENV_SPEC = "TMHPVSIM_CHAOS"
ENV_SEED = "TMHPVSIM_CHAOS_SEED"

#: the instrumented chokepoints (``broker.*`` fires in all three
#: transports; ``tcp.partition`` only in the tcp subscriber loop)
POINTS = (
    "broker.connect",
    "broker.publish",
    "broker.deliver",
    "tcp.partition",
    "funnel.stall",
    "serve.dispatch",
    "checkpoint.write",
    "checkpoint.corrupt",
    "checkpoint.committed",
    "signal.preempt",
    # host-side stall before a block dispatch (engine/simulation.py
    # per-block loops — NEVER in-graph), the deterministic straggler
    # for pod-skew tests: --chaos 'block.stall=delay:0.5@every2'
    "block.stall",
)

ACTIONS = ("raise", "delay", "drop", "dup", "kill", "truncate")


class FaultInjected(ConnectionError):
    """Raised at a chokepoint when the active plan schedules ``raise``."""


class _Rule:
    __slots__ = ("point", "action", "arg", "trigger", "k", "prob",
                 "count", "calls", "fired", "rng", "spec")

    def __init__(self, point, action, arg, trigger, k, prob, count,
                 rng, spec):
        self.point = point
        self.action = action
        self.arg = arg
        self.trigger = trigger  # "n" | "every" | "p"
        self.k = k
        self.prob = prob
        self.count = count      # None = unlimited (every/p only)
        self.calls = 0
        self.fired = 0
        self.rng = rng
        self.spec = spec

    def should_fire(self) -> bool:
        """Decide for the current call (``calls`` already incremented)."""
        if self.trigger == "n":
            width = 1 if self.count is None else self.count
            return self.k <= self.calls < self.k + width
        if self.count is not None and self.fired >= self.count:
            return False
        if self.trigger == "every":
            return self.calls % self.k == 0
        return self.rng.random() < self.prob


def _parse_rule(raw: str, idx: int, seed: int) -> _Rule:
    text = raw.strip()
    try:
        point, rhs = text.split("=", 1)
        action_part, trigger_part = rhs.split("@", 1)
    except ValueError:
        raise ValueError(
            f"chaos rule {text!r}: expected POINT=ACTION@TRIGGER") from None
    point = point.strip()
    if point not in POINTS:
        raise ValueError(
            f"chaos rule {text!r}: unknown point {point!r} "
            f"(known: {', '.join(POINTS)})")

    action, _, argtext = action_part.strip().partition(":")
    if action not in ACTIONS:
        raise ValueError(
            f"chaos rule {text!r}: unknown action {action!r} "
            f"(known: {', '.join(ACTIONS)})")
    arg = 0.0
    if action == "delay":
        try:
            arg = float(argtext)
        except ValueError:
            raise ValueError(
                f"chaos rule {text!r}: delay needs seconds "
                f"(delay:0.5)") from None
    elif action == "truncate":
        try:
            arg = int(argtext)
        except ValueError:
            raise ValueError(
                f"chaos rule {text!r}: truncate needs a byte offset "
                f"(truncate:128)") from None
        if arg < 0:
            raise ValueError(
                f"chaos rule {text!r}: truncate offset must be >= 0")
    elif argtext:
        raise ValueError(
            f"chaos rule {text!r}: action {action!r} takes no argument")

    trig = trigger_part.strip()
    count = None
    if "x" in trig:
        trig, _, counttext = trig.rpartition("x")
        try:
            count = int(counttext)
        except ValueError:
            raise ValueError(
                f"chaos rule {text!r}: count {counttext!r} not an "
                f"integer") from None
        if count < 1:
            raise ValueError(f"chaos rule {text!r}: count must be >= 1")
    k, prob, kind = 0, 0.0, None
    try:
        if trig.startswith("every"):
            kind, k = "every", int(trig[len("every"):])
        elif trig.startswith("n"):
            kind, k = "n", int(trig[1:])
        elif trig.startswith("p"):
            kind, prob = "p", float(trig[1:])
        else:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"chaos rule {text!r}: bad trigger {trig!r} (nK, everyK, "
            f"or pFLOAT)") from None
    if kind in ("n", "every") and k < 1:
        raise ValueError(f"chaos rule {text!r}: trigger index must be >= 1")
    if kind == "p" and not 0.0 <= prob <= 1.0:
        raise ValueError(f"chaos rule {text!r}: probability outside [0, 1]")

    rng = random.Random(1_000_003 * int(seed) + idx)
    return _Rule(point, action, arg, kind, k, prob, count, rng, text)


class FaultPlan:
    """A parsed, seeded fault schedule.  Thread-safe (one lock guards the
    per-rule call counters: chokepoints fire from the event loop, worker
    threads, and the checkpoint writer alike)."""

    def __init__(self, rules, *, seed: int = 0, spec: str = ""):
        self.rules = list(rules)
        self.seed = seed
        self.spec = spec
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        rules = [
            _parse_rule(raw, idx, seed)
            for idx, raw in enumerate(
                s for s in (spec or "").split(";") if s.strip())
        ]
        if not rules:
            raise ValueError("chaos spec is empty")
        return cls(rules, seed=seed, spec=spec)

    def decide(self, point: str):
        """The rule firing at ``point`` for this call, or None.  Every
        rule on the point counts the call; the first firing rule wins."""
        hit = None
        with self._lock:
            for rule in self.rules:
                if rule.point != point:
                    continue
                rule.calls += 1
                if hit is None and rule.should_fire():
                    rule.fired += 1
                    hit = rule
        return hit

    def describe(self) -> str:
        return "; ".join(r.spec for r in self.rules)


#: the process-wide active plan — chokepoints do nothing unless set
ACTIVE: FaultPlan | None = None


def activate(plan: FaultPlan) -> None:
    global ACTIVE
    ACTIVE = plan
    logger.info("chaos plan active (seed %d): %s", plan.seed,
                plan.describe())


def deactivate() -> None:
    global ACTIVE
    ACTIVE = None


@contextlib.contextmanager
def active(plan: FaultPlan):
    """Scope a plan to a ``with`` block (tests)."""
    activate(plan)
    try:
        yield plan
    finally:
        deactivate()


def install_from_env(environ=os.environ) -> FaultPlan | None:
    """Activate a plan from ``TMHPVSIM_CHAOS`` if set (subprocesses of a
    supervised run inherit chaos through the environment)."""
    spec = environ.get(ENV_SPEC)
    if not spec:
        return None
    plan = FaultPlan.parse(spec, seed=int(environ.get(ENV_SEED, "0") or 0))
    activate(plan)
    return plan


def _record(point: str, action: str) -> None:
    from tmhpvsim_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.get_registry()
    reg.counter("faults.injected_total").inc()
    reg.counter(f"faults.injected.{point}").inc()
    logger.warning("chaos: injecting %s at %s", action, point)


def _apply(rule: _Rule, point: str, ctx: dict):
    """Common tail of fire/afire once a rule fired: record, then either
    kill/raise/truncate here or hand drop/dup/delay back to the
    caller.  ``ctx`` is the keyword context the chokepoint passed to
    :func:`fire` (``truncate`` needs a ``path``)."""
    _record(point, rule.action)
    if rule.action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60)  # pragma: no cover - signal delivery race
    if rule.action == "raise":
        raise FaultInjected(f"injected fault at {point} ({rule.spec})")
    if rule.action == "truncate":
        path = ctx.get("path")
        if path is None:
            logger.warning("chaos: %s fired at %s but the chokepoint "
                           "passed no path= context; nothing truncated",
                           rule.spec, point)
        else:
            try:
                size = os.path.getsize(path)
                os.truncate(path, min(int(rule.arg), size))
                logger.warning("chaos: truncated %s from %d to %d bytes",
                               path, size, min(int(rule.arg), size))
            except OSError as e:
                logger.warning("chaos: truncate of %s failed: %s",
                               path, e)
    return rule.action


def fire(point: str, **ctx):
    """Synchronous chokepoint: returns ``"drop"``/``"dup"``/``None``;
    ``delay`` sleeps inline; ``raise`` raises :class:`FaultInjected`;
    ``kill`` does not return; ``truncate`` tears the ``path=`` keyword
    file in place.  Callers guard with ``if faults.ACTIVE is not None:``
    so the default path stays a single attribute test."""
    plan = ACTIVE
    if plan is None:
        return None
    rule = plan.decide(point)
    if rule is None:
        return None
    action = _apply(rule, point, ctx)
    if action == "delay":
        time.sleep(rule.arg)
        return None
    return action


async def afire(point: str, **ctx):
    """Async chokepoint twin of :func:`fire` (``delay`` awaits instead
    of blocking the loop)."""
    plan = ACTIVE
    if plan is None:
        return None
    rule = plan.decide(point)
    if rule is None:
        return None
    action = _apply(rule, point, ctx)
    if action == "delay":
        import asyncio

        await asyncio.sleep(rule.arg)
        return None
    return action
