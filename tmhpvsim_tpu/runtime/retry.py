"""Deprecated shim — the retry loop moved to ``runtime/resilience.py``.

``asyncretry``/``forever``/``propagate`` live on unchanged (the
decorator is now expressed over :class:`ResiliencePolicy`); import them
from :mod:`tmhpvsim_tpu.runtime.resilience` (or the ``runtime`` package
root).  This module re-exports them for one release and will then be
removed, like the old ``engine/profiling.py`` shim before it.
"""

from __future__ import annotations

import warnings

from tmhpvsim_tpu.runtime.resilience import (  # noqa: F401
    asyncretry,
    forever,
    propagate,
)

warnings.warn(
    "tmhpvsim_tpu.runtime.retry is deprecated; import asyncretry/forever"
    " from tmhpvsim_tpu.runtime.resilience (or tmhpvsim_tpu.runtime)",
    DeprecationWarning, stacklevel=2,
)
