"""Retry decorator for long-lived connection coroutines.

Reference semantics (utils.py:69-161): wrap an async function so failures
re-invoke it after ``delay`` seconds, up to ``attempts`` times (the
``forever`` sentinel means unbounded — how both AMQP coroutines ride out
broker outages, metersim.py:13, pvsim.py:43).  ``asyncio.CancelledError``
is always fatal (shutdown must win over resilience).  On exhaustion the
``fallback`` policy applies: re-raise (default), a constant, or a callable
receiving the exception.

The reference's latent bugs in the callable-fallback path
(``isinstance(Exception)`` with one argument, undefined ``loop``,
utils.py:134,138) are simply not reproduced.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
import logging

logger = logging.getLogger(__name__)

#: Sentinel for unbounded retries (the reference's ``forever = ...``,
#: utils.py:71).
forever = ...


class _Propagate:
    pass


propagate = _Propagate()


def asyncretry(func=None, *, attempts=3, delay: float = 0.0,
               fallback=propagate):
    """Decorator: retry an async callable on exception.

    Usable bare (``@asyncretry``) or parameterised
    (``@asyncretry(delay=5, attempts=forever)``).
    """
    if func is None:
        return functools.partial(
            asyncretry, attempts=attempts, delay=delay, fallback=fallback
        )

    qualname = func.__qualname__

    @functools.wraps(func)
    async def wrapper(*args, **kwargs):
        from tmhpvsim_tpu.obs import metrics as obs_metrics

        n = 0
        while True:
            try:
                return await func(*args, **kwargs)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                n += 1
                # per-qualname counters against the CURRENT process
                # default registry (looked up per event, not cached at
                # decoration: apps swap registries per run)
                obs_metrics.get_registry().counter(
                    f"retry.attempts.{qualname}").inc()
                if attempts is not forever and n >= attempts:
                    obs_metrics.get_registry().counter(
                        f"retry.exhausted.{qualname}").inc()
                    # WARN on exhaustion whichever way it resolves: the
                    # fallback path would otherwise swallow the failure
                    # silently (only per-attempt INFO lines exist)
                    logger.warning(
                        "%s exhausted %d attempt(s); final failure %s: "
                        "%s (%s)", qualname, n, type(exc).__name__, exc,
                        "re-raising" if fallback is propagate
                        else "applying fallback",
                    )
                    if fallback is propagate:
                        raise
                    if callable(fallback):
                        res = fallback(exc)
                        if inspect.isawaitable(res):
                            res = await res
                        return res
                    return fallback
                logger.info(
                    "%s failed (%s: %s); retrying in %.1f s (attempt %s)",
                    func.__qualname__, type(exc).__name__, exc, delay,
                    f"{n}/{attempts}" if attempts is not forever else n,
                )
                await asyncio.sleep(delay)

    return wrapper
