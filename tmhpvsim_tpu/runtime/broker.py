"""Message transport: AMQP fanout semantics behind one small interface.

The reference's cross-process boundary is a RabbitMQ fanout exchange
(SURVEY.md §2.4): the producer declares exchange ``name`` and publishes
JSON floats with the measurement time in the AMQP ``timestamp`` property
(metersim.py:25-42); each consumer binds an exclusive queue so every
consumer sees every message (pvsim.py:56-67).

Two interchangeable transports implement those semantics:

* :class:`AmqpTransport` — real AMQP via ``aio_pika`` when a broker URL is
  given AND aio_pika is importable (it is not part of this image's baked
  dependency set, so the import is gated);
* :class:`LocalTransport` — an in-process fanout broker with identical
  pub/sub behaviour, selected by ``amqp_url='local://...'``.  It is the
  test transport (SURVEY.md §4: "fake the transport with an in-memory
  broker") and the default when no broker is reachable, letting the two
  apps run in one process out of the box.

Wire format matches the reference: UTF-8 JSON float body + POSIX-seconds
timestamp.  Metadata (``meta``) rides OUT-OF-BAND — the LocalTransport
Message field, AMQP headers, the tcp wire's optional ``"m"`` key — so
the body stays a plain JSON float and reference consumers parsing it are
unaffected by metersim's seq/publish-time stamping (obs/trace.py).
"""

from __future__ import annotations

import asyncio
import dataclasses
import datetime as _dt
import json
import logging
import weakref
from typing import AsyncIterator, Dict, List, Optional, Tuple

from tmhpvsim_tpu.obs import trace as obs_trace
from tmhpvsim_tpu.runtime import faults

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class Message:
    body: bytes
    timestamp: Optional[_dt.datetime]
    #: additive metadata (e.g. metersim's {"seq": n, "pub_us": mono-µs});
    #: None on the reference wire shape
    meta: Optional[dict] = None


def encode(value: float, time: _dt.datetime,
           meta: Optional[dict] = None) -> Message:
    """JSON float body + timestamp property (metersim.py:38-42)."""
    return Message(body=json.dumps(value).encode(), timestamp=time,
                   meta=meta)


def decode(msg: Message) -> Tuple[_dt.datetime, float]:
    """(measurement time, value) — the consumer's view (pvsim.py:66-70)."""
    return msg.timestamp, json.loads(msg.body.decode())


def decode_with_meta(msg: Message) -> Tuple[_dt.datetime, float,
                                            Optional[dict]]:
    """(time, value, meta) — the instrumented consumer's view."""
    return msg.timestamp, json.loads(msg.body.decode()), msg.meta


#: endpoints (url, exchange) each REGISTRY has seen a connect for —
#: distinguishes first connects from reconnects without leaking state
#: across per-run registries (keyed weakly on the registry object)
_seen_endpoints: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _count_connect(url: str, exchange: str) -> None:
    """connect/reconnect counters on the current default registry.

    "Reconnect" means: this registry already saw a connect to this
    (url, exchange).  That is exact for the deployed one-app-per-process
    shape; when BOTH apps share a process and registry (the e2e tests),
    the consumer's first connect after the producer's counts as one —
    an accepted approximation, not worth plumbing a role through every
    transport."""
    from tmhpvsim_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.get_registry()
    reg.counter("broker.connects_total").inc()
    try:
        seen = _seen_endpoints.setdefault(reg, set())
    except TypeError:
        return  # non-weakrefable registry stand-in: skip reconnect split
    if (url, exchange) in seen:
        reg.counter("broker.reconnects_total").inc()
    else:
        seen.add((url, exchange))


def _pub_counter():
    from tmhpvsim_tpu.obs import metrics as obs_metrics

    return obs_metrics.get_registry().counter("broker.published_total")


def _deliver_counter():
    from tmhpvsim_tpu.obs import metrics as obs_metrics

    return obs_metrics.get_registry().counter("broker.delivered_total")


# ---------------------------------------------------------------------------
# in-process fanout broker
# ---------------------------------------------------------------------------


#: per-consumer buffered messages before oldest-first drop — the same
#: leak-fix policy as the tcp broker (tcpbroker.MAX_SUBSCRIBER_BACKLOG):
#: a consumer that stopped iterating its subscription must not grow its
#: queue without bound for the life of the process
MAX_CONSUMER_BACKLOG = 10_000


class _LocalBroker:
    """Named fanout exchanges; one bounded per-consumer queue each
    (oldest-first drop past :data:`MAX_CONSUMER_BACKLOG`, counted in
    ``broker.dropped_total``)."""

    _registry: Dict[str, "_LocalBroker"] = {}

    def __init__(self):
        self._exchanges: Dict[str, List[asyncio.Queue]] = {}

    @classmethod
    def get(cls, url: str) -> "_LocalBroker":
        """One broker instance per local:// URL (vhost-like isolation)."""
        return cls._registry.setdefault(url, cls())

    def publish(self, exchange: str, msg: Message) -> None:
        depth = 0
        dropped = 0
        for q in self._exchanges.get(exchange, []):
            while q.qsize() >= MAX_CONSUMER_BACKLOG:
                q.get_nowait()
                dropped += 1
            q.put_nowait(msg)
            depth = max(depth, q.qsize())
        if dropped:
            from tmhpvsim_tpu.obs import metrics as obs_metrics

            obs_metrics.get_registry().counter(
                "broker.dropped_total").inc(dropped)
            logger.warning(
                "local broker: consumer backlog exceeded %d on %r; "
                "dropped %d oldest messages (consumer stalled?)",
                MAX_CONSUMER_BACKLOG, exchange, dropped)
        if depth:
            from tmhpvsim_tpu.obs import metrics as obs_metrics

            obs_metrics.get_registry().gauge(
                "broker.queue_depth").set(depth)

    def bind(self, exchange: str) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._exchanges.setdefault(exchange, []).append(q)
        return q

    def unbind(self, exchange: str, q: asyncio.Queue) -> None:
        try:
            self._exchanges.get(exchange, []).remove(q)
        except ValueError:
            pass


class LocalTransport:
    """Fanout pub/sub inside one process (``local://`` URLs)."""

    def __init__(self, url: str, exchange: str):
        self._url = url
        self._broker = _LocalBroker.get(url)
        self._exchange = exchange

    async def __aenter__(self):
        if faults.ACTIVE is not None:
            await faults.afire("broker.connect")
        _count_connect(self._url, self._exchange)
        return self

    async def __aexit__(self, *exc):
        return False

    async def publish(self, value: float, time: _dt.datetime,
                      meta: Optional[dict] = None) -> None:
        # no-op unless trace propagation is on (--obs-port / tests); a
        # dup-faulted resend keeps the SAME ids — it is the same message
        meta = obs_trace.stamp(meta)
        act = None
        if faults.ACTIVE is not None:
            act = await faults.afire("broker.publish")
            if act == "drop":
                return
        self._broker.publish(self._exchange, encode(value, time, meta))
        _pub_counter().inc()
        if act == "dup":
            self._broker.publish(self._exchange, encode(value, time, meta))
            _pub_counter().inc()

    async def subscribe(self, with_meta: bool = False) -> AsyncIterator:
        """Yields ``(time, value)``; ``with_meta=True`` yields
        ``(time, value, meta-or-None)`` (3-tuples are opt-in so the
        reference-shaped consumers keep their 2-tuple unpacking)."""
        q = self._broker.bind(self._exchange)
        deliver = _deliver_counter()
        try:
            while True:
                msg = await q.get()
                if faults.ACTIVE is not None:
                    act = await faults.afire("broker.deliver")
                    if act == "drop":
                        continue
                    if act == "dup":
                        deliver.inc()
                        yield (decode_with_meta(msg) if with_meta
                               else decode(msg))
                deliver.inc()
                yield decode_with_meta(msg) if with_meta else decode(msg)
        finally:
            self._broker.unbind(self._exchange, q)


# ---------------------------------------------------------------------------
# real AMQP (gated on aio_pika availability)
# ---------------------------------------------------------------------------


class AmqpTransport:
    """Fanout pub/sub over a RabbitMQ broker via aio_pika.

    Mirrors the reference topology: durable-less named fanout exchange,
    publisher without confirms but with ``asyncio.shield`` around publish
    (metersim.py:43-45); consumer with an exclusive auto-delete queue and
    prefetch 1 (pvsim.py:53-63).
    """

    def __init__(self, url: str, exchange: str):
        try:
            import aio_pika  # noqa: F401
        except ImportError as err:
            raise RuntimeError(
                "aio_pika is not installed; use a local:// URL for the "
                "in-process transport or install aio-pika for AMQP"
            ) from err
        self._aio_pika = __import__("aio_pika")
        self._url = url
        self._exchange_name = exchange
        self._conn = None

    async def __aenter__(self):
        ap = self._aio_pika
        if faults.ACTIVE is not None:
            await faults.afire("broker.connect")
        self._conn = await ap.connect_robust(self._url)
        self._channel = await self._conn.channel()
        self._exchange = await self._channel.declare_exchange(
            self._exchange_name, ap.ExchangeType.FANOUT
        )
        _count_connect(self._url, self._exchange_name)
        return self

    async def __aexit__(self, *exc):
        if self._conn is not None:
            await self._conn.close()
        return False

    async def publish(self, value: float, time: _dt.datetime,
                      meta: Optional[dict] = None) -> None:
        ap = self._aio_pika
        # meta rides in AMQP headers, NOT the body: the reference
        # consumer json.loads()es the body as a bare float and must keep
        # working against a stamping producer
        meta = obs_trace.stamp(meta)
        act = None
        if faults.ACTIVE is not None:
            act = await faults.afire("broker.publish")
            if act == "drop":
                return
        msg = ap.Message(
            body=json.dumps(value).encode(),
            timestamp=time,
            headers=meta or None,
        )
        await asyncio.shield(self._exchange.publish(msg, routing_key=""))
        _pub_counter().inc()
        if act == "dup":
            await asyncio.shield(
                self._exchange.publish(msg, routing_key=""))
            _pub_counter().inc()

    async def subscribe(self, with_meta: bool = False) -> AsyncIterator:
        await self._channel.set_qos(prefetch_count=1)
        queue = await self._channel.declare_queue(exclusive=True)
        await queue.bind(self._exchange)
        deliver = _deliver_counter()
        async with queue.iterator() as it:
            async for message in it:
                async with message.process():
                    act = None
                    if faults.ACTIVE is not None:
                        act = await faults.afire("broker.deliver")
                        if act == "drop":
                            continue
                    ts = message.timestamp
                    if isinstance(ts, (int, float)):
                        ts = _dt.datetime.fromtimestamp(ts)
                    deliver.inc()
                    value = json.loads(message.body.decode())
                    if with_meta:
                        meta = dict(message.headers) \
                            if message.headers else None
                        item = ts, value, meta
                    else:
                        item = ts, value
                    yield item
                    if act == "dup":
                        deliver.inc()
                        yield item


def make_transport(url: Optional[str], exchange: str):
    """Transport from a URL: ``local://`` -> in-process, ``tcp://`` ->
    the in-tree TCP fanout broker (runtime/tcpbroker.py, no external
    services), else AMQP/RabbitMQ."""
    url = url or "local://default"
    if url.startswith("local://"):
        return LocalTransport(url, exchange)
    if url.startswith("tcp://"):
        from tmhpvsim_tpu.runtime.tcpbroker import TcpTransport

        return TcpTransport(url, exchange)
    return AmqpTransport(url, exchange)
