"""Top-level coroutine runner with orderly SIGINT shutdown.

Reference semantics (utils.py:174-197): run the coroutine on a fresh event
loop, convert the first SIGINT into task cancellation (so ``finally``
blocks and shutdown accounting run), and shut down async generators before
closing the loop.
"""

from __future__ import annotations

import asyncio
import signal


def asyncrun(coro):
    """Run ``coro`` to completion; SIGINT cancels it cleanly.

    Returns the coroutine's result, or None if it was cancelled.
    """
    loop = asyncio.new_event_loop()
    task = loop.create_task(coro)

    def _cancel():
        task.cancel()

    try:
        loop.add_signal_handler(signal.SIGINT, _cancel)
    except (NotImplementedError, RuntimeError):
        pass  # non-main thread or platform without signal support
    try:
        return loop.run_until_complete(task)
    except asyncio.CancelledError:
        return None
    finally:
        try:
            loop.remove_signal_handler(signal.SIGINT)
        except (NotImplementedError, RuntimeError, ValueError):
            pass
        loop.run_until_complete(loop.shutdown_asyncgens())
        loop.close()
